"""DeepSpeed-compatible JSON config → typed config objects.

Reference: deepspeed/runtime/config.py:682 (DeepSpeedConfig), including the
train-batch triple inference (config.py:869-924) and duplicate-key rejection
(config.py:688-691).  The schema is the reference's; the backing runtime is
TPU-native (JAX meshes instead of NCCL process groups).
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from . import constants as C
from .config_utils import get_scalar_param, load_config_dict


class DeepSpeedConfigError(Exception):
    pass


@dataclass
class FP16Config:
    enabled: bool = C.FP16_ENABLED_DEFAULT
    loss_scale: float = C.FP16_LOSS_SCALE_DEFAULT
    initial_scale_power: int = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    loss_scale_window: int = C.FP16_LOSS_SCALE_WINDOW_DEFAULT
    hysteresis: int = C.FP16_HYSTERESIS_DEFAULT
    min_loss_scale: float = C.FP16_MIN_LOSS_SCALE_DEFAULT
    fp16_master_weights_and_grads: bool = C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "FP16Config":
        d = d or {}
        return FP16Config(
            enabled=get_scalar_param(d, C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT),
            loss_scale=get_scalar_param(d, C.FP16_LOSS_SCALE,
                                        C.FP16_LOSS_SCALE_DEFAULT),
            initial_scale_power=get_scalar_param(
                d, C.FP16_INITIAL_SCALE_POWER, C.FP16_INITIAL_SCALE_POWER_DEFAULT),
            loss_scale_window=get_scalar_param(d, C.FP16_LOSS_SCALE_WINDOW,
                                               C.FP16_LOSS_SCALE_WINDOW_DEFAULT),
            hysteresis=get_scalar_param(d, C.FP16_HYSTERESIS,
                                        C.FP16_HYSTERESIS_DEFAULT),
            min_loss_scale=get_scalar_param(d, C.FP16_MIN_LOSS_SCALE,
                                            C.FP16_MIN_LOSS_SCALE_DEFAULT),
            fp16_master_weights_and_grads=get_scalar_param(
                d, C.FP16_MASTER_WEIGHTS_AND_GRADS,
                C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT),
        )


@dataclass
class BF16Config:
    """TPU-native: bf16 is the preferred training dtype on TPU (MXU-native,
    no loss scaling required)."""
    enabled: bool = C.BF16_ENABLED_DEFAULT
    # bf16 gradient buffers (reference analog: fp16 grads under ZeRO
    # stage 1/2 — deepspeed/runtime/zero/stage2.py keeps fp16 grad
    # buffers and the fp32 upcast happens in the optimizer).  Halves
    # grad HBM + stage-2 reduce-scatter width; micro-batch accumulation
    # rounds through bf16 like the reference's fp16 accumulation.
    grads_in_compute_dtype: bool = C.BF16_GRADS_IN_COMPUTE_DTYPE_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "BF16Config":
        d = d or {}
        return BF16Config(
            enabled=get_scalar_param(d, C.BF16_ENABLED,
                                     C.BF16_ENABLED_DEFAULT),
            grads_in_compute_dtype=get_scalar_param(
                d, C.BF16_GRADS_IN_COMPUTE_DTYPE,
                C.BF16_GRADS_IN_COMPUTE_DTYPE_DEFAULT))


@dataclass
class OffloadParamConfig:
    device: str = C.OFFLOAD_PARAM_DEVICE_DEFAULT
    nvme_path: Optional[str] = C.OFFLOAD_PARAM_NVME_PATH_DEFAULT
    buffer_count: int = C.OFFLOAD_PARAM_BUFFER_COUNT_DEFAULT
    buffer_size: int = C.OFFLOAD_PARAM_BUFFER_SIZE_DEFAULT
    max_in_cpu: int = C.OFFLOAD_PARAM_MAX_IN_CPU_DEFAULT
    pin_memory: bool = C.OFFLOAD_PARAM_PIN_MEMORY_DEFAULT
    prefetch_depth: int = C.OFFLOAD_PARAM_PREFETCH_DEPTH_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["OffloadParamConfig"]:
        if d is None:
            return None
        buffer_count = int(get_scalar_param(
            d, C.OFFLOAD_PARAM_BUFFER_COUNT,
            C.OFFLOAD_PARAM_BUFFER_COUNT_DEFAULT))
        prefetch_depth = int(get_scalar_param(
            d, C.OFFLOAD_PARAM_PREFETCH_DEPTH,
            C.OFFLOAD_PARAM_PREFETCH_DEPTH_DEFAULT))
        if prefetch_depth < 0:
            raise DeepSpeedConfigError(
                f"offload_param.{C.OFFLOAD_PARAM_PREFETCH_DEPTH}="
                f"{prefetch_depth} — must be >= 0 (< 2 disables NVMe "
                "prefetch, 2 is the double buffer)")
        # the streaming window clamps to >= 2 slots (infinity.py), so the
        # depth bound checks against the same clamp
        if prefetch_depth > max(2, buffer_count):
            raise DeepSpeedConfigError(
                f"offload_param.{C.OFFLOAD_PARAM_PREFETCH_DEPTH}="
                f"{prefetch_depth} exceeds "
                f"{C.OFFLOAD_PARAM_BUFFER_COUNT}={buffer_count} — every "
                "in-flight swap-in pins one window buffer; raise "
                "buffer_count or lower the depth")
        return OffloadParamConfig(
            device=get_scalar_param(d, C.OFFLOAD_PARAM_DEVICE,
                                    C.OFFLOAD_PARAM_DEVICE_DEFAULT),
            nvme_path=get_scalar_param(d, C.OFFLOAD_PARAM_NVME_PATH,
                                       C.OFFLOAD_PARAM_NVME_PATH_DEFAULT),
            buffer_count=buffer_count,
            buffer_size=int(get_scalar_param(d, C.OFFLOAD_PARAM_BUFFER_SIZE,
                                             C.OFFLOAD_PARAM_BUFFER_SIZE_DEFAULT)),
            max_in_cpu=int(get_scalar_param(d, C.OFFLOAD_PARAM_MAX_IN_CPU,
                                            C.OFFLOAD_PARAM_MAX_IN_CPU_DEFAULT)),
            pin_memory=get_scalar_param(d, C.OFFLOAD_PARAM_PIN_MEMORY,
                                        C.OFFLOAD_PARAM_PIN_MEMORY_DEFAULT),
            prefetch_depth=prefetch_depth,
        )


@dataclass
class OffloadOptimizerConfig:
    device: str = C.OFFLOAD_OPTIMIZER_DEVICE_DEFAULT
    nvme_path: Optional[str] = C.OFFLOAD_OPTIMIZER_NVME_PATH_DEFAULT
    buffer_count: int = C.OFFLOAD_OPTIMIZER_BUFFER_COUNT_DEFAULT
    pin_memory: bool = C.OFFLOAD_OPTIMIZER_PIN_MEMORY_DEFAULT
    pipeline_read: bool = C.OFFLOAD_OPTIMIZER_PIPELINE_READ_DEFAULT
    pipeline_write: bool = C.OFFLOAD_OPTIMIZER_PIPELINE_WRITE_DEFAULT
    fast_init: bool = C.OFFLOAD_OPTIMIZER_FAST_INIT_DEFAULT
    pipeline_depth: int = C.OFFLOAD_OPTIMIZER_PIPELINE_DEPTH_DEFAULT

    @property
    def pipeline(self) -> bool:
        return self.pipeline_read or self.pipeline_write

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["OffloadOptimizerConfig"]:
        if d is None:
            return None
        pipeline_depth = int(get_scalar_param(
            d, C.OFFLOAD_OPTIMIZER_PIPELINE_DEPTH,
            C.OFFLOAD_OPTIMIZER_PIPELINE_DEPTH_DEFAULT))
        if pipeline_depth < 2:
            raise DeepSpeedConfigError(
                f"offload_optimizer.{C.OFFLOAD_OPTIMIZER_PIPELINE_DEPTH}="
                f"{pipeline_depth} — the leaf sweep needs >= 2 rotating "
                "buffer triples to overlap reads/Adam/write-backs "
                "(reference PipelinedOptimizerSwapper is depth 2)")
        return OffloadOptimizerConfig(
            device=get_scalar_param(d, C.OFFLOAD_OPTIMIZER_DEVICE,
                                    C.OFFLOAD_OPTIMIZER_DEVICE_DEFAULT),
            nvme_path=get_scalar_param(d, C.OFFLOAD_OPTIMIZER_NVME_PATH,
                                       C.OFFLOAD_OPTIMIZER_NVME_PATH_DEFAULT),
            buffer_count=int(get_scalar_param(
                d, C.OFFLOAD_OPTIMIZER_BUFFER_COUNT,
                C.OFFLOAD_OPTIMIZER_BUFFER_COUNT_DEFAULT)),
            pin_memory=get_scalar_param(d, C.OFFLOAD_OPTIMIZER_PIN_MEMORY,
                                        C.OFFLOAD_OPTIMIZER_PIN_MEMORY_DEFAULT),
            pipeline_read=get_scalar_param(
                d, C.OFFLOAD_OPTIMIZER_PIPELINE_READ,
                C.OFFLOAD_OPTIMIZER_PIPELINE_READ_DEFAULT),
            pipeline_write=get_scalar_param(
                d, C.OFFLOAD_OPTIMIZER_PIPELINE_WRITE,
                C.OFFLOAD_OPTIMIZER_PIPELINE_WRITE_DEFAULT),
            fast_init=get_scalar_param(d, C.OFFLOAD_OPTIMIZER_FAST_INIT,
                                       C.OFFLOAD_OPTIMIZER_FAST_INIT_DEFAULT),
            pipeline_depth=pipeline_depth,
        )


@dataclass
class ZeroLowBandwidthConfig:
    """ZeRO++-style low-bandwidth collectives (arXiv:2306.10209).

    qwz_bits: blockwise-quantized weight all-gather width (0=off, 4, 8).
    qgz_bits: quantized gradient reduce-scatter width (0=off, 4, 8) —
        int4 rides the wire packed two-per-byte.
    hpz_group_size: size of the sub-mesh holding the secondary weight
        partition (0/1 = off); must equal the product of a suffix of the
        ZeRO mesh axes (partition.resolve_hpz_axes).
    block_size: elements per quantization block (scale granularity).
    fused_collective_matmul: T3-style per-tile fusion of the qwZ/qgZ
        transports with the producer/consumer GEMM schedule
        (ops/collective_matmul.py): the streamed-ZeRO-3 gathers and
        grad scatters move tile-by-tile over a ring instead of as one
        monolithic collective, and the Schedule Auditor classifies the
        per-tile wire as fused/hidden.  Off by default.
    onebit: 1-bit optimizer wire tier (docs/onebit.md): after the onebit
        optimizer's freeze_step the data-parallel grad allreduce is
        removed from the grad program and replaced by an error-feedback
        sign+scale momentum sync on a packed int8 wire
        (comm/compressed.py wire="packed").  Requires a OneBitAdam /
        OneBitLamb optimizer and ZeRO stage <= 2; hpz_group_size doubles
        as the hierarchical group size (intra-group dense, cross-group
        1-bit).  Off by default.
    """
    qwz_bits: int = C.LOW_BANDWIDTH_QWZ_BITS_DEFAULT
    qgz_bits: int = C.LOW_BANDWIDTH_QGZ_BITS_DEFAULT
    hpz_group_size: int = C.LOW_BANDWIDTH_HPZ_GROUP_SIZE_DEFAULT
    block_size: int = C.LOW_BANDWIDTH_BLOCK_SIZE_DEFAULT
    fused_collective_matmul: bool = C.LOW_BANDWIDTH_FCM_DEFAULT
    onebit: bool = C.LOW_BANDWIDTH_ONEBIT_DEFAULT

    @property
    def enabled(self) -> bool:
        # fused_collective_matmul alone engages the low-bandwidth
        # context: the per-tile ring schedule applies at native width
        # even with both quantizers off.  `onebit` deliberately does NOT
        # feed this property — it is a data-parallel wire feature, not a
        # stage-3 streaming transport, and must not engage the streaming
        # context (or its stage<3 "will be ignored" warning).
        return bool(self.qwz_bits or self.qgz_bits or
                    self.hpz_group_size > 1 or
                    self.fused_collective_matmul)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ZeroLowBandwidthConfig":
        d = d or {}
        cfg = ZeroLowBandwidthConfig(
            qwz_bits=int(get_scalar_param(d, C.LOW_BANDWIDTH_QWZ_BITS,
                                          C.LOW_BANDWIDTH_QWZ_BITS_DEFAULT)),
            qgz_bits=int(get_scalar_param(d, C.LOW_BANDWIDTH_QGZ_BITS,
                                          C.LOW_BANDWIDTH_QGZ_BITS_DEFAULT)),
            hpz_group_size=int(get_scalar_param(
                d, C.LOW_BANDWIDTH_HPZ_GROUP_SIZE,
                C.LOW_BANDWIDTH_HPZ_GROUP_SIZE_DEFAULT)),
            block_size=int(get_scalar_param(
                d, C.LOW_BANDWIDTH_BLOCK_SIZE,
                C.LOW_BANDWIDTH_BLOCK_SIZE_DEFAULT)),
            fused_collective_matmul=get_scalar_param(
                d, C.LOW_BANDWIDTH_FCM, C.LOW_BANDWIDTH_FCM_DEFAULT),
            onebit=get_scalar_param(
                d, C.LOW_BANDWIDTH_ONEBIT, C.LOW_BANDWIDTH_ONEBIT_DEFAULT),
        )
        for name, bits in ((C.LOW_BANDWIDTH_QWZ_BITS, cfg.qwz_bits),
                           (C.LOW_BANDWIDTH_QGZ_BITS, cfg.qgz_bits)):
            if bits not in (0, 4, 8):
                raise DeepSpeedConfigError(
                    f"zero_optimization.low_bandwidth.{name}={bits} — "
                    "supported widths are 0 (off), 4, and 8")
        if cfg.block_size < 1:
            raise DeepSpeedConfigError(
                "zero_optimization.low_bandwidth.block_size must be >= 1, "
                f"got {cfg.block_size}")
        if not isinstance(cfg.fused_collective_matmul, bool):
            raise DeepSpeedConfigError(
                f"zero_optimization.low_bandwidth.{C.LOW_BANDWIDTH_FCM} "
                f"must be a bool, got {cfg.fused_collective_matmul!r}")
        if not isinstance(cfg.onebit, bool):
            raise DeepSpeedConfigError(
                f"zero_optimization.low_bandwidth.{C.LOW_BANDWIDTH_ONEBIT} "
                f"must be a bool, got {cfg.onebit!r}")
        return cfg


def _validated_prefetch_mode(mode: str) -> str:
    if mode not in C.ZERO_OPTIMIZATION_PREFETCH_MODES:
        raise DeepSpeedConfigError(
            f"zero_optimization.{C.ZERO_OPTIMIZATION_PREFETCH_MODE}="
            f"{mode!r} — supported modes are "
            f"{list(C.ZERO_OPTIMIZATION_PREFETCH_MODES)}")
    return mode


@dataclass
class ZeroConfig:
    """Reference: deepspeed/runtime/zero/config.py:18 (DeepSpeedZeroConfig)."""
    stage: int = C.ZERO_OPTIMIZATION_STAGE_DEFAULT
    contiguous_gradients: bool = True
    reduce_scatter: bool = C.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT
    reduce_bucket_size: int = C.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT
    allgather_partitions: bool = C.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT
    allgather_bucket_size: int = C.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT
    overlap_comm: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = C.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT
    max_live_parameters: int = C.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT
    max_reuse_distance: int = C.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT
    prefetch_bucket_size: int = C.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT
    prefetch_mode: str = C.ZERO_OPTIMIZATION_PREFETCH_MODE_DEFAULT
    param_persistence_threshold: int = (
        C.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT)
    gather_fp16_weights_on_model_save: bool = (
        C.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT)
    ignore_unused_parameters: bool = (
        C.ZERO_OPTIMIZATION_IGNORE_UNUSED_PARAMETERS_DEFAULT)
    legacy_stage1: bool = C.ZERO_OPTIMIZATION_LEGACY_STAGE1_DEFAULT
    elastic_checkpoint: bool = C.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT
    cpu_offload: bool = C.ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT
    cpu_offload_params: bool = C.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT
    low_bandwidth: ZeroLowBandwidthConfig = field(
        default_factory=ZeroLowBandwidthConfig)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ZeroConfig":
        if d is None:
            d = {}
        if isinstance(d, bool):  # "zero_optimization": true → stage 1
            d = {C.ZERO_OPTIMIZATION_STAGE: 1 if d else 0}
        stage = get_scalar_param(d, C.ZERO_OPTIMIZATION_STAGE,
                                 C.ZERO_OPTIMIZATION_STAGE_DEFAULT)
        # Legacy cpu_offload flags map onto the offload_* sub-dicts
        # (reference: zero/config.py offload back-compat).
        cpu_offload = get_scalar_param(d, C.ZERO_OPTIMIZATION_CPU_OFFLOAD,
                                       C.ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        cpu_offload_params = get_scalar_param(
            d, C.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS,
            C.ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT)
        cpu_offload_pin = get_scalar_param(
            d, C.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY,
            C.ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT)
        offload_param = OffloadParamConfig.from_dict(
            d.get(C.ZERO_OPTIMIZATION_OFFLOAD_PARAM))
        offload_optimizer = OffloadOptimizerConfig.from_dict(
            d.get(C.ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER))
        if cpu_offload and offload_optimizer is None:
            offload_optimizer = OffloadOptimizerConfig(
                device=C.OFFLOAD_CPU_DEVICE, pin_memory=cpu_offload_pin)
        if cpu_offload_params and offload_param is None:
            offload_param = OffloadParamConfig(
                device=C.OFFLOAD_CPU_DEVICE, pin_memory=cpu_offload_pin)
        overlap_default = stage == C.ZERO_OPTIMIZATION_WEIGHTS
        contiguous_default = True
        return ZeroConfig(
            stage=stage,
            contiguous_gradients=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS, contiguous_default),
            reduce_scatter=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_REDUCE_SCATTER,
                C.ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT),
            reduce_bucket_size=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
                C.ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)),
            allgather_partitions=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
                C.ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT),
            allgather_bucket_size=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
                C.ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)),
            overlap_comm=get_scalar_param(d, C.ZERO_OPTIMIZATION_OVERLAP_COMM,
                                          overlap_default),
            offload_param=offload_param,
            offload_optimizer=offload_optimizer,
            sub_group_size=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_SUB_GROUP_SIZE,
                C.ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT)),
            max_live_parameters=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
                C.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT)),
            max_reuse_distance=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE,
                C.ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT)),
            prefetch_bucket_size=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
                C.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT)),
            prefetch_mode=_validated_prefetch_mode(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_PREFETCH_MODE,
                C.ZERO_OPTIMIZATION_PREFETCH_MODE_DEFAULT)),
            param_persistence_threshold=int(get_scalar_param(
                d, C.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD,
                C.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT)),
            gather_fp16_weights_on_model_save=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE,
                C.ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT),
            ignore_unused_parameters=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_IGNORE_UNUSED_PARAMETERS,
                C.ZERO_OPTIMIZATION_IGNORE_UNUSED_PARAMETERS_DEFAULT),
            legacy_stage1=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_LEGACY_STAGE1,
                C.ZERO_OPTIMIZATION_LEGACY_STAGE1_DEFAULT),
            elastic_checkpoint=get_scalar_param(
                d, C.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
                C.ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT),
            cpu_offload=cpu_offload,
            cpu_offload_params=cpu_offload_params,
            low_bandwidth=ZeroLowBandwidthConfig.from_dict(
                d.get(C.ZERO_OPTIMIZATION_LOW_BANDWIDTH)),
        )


@dataclass
class AioConfig:
    """Reference: deepspeed/runtime/swap_tensor/aio_config.py:18, plus the
    `backend` engine selector (io_uring | batched | threadpool | auto —
    constants.AIO_BACKENDS, resolved at handle-creation time by
    swap_tensor/aio_handle.resolve_backend with a loud fallback log when
    io_uring is requested but the kernel can't deliver it)."""
    block_size: int = C.AIO_BLOCK_SIZE_DEFAULT
    queue_depth: int = C.AIO_QUEUE_DEPTH_DEFAULT
    thread_count: int = C.AIO_THREAD_COUNT_DEFAULT
    single_submit: bool = C.AIO_SINGLE_SUBMIT_DEFAULT
    overlap_events: bool = C.AIO_OVERLAP_EVENTS_DEFAULT
    backend: str = C.AIO_BACKEND_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "AioConfig":
        d = d or {}
        block_size = int(get_scalar_param(d, C.AIO_BLOCK_SIZE,
                                          C.AIO_BLOCK_SIZE_DEFAULT))
        if block_size < C.AIO_BLOCK_SIZE_MIN:
            raise DeepSpeedConfigError(
                f"aio.{C.AIO_BLOCK_SIZE}={block_size} — below the "
                f"{C.AIO_BLOCK_SIZE_MIN}-byte I/O alignment floor")
        queue_depth = int(get_scalar_param(d, C.AIO_QUEUE_DEPTH,
                                           C.AIO_QUEUE_DEPTH_DEFAULT))
        if queue_depth < 1:
            raise DeepSpeedConfigError(
                f"aio.{C.AIO_QUEUE_DEPTH}={queue_depth} — must be >= 1")
        thread_count = int(get_scalar_param(d, C.AIO_THREAD_COUNT,
                                            C.AIO_THREAD_COUNT_DEFAULT))
        if thread_count < 1:
            raise DeepSpeedConfigError(
                f"aio.{C.AIO_THREAD_COUNT}={thread_count} — must be >= 1")
        backend = get_scalar_param(d, C.AIO_BACKEND, C.AIO_BACKEND_DEFAULT)
        if backend not in C.AIO_BACKENDS:
            raise DeepSpeedConfigError(
                f"aio.{C.AIO_BACKEND}={backend!r} — supported backends "
                f"are {list(C.AIO_BACKENDS)}")
        return AioConfig(
            block_size=block_size,
            queue_depth=queue_depth,
            thread_count=thread_count,
            single_submit=get_scalar_param(d, C.AIO_SINGLE_SUBMIT,
                                           C.AIO_SINGLE_SUBMIT_DEFAULT),
            overlap_events=get_scalar_param(d, C.AIO_OVERLAP_EVENTS,
                                            C.AIO_OVERLAP_EVENTS_DEFAULT),
            backend=backend,
        )


@dataclass
class ActivationCheckpointingConfig:
    """Reference: runtime/activation_checkpointing/config.py:103."""
    partition_activations: bool = C.ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT
    contiguous_memory_optimization: bool = (
        C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
    cpu_checkpointing: bool = C.ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT
    number_checkpoints: Optional[int] = C.ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT
    synchronize_checkpoint_boundary: bool = (
        C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)
    profile: bool = C.ACT_CHKPT_PROFILE_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ActivationCheckpointingConfig":
        d = d or {}
        return ActivationCheckpointingConfig(
            partition_activations=get_scalar_param(
                d, C.ACT_CHKPT_PARTITION_ACTIVATIONS,
                C.ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT),
            contiguous_memory_optimization=get_scalar_param(
                d, C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
                C.ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT),
            cpu_checkpointing=get_scalar_param(
                d, C.ACT_CHKPT_CPU_CHECKPOINTING,
                C.ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT),
            number_checkpoints=get_scalar_param(
                d, C.ACT_CHKPT_NUMBER_CHECKPOINTS,
                C.ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT),
            synchronize_checkpoint_boundary=get_scalar_param(
                d, C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
                C.ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT),
            profile=get_scalar_param(d, C.ACT_CHKPT_PROFILE,
                                     C.ACT_CHKPT_PROFILE_DEFAULT),
        )


@dataclass
class FlopsProfilerConfig:
    """Reference: deepspeed/profiling/config.py:49."""
    enabled: bool = C.FLOPS_PROFILER_ENABLED_DEFAULT
    profile_step: int = C.FLOPS_PROFILER_PROFILE_STEP_DEFAULT
    module_depth: int = C.FLOPS_PROFILER_MODULE_DEPTH_DEFAULT
    top_modules: int = C.FLOPS_PROFILER_TOP_MODULES_DEFAULT
    detailed: bool = C.FLOPS_PROFILER_DETAILED_DEFAULT
    output_file: Optional[str] = C.FLOPS_PROFILER_OUTPUT_FILE_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "FlopsProfilerConfig":
        d = d or {}
        return FlopsProfilerConfig(
            enabled=get_scalar_param(d, C.FLOPS_PROFILER_ENABLED,
                                     C.FLOPS_PROFILER_ENABLED_DEFAULT),
            profile_step=get_scalar_param(d, C.FLOPS_PROFILER_PROFILE_STEP,
                                          C.FLOPS_PROFILER_PROFILE_STEP_DEFAULT),
            module_depth=get_scalar_param(d, C.FLOPS_PROFILER_MODULE_DEPTH,
                                          C.FLOPS_PROFILER_MODULE_DEPTH_DEFAULT),
            top_modules=get_scalar_param(d, C.FLOPS_PROFILER_TOP_MODULES,
                                         C.FLOPS_PROFILER_TOP_MODULES_DEFAULT),
            detailed=get_scalar_param(d, C.FLOPS_PROFILER_DETAILED,
                                      C.FLOPS_PROFILER_DETAILED_DEFAULT),
            output_file=get_scalar_param(d, C.FLOPS_PROFILER_OUTPUT_FILE,
                                         C.FLOPS_PROFILER_OUTPUT_FILE_DEFAULT),
        )


@dataclass
class TensorboardConfig:
    enabled: bool = C.TENSORBOARD_ENABLED_DEFAULT
    output_path: str = C.TENSORBOARD_OUTPUT_PATH_DEFAULT
    job_name: str = C.TENSORBOARD_JOB_NAME_DEFAULT
    # scalar-write cadence in optimizer steps; None inherits steps_per_print
    # (writing every step forces a device sync per step — see engine.step)
    write_interval: Optional[int] = C.TENSORBOARD_WRITE_INTERVAL_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "TensorboardConfig":
        d = d or {}
        interval = get_scalar_param(d, C.TENSORBOARD_WRITE_INTERVAL,
                                    C.TENSORBOARD_WRITE_INTERVAL_DEFAULT)
        if interval is not None and int(interval) <= 0:
            raise DeepSpeedConfigError(
                f"tensorboard.write_interval must be positive, got {interval}")
        return TensorboardConfig(
            enabled=get_scalar_param(d, C.TENSORBOARD_ENABLED,
                                     C.TENSORBOARD_ENABLED_DEFAULT),
            output_path=get_scalar_param(d, C.TENSORBOARD_OUTPUT_PATH,
                                         C.TENSORBOARD_OUTPUT_PATH_DEFAULT),
            job_name=get_scalar_param(d, C.TENSORBOARD_JOB_NAME,
                                      C.TENSORBOARD_JOB_NAME_DEFAULT),
            write_interval=None if interval is None else int(interval),
        )


@dataclass
class FusedStepConfig:
    """Fused whole-step train program (docs/fused_step.md): gradient
    accumulation as an in-program ``lax.scan`` + the optimizer apply in the
    same compiled program — one XLA dispatch per optimizer step.  Off by
    default; the engine falls back to the modular forward/backward/step
    loop automatically whenever a host-interactive feature is active (the
    fallback matrix is logged and exposed as ``engine.fused_step_reason``).
    """
    enabled: bool = C.FUSED_STEP_ENABLED_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "FusedStepConfig":
        d = d or {}
        return FusedStepConfig(
            enabled=get_scalar_param(d, C.FUSED_STEP_ENABLED,
                                     C.FUSED_STEP_ENABLED_DEFAULT))


@dataclass
class MonitorCaptureConfig:
    """Anomaly-triggered deep profiling (monitor/capture.py): a bounded
    ``jax.profiler`` trace capture armed when a reconciliation band is
    breached or a fleet health event flags THIS host.  Off by default;
    rate-limited so a persistently-bad band yields a few traces, never a
    full-run profile."""
    enabled: bool = C.MONITOR_CAPTURE_ENABLED_DEFAULT
    steps: int = C.MONITOR_CAPTURE_STEPS_DEFAULT
    max_captures: int = C.MONITOR_CAPTURE_MAX_CAPTURES_DEFAULT
    cooldown_steps: int = C.MONITOR_CAPTURE_COOLDOWN_STEPS_DEFAULT
    output_path: str = C.MONITOR_CAPTURE_OUTPUT_PATH_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "MonitorCaptureConfig":
        if d is True:
            # the natural shorthand for "just turn it on"
            d = {C.MONITOR_CAPTURE_ENABLED: True}
        elif d in (None, False):
            d = {}
        elif not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"monitor.capture must be a config object (or true/"
                f"false), got {d!r}")
        cfg = MonitorCaptureConfig(
            enabled=bool(get_scalar_param(
                d, C.MONITOR_CAPTURE_ENABLED,
                C.MONITOR_CAPTURE_ENABLED_DEFAULT)),
            steps=int(get_scalar_param(
                d, C.MONITOR_CAPTURE_STEPS,
                C.MONITOR_CAPTURE_STEPS_DEFAULT)),
            max_captures=int(get_scalar_param(
                d, C.MONITOR_CAPTURE_MAX_CAPTURES,
                C.MONITOR_CAPTURE_MAX_CAPTURES_DEFAULT)),
            cooldown_steps=int(get_scalar_param(
                d, C.MONITOR_CAPTURE_COOLDOWN_STEPS,
                C.MONITOR_CAPTURE_COOLDOWN_STEPS_DEFAULT)),
            output_path=get_scalar_param(
                d, C.MONITOR_CAPTURE_OUTPUT_PATH,
                C.MONITOR_CAPTURE_OUTPUT_PATH_DEFAULT) or "",
        )
        if cfg.steps <= 0:
            raise DeepSpeedConfigError(
                f"monitor.capture.steps must be positive, got {cfg.steps}")
        if cfg.max_captures <= 0:
            raise DeepSpeedConfigError(
                "monitor.capture.max_captures must be positive, got "
                f"{cfg.max_captures}")
        if cfg.cooldown_steps < 0:
            raise DeepSpeedConfigError(
                "monitor.capture.cooldown_steps must be >= 0, got "
                f"{cfg.cooldown_steps}")
        return cfg


@dataclass
class MonitorMoeConfig:
    """MoE routing observability (monitor/moe.py, docs/telemetry.md):
    device-resident RoutingStats accumulation in the traced step
    programs, one ``moe`` record + ExpertPopularitySnapshot per flush
    window, fleet load-skew slots, and the three MoE health rules.
    Off by default; on a dense model it is inert (no gate ever emits)."""
    enabled: bool = C.MONITOR_MOE_ENABLED_DEFAULT
    popularity_ewma_alpha: float = C.MONITOR_MOE_EWMA_ALPHA_DEFAULT
    hot_k: int = C.MONITOR_MOE_HOT_K_DEFAULT
    dead_expert_threshold: float = (
        C.MONITOR_MOE_DEAD_EXPERT_THRESHOLD_DEFAULT)
    dead_expert_windows: int = C.MONITOR_MOE_DEAD_EXPERT_WINDOWS_DEFAULT
    entropy_floor: float = C.MONITOR_MOE_ENTROPY_FLOOR_DEFAULT
    collapse_windows: int = C.MONITOR_MOE_COLLAPSE_WINDOWS_DEFAULT
    ep_imbalance_ratio: float = C.MONITOR_MOE_EP_IMBALANCE_RATIO_DEFAULT
    ep_imbalance_windows: int = (
        C.MONITOR_MOE_EP_IMBALANCE_WINDOWS_DEFAULT)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "MonitorMoeConfig":
        if d is True:  # shorthand, like monitor.capture
            d = {C.MONITOR_MOE_ENABLED: True}
        elif d in (None, False):
            d = {}
        elif not isinstance(d, dict):
            raise DeepSpeedConfigError(
                f"monitor.moe must be a config object (or true/false), "
                f"got {d!r}")
        cfg = MonitorMoeConfig(
            enabled=bool(get_scalar_param(
                d, C.MONITOR_MOE_ENABLED, C.MONITOR_MOE_ENABLED_DEFAULT)),
            popularity_ewma_alpha=float(get_scalar_param(
                d, C.MONITOR_MOE_EWMA_ALPHA,
                C.MONITOR_MOE_EWMA_ALPHA_DEFAULT)),
            hot_k=int(get_scalar_param(
                d, C.MONITOR_MOE_HOT_K, C.MONITOR_MOE_HOT_K_DEFAULT)),
            dead_expert_threshold=float(get_scalar_param(
                d, C.MONITOR_MOE_DEAD_EXPERT_THRESHOLD,
                C.MONITOR_MOE_DEAD_EXPERT_THRESHOLD_DEFAULT)),
            dead_expert_windows=int(get_scalar_param(
                d, C.MONITOR_MOE_DEAD_EXPERT_WINDOWS,
                C.MONITOR_MOE_DEAD_EXPERT_WINDOWS_DEFAULT)),
            entropy_floor=float(get_scalar_param(
                d, C.MONITOR_MOE_ENTROPY_FLOOR,
                C.MONITOR_MOE_ENTROPY_FLOOR_DEFAULT)),
            collapse_windows=int(get_scalar_param(
                d, C.MONITOR_MOE_COLLAPSE_WINDOWS,
                C.MONITOR_MOE_COLLAPSE_WINDOWS_DEFAULT)),
            ep_imbalance_ratio=float(get_scalar_param(
                d, C.MONITOR_MOE_EP_IMBALANCE_RATIO,
                C.MONITOR_MOE_EP_IMBALANCE_RATIO_DEFAULT)),
            ep_imbalance_windows=int(get_scalar_param(
                d, C.MONITOR_MOE_EP_IMBALANCE_WINDOWS,
                C.MONITOR_MOE_EP_IMBALANCE_WINDOWS_DEFAULT)),
        )
        if not 0.0 < cfg.popularity_ewma_alpha <= 1.0:
            raise DeepSpeedConfigError(
                "monitor.moe.popularity_ewma_alpha must be in (0, 1], "
                f"got {cfg.popularity_ewma_alpha}")
        if cfg.hot_k < 1:
            raise DeepSpeedConfigError(
                f"monitor.moe.hot_k must be >= 1, got {cfg.hot_k}")
        if not 0.0 <= cfg.dead_expert_threshold < 1.0:
            raise DeepSpeedConfigError(
                "monitor.moe.dead_expert_threshold must be in [0, 1) — "
                "a fraction of the fair per-expert share, got "
                f"{cfg.dead_expert_threshold}")
        if not 0.0 <= cfg.entropy_floor < 1.0:
            raise DeepSpeedConfigError(
                "monitor.moe.entropy_floor must be in [0, 1) — router "
                "entropy is normalized by ln(num_experts), got "
                f"{cfg.entropy_floor}")
        if cfg.ep_imbalance_ratio <= 1.0:
            raise DeepSpeedConfigError(
                "monitor.moe.ep_imbalance_ratio must be > 1.0 (a hot "
                "host carries MORE than the peer-median load), got "
                f"{cfg.ep_imbalance_ratio}")
        for name, v in ((C.MONITOR_MOE_DEAD_EXPERT_WINDOWS,
                         cfg.dead_expert_windows),
                        (C.MONITOR_MOE_COLLAPSE_WINDOWS,
                         cfg.collapse_windows),
                        (C.MONITOR_MOE_EP_IMBALANCE_WINDOWS,
                         cfg.ep_imbalance_windows)):
            if v < 1:
                raise DeepSpeedConfigError(
                    f"monitor.moe.{name} must be >= 1, got {v}")
        return cfg


@dataclass
class MonitorConfig:
    """Runtime telemetry block (docs/telemetry.md): per-step structured
    metric records, pluggable writers, optional Chrome/Perfetto trace
    export, and the measured-vs-predicted reconciliation report — plus
    the fleet layer (cross-host aggregation + straggler/divergence
    health, heartbeat liveness, anomaly-triggered profiler capture).
    Off by default; with it on, all host reads AND all cross-host
    aggregation traffic stay batched at flush-window boundaries (the
    async-host-loop discipline)."""
    enabled: bool = C.MONITOR_ENABLED_DEFAULT
    output_path: str = C.MONITOR_OUTPUT_PATH_DEFAULT
    job_name: str = C.MONITOR_JOB_NAME_DEFAULT
    writers: tuple = C.MONITOR_WRITERS_DEFAULT
    write_interval: Optional[int] = C.MONITOR_WRITE_INTERVAL_DEFAULT
    trace: bool = C.MONITOR_TRACE_DEFAULT
    trace_steps: int = C.MONITOR_TRACE_STEPS_DEFAULT
    reconcile: bool = C.MONITOR_RECONCILE_DEFAULT
    step_time_ratio_max: float = C.MONITOR_STEP_TIME_RATIO_MAX_DEFAULT
    hbm_ratio_max: float = C.MONITOR_HBM_RATIO_MAX_DEFAULT
    swap_min_vs_ceiling: float = C.MONITOR_SWAP_MIN_VS_CEILING_DEFAULT
    fleet: bool = C.MONITOR_FLEET_DEFAULT
    heartbeat: bool = C.MONITOR_HEARTBEAT_DEFAULT
    straggler_zscore: float = C.MONITOR_STRAGGLER_ZSCORE_DEFAULT
    straggler_min_ratio: float = C.MONITOR_STRAGGLER_MIN_RATIO_DEFAULT
    divergence_rel_spread: float = C.MONITOR_DIVERGENCE_REL_SPREAD_DEFAULT
    health_warmup_windows: int = C.MONITOR_HEALTH_WARMUP_WINDOWS_DEFAULT
    fleet_exchange_deadline_s: float = (
        C.MONITOR_FLEET_EXCHANGE_DEADLINE_S_DEFAULT)
    capture: MonitorCaptureConfig = field(
        default_factory=MonitorCaptureConfig)
    moe: MonitorMoeConfig = field(default_factory=MonitorMoeConfig)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "MonitorConfig":
        d = d or {}
        writers = d.get(C.MONITOR_WRITERS, C.MONITOR_WRITERS_DEFAULT)
        if isinstance(writers, str):
            writers = (writers,)
        try:
            writers = tuple(writers)
        except TypeError:
            raise DeepSpeedConfigError(
                f"monitor.writers must be a list of backend names "
                f"(supported: {list(C.MONITOR_WRITER_KINDS)}), got "
                f"{writers!r}")
        interval = get_scalar_param(d, C.MONITOR_WRITE_INTERVAL,
                                    C.MONITOR_WRITE_INTERVAL_DEFAULT)
        cfg = MonitorConfig(
            enabled=get_scalar_param(d, C.MONITOR_ENABLED,
                                     C.MONITOR_ENABLED_DEFAULT),
            output_path=get_scalar_param(d, C.MONITOR_OUTPUT_PATH,
                                         C.MONITOR_OUTPUT_PATH_DEFAULT),
            job_name=get_scalar_param(d, C.MONITOR_JOB_NAME,
                                      C.MONITOR_JOB_NAME_DEFAULT),
            writers=writers,
            write_interval=None if interval is None else int(interval),
            trace=bool(get_scalar_param(d, C.MONITOR_TRACE,
                                        C.MONITOR_TRACE_DEFAULT)),
            trace_steps=int(get_scalar_param(
                d, C.MONITOR_TRACE_STEPS, C.MONITOR_TRACE_STEPS_DEFAULT)),
            reconcile=bool(get_scalar_param(d, C.MONITOR_RECONCILE,
                                            C.MONITOR_RECONCILE_DEFAULT)),
            step_time_ratio_max=float(get_scalar_param(
                d, C.MONITOR_STEP_TIME_RATIO_MAX,
                C.MONITOR_STEP_TIME_RATIO_MAX_DEFAULT)),
            hbm_ratio_max=float(get_scalar_param(
                d, C.MONITOR_HBM_RATIO_MAX,
                C.MONITOR_HBM_RATIO_MAX_DEFAULT)),
            swap_min_vs_ceiling=float(get_scalar_param(
                d, C.MONITOR_SWAP_MIN_VS_CEILING,
                C.MONITOR_SWAP_MIN_VS_CEILING_DEFAULT)),
            fleet=bool(get_scalar_param(d, C.MONITOR_FLEET,
                                        C.MONITOR_FLEET_DEFAULT)),
            heartbeat=bool(get_scalar_param(d, C.MONITOR_HEARTBEAT,
                                            C.MONITOR_HEARTBEAT_DEFAULT)),
            straggler_zscore=float(get_scalar_param(
                d, C.MONITOR_STRAGGLER_ZSCORE,
                C.MONITOR_STRAGGLER_ZSCORE_DEFAULT)),
            straggler_min_ratio=float(get_scalar_param(
                d, C.MONITOR_STRAGGLER_MIN_RATIO,
                C.MONITOR_STRAGGLER_MIN_RATIO_DEFAULT)),
            divergence_rel_spread=float(get_scalar_param(
                d, C.MONITOR_DIVERGENCE_REL_SPREAD,
                C.MONITOR_DIVERGENCE_REL_SPREAD_DEFAULT)),
            health_warmup_windows=int(get_scalar_param(
                d, C.MONITOR_HEALTH_WARMUP_WINDOWS,
                C.MONITOR_HEALTH_WARMUP_WINDOWS_DEFAULT)),
            fleet_exchange_deadline_s=float(get_scalar_param(
                d, C.MONITOR_FLEET_EXCHANGE_DEADLINE_S,
                C.MONITOR_FLEET_EXCHANGE_DEADLINE_S_DEFAULT)),
            capture=MonitorCaptureConfig.from_dict(
                d.get(C.MONITOR_CAPTURE)),
            moe=MonitorMoeConfig.from_dict(d.get(C.MONITOR_MOE)),
        )
        unknown = [w for w in cfg.writers if w not in C.MONITOR_WRITER_KINDS]
        if unknown:
            raise DeepSpeedConfigError(
                f"monitor.writers contains unknown backend(s) {unknown} — "
                f"supported: {list(C.MONITOR_WRITER_KINDS)}")
        if cfg.enabled and not cfg.writers:
            raise DeepSpeedConfigError(
                "monitor.enabled requires at least one writer backend "
                f"(supported: {list(C.MONITOR_WRITER_KINDS)})")
        if cfg.write_interval is not None and cfg.write_interval <= 0:
            raise DeepSpeedConfigError(
                "monitor.write_interval must be positive, got "
                f"{cfg.write_interval}")
        if cfg.trace_steps <= 0:
            raise DeepSpeedConfigError(
                f"monitor.trace_steps must be positive, got "
                f"{cfg.trace_steps}")
        if cfg.step_time_ratio_max <= 1.0:
            raise DeepSpeedConfigError(
                "monitor.step_time_ratio_max must be > 1.0 (measured is "
                f"compared against a LOWER bound), got "
                f"{cfg.step_time_ratio_max}")
        if cfg.hbm_ratio_max <= 1.0:
            raise DeepSpeedConfigError(
                "monitor.hbm_ratio_max must be > 1.0, got "
                f"{cfg.hbm_ratio_max}")
        if not 0.0 <= cfg.swap_min_vs_ceiling <= 1.0:
            raise DeepSpeedConfigError(
                "monitor.swap_min_vs_ceiling must be in [0, 1], got "
                f"{cfg.swap_min_vs_ceiling}")
        if cfg.straggler_zscore <= 0:
            raise DeepSpeedConfigError(
                "monitor.straggler_zscore must be positive, got "
                f"{cfg.straggler_zscore}")
        if cfg.straggler_min_ratio < 1.0:
            raise DeepSpeedConfigError(
                "monitor.straggler_min_ratio must be >= 1.0 (a straggler "
                "is SLOWER than the fleet median), got "
                f"{cfg.straggler_min_ratio}")
        if cfg.divergence_rel_spread <= 0:
            raise DeepSpeedConfigError(
                "monitor.divergence_rel_spread must be positive, got "
                f"{cfg.divergence_rel_spread}")
        if cfg.health_warmup_windows < 0:
            raise DeepSpeedConfigError(
                "monitor.health_warmup_windows must be >= 0, got "
                f"{cfg.health_warmup_windows}")
        if cfg.fleet_exchange_deadline_s < 0:
            raise DeepSpeedConfigError(
                "monitor.fleet_exchange_deadline_s must be >= 0 "
                f"(0 disables the watchdog), got "
                f"{cfg.fleet_exchange_deadline_s}")
        return cfg


def validate_hw_constants(hw: Dict[str, Any],
                          context: str = "analysis") -> Dict[str, float]:
    """Positivity gate for the canonical hardware-model constants
    (C.ANALYSIS_HW_KEYS: hw_peak_tflops / hw_hbm_gbps / hw_ici_gbps).
    Single-sourced so the ``analysis`` config block and the autotuner's
    calibration file validate the SAME names the same way — returns the
    validated subset as floats."""
    out: Dict[str, float] = {}
    for key in C.ANALYSIS_HW_KEYS:
        if key not in hw or hw[key] is None:
            continue
        val = float(hw[key])
        if val <= 0:
            raise DeepSpeedConfigError(
                f"{context}.{key} must be > 0, got {val}")
        out[key] = val
    return out


@dataclass
class AnalysisConfig:
    """Program Auditor block (docs/program_auditor.md): static jaxpr lint
    of the traced step programs at engine init, plus the runtime
    recompile guard.  ``mode`` "off" (default) skips everything; "warn"
    logs findings; "error" raises ProgramAuditError on error-severity
    findings (CI posture)."""
    mode: str = C.ANALYSIS_MODE_DEFAULT
    comm_budget_mb: Optional[float] = C.ANALYSIS_COMM_BUDGET_MB_DEFAULT
    max_retraces: int = C.ANALYSIS_MAX_RETRACES_DEFAULT
    donation_min_mb: float = C.ANALYSIS_DONATION_MIN_MB_DEFAULT
    dtype_min_elements: int = C.ANALYSIS_DTYPE_MIN_ELEMENTS_DEFAULT
    expected_signature: Optional[str] = (
        C.ANALYSIS_EXPECTED_SIGNATURE_DEFAULT)
    hbm_budget_mb: Optional[float] = C.ANALYSIS_HBM_BUDGET_MB_DEFAULT
    require_overlap: bool = C.ANALYSIS_REQUIRE_OVERLAP_DEFAULT
    overlap_min_hidden_fraction: float = (
        C.ANALYSIS_OVERLAP_MIN_HIDDEN_DEFAULT)
    hw_peak_tflops: float = C.ANALYSIS_HW_PEAK_TFLOPS_DEFAULT
    hw_hbm_gbps: float = C.ANALYSIS_HW_HBM_GBPS_DEFAULT
    hw_ici_gbps: float = C.ANALYSIS_HW_ICI_GBPS_DEFAULT
    # HLO-level SPMD audit (analysis/hlo_audit.py): compile each audited
    # program through XLA's SPMD partitioner and cross-check the jaxpr
    # wire story against the collectives the compiler actually inserted
    hlo_audit: bool = C.ANALYSIS_HLO_AUDIT_DEFAULT
    require_spmd_match: bool = C.ANALYSIS_REQUIRE_SPMD_MATCH_DEFAULT
    spmd_reshard_min_mb: float = C.ANALYSIS_SPMD_RESHARD_MIN_MB_DEFAULT
    spmd_match_tolerance: float = C.ANALYSIS_SPMD_MATCH_TOLERANCE_DEFAULT

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "AnalysisConfig":
        d = d or {}
        budget = get_scalar_param(d, C.ANALYSIS_COMM_BUDGET_MB,
                                  C.ANALYSIS_COMM_BUDGET_MB_DEFAULT)
        hbm_budget = get_scalar_param(d, C.ANALYSIS_HBM_BUDGET_MB,
                                      C.ANALYSIS_HBM_BUDGET_MB_DEFAULT)
        cfg = AnalysisConfig(
            mode=get_scalar_param(d, C.ANALYSIS_MODE,
                                  C.ANALYSIS_MODE_DEFAULT),
            comm_budget_mb=None if budget is None else float(budget),
            max_retraces=int(get_scalar_param(
                d, C.ANALYSIS_MAX_RETRACES,
                C.ANALYSIS_MAX_RETRACES_DEFAULT)),
            donation_min_mb=float(get_scalar_param(
                d, C.ANALYSIS_DONATION_MIN_MB,
                C.ANALYSIS_DONATION_MIN_MB_DEFAULT)),
            dtype_min_elements=int(get_scalar_param(
                d, C.ANALYSIS_DTYPE_MIN_ELEMENTS,
                C.ANALYSIS_DTYPE_MIN_ELEMENTS_DEFAULT)),
            expected_signature=get_scalar_param(
                d, C.ANALYSIS_EXPECTED_SIGNATURE,
                C.ANALYSIS_EXPECTED_SIGNATURE_DEFAULT),
            hbm_budget_mb=None if hbm_budget is None else float(hbm_budget),
            require_overlap=bool(get_scalar_param(
                d, C.ANALYSIS_REQUIRE_OVERLAP,
                C.ANALYSIS_REQUIRE_OVERLAP_DEFAULT)),
            overlap_min_hidden_fraction=float(get_scalar_param(
                d, C.ANALYSIS_OVERLAP_MIN_HIDDEN,
                C.ANALYSIS_OVERLAP_MIN_HIDDEN_DEFAULT)),
            hw_peak_tflops=float(get_scalar_param(
                d, C.ANALYSIS_HW_PEAK_TFLOPS,
                C.ANALYSIS_HW_PEAK_TFLOPS_DEFAULT)),
            hw_hbm_gbps=float(get_scalar_param(
                d, C.ANALYSIS_HW_HBM_GBPS,
                C.ANALYSIS_HW_HBM_GBPS_DEFAULT)),
            hw_ici_gbps=float(get_scalar_param(
                d, C.ANALYSIS_HW_ICI_GBPS,
                C.ANALYSIS_HW_ICI_GBPS_DEFAULT)),
            hlo_audit=bool(get_scalar_param(
                d, C.ANALYSIS_HLO_AUDIT, C.ANALYSIS_HLO_AUDIT_DEFAULT)),
            require_spmd_match=bool(get_scalar_param(
                d, C.ANALYSIS_REQUIRE_SPMD_MATCH,
                C.ANALYSIS_REQUIRE_SPMD_MATCH_DEFAULT)),
            spmd_reshard_min_mb=float(get_scalar_param(
                d, C.ANALYSIS_SPMD_RESHARD_MIN_MB,
                C.ANALYSIS_SPMD_RESHARD_MIN_MB_DEFAULT)),
            spmd_match_tolerance=float(get_scalar_param(
                d, C.ANALYSIS_SPMD_MATCH_TOLERANCE,
                C.ANALYSIS_SPMD_MATCH_TOLERANCE_DEFAULT)),
        )
        if cfg.mode not in C.ANALYSIS_MODES:
            raise DeepSpeedConfigError(
                f"analysis.mode={cfg.mode!r} — supported modes are "
                f"{list(C.ANALYSIS_MODES)}")
        if cfg.comm_budget_mb is not None and cfg.comm_budget_mb < 0:
            raise DeepSpeedConfigError(
                "analysis.comm_budget_mb must be >= 0, got "
                f"{cfg.comm_budget_mb}")
        if cfg.max_retraces < 1:
            raise DeepSpeedConfigError(
                f"analysis.max_retraces must be >= 1, got "
                f"{cfg.max_retraces}")
        if cfg.hbm_budget_mb is not None and cfg.hbm_budget_mb < 0:
            raise DeepSpeedConfigError(
                "analysis.hbm_budget_mb must be >= 0, got "
                f"{cfg.hbm_budget_mb}")
        if not 0.0 < cfg.overlap_min_hidden_fraction <= 1.0:
            raise DeepSpeedConfigError(
                "analysis.overlap_min_hidden_fraction must be in (0, 1], "
                f"got {cfg.overlap_min_hidden_fraction}")
        if cfg.spmd_reshard_min_mb < 0:
            raise DeepSpeedConfigError(
                "analysis.spmd_reshard_min_mb must be >= 0, got "
                f"{cfg.spmd_reshard_min_mb}")
        if cfg.spmd_match_tolerance < 0:
            raise DeepSpeedConfigError(
                "analysis.spmd_match_tolerance must be >= 0, got "
                f"{cfg.spmd_match_tolerance}")
        validate_hw_constants({
            C.ANALYSIS_HW_PEAK_TFLOPS: cfg.hw_peak_tflops,
            C.ANALYSIS_HW_HBM_GBPS: cfg.hw_hbm_gbps,
            C.ANALYSIS_HW_ICI_GBPS: cfg.hw_ici_gbps})
        return cfg

    def hw_overridden(self, hw: Dict[str, Any]) -> "AnalysisConfig":
        """A copy with the canonical hardware constants replaced from a
        validated mapping (the autotuner's calibration-file hook) — keys
        outside C.ANALYSIS_HW_KEYS are rejected by the shared gate."""
        from dataclasses import replace
        valid = validate_hw_constants(hw, context="calibration")
        return replace(
            self,
            hw_peak_tflops=valid.get(C.ANALYSIS_HW_PEAK_TFLOPS,
                                     self.hw_peak_tflops),
            hw_hbm_gbps=valid.get(C.ANALYSIS_HW_HBM_GBPS,
                                  self.hw_hbm_gbps),
            hw_ici_gbps=valid.get(C.ANALYSIS_HW_ICI_GBPS,
                                  self.hw_ici_gbps))


def _as_tuple(val, cast) -> tuple:
    """Coerce a config axis (scalar or list) to a tuple of `cast`."""
    if isinstance(val, (list, tuple)):
        return tuple(cast(v) for v in val)
    return (cast(val),)


@dataclass
class AutotuningConfig:
    """Config-autotuner block (docs/autotuner.md): the offline search
    bounds, fixed knobs, and budget for ``python -m
    deepspeed_tpu.analysis tune``.  Purely a SEARCH description — the
    engine never reads it, so a bench-ready emitted config can carry the
    block that produced it as provenance."""
    chips: Optional[int] = C.AUTOTUNING_CHIPS_DEFAULT
    global_batch: Optional[int] = C.AUTOTUNING_GLOBAL_BATCH_DEFAULT
    top_k: int = C.AUTOTUNING_TOP_K_DEFAULT
    hbm_budget_mb: Optional[float] = C.AUTOTUNING_HBM_BUDGET_MB_DEFAULT
    max_candidates: int = C.AUTOTUNING_MAX_CANDIDATES_DEFAULT
    mesh_model: tuple = C.AUTOTUNING_MESH_MODEL_DEFAULT
    mesh_expert: tuple = C.AUTOTUNING_MESH_EXPERT_DEFAULT
    zero_stages: tuple = C.AUTOTUNING_ZERO_STAGES_DEFAULT
    stage3_variants: tuple = C.AUTOTUNING_STAGE3_VARIANTS_DEFAULT
    prefetch_modes: tuple = C.AUTOTUNING_PREFETCH_MODES_DEFAULT
    stage3_bucket_sizes: tuple = C.AUTOTUNING_STAGE3_BUCKET_SIZES_DEFAULT
    micro_batches: Optional[tuple] = C.AUTOTUNING_MICRO_BATCHES_DEFAULT
    qwz_bits: tuple = C.AUTOTUNING_QWZ_BITS_DEFAULT
    qgz_bits: tuple = C.AUTOTUNING_QGZ_BITS_DEFAULT
    hpz_group_sizes: tuple = C.AUTOTUNING_HPZ_GROUP_SIZES_DEFAULT
    fused: tuple = C.AUTOTUNING_FUSED_DEFAULT
    fused_collective_matmul: tuple = C.AUTOTUNING_FCM_DEFAULT
    onebit: tuple = C.AUTOTUNING_ONEBIT_DEFAULT
    offload: tuple = C.AUTOTUNING_OFFLOAD_TIERS_DEFAULT
    nvme_prefetch_depths: tuple = C.AUTOTUNING_NVME_PREFETCH_DEPTHS_DEFAULT
    opt_pipeline_depths: tuple = C.AUTOTUNING_OPT_PIPELINE_DEPTHS_DEFAULT
    fixed: Optional[Dict[str, Any]] = C.AUTOTUNING_FIXED_DEFAULT
    calibration_file: Optional[str] = C.AUTOTUNING_CALIBRATION_FILE_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "AutotuningConfig":
        d = d or {}
        chips = get_scalar_param(d, C.AUTOTUNING_CHIPS,
                                 C.AUTOTUNING_CHIPS_DEFAULT)
        gb = get_scalar_param(d, C.AUTOTUNING_GLOBAL_BATCH,
                              C.AUTOTUNING_GLOBAL_BATCH_DEFAULT)
        budget = get_scalar_param(d, C.AUTOTUNING_HBM_BUDGET_MB,
                                  C.AUTOTUNING_HBM_BUDGET_MB_DEFAULT)
        micro = d.get(C.AUTOTUNING_MICRO_BATCHES,
                      C.AUTOTUNING_MICRO_BATCHES_DEFAULT)
        cfg = AutotuningConfig(
            chips=None if chips is None else int(chips),
            global_batch=None if gb is None else int(gb),
            top_k=int(get_scalar_param(d, C.AUTOTUNING_TOP_K,
                                       C.AUTOTUNING_TOP_K_DEFAULT)),
            hbm_budget_mb=None if budget is None else float(budget),
            max_candidates=int(get_scalar_param(
                d, C.AUTOTUNING_MAX_CANDIDATES,
                C.AUTOTUNING_MAX_CANDIDATES_DEFAULT)),
            mesh_model=_as_tuple(d.get(
                C.AUTOTUNING_MESH_MODEL,
                C.AUTOTUNING_MESH_MODEL_DEFAULT), int),
            mesh_expert=_as_tuple(d.get(
                C.AUTOTUNING_MESH_EXPERT,
                C.AUTOTUNING_MESH_EXPERT_DEFAULT), int),
            zero_stages=_as_tuple(d.get(
                C.AUTOTUNING_ZERO_STAGES,
                C.AUTOTUNING_ZERO_STAGES_DEFAULT), int),
            stage3_variants=_as_tuple(d.get(
                C.AUTOTUNING_STAGE3_VARIANTS,
                C.AUTOTUNING_STAGE3_VARIANTS_DEFAULT), str),
            prefetch_modes=_as_tuple(d.get(
                C.AUTOTUNING_PREFETCH_MODES,
                C.AUTOTUNING_PREFETCH_MODES_DEFAULT), str),
            stage3_bucket_sizes=_as_tuple(d.get(
                C.AUTOTUNING_STAGE3_BUCKET_SIZES,
                C.AUTOTUNING_STAGE3_BUCKET_SIZES_DEFAULT), int),
            micro_batches=(None if micro is None
                           else _as_tuple(micro, int)),
            qwz_bits=_as_tuple(d.get(C.AUTOTUNING_QWZ_BITS,
                                     C.AUTOTUNING_QWZ_BITS_DEFAULT), int),
            qgz_bits=_as_tuple(d.get(C.AUTOTUNING_QGZ_BITS,
                                     C.AUTOTUNING_QGZ_BITS_DEFAULT), int),
            hpz_group_sizes=_as_tuple(d.get(
                C.AUTOTUNING_HPZ_GROUP_SIZES,
                C.AUTOTUNING_HPZ_GROUP_SIZES_DEFAULT), int),
            fused=_as_tuple(d.get(C.AUTOTUNING_FUSED,
                                  C.AUTOTUNING_FUSED_DEFAULT), bool),
            fused_collective_matmul=_as_tuple(
                d.get(C.AUTOTUNING_FCM, C.AUTOTUNING_FCM_DEFAULT), bool),
            onebit=_as_tuple(
                d.get(C.AUTOTUNING_ONEBIT, C.AUTOTUNING_ONEBIT_DEFAULT),
                bool),
            offload=_as_tuple(d.get(C.AUTOTUNING_OFFLOAD_TIERS,
                                    C.AUTOTUNING_OFFLOAD_TIERS_DEFAULT),
                              str),
            nvme_prefetch_depths=_as_tuple(d.get(
                C.AUTOTUNING_NVME_PREFETCH_DEPTHS,
                C.AUTOTUNING_NVME_PREFETCH_DEPTHS_DEFAULT), int),
            opt_pipeline_depths=_as_tuple(d.get(
                C.AUTOTUNING_OPT_PIPELINE_DEPTHS,
                C.AUTOTUNING_OPT_PIPELINE_DEPTHS_DEFAULT), int),
            fixed=d.get(C.AUTOTUNING_FIXED, C.AUTOTUNING_FIXED_DEFAULT),
            calibration_file=get_scalar_param(
                d, C.AUTOTUNING_CALIBRATION_FILE,
                C.AUTOTUNING_CALIBRATION_FILE_DEFAULT),
        )
        for knob, val, floor in ((C.AUTOTUNING_CHIPS, cfg.chips, 1),
                                 (C.AUTOTUNING_GLOBAL_BATCH,
                                  cfg.global_batch, 1),
                                 (C.AUTOTUNING_TOP_K, cfg.top_k, 1),
                                 (C.AUTOTUNING_MAX_CANDIDATES,
                                  cfg.max_candidates, 1)):
            if val is not None and val < floor:
                raise DeepSpeedConfigError(
                    f"autotuning.{knob} must be >= {floor}, got {val}")
        if cfg.hbm_budget_mb is not None and cfg.hbm_budget_mb <= 0:
            raise DeepSpeedConfigError(
                "autotuning.hbm_budget_mb must be > 0, got "
                f"{cfg.hbm_budget_mb}")
        for knob, vals, floor in (
                (C.AUTOTUNING_MESH_MODEL, cfg.mesh_model, 1),
                (C.AUTOTUNING_MESH_EXPERT, cfg.mesh_expert, 1),
                (C.AUTOTUNING_STAGE3_BUCKET_SIZES,
                 cfg.stage3_bucket_sizes, 1),
                (C.AUTOTUNING_NVME_PREFETCH_DEPTHS,
                 cfg.nvme_prefetch_depths, 1),
                (C.AUTOTUNING_OPT_PIPELINE_DEPTHS,
                 cfg.opt_pipeline_depths, 2),
                (C.AUTOTUNING_HPZ_GROUP_SIZES, cfg.hpz_group_sizes, 0),
                (C.AUTOTUNING_MICRO_BATCHES, cfg.micro_batches or (1,),
                 1)):
            if not vals or any(v < floor for v in vals):
                raise DeepSpeedConfigError(
                    f"autotuning.{knob} must be a non-empty list of "
                    f"ints >= {floor}, got {list(vals)}")
        for knob, vals, allowed in (
                (C.AUTOTUNING_ZERO_STAGES, cfg.zero_stages, (1, 2, 3)),
                (C.AUTOTUNING_STAGE3_VARIANTS, cfg.stage3_variants,
                 C.AUTOTUNING_STAGE3_VARIANTS_ALL),
                (C.AUTOTUNING_PREFETCH_MODES, cfg.prefetch_modes,
                 C.ZERO_OPTIMIZATION_PREFETCH_MODES),
                (C.AUTOTUNING_QWZ_BITS, cfg.qwz_bits, (0, 4, 8)),
                (C.AUTOTUNING_QGZ_BITS, cfg.qgz_bits, (0, 4, 8)),
                (C.AUTOTUNING_OFFLOAD_TIERS, cfg.offload,
                 C.AUTOTUNING_OFFLOAD_TIERS_ALL)):
            if not vals or any(v not in allowed for v in vals):
                raise DeepSpeedConfigError(
                    f"autotuning.{knob} values must be from "
                    f"{list(allowed)}, got {list(vals)}")
        if cfg.fixed is not None and not isinstance(cfg.fixed, dict):
            raise DeepSpeedConfigError(
                "autotuning.fixed must be a config-overlay dict, got "
                f"{type(cfg.fixed).__name__}")
        return cfg


@dataclass
class EigenvalueConfig:
    enabled: bool = C.EIGENVALUE_ENABLED_DEFAULT
    verbose: bool = C.EIGENVALUE_VERBOSE_DEFAULT
    max_iter: int = C.EIGENVALUE_MAX_ITER_DEFAULT
    tol: float = C.EIGENVALUE_TOL_DEFAULT
    stability: float = C.EIGENVALUE_STABILITY_DEFAULT
    gas_boundary_resolution: int = C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT
    layer_name: str = C.EIGENVALUE_LAYER_NAME_DEFAULT
    layer_num: int = C.EIGENVALUE_LAYER_NUM_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "EigenvalueConfig":
        d = d or {}
        return EigenvalueConfig(
            enabled=get_scalar_param(d, C.EIGENVALUE_ENABLED,
                                     C.EIGENVALUE_ENABLED_DEFAULT),
            verbose=get_scalar_param(d, C.EIGENVALUE_VERBOSE,
                                     C.EIGENVALUE_VERBOSE_DEFAULT),
            max_iter=get_scalar_param(d, C.EIGENVALUE_MAX_ITER,
                                      C.EIGENVALUE_MAX_ITER_DEFAULT),
            tol=get_scalar_param(d, C.EIGENVALUE_TOL, C.EIGENVALUE_TOL_DEFAULT),
            stability=get_scalar_param(d, C.EIGENVALUE_STABILITY,
                                       C.EIGENVALUE_STABILITY_DEFAULT),
            gas_boundary_resolution=get_scalar_param(
                d, C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION,
                C.EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT),
            layer_name=get_scalar_param(d, C.EIGENVALUE_LAYER_NAME,
                                        C.EIGENVALUE_LAYER_NAME_DEFAULT),
            layer_num=get_scalar_param(d, C.EIGENVALUE_LAYER_NUM,
                                       C.EIGENVALUE_LAYER_NUM_DEFAULT),
        )


@dataclass
class PLDConfig:
    enabled: bool = C.PLD_ENABLED_DEFAULT
    theta: float = C.PLD_THETA_DEFAULT
    gamma: float = C.PLD_GAMMA_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "PLDConfig":
        d = d or {}
        return PLDConfig(
            enabled=get_scalar_param(d, C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT),
            theta=get_scalar_param(d, C.PLD_THETA, C.PLD_THETA_DEFAULT),
            gamma=get_scalar_param(d, C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT),
        )


@dataclass
class CurriculumConfig:
    enabled: bool = C.CURRICULUM_ENABLED_DEFAULT
    params: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "CurriculumConfig":
        d = d or {}
        return CurriculumConfig(
            enabled=get_scalar_param(d, C.CURRICULUM_ENABLED,
                                     C.CURRICULUM_ENABLED_DEFAULT),
            params=dict(d),
        )


@dataclass
class QuantizeTrainingConfig:
    """MoQ — reference: runtime/config.py get_quantize_enabled + quantize keys."""
    enabled: bool = C.QUANTIZE_TRAINING_ENABLED_DEFAULT
    quantize_verbose: bool = C.QUANTIZE_VERBOSE_DEFAULT
    quantizer_kernel: bool = C.QUANTIZER_KERNEL_DEFAULT
    start_bits: int = C.QUANTIZE_START_BITS_DEFAULT
    target_bits: int = C.QUANTIZE_TARGET_BITS_DEFAULT
    quantize_period: int = C.QUANTIZE_PERIOD_DEFAULT
    schedule_offset: int = C.QUANTIZE_OFFSET_DEFAULT
    quantize_groups: int = C.QUANTIZE_GROUPS_DEFAULT
    quantize_type: int = C.QUANTIZE_TYPE_DEFAULT  # 0 symmetric / 1 asymmetric
    rounding: int = C.QUANTIZE_ROUNDING_DEFAULT  # 0 nearest / 1 stochastic
    fp16_mixed_quantize: bool = C.FP16_MIXED_QUANTIZE_ENABLED_DEFAULT
    quantize_change_ratio: float = C.QUANTIZE_CHANGE_RATIO_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "QuantizeTrainingConfig":
        d = d or {}
        bits = d.get(C.QUANTIZE_BITS, {})
        schedule = d.get(C.QUANTIZE_SCHEDULE, {})
        algo = d.get(C.QUANTIZE_ALGO, {})
        mixed = d.get(C.FP16_MIXED_QUANTIZE, {})
        qtype = algo.get(C.QUANTIZE_TYPE, C.QUANTIZE_SYMMETRIC)
        rounding = algo.get(C.QUANTIZE_ROUNDING, C.NEAREST_ROUNDING)
        return QuantizeTrainingConfig(
            enabled=get_scalar_param(d, C.QUANTIZE_TRAINING_ENABLED,
                                     C.QUANTIZE_TRAINING_ENABLED_DEFAULT),
            quantize_verbose=get_scalar_param(d, C.QUANTIZE_VERBOSE,
                                              C.QUANTIZE_VERBOSE_DEFAULT),
            quantizer_kernel=get_scalar_param(d, C.QUANTIZER_KERNEL,
                                              C.QUANTIZER_KERNEL_DEFAULT),
            start_bits=bits.get(C.START_BITS, C.QUANTIZE_START_BITS_DEFAULT),
            target_bits=bits.get(C.TARGET_BITS, C.QUANTIZE_TARGET_BITS_DEFAULT),
            quantize_period=schedule.get(C.QUANTIZE_PERIOD,
                                         C.QUANTIZE_PERIOD_DEFAULT),
            schedule_offset=schedule.get(C.SCHEDULE_OFFSET,
                                         C.QUANTIZE_OFFSET_DEFAULT),
            quantize_groups=get_scalar_param(d, C.QUANTIZE_GROUPS,
                                             C.QUANTIZE_GROUPS_DEFAULT),
            quantize_type=(0 if qtype == C.QUANTIZE_SYMMETRIC else 1),
            rounding=(1 if rounding == C.STOCHASTIC_ROUNDING else 0),
            fp16_mixed_quantize=mixed.get(C.FP16_MIXED_QUANTIZE_ENABLED,
                                          C.FP16_MIXED_QUANTIZE_ENABLED_DEFAULT),
            quantize_change_ratio=mixed.get(C.QUANTIZE_CHANGE_RATIO,
                                            C.QUANTIZE_CHANGE_RATIO_DEFAULT),
        )


@dataclass
class CheckpointConfig:
    tag_validation: str = C.CHECKPOINT_TAG_VALIDATION_DEFAULT
    # None = auto: sharded whenever multi-process (a consolidated save
    # would gather non-addressable arrays); True/False forces the layout.
    sharded: Optional[bool] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "CheckpointConfig":
        d = d or {}
        mode = get_scalar_param(d, C.CHECKPOINT_TAG_VALIDATION,
                                C.CHECKPOINT_TAG_VALIDATION_DEFAULT).upper()
        if mode not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(
                "Checkpoint config {} only supports {}".format(
                    C.CHECKPOINT_TAG_VALIDATION, C.CHECKPOINT_TAG_VALIDATION_MODES))
        return CheckpointConfig(tag_validation=mode,
                                sharded=d.get("sharded"))


@dataclass
class PreemptionConfig:
    """SIGTERM/SIGINT → graceful stop at the next step boundary with an
    emergency checkpoint (TPU-native: preemptible pods)."""
    enabled: bool = C.PREEMPTION_ENABLED_DEFAULT
    signals: tuple = C.PREEMPTION_SIGNALS_DEFAULT
    emergency_tag_prefix: str = C.PREEMPTION_EMERGENCY_TAG_PREFIX_DEFAULT
    save_dir: Optional[str] = C.PREEMPTION_SAVE_DIR_DEFAULT
    reraise: bool = C.PREEMPTION_RERAISE_DEFAULT
    grace_s: float = C.PREEMPTION_GRACE_S_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "PreemptionConfig":
        d = d or {}
        signals = d.get(C.PREEMPTION_SIGNALS, C.PREEMPTION_SIGNALS_DEFAULT)
        if isinstance(signals, str):
            signals = [signals]  # a bare "SIGTERM" is not 7 signals
        import signal as _signal
        for name in signals:
            # membership in Signals, not hasattr: the signal module also
            # exposes non-signal attributes (SIG_DFL, SIG_IGN, ...) that
            # would install a handler on the wrong signal
            if not (isinstance(name, str)
                    and name in _signal.Signals.__members__):
                raise DeepSpeedConfigError(
                    f"resilience.preemption.signals entry {name!r} is not "
                    "a signal name (expected e.g. \"SIGTERM\", \"SIGINT\")")
        grace = float(get_scalar_param(d, C.PREEMPTION_GRACE_S,
                                       C.PREEMPTION_GRACE_S_DEFAULT))
        if grace < 0:
            raise DeepSpeedConfigError(
                f"resilience.preemption.grace_s must be >= 0, got {grace}")
        enabled = get_scalar_param(d, C.PREEMPTION_ENABLED,
                                   C.PREEMPTION_ENABLED_DEFAULT)
        if enabled and grace > 0:
            # The grace-deadline forced save runs on a single host's
            # timer thread; on a multi-process run it would write a
            # one-host checkpoint while the other hosts are mid-step —
            # never collective-consistent.  The config used to accept
            # this silently; fail loudly at parse time instead.
            try:
                import jax
                nproc = jax.process_count()
            except Exception:  # noqa: BLE001 — no jax at parse time
                nproc = 1
            if nproc > 1:
                raise DeepSpeedConfigError(
                    "resilience.preemption.grace_s forced saves are "
                    "single-process only: the grace deadline fires on a "
                    "per-host timer thread and cannot coordinate a "
                    f"collective save across {nproc} processes. Set "
                    "grace_s to 0 on multihost and rely on the "
                    "step-boundary emergency save (the default "
                    "preemption path), which stops every host at the "
                    "same completed step.")
        return PreemptionConfig(
            enabled=enabled,
            signals=tuple(signals),
            emergency_tag_prefix=get_scalar_param(
                d, C.PREEMPTION_EMERGENCY_TAG_PREFIX,
                C.PREEMPTION_EMERGENCY_TAG_PREFIX_DEFAULT),
            save_dir=get_scalar_param(d, C.PREEMPTION_SAVE_DIR,
                                      C.PREEMPTION_SAVE_DIR_DEFAULT),
            reraise=get_scalar_param(d, C.PREEMPTION_RERAISE,
                                     C.PREEMPTION_RERAISE_DEFAULT),
            grace_s=grace,
        )


@dataclass
class SentinelConfig:
    """On-device training-health monitor: EWMA of loss + global grad-norm,
    NaN/Inf and k-sigma spike detection — catches bf16 blow-ups the fp16
    overflow skip never sees."""
    enabled: bool = C.SENTINEL_ENABLED_DEFAULT
    ewma_alpha: float = C.SENTINEL_EWMA_ALPHA_DEFAULT
    k_sigma: float = C.SENTINEL_K_SIGMA_DEFAULT
    warmup_steps: int = C.SENTINEL_WARMUP_STEPS_DEFAULT
    policy: str = C.SENTINEL_POLICY_DEFAULT
    anomaly_budget: int = C.SENTINEL_ANOMALY_BUDGET_DEFAULT
    monitor_grad_norm: bool = C.SENTINEL_MONITOR_GRAD_NORM_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "SentinelConfig":
        d = d or {}
        cfg = SentinelConfig(
            enabled=get_scalar_param(d, C.SENTINEL_ENABLED,
                                     C.SENTINEL_ENABLED_DEFAULT),
            ewma_alpha=float(get_scalar_param(
                d, C.SENTINEL_EWMA_ALPHA, C.SENTINEL_EWMA_ALPHA_DEFAULT)),
            k_sigma=float(get_scalar_param(d, C.SENTINEL_K_SIGMA,
                                           C.SENTINEL_K_SIGMA_DEFAULT)),
            warmup_steps=int(get_scalar_param(
                d, C.SENTINEL_WARMUP_STEPS, C.SENTINEL_WARMUP_STEPS_DEFAULT)),
            policy=get_scalar_param(d, C.SENTINEL_POLICY,
                                    C.SENTINEL_POLICY_DEFAULT),
            anomaly_budget=int(get_scalar_param(
                d, C.SENTINEL_ANOMALY_BUDGET,
                C.SENTINEL_ANOMALY_BUDGET_DEFAULT)),
            monitor_grad_norm=get_scalar_param(
                d, C.SENTINEL_MONITOR_GRAD_NORM,
                C.SENTINEL_MONITOR_GRAD_NORM_DEFAULT),
        )
        if cfg.policy not in C.SENTINEL_POLICIES:
            raise DeepSpeedConfigError(
                f"resilience.sentinel.policy={cfg.policy!r} — supported "
                f"policies are {list(C.SENTINEL_POLICIES)}")
        if not 0.0 < cfg.ewma_alpha <= 1.0:
            raise DeepSpeedConfigError(
                "resilience.sentinel.ewma_alpha must be in (0, 1], got "
                f"{cfg.ewma_alpha}")
        if cfg.anomaly_budget < 1:
            raise DeepSpeedConfigError(
                "resilience.sentinel.anomaly_budget must be >= 1, got "
                f"{cfg.anomaly_budget}")
        return cfg


@dataclass
class ChaosConfig:
    """Deterministic fault-injection plane (resilience/chaos.py) — off
    by default.  ``faults`` is a tuple of fault-spec dicts, each
    validated at parse time against the injection-point catalog: a
    typo'd point or a kind that makes no sense at that surface fails
    here, not by silently never firing."""
    enabled: bool = C.CHAOS_ENABLED_DEFAULT
    seed: int = C.CHAOS_SEED_DEFAULT
    faults: tuple = C.CHAOS_FAULTS_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ChaosConfig":
        d = d or {}
        faults = d.get(C.CHAOS_FAULTS, C.CHAOS_FAULTS_DEFAULT)
        if isinstance(faults, dict):
            faults = [faults]
        try:
            faults = tuple(faults)
        except TypeError:
            raise DeepSpeedConfigError(
                "resilience.chaos.faults must be a list of fault specs "
                f"(dicts), got {faults!r}")
        cfg = ChaosConfig(
            enabled=bool(get_scalar_param(d, C.CHAOS_ENABLED,
                                          C.CHAOS_ENABLED_DEFAULT)),
            seed=int(get_scalar_param(d, C.CHAOS_SEED,
                                      C.CHAOS_SEED_DEFAULT)),
            faults=faults,
        )
        # validate every spec against the catalog (lazy import: the
        # chaos module is only needed when the block is present)
        from .runtime.resilience.chaos import ChaosFault
        for spec in cfg.faults:
            if not isinstance(spec, dict):
                raise DeepSpeedConfigError(
                    "resilience.chaos.faults entries must be dicts "
                    f"(point/kind/trigger), got {spec!r}")
            try:
                ChaosFault.from_dict(spec)
            except (ValueError, TypeError) as e:
                raise DeepSpeedConfigError(
                    f"resilience.chaos.faults entry {spec!r} is "
                    f"invalid: {e}")
        return cfg


@dataclass
class ResilienceConfig:
    """Fault-tolerance block (all off by default — the engine is
    byte-identical to the pre-resilience behavior when disabled, except
    the always-on atomic `latest` rename bugfix)."""
    enabled: bool = C.RESILIENCE_ENABLED_DEFAULT
    atomic_checkpoints: bool = C.RESILIENCE_ATOMIC_CHECKPOINTS_DEFAULT
    verify_on_load: bool = C.RESILIENCE_VERIFY_ON_LOAD_DEFAULT
    max_fallback_tags: int = C.RESILIENCE_MAX_FALLBACK_TAGS_DEFAULT
    keep_last_n: int = C.RESILIENCE_KEEP_LAST_N_DEFAULT
    keep_every: int = C.RESILIENCE_KEEP_EVERY_DEFAULT
    io_retries: int = C.RESILIENCE_IO_RETRIES_DEFAULT
    io_backoff_seconds: float = C.RESILIENCE_IO_BACKOFF_SECONDS_DEFAULT
    retry_jitter: float = C.RESILIENCE_RETRY_JITTER_DEFAULT
    retry_seed: int = C.RESILIENCE_RETRY_SEED_DEFAULT
    retry_max_backoff_seconds: float = (
        C.RESILIENCE_RETRY_MAX_BACKOFF_SECONDS_DEFAULT)
    verify_lockstep_on_resume: bool = (
        C.RESILIENCE_VERIFY_LOCKSTEP_ON_RESUME_DEFAULT)
    preemption: PreemptionConfig = field(default_factory=PreemptionConfig)
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)

    @property
    def atomic_enabled(self) -> bool:
        return self.enabled and self.atomic_checkpoints

    @property
    def verify_enabled(self) -> bool:
        return self.enabled and self.verify_on_load

    @property
    def gc_enabled(self) -> bool:
        return self.enabled and self.keep_last_n > 0

    @property
    def lockstep_resume_enabled(self) -> bool:
        return self.enabled and self.verify_lockstep_on_resume

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ResilienceConfig":
        d = d or {}
        cfg = ResilienceConfig(
            enabled=get_scalar_param(d, C.RESILIENCE_ENABLED,
                                     C.RESILIENCE_ENABLED_DEFAULT),
            atomic_checkpoints=get_scalar_param(
                d, C.RESILIENCE_ATOMIC_CHECKPOINTS,
                C.RESILIENCE_ATOMIC_CHECKPOINTS_DEFAULT),
            verify_on_load=get_scalar_param(
                d, C.RESILIENCE_VERIFY_ON_LOAD,
                C.RESILIENCE_VERIFY_ON_LOAD_DEFAULT),
            max_fallback_tags=int(get_scalar_param(
                d, C.RESILIENCE_MAX_FALLBACK_TAGS,
                C.RESILIENCE_MAX_FALLBACK_TAGS_DEFAULT)),
            keep_last_n=int(get_scalar_param(
                d, C.RESILIENCE_KEEP_LAST_N,
                C.RESILIENCE_KEEP_LAST_N_DEFAULT)),
            keep_every=int(get_scalar_param(
                d, C.RESILIENCE_KEEP_EVERY, C.RESILIENCE_KEEP_EVERY_DEFAULT)),
            io_retries=int(get_scalar_param(
                d, C.RESILIENCE_IO_RETRIES, C.RESILIENCE_IO_RETRIES_DEFAULT)),
            io_backoff_seconds=float(get_scalar_param(
                d, C.RESILIENCE_IO_BACKOFF_SECONDS,
                C.RESILIENCE_IO_BACKOFF_SECONDS_DEFAULT)),
            retry_jitter=float(get_scalar_param(
                d, C.RESILIENCE_RETRY_JITTER,
                C.RESILIENCE_RETRY_JITTER_DEFAULT)),
            retry_seed=int(get_scalar_param(
                d, C.RESILIENCE_RETRY_SEED,
                C.RESILIENCE_RETRY_SEED_DEFAULT)),
            retry_max_backoff_seconds=float(get_scalar_param(
                d, C.RESILIENCE_RETRY_MAX_BACKOFF_SECONDS,
                C.RESILIENCE_RETRY_MAX_BACKOFF_SECONDS_DEFAULT)),
            verify_lockstep_on_resume=get_scalar_param(
                d, C.RESILIENCE_VERIFY_LOCKSTEP_ON_RESUME,
                C.RESILIENCE_VERIFY_LOCKSTEP_ON_RESUME_DEFAULT),
            preemption=PreemptionConfig.from_dict(
                d.get(C.RESILIENCE_PREEMPTION)),
            sentinel=SentinelConfig.from_dict(d.get(C.RESILIENCE_SENTINEL)),
            chaos=ChaosConfig.from_dict(d.get(C.RESILIENCE_CHAOS)),
        )
        if cfg.keep_last_n < 0 or cfg.keep_every < 0:
            raise DeepSpeedConfigError(
                "resilience.keep_last_n / keep_every must be >= 0, got "
                f"{cfg.keep_last_n} / {cfg.keep_every}")
        if cfg.io_retries < 0:
            raise DeepSpeedConfigError(
                f"resilience.io_retries must be >= 0, got {cfg.io_retries}")
        if cfg.retry_jitter < 0:
            raise DeepSpeedConfigError(
                f"resilience.retry_jitter must be >= 0, got "
                f"{cfg.retry_jitter}")
        if cfg.retry_max_backoff_seconds <= 0:
            raise DeepSpeedConfigError(
                "resilience.retry_max_backoff_seconds must be > 0, got "
                f"{cfg.retry_max_backoff_seconds}")
        return cfg

    def build_retry_policy(self, sleep=None):
        """The shared RetryPolicy for NVMe swap I/O and checkpoint
        staging, or None when resilience is off / retries are 0."""
        if not self.enabled or self.io_retries <= 0:
            return None
        from .runtime.resilience.retry import RetryPolicy
        return RetryPolicy(retries=self.io_retries,
                           backoff_s=self.io_backoff_seconds,
                           max_backoff_s=self.retry_max_backoff_seconds,
                           jitter=self.retry_jitter,
                           seed=self.retry_seed, sleep=sleep)


@dataclass
class MeshConfig:
    """TPU-native: named-axis device mesh shape.  -1 means "fill with the
    remaining devices" (like a reshape wildcard); exactly one axis may be -1.
    Axis order is ICI-aware: data outermost, model innermost so tensor-parallel
    collectives ride the fastest links."""
    data: int = -1
    model: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "MeshConfig":
        d = d or {}
        return MeshConfig(
            data=int(d.get(C.MESH_DATA_AXIS, -1)),
            model=int(d.get(C.MESH_MODEL_AXIS, 1)),
            pipe=int(d.get(C.MESH_PIPE_AXIS, 1)),
            expert=int(d.get(C.MESH_EXPERT_AXIS, 1)),
            seq=int(d.get(C.MESH_SEQ_AXIS, 1)),
        )


@dataclass
class SequenceParallelConfig:
    """TPU-native long-context layer (ring attention / Ulysses)."""
    mode: str = C.SEQUENCE_PARALLEL_MODE_DEFAULT
    size: int = C.SEQUENCE_PARALLEL_SIZE_DEFAULT

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "SequenceParallelConfig":
        d = d or {}
        return SequenceParallelConfig(
            mode=get_scalar_param(d, C.SEQUENCE_PARALLEL_MODE,
                                  C.SEQUENCE_PARALLEL_MODE_DEFAULT),
            size=int(get_scalar_param(d, C.SEQUENCE_PARALLEL_SIZE,
                                      C.SEQUENCE_PARALLEL_SIZE_DEFAULT)),
        )


class DeepSpeedConfig:
    """Parse a DeepSpeed-style JSON config (path or dict) into typed configs.

    Reference semantics: deepspeed/runtime/config.py:682.  `world_size` here is
    the data-parallel world size used in the batch triple inference
    (reference: config.py:869 train_batch = micro_batch × gas × dp_world).
    """

    def __init__(self, config, world_size: int = 1, elastic_resolver=None):
        self._param_dict = load_config_dict(config)
        self.world_size = world_size

        # Elasticity may rewrite the batch keys before inference
        # (reference: runtime/config.py:707-757).
        self.elasticity_enabled = False
        elastic_dict = self._param_dict.get(C.ELASTICITY)
        if elastic_dict and get_scalar_param(elastic_dict, C.ENABLED,
                                             C.ENABLED_DEFAULT):
            self.elasticity_enabled = True
            from .elasticity import apply_elasticity
            apply_elasticity(self._param_dict, world_size)

        self._initialize_params(self._param_dict)
        self._batch_assertion()

    # ------------------------------------------------------------------ #
    def _initialize_params(self, pd: Dict[str, Any]) -> None:
        self.train_batch_size = get_scalar_param(pd, C.TRAIN_BATCH_SIZE,
                                                 C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get_scalar_param(
            pd, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get_scalar_param(
            pd, C.GRADIENT_ACCUMULATION_STEPS,
            C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self._infer_batch_params()

        self.steps_per_print = get_scalar_param(pd, C.STEPS_PER_PRINT,
                                                C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(pd, C.DUMP_STATE,
                                           C.DUMP_STATE_DEFAULT)
        self.prng_impl = get_scalar_param(pd, C.PRNG_IMPL,
                                          C.PRNG_IMPL_DEFAULT)
        self.gradient_clipping = get_scalar_param(pd, C.GRADIENT_CLIPPING,
                                                  C.GRADIENT_CLIPPING_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(
            pd, C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.prescale_gradients = get_scalar_param(pd, C.PRESCALE_GRADIENTS,
                                                   C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(
            pd, C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.fp32_allreduce = get_scalar_param(pd, C.FP32_ALLREDUCE,
                                               C.FP32_ALLREDUCE_DEFAULT)
        self.disable_allgather = get_scalar_param(pd, C.DISABLE_ALLGATHER,
                                                  C.DISABLE_ALLGATHER_DEFAULT)
        self.wall_clock_breakdown = get_scalar_param(
            pd, C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(pd, C.MEMORY_BREAKDOWN,
                                                 C.MEMORY_BREAKDOWN_DEFAULT)
        self.zero_allow_untested_optimizer = get_scalar_param(
            pd, C.ZERO_ALLOW_UNTESTED_OPTIMIZER,
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)

        opt = pd.get(C.OPTIMIZER)
        self.optimizer_name = (opt.get(C.TYPE).lower()
                               if opt and opt.get(C.TYPE) else None)
        self.optimizer_params = opt.get(C.OPTIMIZER_PARAMS, {}) if opt else {}
        self.optimizer_legacy_fusion = (opt.get(C.LEGACY_FUSION,
                                                C.LEGACY_FUSION_DEFAULT)
                                        if opt else C.LEGACY_FUSION_DEFAULT)

        sched = pd.get(C.SCHEDULER)
        self.scheduler_name = sched.get(C.TYPE) if sched else None
        self.scheduler_params = sched.get(C.SCHEDULER_PARAMS, {}) if sched else {}

        self.fp16 = FP16Config.from_dict(pd.get(C.FP16))
        self.bf16 = BF16Config.from_dict(pd.get(C.BF16))
        self.amp = pd.get(C.AMP, {})
        self.amp_enabled = self.amp.get(C.AMP_ENABLED, C.AMP_ENABLED_DEFAULT)

        self.zero_config = ZeroConfig.from_dict(pd.get(C.ZERO_OPTIMIZATION))
        self.aio_config = AioConfig.from_dict(pd.get(C.AIO))
        self.activation_checkpointing_config = (
            ActivationCheckpointingConfig.from_dict(
                pd.get(C.ACTIVATION_CHECKPOINTING)))
        self.flops_profiler_config = FlopsProfilerConfig.from_dict(
            pd.get(C.FLOPS_PROFILER))
        self.tensorboard_config = TensorboardConfig.from_dict(
            pd.get(C.TENSORBOARD))
        self.fused_step_config = FusedStepConfig.from_dict(
            pd.get(C.FUSED_STEP))
        self.analysis_config = AnalysisConfig.from_dict(pd.get(C.ANALYSIS))
        self.autotuning_config = AutotuningConfig.from_dict(
            pd.get(C.AUTOTUNING))
        self.monitor_config = MonitorConfig.from_dict(pd.get(C.MONITOR))
        self.eigenvalue_config = EigenvalueConfig.from_dict(pd.get(C.EIGENVALUE))
        self.pld_config = PLDConfig.from_dict(pd.get(C.PROGRESSIVE_LAYER_DROP))
        self.curriculum_config = CurriculumConfig.from_dict(
            pd.get(C.CURRICULUM_LEARNING))
        self.quantize_training_config = QuantizeTrainingConfig.from_dict(
            pd.get(C.QUANTIZE_TRAINING))
        self.checkpoint_config = CheckpointConfig.from_dict(pd.get(C.CHECKPOINT))
        self.resilience_config = ResilienceConfig.from_dict(
            pd.get(C.RESILIENCE))
        self.sparse_attention = pd.get(C.SPARSE_ATTENTION)
        self.mesh_config = MeshConfig.from_dict(pd.get(C.MESH))
        self.sequence_parallel_config = SequenceParallelConfig.from_dict(
            pd.get(C.SEQUENCE_PARALLEL))
        self.pipeline = pd.get(C.PIPELINE, {})
        self.vocabulary_size = get_scalar_param(pd, C.VOCABULARY_SIZE,
                                                C.VOCABULARY_SIZE_DEFAULT)
        self._validate_onebit()

    # ------------------------------------------------------------------ #
    def _validate_onebit(self) -> None:
        """1-bit optimizer tier cross-field validation (docs/onebit.md).

        Two layers: the onebit optimizers' params block is validated
        whenever a OneBitAdam/OneBitLamb optimizer is named, and the
        wire tier (`zero_optimization.low_bandwidth.onebit`) is checked
        against every feature it cannot compose with — each conflict is
        a loud DeepSpeedConfigError naming the offending knob, never a
        silent numerics-only fallback."""
        # spellings owned by runtime/optimizers.py (lowered there too)
        onebit_names = ("onebitadam", "onebitlamb")
        is_onebit_opt = self.optimizer_name in onebit_names
        if is_onebit_opt:
            freeze = self.optimizer_params.get("freeze_step", 100)
            if not isinstance(freeze, int) or freeze < 1:
                raise DeepSpeedConfigError(
                    f"optimizer.params.freeze_step must be an int >= 1 "
                    f"for {self.optimizer_name}, got {freeze!r}")
            betas = self.optimizer_params.get("betas", (0.9, 0.999))
            if (len(tuple(betas)) != 2
                    or not all(0.0 <= float(b) < 1.0 for b in betas)):
                raise DeepSpeedConfigError(
                    f"optimizer.params.betas for {self.optimizer_name} "
                    f"must be two floats in [0, 1), got {betas!r}")
        lb = self.zero_config.low_bandwidth
        if not lb.onebit:
            return
        prefix = (f"zero_optimization.low_bandwidth."
                  f"{C.LOW_BANDWIDTH_ONEBIT}=true conflicts with ")
        if not is_onebit_opt:
            raise DeepSpeedConfigError(
                f"zero_optimization.low_bandwidth.{C.LOW_BANDWIDTH_ONEBIT}"
                f"=true requires a OneBitAdam or OneBitLamb optimizer "
                f"(the wire format is the optimizer's error-feedback "
                f"momentum), got optimizer.type="
                f"{self.optimizer_name!r}")
        if self.zero_config.stage >= 3:
            raise DeepSpeedConfigError(
                prefix + f"zero_optimization.stage="
                f"{self.zero_config.stage}: the ZeRO-3 streaming path "
                "gathers params/scatters grads inside the step program "
                "and has no whole-gradient allreduce to replace — use "
                "stage <= 2")
        if self.zero_config.offload_optimizer is not None:
            raise DeepSpeedConfigError(
                prefix + "zero_optimization.offload_optimizer: the "
                "compressed phase keeps momentum (and its error "
                "feedback) device-resident and replicated; an offloaded "
                "optimizer state cannot host the packed momentum sync")
        if self.sparse_gradients_enabled:
            raise DeepSpeedConfigError(
                prefix + "sparse_gradients: both features rewrite the "
                "data-parallel gradient reduction and cannot stack")
        if self.gradient_clipping and self.gradient_clipping > 0:
            raise DeepSpeedConfigError(
                prefix + f"gradient_clipping={self.gradient_clipping}: "
                "global-norm clipping needs the dense gradient on every "
                "worker before the optimizer sees it, which is exactly "
                "the allreduce the 1-bit tier removes")

    # ------------------------------------------------------------------ #
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def quantize_training_enabled(self) -> bool:
        return self.quantize_training_config.enabled

    @property
    def pld_enabled(self) -> bool:
        return self.pld_config.enabled

    @property
    def curriculum_enabled(self) -> bool:
        return self.curriculum_config.enabled

    # ------------------------------------------------------------------ #
    def _infer_batch_params(self) -> None:
        """Resolve (train_batch, micro_batch, gas) given any subset
        (reference: config.py:874-924)."""
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        ws = self.world_size

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * ws)
        elif train is not None and gas is not None:
            micro = train // (ws * gas)
        elif micro is not None and gas is not None:
            train = micro * gas * ws
        elif train is not None:
            gas = 1
            micro = train // ws
        elif micro is not None:
            train = micro * ws
            gas = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    def _batch_assertion(self) -> None:
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        ws = self.world_size
        if train <= 0:
            raise DeepSpeedConfigError(
                f"Train batch size: {train} has to be greater than 0")
        if micro <= 0:
            raise DeepSpeedConfigError(
                f"Micro batch size per gpu: {micro} has to be greater than 0")
        if gas <= 0:
            raise DeepSpeedConfigError(
                f"Gradient accumulation steps: {gas} has to be greater than 0")
        if train != micro * gas * ws:
            raise DeepSpeedConfigError(
                f"Check batch related parameters. train_batch_size is not equal"
                f" to micro_batch_per_gpu * gradient_acc_step * world_size "
                f"{train} != {micro} * {gas} * {ws}")

    def print_config(self, logger_fn=print) -> None:
        logger_fn("DeepSpeedConfig:")
        for k, v in sorted(self.__dict__.items()):
            if k == "_param_dict":
                continue
            logger_fn("  {:40s} {}".format(k, v))
