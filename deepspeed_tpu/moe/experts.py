"""Expert modules (reference: deepspeed/moe/experts.py:9 — class Experts).

The reference deep-copies the user's expert module `num_local_experts` times
per rank and tags params `allreduce=False, group_name` so the engine reduces
them over the expert-data group only.  Under SPMD the stacked [E, ...] expert
params carry a leading "expert" PartitionSpec instead (each expert-parallel
shard holds E/ep_size experts), and the gradient reduction scope follows from
the sharding — no tags needed.
"""

import numpy as np

import jax
import jax.numpy as jnp


class ExpertMLP:
    """Default expert: 2-layer GeLU MLP, the standard GShard/transformer
    expert shape (plays the role of the user-supplied expert module in
    reference moe/layer.py:18)."""

    def __init__(self, d_model: int, d_ff: int = None):
        self.d_model = d_model
        self.d_ff = d_ff or 4 * d_model

    def init_params(self, rng, x):
        k1, k2 = jax.random.split(rng)
        s1 = 1.0 / np.sqrt(self.d_model)
        s2 = 1.0 / np.sqrt(self.d_ff)
        return {
            "wi": jax.random.normal(k1, (self.d_model, self.d_ff),
                                    jnp.float32) * s1,
            "bi": jnp.zeros((self.d_ff,), jnp.float32),
            "wo": jax.random.normal(k2, (self.d_ff, self.d_model),
                                    jnp.float32) * s2,
            "bo": jnp.zeros((self.d_model,), jnp.float32),
        }

    def apply(self, params, x, rng=None):
        h = jax.nn.gelu(x @ params["wi"].astype(x.dtype) +
                        params["bi"].astype(x.dtype))
        return h @ params["wo"].astype(x.dtype) + params["bo"].astype(x.dtype)

    def apply_tp(self, params, x, tp_axis: str):
        """Megatron-split expert for MANUAL tensor parallelism: params are
        LOCAL shards (wi/bi column-split, wo row-split on the d_ff dim —
        tp_partition_specs) and the output partials are psum'd explicitly
        (tp_psum is branch-safe inside the gated executor's lax.cond,
        unlike GSPMD-placed collectives).  The replicated output bias is
        added AFTER the psum, once."""
        from ..ops.tp_collectives import tp_psum

        h = jax.nn.gelu(x @ params["wi"].astype(x.dtype) +
                        params["bi"].astype(x.dtype))
        out = tp_psum(h @ params["wo"].astype(x.dtype), tp_axis)
        return out + params["bo"].astype(x.dtype)

    @staticmethod
    def tp_partition_specs(model_axis: str):
        """Per-leaf specs over the model axis for the manual-TP shards
        (leading dims — expert stack — handled by the caller)."""
        from jax.sharding import PartitionSpec as P
        return {"wi": P(None, model_axis), "bi": P(model_axis),
                "wo": P(model_axis, None), "bo": P()}
