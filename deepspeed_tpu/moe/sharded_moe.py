"""GShard-style gated mixture-of-experts with expert parallelism.

Reference: deepspeed/moe/sharded_moe.py — top1gating:99, top2gating:173,
TopKGate:247 (fp32 gate, capacity factor, jitter/RSample noise, l_aux
load-balance loss), MOELayer:312 (einsum dispatch → all-to-all → experts →
all-to-all → einsum combine), _AllToAll:77.

TPU-native design: the reference wraps torch all_to_all_single in an autograd
Function; here dispatch/combine are einsums whose operands carry sharding
constraints — tokens sharded over the data axes, the dispatched [E, C, d]
buffer and stacked expert params sharded over the "expert" mesh axis.  XLA
lowers the resharding between those layouts to the same all-to-all over ICI,
and reverses it in the backward pass automatically.  Gating math stays fp32
exactly like the reference's fp32 gate (sharded_moe.py:247).

Capacity is static (token count is known at trace time), keeping shapes
XLA-friendly; tokens over capacity are dropped by the position mask exactly
like the reference's `locations < capacity` test.
"""

import contextlib
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.mesh import EXPERT_AXIS

JITTER_EPS = 1e-2

# entropy clip floor: softmax outputs are strictly positive, but fp32
# underflow on very peaked routers would otherwise produce 0 * -inf
_ENTROPY_EPS = 1e-20


class RoutingStats(NamedTuple):
    """Per-gate routing telemetry, a pure pytree of device scalars/[E]
    vectors so it sums across layers (``emit_routing_stats`` inside one
    traced forward), microbatches (the fused gas scan), and optimizer
    steps (the engine's device-resident accumulator) with plain
    ``jax.tree.map(jnp.add)`` — and is host-read ONLY at monitor
    flush-window boundaries (docs/telemetry.md "MoE routing
    observability").  Everything is POST-capacity-mask reality: a token
    the ``locations < capacity`` test dropped never counts as routed.

    This is the in-program half of the expert-popularity prefetch
    oracle ROADMAP item 6's NVMe expert streaming keys on
    (monitor/moe.py turns the accumulated ``expert_counts`` into the
    ``ExpertPopularitySnapshot`` the streamer consumes)."""
    expert_counts: jnp.ndarray    # f32[E] routed token-slots per expert
    overflow_counts: jnp.ndarray  # f32[E] capacity-dropped slots per
    #                               WANTED expert (where demand exceeded
    #                               the slot budget)
    tokens: jnp.ndarray           # f32[] token-slots wanted (k x tokens,
    #                               used_token-masked)
    dropped: jnp.ndarray          # f32[] token-slots dropped (= tokens
    #                               - routed)
    entropy: jnp.ndarray          # f32[] sum over tokens of router
    #                               softmax entropy (nats)
    confidence: jnp.ndarray       # f32[] sum over tokens of raw top-k
    #                               gate probability mass
    gate_tokens: jnp.ndarray      # f32[] tokens contributing entropy/
    #                               confidence
    l_aux: jnp.ndarray            # f32[] summed load-balance loss
    layers: jnp.ndarray           # f32[] gate invocations folded in


def _routing_stats(gates, wanted_counts, routed_counts, topk_mass,
                   l_aux, used_token=None) -> RoutingStats:
    """Assemble one gate invocation's RoutingStats.

    ``wanted_counts``/``routed_counts``: [E] pre-/post-capacity-mask
    token-slot counts; ``topk_mass``: [S] raw gate probability mass on
    the selected (pre-capacity) experts; ``used_token``: optional [S]
    validity mask (padding tokens contribute nothing)."""
    ent = -jnp.sum(gates * jnp.log(jnp.clip(gates, _ENTROPY_EPS, 1.0)),
                   axis=-1)
    if used_token is not None:
        u = used_token.astype(jnp.float32)
        ent = ent * u
        topk_mass = topk_mass * u
        gate_tokens = u.sum()
    else:
        gate_tokens = jnp.float32(gates.shape[0])
    wanted = wanted_counts.astype(jnp.float32)
    routed = routed_counts.astype(jnp.float32)
    return RoutingStats(
        expert_counts=routed,
        overflow_counts=wanted - routed,
        tokens=wanted.sum(),
        dropped=(wanted - routed).sum(),
        entropy=ent.sum().astype(jnp.float32),
        confidence=topk_mass.sum().astype(jnp.float32),
        gate_tokens=gate_tokens,
        l_aux=l_aux.astype(jnp.float32),
        layers=jnp.float32(1.0))


# ---- routing-stats collection tap ------------------------------------ #
# The model's loss function returns a scalar, so routing stats leave the
# traced program through a trace-time side channel: the engine installs
# a tap around the model apply INSIDE its loss_fn (same trace scope),
# MOELayer.apply emits each gate's RoutingStats into it, and the engine
# returns the summed pytree as a grad aux output.  The stack is plain
# trace-time Python state (tracing is single-threaded per process);
# nothing here runs per step at execution time.
_ACTIVE_TAPS: List[list] = []


@contextlib.contextmanager
def collect_routing_stats():
    """Context manager: collect every RoutingStats emitted while tracing
    the enclosed computation.  MUST wrap code in the SAME trace scope as
    the emissions — stats emitted inside an inner lax.scan body cannot
    escape to an outer tap (see sum_routing_stats)."""
    tap: list = []
    _ACTIVE_TAPS.append(tap)
    try:
        yield tap
    finally:
        _ACTIVE_TAPS.pop()


def emit_routing_stats(stats: RoutingStats) -> None:
    """Offer one gate invocation's stats to the innermost active tap
    (no-op when no tap is installed — gating stays side-effect-free
    outside a collecting engine)."""
    if _ACTIVE_TAPS:
        _ACTIVE_TAPS[-1].append(stats)


_SUM_WARNED = set()


def sum_routing_stats(entries: list) -> Optional[RoutingStats]:
    """Sum a tap's collected stats into one RoutingStats (None when
    nothing was emitted — a dense model under a collecting engine).

    Two degradations, both loud-once instead of crashing the trace:
    mixed expert counts across layers cannot share one [E] accumulator
    (entries whose num_experts differs from the first gate's are dropped
    entirely — the simplest honest contract); and stats emitted inside
    an INNER scan body (e.g. a
    hypothetical MoE layer under the ZeRO-3 streamed layer scan) are
    body-local tracers that cannot escape to this scope — they surface
    as escaped-tracer errors here and are dropped with a warning naming
    the fix (thread the layer out of the streamed scan or disable
    monitor.moe)."""
    if not entries:
        return None
    from ..utils.logging import logger
    e0 = entries[0].expert_counts.shape[0]
    keep, skipped = [], 0
    for s in entries:
        if s.expert_counts.shape[0] != e0:
            skipped += 1
            continue
        keep.append(s)
    if skipped and "mixed_E" not in _SUM_WARNED:
        _SUM_WARNED.add("mixed_E")
        logger.warning(
            f"routing stats: {skipped} gate(s) with num_experts != {e0} "
            "dropped from the accumulator — per-layer expert counts must "
            "match to share one [E] histogram (first layer wins)")
    total = keep[0]
    try:
        for s in keep[1:]:
            total = jax.tree.map(jnp.add, total, s)
        # touch the result so an escaped tracer surfaces HERE (a single
        # leaked entry raises on first use, which may be the return)
        total = jax.tree.map(lambda x: x + 0.0, total)
    except Exception as e:  # noqa: BLE001 — escaped inner-scan tracers
        if "escaped" not in _SUM_WARNED:
            _SUM_WARNED.add("escaped")
            logger.warning(
                "routing stats: emitted stats could not escape their "
                f"trace scope ({type(e).__name__}) — MoE layers inside "
                "an inner scan (e.g. the ZeRO-3 streamed layer scan) "
                "cannot feed the outer accumulator; their stats are "
                "dropped for this program")
        return None
    return total


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """Static per-expert slot count (reference: sharded_moe.py:90)."""
    cap = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, num_classes):
    return jax.nn.one_hot(idx.astype(jnp.int32), num_classes,
                          dtype=jnp.float32)


def gumbel_rsample(rng, shape):
    """Gumbel noise for the RSample noisy gate policy
    (reference: sharded_moe.py:57)."""
    return jax.random.gumbel(rng, shape, dtype=jnp.float32)


def top1gating_compact(
        logits: jnp.ndarray, capacity_factor: float = 1.0,
        min_capacity: int = 4, used_token: Optional[jnp.ndarray] = None,
        noisy_gate_policy: Optional[str] = None,
        rng: Optional[jax.Array] = None):
    """Top-1 gating, compact form — the single source of routing truth.

    Returns (l_aux, capacity, experts [S,1], slots [S,1], weights [S,1]
    fp32 with zeros for dropped tokens, exp_counts [E], stats
    RoutingStats).  ``exp_counts`` is POST-capacity-mask: a token the
    ``locations < capacity`` test dropped is not routed anywhere, so it
    must not count (the pre-capacity demand survives in
    ``stats.overflow_counts``).  The [S,E,C] mask form (top1gating)
    expands from this; the scatter dispatcher consumes it directly with
    O(S·d) memory instead of O(S·E·C).
    """
    num_tokens, num_experts = logits.shape
    capacity = _capacity(num_tokens, num_experts, capacity_factor,
                         min_capacity)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    select_logits = logits
    if noisy_gate_policy == "RSample":
        assert rng is not None, "RSample needs an rng"
        select_logits = logits + gumbel_rsample(rng, logits.shape)
    indices1 = jnp.argmax(select_logits, axis=-1)
    mask1 = _one_hot(indices1, num_experts)
    if used_token is not None:  # mask out padding tokens
        mask1 = mask1 * used_token.astype(mask1.dtype)[:, None]

    wanted_counts = mask1.sum(axis=0)  # pre-capacity demand per expert
    topk_mass = (gates * mask1).sum(axis=-1)

    # load-balance loss (reference: sharded_moe.py:133): fraction of router
    # probability × fraction of tokens per expert
    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    l_aux = jnp.sum(me * ce) * num_experts

    # position of each token within its expert's queue; drop over-capacity
    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    mask1 = mask1 * (locations1 < capacity)
    locations1_s = (locations1 * mask1).sum(axis=-1)
    gates1_s = (gates * mask1).sum(axis=-1)  # 0 for dropped tokens

    exp_counts = mask1.sum(axis=0)
    stats = _routing_stats(gates, wanted_counts, exp_counts, topk_mass,
                           l_aux, used_token)
    return (l_aux, capacity, indices1[:, None],
            locations1_s.astype(jnp.int32)[:, None], gates1_s[:, None],
            exp_counts, stats)


def _expand_compact(capacity, num_experts, experts, slots, weights):
    """Compact routing -> legacy (combine [S,E,C], dispatch [S,E,C])."""
    combine = jnp.zeros((experts.shape[0], num_experts, capacity),
                        jnp.float32)
    for i in range(experts.shape[1]):
        combine = combine + (weights[:, i, None, None] *
                             _one_hot(experts[:, i], num_experts)[:, :, None] *
                             _one_hot(slots[:, i], capacity)[:, None, :])
    return combine, combine > 0


def top1gating(logits: jnp.ndarray, capacity_factor: float = 1.0,
               min_capacity: int = 4, used_token: Optional[jnp.ndarray] = None,
               noisy_gate_policy: Optional[str] = None,
               rng: Optional[jax.Array] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 gating (reference: sharded_moe.py:99).

    Returns (l_aux, combine_weights [S,E,C], dispatch_mask [S,E,C] bool,
    exp_counts [E] post-capacity, stats RoutingStats).
    """
    (l_aux, capacity, experts, slots, weights, exp_counts,
     stats) = top1gating_compact(
        logits, capacity_factor, min_capacity, used_token,
        noisy_gate_policy, rng)
    combine, dispatch = _expand_compact(capacity, logits.shape[1],
                                        experts, slots, weights)
    return l_aux, combine, dispatch, exp_counts, stats


def top2gating_compact(
        logits: jnp.ndarray, capacity_factor: float = 1.0,
        min_capacity: int = 4, rng: Optional[jax.Array] = None,
        noisy_gate_policy: Optional[str] = None):
    """Top-2 gating, compact form (see top1gating_compact).

    Returns (l_aux, capacity, experts [S,2], slots [S,2], weights [S,2]
    fp32 normalized over the kept choices with zeros for dropped slots,
    exp_counts [E] post-capacity, stats RoutingStats).  Top-2 doubles
    the slot budget (2 * capacity_factor), so stats.overflow_counts
    reflects demand against the DOUBLED capacity.
    """
    num_tokens, num_experts = logits.shape
    capacity = _capacity(num_tokens, num_experts, 2 * capacity_factor,
                         min_capacity)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    indices1 = jnp.argmax(logits, axis=-1)
    mask1 = _one_hot(indices1, num_experts)

    select2 = logits.astype(jnp.float32)
    if noisy_gate_policy == "RSample":
        assert rng is not None, "RSample needs an rng"
    if rng is not None:
        # Reference noises the second choice unconditionally
        # (sharded_moe.py:180 logits_w_noise); here that needs a key.
        select2 = select2 + gumbel_rsample(rng, logits.shape)
    select2 = select2 + mask1 * -1e9  # exclude the first expert
    indices2 = jnp.argmax(select2, axis=-1)
    mask2 = _one_hot(indices2, num_experts)

    wanted_counts = (mask1 + mask2).sum(axis=0)
    topk_mass = (gates * (mask1 + mask2)).sum(axis=-1)

    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    l_aux = jnp.sum(me * ce) * num_experts

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # second-choice tokens queue behind all first-choice tokens
    locations2 = (jnp.cumsum(mask2, axis=0) - mask2 +
                  mask1.sum(axis=0, keepdims=True))
    mask1 = mask1 * (locations1 < capacity)
    mask2 = mask2 * (locations2 < capacity)
    locations1_s = (locations1 * mask1).sum(axis=-1)
    locations2_s = (locations2 * mask2).sum(axis=-1)

    gates1_s = (gates * mask1).sum(axis=-1)
    gates2_s = (gates * mask2).sum(axis=-1)
    denom = jnp.clip(gates1_s + gates2_s, 1e-9, None)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    experts = jnp.stack([indices1, indices2], axis=1)
    slots = jnp.stack([locations1_s, locations2_s], axis=1).astype(jnp.int32)
    weights = jnp.stack([gates1_s, gates2_s], axis=1)
    exp_counts = (mask1 + mask2).sum(axis=0)
    stats = _routing_stats(gates, wanted_counts, exp_counts, topk_mass,
                           l_aux)
    return l_aux, capacity, experts, slots, weights, exp_counts, stats


def top2gating(logits: jnp.ndarray, capacity_factor: float = 1.0,
               min_capacity: int = 4, rng: Optional[jax.Array] = None,
               noisy_gate_policy: Optional[str] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-2 gating (reference: sharded_moe.py:173).

    Second expert chosen with the top-1 expert masked out; gumbel noise is
    added to the selection when an rng is available (the reference noises
    unconditionally via torch's implicit global RNG; JAX needs an explicit
    key, so pass rng= for reference-parity stochastic second choice).
    Top-2 capacity doubles the slot budget like the reference (2 * S / E).
    Returns (l_aux, combine, dispatch, exp_counts [E] post-capacity,
    stats RoutingStats).
    """
    (l_aux, capacity, experts, slots, weights, exp_counts,
     stats) = top2gating_compact(
        logits, capacity_factor, min_capacity, rng, noisy_gate_policy)
    combine, dispatch = _expand_compact(capacity, logits.shape[1],
                                        experts, slots, weights)
    return l_aux, combine, dispatch, exp_counts, stats


class TopKGate:
    """Router with fp32 gate weights (reference: sharded_moe.py:247)."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None):
        assert k in (1, 2), "Only top-1 and top-2 gating are supported"
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy

    def init_params(self, rng):
        scale = 1.0 / np.sqrt(self.model_dim)
        return {"wg": (jax.random.normal(
            rng, (self.model_dim, self.num_experts), jnp.float32) * scale)}

    def apply(self, params, x, rng=None, train=True):
        """x: [S, d] tokens → (l_aux, combine, dispatch, exp_counts,
        stats) — the legacy [S,E,C] form, expanded from the compact
        routing so the einsum and scatter dispatch paths can never route
        differently."""
        l_aux, capacity, experts, slots, weights, exp_counts, stats = \
            self.apply_compact(params, x, rng=rng, train=train)
        combine, dispatch = _expand_compact(capacity, self.num_experts,
                                            experts, slots, weights)
        return l_aux, combine, dispatch, exp_counts, stats

    def apply_compact(self, params, x, rng=None, train=True):
        """x: [S, d] → (l_aux, capacity, experts [S,k], slots [S,k],
        weights [S,k], exp_counts, stats) — no [S,E,C]
        materialization."""
        x32 = x.astype(jnp.float32)
        if train and self.noisy_gate_policy == "Jitter":
            if rng is None:
                raise ValueError(
                    "noisy_gate_policy='Jitter' needs an rng during training "
                    "— pass rng= to MoE.apply (RSample enforces the same)")
            rng, sub = jax.random.split(rng)
            x32 = x32 * jax.random.uniform(
                sub, x32.shape, jnp.float32, 1.0 - JITTER_EPS, 1.0 + JITTER_EPS)
        logits = x32 @ params["wg"]
        cf = self.capacity_factor if train else self.eval_capacity_factor
        policy = self.noisy_gate_policy if train else None
        rng = rng if train else None
        if self.k == 1:
            return top1gating_compact(logits, cf, self.min_capacity,
                                      noisy_gate_policy=policy, rng=rng)
        return top2gating_compact(logits, cf, self.min_capacity, rng=rng,
                                  noisy_gate_policy=policy)


class MOELayer:
    """GShard MoE layer (reference: sharded_moe.py:312).

    expert: an object with init_params(rng, x) / apply(params, x, rng=None)
    (the PipeLayer protocol) applied per-expert to [C, d] slot buffers.
    """

    def __init__(self, gate: TopKGate, expert, num_local_experts_total: int,
                 dispatch_impl: str = "scatter"):
        if dispatch_impl not in ("scatter", "einsum"):
            raise ValueError(f"dispatch_impl must be 'scatter' or 'einsum', "
                             f"got {dispatch_impl!r}")
        self.gate = gate
        self.expert = expert
        self.num_experts = num_local_experts_total
        self.dispatch_impl = dispatch_impl

    def init_params(self, rng, x):
        gate_rng, exp_rng = jax.random.split(rng)
        token_shape = x.reshape(-1, x.shape[-1])[:1]
        expert_params = []
        for i in range(self.num_experts):
            expert_params.append(self.expert.init_params(
                jax.random.fold_in(exp_rng, i), token_shape))
        stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves),
                               *expert_params)
        return {"gate": self.gate.init_params(gate_rng), "experts": stacked}

    def param_partition_specs(self, params=None):
        from jax.sharding import PartitionSpec
        if params is None:
            # Zero-arg protocol (engine/pipe discovery): recover the param
            # tree structure abstractly — no arrays are materialized.
            params = jax.eval_shape(
                self.init_params, jax.random.PRNGKey(0),
                jax.ShapeDtypeStruct((1, self.gate.model_dim), jnp.float32))
        return {
            "gate": jax.tree.map(lambda _: None, params["gate"]),
            "experts": jax.tree.map(lambda _: PartitionSpec(EXPERT_AXIS),
                                    params["experts"]),
        }

    def apply(self, params, x, rng=None, train=True, tp_axis=None):
        """x: [..., d] → (y [..., d], l_aux, exp_counts).

        Two dispatch implementations (both lower the token→slot resharding
        to the reference's two all-to-alls, sharded_moe.py:358,366):

        - "scatter" (default): tokens scatter-add into their [E, C, d]
          slots by flat slot id and gather back weighted — O(S·k·d)
          working set, the TPU-idiomatic form at scale;
        - "einsum": the GShard-paper [S, E, C] mask einsums — O(S·E·C)
          memory, kept as the parity reference.

        tp_axis: MANUAL tensor parallelism over the expert FFNs — the
        gate runs replicated (wg replicated → identical logits → every
        model peer routes identically), dispatch/combine stay local, and
        each expert computes with local Megatron shards + explicit psum
        (ExpertMLP.apply_tp).  This is how MoE composes with the gated
        pipeline executor's manual model axis (reference: the expert FFN
        position of sharded_moe.py:312 under Megatron mp).
        """
        if self.dispatch_impl == "scatter":
            return self._apply_scatter(params, x, rng=rng, train=train,
                                       tp_axis=tp_axis)
        return self._apply_einsum(params, x, rng=rng, train=train,
                                  tp_axis=tp_axis)

    def _expert_apply(self, params, dispatched, tp_axis):
        if tp_axis is not None:
            return jax.vmap(
                lambda p, slot: self.expert.apply_tp(p, slot, tp_axis))(
                    params, dispatched)
        return jax.vmap(
            lambda p, slot: self.expert.apply(p, slot, rng=None))(
                params, dispatched)

    def _apply_scatter(self, params, x, rng=None, train=True, tp_axis=None):
        orig_shape = x.shape
        d_model = x.shape[-1]
        tokens = x.reshape(-1, d_model)
        s = tokens.shape[0]

        l_aux, capacity, experts, slots, weights, exp_counts, stats = \
            self.gate.apply_compact(params["gate"], tokens, rng=rng,
                                    train=train)
        emit_routing_stats(stats)
        k = experts.shape[1]
        e_total = self.num_experts
        valid = weights > 0.0
        # flat slot id; dropped tokens land in a dump row that is sliced off
        flat_slot = jnp.where(valid, experts * capacity + slots,
                              e_total * capacity)

        # manual TP: the "f" operator on the EXPERT-dispatch input only
        # (identity fwd / psum bwd) — each peer's expert shard produces a
        # PARTIAL token cotangent that the psum restores to full for the
        # replicated upstream.  The gate above reads the raw tokens: its
        # computation is replicated per peer and its cotangent is already
        # full — routing it through the psum would overcount it by tp.
        tokens_e = tokens
        if tp_axis is not None:
            from ..ops.tp_collectives import tp_fcast
            tokens_e = tp_fcast(tokens, tp_axis)

        # dispatch (all-to-all #1): scatter-add — valid (expert, slot)
        # pairs are unique by construction, so add == set for them
        flat = jnp.zeros((e_total * capacity + 1, d_model), x.dtype)
        contrib = jnp.where(valid[..., None],
                            jnp.broadcast_to(tokens_e[:, None, :],
                                             (s, k, d_model)), 0)
        flat = flat.at[flat_slot.reshape(-1)].add(
            contrib.reshape(-1, d_model).astype(x.dtype))
        dispatched = _constrain_expert(
            flat[:e_total * capacity].reshape(e_total, capacity, d_model))

        expert_out = self._expert_apply(params["experts"], dispatched,
                                        tp_axis)
        expert_out = _constrain_expert(expert_out)

        # combine (all-to-all #2): gather each token's k slot outputs and
        # weight them; the dump row contributes zero weight
        flat_out = jnp.concatenate(
            [expert_out.reshape(e_total * capacity, d_model),
             jnp.zeros((1, d_model), expert_out.dtype)], axis=0)
        gathered = flat_out[flat_slot]                  # [S, k, d]
        out = (weights[..., None].astype(gathered.dtype) * gathered).sum(
            axis=1)
        return out.astype(x.dtype).reshape(orig_shape), l_aux, exp_counts

    def _apply_einsum(self, params, x, rng=None, train=True, tp_axis=None):
        orig_shape = x.shape
        d_model = x.shape[-1]
        tokens = x.reshape(-1, d_model)

        l_aux, combine, dispatch, exp_counts, stats = self.gate.apply(
            params["gate"], tokens, rng=rng, train=train)
        emit_routing_stats(stats)

        tokens_e = tokens
        if tp_axis is not None:  # see _apply_scatter: expert input only
            from ..ops.tp_collectives import tp_fcast
            tokens_e = tp_fcast(tokens, tp_axis)

        # dispatch: [S, E, C] × [S, d] → [E, C, d]   (all-to-all #1)
        dispatched = jnp.einsum("sec,sd->ecd",
                                dispatch.astype(x.dtype), tokens_e)
        dispatched = _constrain_expert(dispatched)

        expert_out = self._expert_apply(params["experts"], dispatched,
                                        tp_axis)
        expert_out = _constrain_expert(expert_out)

        # combine: [S, E, C] × [E, C, d] → [S, d]    (all-to-all #2)
        out = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), expert_out)
        return out.reshape(orig_shape), l_aux, exp_counts


def _constrain_expert(x):
    """Pin the [E, C, d] buffer's leading dim to the expert axis when a mesh
    is live (no-op otherwise, so gating stays unit-testable without a mesh)."""
    from ..parallel import mesh as mesh_mod
    ctx = mesh_mod.get_mesh_context(required=False)
    if ctx is None or ctx.axis_size(EXPERT_AXIS) == 1:
        return x
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(ctx.mesh, PartitionSpec(EXPERT_AXIS)))
