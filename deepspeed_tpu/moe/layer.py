"""MoE layer wrapper — the user-facing API (reference: deepspeed/moe/layer.py:18).

Reference semantics: `MoE(hidden_size, expert, num_experts, k, capacity_factor,
eval_capacity_factor, min_capacity, noisy_gate_policy)` creates the expert
parallel group (ep_size bounded by world size) and wraps gate + experts;
forward returns (output, l_aux, exp_counts).

TPU-native: ep_size is the mesh's "expert" axis; num_experts must divide over
it.  The layer conforms to the PipeLayer protocol (init_params/apply) so it
drops into plain models, pipeline bodies, and the engine's partition-spec
discovery alike.
"""

from typing import Optional

from ..parallel import mesh as mesh_mod
from ..utils.logging import log_dist
from .experts import ExpertMLP
from .sharded_moe import MOELayer, TopKGate


class MoE:
    """Gated mixture-of-experts layer (reference: moe/layer.py:18)."""

    def __init__(self, hidden_size: int, expert=None, num_experts: int = 1,
                 k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None,
                 expert_ff_size: Optional[int] = None,
                 dispatch_impl: str = "scatter"):
        if noisy_gate_policy is not None and noisy_gate_policy not in (
                "None", "Jitter", "RSample"):
            raise ValueError(
                f"Unsupported noisy_gate_policy {noisy_gate_policy!r}")
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        # ep_size comes from the mesh, which usually doesn't exist yet at
        # model-construction time (the engine creates it from the config in
        # deepspeed_tpu.initialize).  Validate lazily on first use; an early
        # check here still fires for callers that initialized the mesh first.
        self.ep_size = 1
        self.num_local_experts = num_experts
        self._mesh_checked = False
        if mesh_mod.get_mesh_context(required=False) is not None:
            self._check_mesh()

        expert = expert if expert is not None else ExpertMLP(
            hidden_size, expert_ff_size)
        gate = TopKGate(hidden_size, num_experts, k, capacity_factor,
                        eval_capacity_factor, min_capacity,
                        None if noisy_gate_policy == "None"
                        else noisy_gate_policy)
        self.deepspeed_moe = MOELayer(gate, expert, num_experts,
                                      dispatch_impl=dispatch_impl)

    def _check_mesh(self):
        ctx = mesh_mod.get_mesh_context(required=False)
        if ctx is None:
            return  # no mesh yet; stay at the ep_size=1 defaults
        ep_size = ctx.expert_parallel_world_size
        if self.num_experts % max(1, ep_size) != 0:
            # actionable: name BOTH sides of the mismatch and the
            # nearest expert counts that would divide this mesh (the
            # discovery-time hook in engine.py runs this after mesh
            # creation, so the operator sees it at engine build)
            below = (self.num_experts // ep_size) * ep_size
            above = below + ep_size
            nearest = [n for n in (below, above) if n >= ep_size]
            raise ValueError(
                f"MoE: num_experts={self.num_experts} does not divide "
                f"the mesh's expert axis (expert={ep_size}) — each of "
                f"the {ep_size} expert-parallel shards must own the "
                f"same number of experts. Nearest valid num_experts: "
                f"{' or '.join(str(n) for n in nearest)}; or resize "
                f"the mesh's expert axis to a divisor of "
                f"{self.num_experts}")
        self.ep_size = ep_size
        self.num_local_experts = self.num_experts // max(1, ep_size)
        if not self._mesh_checked:
            log_dist(
                f"MoE: num_experts={self.num_experts} ep_size={ep_size} "
                f"local_experts={self.num_local_experts}", ranks=[0])
        self._mesh_checked = True

    # -- PipeLayer protocol ------------------------------------------- #
    def init_params(self, rng, x):
        return self.deepspeed_moe.init_params(rng, x)

    def param_partition_specs(self, params=None):
        self._check_mesh()
        return self.deepspeed_moe.param_partition_specs(params)

    def apply(self, params, x, rng=None, train=True, tp_axis=None):
        """Returns (output, l_aux, exp_counts) like the reference forward
        (moe/layer.py:42).  tp_axis: manual tensor parallelism — expert
        params are local Megatron shards and expert outputs are psum'd
        explicitly (ExpertMLP.apply_tp); gating stays replicated."""
        self._check_mesh()
        return self.deepspeed_moe.apply(params, x, rng=rng, train=train,
                                        tp_axis=tp_axis)
