from .experts import ExpertMLP
from .layer import MoE
from .sharded_moe import (MOELayer, RoutingStats, TopKGate,
                          collect_routing_stats, emit_routing_stats,
                          sum_routing_stats, top1gating, top2gating)
