"""Model surgery: convert an HF torch model into our TPU model + params.

Reference: deepspeed/module_inject/replace_module.py
(replace_transformer_layer:89 swapping layers for fused kernels,
ReplaceWithTensorSlicing:11 sharding weights across mp ranks, generic
replace_module:383).

TPU recasting: `replace_transformer_layer(hf_model)` walks the source
module tree, matches each transformer layer against `replace_policies`,
extracts weights via the policy, stacks them along a leading layer axis
(the lax.scan layout of models/gpt2.py), and returns
(tpu_model, params).  Tensor-parallel slicing needs no per-rank loops:
the returned model's `param_partition_specs()` + `jax.device_put` with a
NamedSharding ARE the ReplaceWithTensorSlicing step — GSPMD splits qkv/
inter column-wise and ow/output row-wise exactly like the reference's
mp_replace.qkv_copy/copy.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import log_dist
from .replace_policy import replace_policies


def _find_layers(module, policy_cls) -> List[Any]:
    """Depth-first collect source layers matching the policy (the reference
    walks named_modules the same way, replace_module.py:383)."""
    found = []
    for child in module.children():
        if policy_cls.matches(child):
            found.append(child)
        else:
            found.extend(_find_layers(child, policy_cls))
    return found


def _detect_policy(model, policy: Optional[type]) -> Tuple[type, List[Any]]:
    if policy is not None:
        layers = _find_layers(model, policy)
        if not layers:
            raise ValueError(
                f"no layers matching {policy.__name__} in {type(model)}")
        return policy, layers
    for cand in replace_policies:
        layers = _find_layers(model, cand)
        if layers:
            return cand, layers
    raise ValueError(
        f"no injection policy matches {type(model).__name__} — pass "
        f"injection_policy= explicitly (reference: replace_module.py:89)")


def _stack_layers(layer_param_dicts: List[Dict[str, np.ndarray]]):
    keys = layer_param_dicts[0].keys()
    return {k: np.stack([d[k] for d in layer_param_dicts]) for k in keys}


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def replace_transformer_layer(model, policy: Optional[type] = None,
                              bf16: bool = True):
    """HF torch model -> (tpu_model, params).

    Supports GPT2LMHeadModel/GPT2Model (-> models.gpt2.GPT2Model) and
    BertModel/BertForMaskedLM (-> models.bert.BertModel).  Returns our
    model object (whose param_partition_specs drives TP sharding) and the
    stacked param pytree.
    """
    policy_cls, layers = _detect_policy(model, policy)
    stacked = _stack_layers(
        [policy_cls(layer).layer_params() for layer in layers])
    name = type(model).__name__

    if not policy_cls.scale_attention:
        # Our flash attention always scales scores by 1/sqrt(head_dim);
        # GPT-Neo's source attention does not.  Folding sqrt(head_dim) into
        # the q projection makes the net scaling identity.
        heads = getattr(model.config, "num_heads",
                        getattr(model.config, "n_head", 1))
        q_cols = stacked["attn_qkvw"].shape[2] // 3  # [L, H, 3H] layout
        root_d = float(np.sqrt(q_cols // heads))
        stacked["attn_qkvw"][:, :, :q_cols] *= root_d
        stacked["attn_qkvb"][:, :q_cols] *= root_d

    if policy_cls.causal:  # GPT-2 / GPT-Neo family
        from ..models.gpt2 import GPT2Config, GPT2Model
        base = getattr(model, "transformer", model)
        wte, wpe = _np(base.wte.weight), _np(base.wpe.weight)
        h = wte.shape[1]
        cfg_src = model.config
        cfg = GPT2Config(
            vocab_size=wte.shape[0], n_positions=wpe.shape[0],
            hidden_size=h, num_layers=len(layers),
            num_heads=next(
                (int(getattr(cfg_src, a)) for a in
                 ("n_head", "num_heads", "num_attention_heads")
                 if getattr(cfg_src, a, None) is not None), 12),
            intermediate_size=stacked["inter_w"].shape[-1],
            layer_norm_eps=getattr(cfg_src, "layer_norm_epsilon", 1e-5),
            embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0,
            bf16=bf16, tie_word_embeddings=True)
        params = {
            "wte": wte, "wpe": wpe, "h": stacked,
            "ln_f": {"w": _np(base.ln_f.weight), "b": _np(base.ln_f.bias)},
        }
        tpu_model = GPT2Model(cfg)
    else:  # BERT family
        from ..models.bert import BertConfig, BertModel
        base = getattr(model, "bert", model)
        emb = base.embeddings
        wte = _np(emb.word_embeddings.weight)
        wpe = _np(emb.position_embeddings.weight)
        tte = _np(emb.token_type_embeddings.weight)
        cfg_src = model.config
        cfg = BertConfig(
            vocab_size=wte.shape[0], hidden_size=wte.shape[1],
            num_layers=len(layers),
            num_heads=getattr(cfg_src, "num_attention_heads", 12),
            intermediate_size=stacked["inter_w"].shape[-1],
            max_position_embeddings=wpe.shape[0],
            type_vocab_size=tte.shape[0],
            layer_norm_eps=getattr(cfg_src, "layer_norm_eps", 1e-12),
            hidden_act=getattr(cfg_src, "hidden_act", "gelu"),
            embd_dropout=0.0, attn_dropout=0.0, hidden_dropout=0.0,
            bf16=bf16, pre_layer_norm=policy_cls.pre_layer_norm)
        params = {
            "wte": wte, "wpe": wpe, "tte": tte,
            "emb_ln": {"w": _np(emb.LayerNorm.weight),
                       "b": _np(emb.LayerNorm.bias)},
            "h": stacked,
        }
        tpu_model = BertModel(cfg)
    log_dist(
        f"module_inject: {name} -> {type(tpu_model).__name__} "
        f"({len(layers)} layers, policy={policy_cls.__name__})", ranks=[0])
    return tpu_model, params
