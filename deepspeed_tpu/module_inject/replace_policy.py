"""Injection policies — where q,k,v,o,mlp weights live in a source model.

Reference: deepspeed/module_inject/replace_policy.py (HFBertLayerPolicy:43,
HFGPT2LayerPolicy:195, HFGPTNEOLayerPolicy:102, MegatronLayerPolicy:146,
replace_policies:234).  A policy reads one source transformer layer and
returns the weight set; replace_module.py assembles the TPU param trees.

TPU recasting: instead of swapping nn.Modules in place, a policy converts
an HF *torch* model's weights into the stacked pytree layout that
GPT2Model/BertModel/DeepSpeedTransformerInference consume — model surgery
as a checkpoint transform, after which everything is jit/GSPMD-native.

Weight orientation note: our layers compute x @ W with W [in, out].
HF GPT-2 uses Conv1D ([in, out] already); BERT/GPT-Neo use nn.Linear
([out, in]) and need a transpose — the same special-casing the reference
does per policy.
"""

from typing import Dict, List

import numpy as np


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


class InjectBasePolicy:
    """One source layer -> our DeepSpeedTransformerLayer param dict."""

    # subclasses set these
    pre_layer_norm: bool = True
    causal: bool = False
    scale_attention: bool = True

    def __init__(self, layer):
        self.layer = layer

    def layer_params(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    @classmethod
    def matches(cls, module) -> bool:
        return type(module).__name__ in cls.LAYER_CLASS_NAMES


class HFGPT2LayerPolicy(InjectBasePolicy):
    """HF transformers GPT2Block (reference: replace_policy.py:195)."""

    LAYER_CLASS_NAMES = ("GPT2Block", "Block")
    pre_layer_norm = True
    causal = True

    def layer_params(self):
        layer = self.layer
        return {
            "attn_qkvw": _np(layer.attn.c_attn.weight),          # [H, 3H] Conv1D
            "attn_qkvb": _np(layer.attn.c_attn.bias),
            "attn_ow": _np(layer.attn.c_proj.weight),            # [H, H]
            "attn_ob": _np(layer.attn.c_proj.bias),
            "norm_w": _np(layer.ln_1.weight),                    # pre-attn LN
            "norm_b": _np(layer.ln_1.bias),
            "attn_nw": _np(layer.ln_2.weight),                   # pre-MLP LN
            "attn_nb": _np(layer.ln_2.bias),
            "inter_w": _np(layer.mlp.c_fc.weight),               # [H, 4H]
            "inter_b": _np(layer.mlp.c_fc.bias),
            "output_w": _np(layer.mlp.c_proj.weight),            # [4H, H]
            "output_b": _np(layer.mlp.c_proj.bias),
        }


class HFBertLayerPolicy(InjectBasePolicy):
    """HF transformers BertLayer (reference: replace_policy.py:43)."""

    LAYER_CLASS_NAMES = ("BertLayer", "RobertaLayer")
    pre_layer_norm = False
    causal = False

    def layer_params(self):
        layer = self.layer
        att = layer.attention.self
        qkvw = np.concatenate(
            [_np(att.query.weight).T, _np(att.key.weight).T,
             _np(att.value.weight).T], axis=1)               # -> [H, 3H]
        qkvb = np.concatenate(
            [_np(att.query.bias), _np(att.key.bias), _np(att.value.bias)])
        return {
            "attn_qkvw": qkvw,
            "attn_qkvb": qkvb,
            "attn_ow": _np(layer.attention.output.dense.weight).T,
            "attn_ob": _np(layer.attention.output.dense.bias),
            "attn_nw": _np(layer.attention.output.LayerNorm.weight),  # post-attn
            "attn_nb": _np(layer.attention.output.LayerNorm.bias),
            "inter_w": _np(layer.intermediate.dense.weight).T,
            "inter_b": _np(layer.intermediate.dense.bias),
            "output_w": _np(layer.output.dense.weight).T,
            "output_b": _np(layer.output.dense.bias),
            "norm_w": _np(layer.output.LayerNorm.weight),            # post-MLP
            "norm_b": _np(layer.output.LayerNorm.bias),
        }


class HFGPTNEOLayerPolicy(InjectBasePolicy):
    """HF transformers GPTNeoBlock (reference: replace_policy.py:102)."""

    LAYER_CLASS_NAMES = ("GPTNeoBlock",)
    pre_layer_norm = True
    causal = True
    # GPT-Neo attention applies NO 1/sqrt(d) scaling; replace_module folds
    # the compensating sqrt(d) into the q projection.
    scale_attention = False

    def layer_params(self):
        layer = self.layer
        att = layer.attn.attention
        h = _np(att.q_proj.weight).shape[1]
        qkvw = np.concatenate(
            [_np(att.q_proj.weight).T, _np(att.k_proj.weight).T,
             _np(att.v_proj.weight).T], axis=1)
        zeros = np.zeros((h,), np.float32)

        def bias_of(lin):
            return _np(lin.bias) if lin.bias is not None else zeros
        return {
            "attn_qkvw": qkvw,
            "attn_qkvb": np.concatenate(
                [bias_of(att.q_proj), bias_of(att.k_proj),
                 bias_of(att.v_proj)]),
            "attn_ow": _np(att.out_proj.weight).T,
            "attn_ob": bias_of(att.out_proj),
            "norm_w": _np(layer.ln_1.weight), "norm_b": _np(layer.ln_1.bias),
            "attn_nw": _np(layer.ln_2.weight), "attn_nb": _np(layer.ln_2.bias),
            "inter_w": _np(layer.mlp.c_fc.weight).T,
            "inter_b": _np(layer.mlp.c_fc.bias),
            "output_w": _np(layer.mlp.c_proj.weight).T,
            "output_b": _np(layer.mlp.c_proj.bias),
        }


class MegatronLayerPolicy(InjectBasePolicy):
    """Megatron-LM ParallelTransformerLayer (reference:
    replace_policy.py:146).

    Megatron layers are pre-LN causal blocks whose projections are
    nn.Linear ([out, in] — transposed into our [in, out] x@W layout).
    Old Megatron exposes the attention block as ``.attention`` and stores
    query_key_value q/k/v-contiguous [3H, H]; newer source renames it
    ``.self_attention`` AND interleaves the stacking per head,
    [heads, 3, head_dim] flattened over rows (the reference's
    ``version``/megatron-v2 knob, replace_policy.py:146) — both are
    accepted here, keyed off the attribute name, and the v2 layout is
    de-interleaved back to the q/k/v-contiguous [3H, H] our engine's qkv
    split (and state_dict_factory's merge/split) expects."""

    LAYER_CLASS_NAMES = ("ParallelTransformerLayer",)
    pre_layer_norm = True
    causal = True

    @staticmethod
    def _deinterleave_qkv(arr, heads):
        """[heads, 3, head_dim, ...] row blocks -> [3, heads, head_dim, ...]."""
        rows = arr.shape[0]
        hd = rows // (3 * heads)
        rest = arr.shape[1:]
        return (arr.reshape(heads, 3, hd, *rest)
                .swapaxes(0, 1)
                .reshape(rows, *rest))

    def layer_params(self):
        layer = self.layer
        att = getattr(layer, "attention", None)
        v2 = att is None  # .self_attention == new source == interleaved qkv
        if v2:
            att = layer.self_attention

        def bias_of(lin):
            b = getattr(lin, "bias", None)
            return (_np(b) if b is not None
                    else np.zeros((lin.weight.shape[0],), np.float32))

        qkvw = _np(att.query_key_value.weight)        # [3H, H] rows
        qkvb = bias_of(att.query_key_value)
        if v2:
            heads = int(att.num_attention_heads)
            qkvw = self._deinterleave_qkv(qkvw, heads)
            qkvb = self._deinterleave_qkv(qkvb, heads)

        return {
            "attn_qkvw": qkvw.T,                      # [3H,H] -> [H,3H]
            "attn_qkvb": qkvb,
            "attn_ow": _np(att.dense.weight).T,
            "attn_ob": bias_of(att.dense),
            "norm_w": _np(layer.input_layernorm.weight),          # pre-attn LN
            "norm_b": _np(layer.input_layernorm.bias),
            "attn_nw": _np(layer.post_attention_layernorm.weight),  # pre-MLP LN
            "attn_nb": _np(layer.post_attention_layernorm.bias),
            "inter_w": _np(layer.mlp.dense_h_to_4h.weight).T,
            "inter_b": bias_of(layer.mlp.dense_h_to_4h),
            "output_w": _np(layer.mlp.dense_4h_to_h.weight).T,
            "output_b": bias_of(layer.mlp.dense_4h_to_h),
        }


replace_policies: List[type] = [HFGPT2LayerPolicy, HFBertLayerPolicy,
                                HFGPTNEOLayerPolicy, MegatronLayerPolicy]
