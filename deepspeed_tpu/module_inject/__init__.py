from .replace_policy import (HFBertLayerPolicy, HFGPT2LayerPolicy,
                             HFGPTNEOLayerPolicy, InjectBasePolicy,
                             replace_policies)
from .replace_module import replace_transformer_layer
