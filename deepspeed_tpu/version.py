__version__ = "0.4.0"
git_hash = None
git_branch = None
