"""FLOPS profiler — jaxpr cost analysis instead of functional monkey-patching.

Reference: deepspeed/profiling/flops_profiler/profiler.py:11 (FlopsProfiler
wraps torch.nn.functional to count MACs and per-module latency; engine
integration engine.py:200,1231,1276; config profiling/config.py:49).

TPU-native: the model is a traced program, so FLOPs are counted exactly by
walking the jaxpr — dot_general/conv_general_dilated carry their shapes —
and XLA's own compiled cost analysis cross-checks the total.  Per-"module"
attribution uses the primitive breakdown (matmul vs conv vs elementwise)
rather than nn.Module boundaries, which don't exist in a functional model.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax

from ..analysis.jaxpr_walk import as_jaxpr, eqn_scope, sub_jaxprs
from ..utils.logging import log_dist


def _dot_flops(eqn) -> int:
    """2*M*N*K for a dot_general, from the equation's avals."""
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    batch = int(np.prod([lhs.shape[i] for i in lb], initial=1))
    contract = int(np.prod([lhs.shape[i] for i in lc], initial=1))
    lhs_free = int(np.prod(
        [d for i, d in enumerate(lhs.shape) if i not in lc and i not in lb],
        initial=1))
    rhs_free = int(np.prod(
        [d for i, d in enumerate(rhs.shape) if i not in rc and i not in rb],
        initial=1))
    return 2 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    out_elems = int(np.prod(out.shape, initial=1))
    # per output element: 2 * (kernel spatial * in-features)
    per_out = 2 * int(np.prod(rhs.shape[:-1], initial=1))
    return out_elems * per_out


def count_jaxpr_flops(jaxpr, breakdown: Optional[Dict[str, int]] = None,
                      scopes: Optional[Dict[str, int]] = None,
                      _prefix: str = "", _mult: int = 1) -> int:
    """Walk a (closed) jaxpr counting matmul/conv MAC-flops plus elementwise
    ops; sub-jaxpr recursion (pjit/scan/cond/while/remat/custom_vjp/
    shard_map/...) rides the shared dispatcher in analysis/jaxpr_walk.py
    — scan multiplies by trip count, cond counts its most expensive
    branch, while counts cond+body once (dynamic trip counts are
    unknowable statically).

    `scopes` (optional) accumulates flops per `jax.named_scope` path —
    the per-module attribution the reference profiler gets from
    nn.Module hooks (profiler.py:11); models tag embed/attn/mlp/head
    regions (models/gpt2.py, ops/transformer.py) and the tree printer
    renders the hierarchy.  Sub-jaxpr equations carry name stacks
    relative to their enclosing scan/pjit, so recursion threads the
    parent scope as a prefix and scan trip counts as a multiplier."""
    jaxpr = as_jaxpr(jaxpr)
    total = 0
    breakdown = breakdown if breakdown is not None else {}

    def credit(key: str, eqn, f: int) -> None:
        breakdown[key] = breakdown.get(key, 0) + f * _mult
        if scopes is not None:
            sc = eqn_scope(eqn, _prefix)
            scopes[sc] = scopes.get(sc, 0) + f * _mult

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            f = _dot_flops(eqn)
            total += f
            credit("dot_general", eqn, f)
        elif name == "conv_general_dilated":
            f = _conv_flops(eqn)
            total += f
            credit("conv", eqn, f)
        elif name == "cond":
            # count the most expensive branch (what actually runs):
            # ONE walk per branch into fresh dicts, merge the winner
            # (a probe-then-credit double walk would compound 2^d on
            # d-nested conds — the gated 1F1B executor's shape)
            probes = []
            for sub in sub_jaxprs(eqn):
                bd: Dict[str, int] = {}
                sc: Optional[Dict[str, int]] = (
                    {} if scopes is not None else None)
                probes.append((count_jaxpr_flops(
                    sub.jaxpr, bd, sc, _prefix=eqn_scope(eqn, _prefix),
                    _mult=_mult), bd, sc))
            if probes:
                cost, bd, sc = max(probes, key=lambda p: p[0])
                total += cost
                for k, v in bd.items():
                    breakdown[k] = breakdown.get(k, 0) + v
                if scopes is not None and sc is not None:
                    for k, v in sc.items():
                        scopes[k] = scopes.get(k, 0) + v
        else:
            subs = sub_jaxprs(eqn)
            if subs:
                for sub in subs:
                    if sub.trip_count is not None:  # scan body
                        inner = count_jaxpr_flops(
                            sub.jaxpr, breakdown, scopes,
                            _prefix=eqn_scope(eqn, _prefix),
                            _mult=_mult * sub.trip_count)
                        total += inner * sub.trip_count
                    else:
                        # generic call (pjit/remat2/custom_vjp/shard_map/
                        # ...) and while cond+body: counted once —
                        # unifying onto the shared dispatcher fixed the
                        # silent zeros for remat2 (what jax.checkpoint
                        # actually emits), shard_map (the sparse-
                        # gradients region), and while cond jaxprs
                        total += count_jaxpr_flops(
                            sub.jaxpr, breakdown, scopes,
                            _prefix=eqn_scope(eqn, _prefix), _mult=_mult)
            else:
                # elementwise / reduction: one flop per output element
                for ov in eqn.outvars:
                    aval = getattr(ov, "aval", None)
                    if aval is not None and hasattr(aval, "shape"):
                        f = int(np.prod(aval.shape, initial=1))
                        total += f
                        credit("elementwise", eqn, f)
    return total


def eqn_flops(eqn) -> int:
    """Static flops of ONE equation, sub-jaxprs included: matmuls/convs
    exactly, scan bodies trip-weighted, cond at its most expensive
    branch, elementwise as one flop per output element.  This is the
    unit the Schedule Auditor's overlap analysis weighs slack windows
    with (analysis/overlap.py) and the step-time model sums
    (analysis/cost_model.py)."""
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    subs = sub_jaxprs(eqn)
    if subs:
        if name == "cond":
            return max((count_jaxpr_flops(s.jaxpr) for s in subs),
                       default=0)
        return sum(count_jaxpr_flops(s.jaxpr) * (s.trip_count or 1)
                   for s in subs)
    total = 0
    for ov in eqn.outvars:
        aval = getattr(ov, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            total += int(np.prod(aval.shape, initial=1))
    return total


def get_model_profile(fn: Callable, args: Tuple = (), kwargs=None,
                      params: Any = None, as_string: bool = False):
    """(flops, macs, params) of one call of `fn` (reference
    get_model_profile).  flops from the jaxpr; macs = dot/conv flops / 2."""
    kwargs = kwargs or {}
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    breakdown: Dict[str, int] = {}
    flops = count_jaxpr_flops(closed, breakdown)
    macs = (breakdown.get("dot_general", 0) + breakdown.get("conv", 0)) // 2
    n_params = 0
    if params is not None:
        n_params = sum(int(np.prod(leaf.shape, initial=1))
                       for leaf in jax.tree.leaves(params)
                       if hasattr(leaf, "shape"))
    if as_string:
        return (_fmt(flops, "FLOPS"), _fmt(macs, "MACs"),
                _fmt(n_params, "params"))
    return flops, macs, n_params


def _fmt(n: float, unit: str) -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if n >= scale:
            return f"{n / scale:.2f} {suffix}{unit}"
    return f"{n} {unit}"


class FlopsProfiler:
    """Engine-attached profiler (reference FlopsProfiler:11): captures one
    step's flops/params and wall-clock at the configured step."""

    def __init__(self, model=None, config=None):
        self.model = model
        self.config = config
        self.started = False
        self.flops = 0
        self.macs = 0
        self.params = 0
        self.breakdown: Dict[str, int] = {}
        self.scopes: Dict[str, int] = {}
        self._t0 = 0.0
        self.latency = 0.0

    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        self.flops = self.macs = 0
        self.breakdown = {}
        self.scopes = {}
        self._t0 = time.time()

    def profile_fn(self, fn: Callable, *args, **kwargs) -> None:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        self.breakdown = {}
        self.scopes = {}
        self.flops = count_jaxpr_flops(closed, self.breakdown, self.scopes)
        self.macs = (self.breakdown.get("dot_general", 0) +
                     self.breakdown.get("conv", 0)) // 2

    def set_params(self, params: Any) -> None:
        self.params = sum(int(np.prod(leaf.shape, initial=1))
                          for leaf in jax.tree.leaves(params)
                          if hasattr(leaf, "shape"))

    def stop_profile(self) -> None:
        self.latency = time.time() - self._t0
        self.started = False

    def get_total_flops(self, as_string: bool = False):
        return _fmt(self.flops, "FLOPS") if as_string else self.flops

    def get_total_macs(self, as_string: bool = False):
        return _fmt(self.macs, "MACs") if as_string else self.macs

    def get_total_params(self, as_string: bool = False):
        return _fmt(self.params, "params") if as_string else self.params

    def get_total_duration(self, as_string: bool = False):
        return self.latency

    def module_tree(self, module_depth: int = -1) -> Dict[str, int]:
        """Aggregate the per-name-scope flops into a module tree: every
        scope path also credits its ancestors, so 'layer' holds the sum
        of 'layer/attn' + 'layer/mlp' + its own untagged ops (the
        reference's module-hierarchy semantics, profiler.py:11)."""
        tree: Dict[str, int] = {}
        for path, f in self.scopes.items():
            parts = [p for p in path.split("/") if p]
            if not parts:
                parts = ["(untagged)"]
            if module_depth > 0:
                parts = parts[:module_depth]
            for depth in range(1, len(parts) + 1):
                key = "/".join(parts[:depth])
                tree[key] = tree.get(key, 0) + f
        return tree

    def print_model_profile(self, profile_step: int = 1,
                            module_depth: int = -1, top_modules: int = 1,
                            detailed: bool = True, output_file=None) -> None:
        """Reference-style profile dump (profiler.py print_model_profile):
        totals, the per-module tree with top-k modules per depth, and the
        per-primitive breakdown.  Per-module latency is ESTIMATED as the
        module's flops share of the measured step latency — one compiled
        XLA program has no per-module clocks; the flops share is the
        attribution a fused program supports honestly."""
        lines = [
            "----------- flops profiler (jaxpr cost analysis) -----------",
            f"profile step:            {profile_step}",
            f"params:                  {self.get_total_params(True)}",
            f"fwd(+bwd) flops:         {self.get_total_flops(True)}",
            f"fwd(+bwd) MACs:          {self.get_total_macs(True)}",
            f"step latency:            {self.latency * 1e3:.2f} ms",
        ]
        if detailed and self.scopes:
            tree = self.module_tree(module_depth)
            by_depth: Dict[int, list] = {}
            for key, f in tree.items():
                by_depth.setdefault(key.count("/"), []).append((key, f))
            lines.append(
                "per-module tree (named scopes; latency = flops share "
                "x step):")
            total = max(self.flops, 1)
            for depth in sorted(by_depth):
                rows = sorted(by_depth[depth], key=lambda kv: -kv[1])
                lines.append(f"  depth {depth} (top {top_modules}):")
                for key, f in rows[:max(1, top_modules)]:
                    share = f / total
                    lines.append(
                        f"    {key:<40} {_fmt(f, 'FLOPS'):>14} "
                        f"{share * 100:5.1f}%  "
                        f"~{share * self.latency * 1e3:7.2f} ms")
        if detailed and self.breakdown:
            lines.append("breakdown by primitive:")
            for k, v in sorted(self.breakdown.items(),
                               key=lambda kv: -kv[1]):
                lines.append(f"  {k:<14} {_fmt(v, 'FLOPS')}")
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            log_dist(text, ranks=[0])

    def end_profile(self) -> None:
        self.stop_profile()
