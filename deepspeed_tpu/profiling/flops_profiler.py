"""FLOPS profiler — jaxpr cost analysis instead of functional monkey-patching.

Reference: deepspeed/profiling/flops_profiler/profiler.py:11 (FlopsProfiler
wraps torch.nn.functional to count MACs and per-module latency; engine
integration engine.py:200,1231,1276; config profiling/config.py:49).

TPU-native: the model is a traced program, so FLOPs are counted exactly by
walking the jaxpr — dot_general/conv_general_dilated carry their shapes —
and XLA's own compiled cost analysis cross-checks the total.  Per-"module"
attribution uses the primitive breakdown (matmul vs conv vs elementwise)
rather than nn.Module boundaries, which don't exist in a functional model.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import core as jax_core

from ..utils.logging import log_dist


def _dot_flops(eqn) -> int:
    """2*M*N*K for a dot_general, from the equation's avals."""
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    batch = int(np.prod([lhs.shape[i] for i in lb], initial=1))
    contract = int(np.prod([lhs.shape[i] for i in lc], initial=1))
    lhs_free = int(np.prod(
        [d for i, d in enumerate(lhs.shape) if i not in lc and i not in lb],
        initial=1))
    rhs_free = int(np.prod(
        [d for i, d in enumerate(rhs.shape) if i not in rc and i not in rb],
        initial=1))
    return 2 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    out_elems = int(np.prod(out.shape, initial=1))
    # per output element: 2 * (kernel spatial * in-features)
    per_out = 2 * int(np.prod(rhs.shape[:-1], initial=1))
    return out_elems * per_out


def count_jaxpr_flops(jaxpr, breakdown: Optional[Dict[str, int]] = None
                      ) -> int:
    """Walk a (closed) jaxpr counting matmul/conv MAC-flops plus elementwise
    ops; recurses through pjit/scan/cond/while/remat sub-jaxprs (scan
    multiplies by trip count)."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    total = 0
    breakdown = breakdown if breakdown is not None else {}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            f = _dot_flops(eqn)
            total += f
            breakdown["dot_general"] = breakdown.get("dot_general", 0) + f
        elif name == "conv_general_dilated":
            f = _conv_flops(eqn)
            total += f
            breakdown["conv"] = breakdown.get("conv", 0) + f
        elif name == "scan":
            sub_bd: Dict[str, int] = {}
            inner = count_jaxpr_flops(eqn.params["jaxpr"], sub_bd)
            length = eqn.params["length"]
            total += inner * length
            for k, v in sub_bd.items():
                breakdown[k] = breakdown.get(k, 0) + v * length
        elif name in ("pjit", "closed_call", "core_call", "remat",
                      "checkpoint", "custom_vjp_call", "custom_jvp_call",
                      "custom_vjp_call_jaxpr"):
            sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                   or eqn.params.get("fun_jaxpr"))
            if sub is not None:
                total += count_jaxpr_flops(sub, breakdown)
        elif name in ("cond",):
            branches = eqn.params.get("branches", ())
            if branches:
                # count the most expensive branch (what actually runs)
                costs = []
                bds = []
                for b in branches:
                    bd: Dict[str, int] = {}
                    costs.append(count_jaxpr_flops(b, bd))
                    bds.append(bd)
                best = max(range(len(costs)), key=lambda i: costs[i])
                total += costs[best]
                for k, v in bds[best].items():
                    breakdown[k] = breakdown.get(k, 0) + v
        elif name == "while":
            body = eqn.params.get("body_jaxpr")
            if body is not None:
                total += count_jaxpr_flops(body, breakdown)
        else:
            # elementwise / reduction: one flop per output element
            for ov in eqn.outvars:
                aval = getattr(ov, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    f = int(np.prod(aval.shape, initial=1))
                    total += f
                    breakdown["elementwise"] = breakdown.get(
                        "elementwise", 0) + f
    return total


def get_model_profile(fn: Callable, args: Tuple = (), kwargs=None,
                      params: Any = None, as_string: bool = False):
    """(flops, macs, params) of one call of `fn` (reference
    get_model_profile).  flops from the jaxpr; macs = dot/conv flops / 2."""
    kwargs = kwargs or {}
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    breakdown: Dict[str, int] = {}
    flops = count_jaxpr_flops(closed, breakdown)
    macs = (breakdown.get("dot_general", 0) + breakdown.get("conv", 0)) // 2
    n_params = 0
    if params is not None:
        n_params = sum(int(np.prod(l.shape, initial=1))
                       for l in jax.tree.leaves(params)
                       if hasattr(l, "shape"))
    if as_string:
        return (_fmt(flops, "FLOPS"), _fmt(macs, "MACs"),
                _fmt(n_params, "params"))
    return flops, macs, n_params


def _fmt(n: float, unit: str) -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if n >= scale:
            return f"{n / scale:.2f} {suffix}{unit}"
    return f"{n} {unit}"


class FlopsProfiler:
    """Engine-attached profiler (reference FlopsProfiler:11): captures one
    step's flops/params and wall-clock at the configured step."""

    def __init__(self, model=None, config=None):
        self.model = model
        self.config = config
        self.started = False
        self.flops = 0
        self.macs = 0
        self.params = 0
        self.breakdown: Dict[str, int] = {}
        self._t0 = 0.0
        self.latency = 0.0

    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        self.flops = self.macs = 0
        self.breakdown = {}
        self._t0 = time.time()

    def profile_fn(self, fn: Callable, *args, **kwargs) -> None:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        self.breakdown = {}
        self.flops = count_jaxpr_flops(closed, self.breakdown)
        self.macs = (self.breakdown.get("dot_general", 0) +
                     self.breakdown.get("conv", 0)) // 2

    def set_params(self, params: Any) -> None:
        self.params = sum(int(np.prod(l.shape, initial=1))
                          for l in jax.tree.leaves(params)
                          if hasattr(l, "shape"))

    def stop_profile(self) -> None:
        self.latency = time.time() - self._t0
        self.started = False

    def get_total_flops(self, as_string: bool = False):
        return _fmt(self.flops, "FLOPS") if as_string else self.flops

    def get_total_macs(self, as_string: bool = False):
        return _fmt(self.macs, "MACs") if as_string else self.macs

    def get_total_params(self, as_string: bool = False):
        return _fmt(self.params, "params") if as_string else self.params

    def get_total_duration(self, as_string: bool = False):
        return self.latency

    def print_model_profile(self, profile_step: int = 1,
                            module_depth: int = -1, top_modules: int = 1,
                            detailed: bool = True, output_file=None) -> None:
        lines = [
            "----------- flops profiler (jaxpr cost analysis) -----------",
            f"profile step:            {profile_step}",
            f"params:                  {self.get_total_params(True)}",
            f"fwd(+bwd) flops:         {self.get_total_flops(True)}",
            f"fwd(+bwd) MACs:          {self.get_total_macs(True)}",
            f"step latency:            {self.latency * 1e3:.2f} ms",
        ]
        if detailed and self.breakdown:
            lines.append("breakdown by primitive:")
            for k, v in sorted(self.breakdown.items(),
                               key=lambda kv: -kv[1]):
                lines.append(f"  {k:<14} {_fmt(v, 'FLOPS')}")
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            log_dist(text, ranks=[0])

    def end_profile(self) -> None:
        self.stop_profile()
