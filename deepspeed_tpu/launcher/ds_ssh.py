"""ds_ssh — run one command on every worker in the hostfile.

Reference: bin/ds_ssh (pdsh fan-out over /job/hostfile).  TPU recasting:
TPU-VM pods are usually driven via `gcloud compute tpus tpu-vm ssh
--worker=all`, but the hostfile workflow matters for the on-prem /
hostfile-launched case `dslaunch` supports — so ds_ssh mirrors the
reference semantics: read the hostfile, fan the command out over ssh
(pdsh when available, plain ssh loop otherwise), run locally when no
hostfile exists.
"""

import argparse
import shlex
import shutil
import subprocess
import sys

from .runner import DLTS_HOSTFILE, fetch_hostfile


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ds_ssh",
        description="run a command on all hosts in the hostfile")
    p.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE,
                   help="hostfile: one 'hostname slots=N' per line")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.command:
        build_parser().print_usage(sys.stderr)
        return 2
    cmd = args.command
    hosts = fetch_hostfile(args.hostfile)
    if not hosts:
        # reference behavior: no hostfile -> run locally
        print(f"Missing hostfile at {args.hostfile}, executing command "
              "locally", file=sys.stderr)
        return subprocess.call(cmd)
    # the remote shell re-parses one string — quote each arg so spaces
    # and metacharacters survive the trip (shlex.join)
    remote_cmd = shlex.join(cmd)
    if shutil.which("pdsh"):
        host_list = ",".join(hosts)
        return subprocess.call(
            ["pdsh", "-R", "ssh", "-w", host_list, remote_cmd])
    rc = 0
    for host in hosts:
        print(f"== {host} ==", file=sys.stderr)
        r = subprocess.call(["ssh", "-o", "StrictHostKeyChecking=no",
                             host, remote_cmd])
        rc = rc or r
    return rc


if __name__ == "__main__":
    sys.exit(main())
