"""Multi-host launcher — the `deepspeed` CLI analog for TPU pods.

Reference: deepspeed/launcher/runner.py:259 (main: hostfile parse :120,
--include/--exclude resource filtering, base64 world-info, runner choice)
and launcher/launch.py:67 (per-node fork of one process per GPU with
RANK/LOCAL_RANK/WORLD_SIZE env).

TPU recasting: a TPU host runs ONE process that owns all local chips
(multi-controller JAX), so "one proc per GPU" becomes "one proc per host".
The launcher resolves the host list (hostfile or --num_nodes), filters with
--include/--exclude (same syntax: "host1@host2" / "host1:0,1"), exports
DS_COORDINATOR/DS_NUM_PROCESSES/DS_PROCESS_ID consumed by
init_distributed() -> jax.distributed.initialize, and runs the script via
ssh (multi-node) or exec (single node).

Usage:  dslaunch --hostfile hosts.txt train.py --deepspeed_config ds.json
"""

import argparse
import base64
import json
import os
import shlex
import signal as _signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "TPU_NAME",
               "JAX_PLATFORMS", "XLA_FLAGS")


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu multi-host launcher")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile: one 'hostname slots=N' per line "
                             "(reference runner.py:120)")
    parser.add_argument("--tpu", type=str, default="",
                        help="TPU-pod discovery instead of a hostfile: "
                             "the reserved names 'metadata' and 'local' "
                             "both read this TPU VM's own pod topology "
                             "from the GCE metadata server; any other "
                             "value is a TPU name resolved via 'gcloud "
                             "compute tpus tpu-vm describe' "
                             "(launcher/tpu_discovery.py — the "
                             "multinode_runner.py:35 family's TPU form)")
    parser.add_argument("--tpu_zone", type=str, default=None)
    parser.add_argument("--tpu_project", type=str, default=None)
    parser.add_argument("-i", "--include", type=str, default="",
                        help='e.g. "host1@host2" or "host1:0@host2:0,1"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="inverse of --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--ssh_port", type=int, default=None)
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--dry_run", action="store_true",
                        help="print the per-host commands, launch nothing")
    parser.add_argument("--watch", type=str, default="",
                        help="monitor output dir (the training config's "
                             "monitor.output_path on a shared filesystem): "
                             "while workers run, render the per-host "
                             "heartbeat status table every "
                             "--watch_interval seconds "
                             "(monitor/heartbeat.py; needs "
                             "monitor.heartbeat=true in the ds config)")
    parser.add_argument("--watch_interval", type=float, default=10.0)
    parser.add_argument("--watch_stale_s", type=float, default=60.0,
                        help="a running host whose heartbeat is older "
                             "than max(this, 3x its own beat interval) "
                             "is rendered STALE")
    parser.add_argument("--watch_fail_after", type=int, default=0,
                        help="liveness gate for supervisor scripts: when "
                             "a heartbeat stays STALE for this many "
                             "consecutive --watch renders, terminate the "
                             "workers and exit nonzero (rc=3) with the "
                             "stale worker named — no table parsing "
                             "needed (0 = render only, never act)")
    parser.add_argument("--elastic", action="store_true",
                        help="self-healing relaunch loop: on a worker "
                             "failure (nonzero exit or --watch_fail_after "
                             "liveness trip) drop the failed/stale hosts, "
                             "shrink to the survivors, and relaunch — the "
                             "engine-side resilience block resumes from "
                             "the newest checkpoint, resharding ZeRO "
                             "partitions onto the smaller world "
                             "(docs/elastic_fleet.md).  With --tpu the "
                             "pod is re-discovered before each relaunch, "
                             "so replacement workers REGROW the fleet")
    parser.add_argument("--elastic_min_nodes", type=int, default=1,
                        help="stop relaunching (exit with the last rc) "
                             "when fewer hosts than this survive")
    parser.add_argument("--max_relaunches", type=int, default=3,
                        help="bound on --elastic relaunch cycles")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(hostfile_path: str) -> "OrderedDict[str, int]":
    """Parse 'hostname slots=N' lines (reference: runner.py:120)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    if not os.path.isfile(hostfile_path):
        return resources
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                host, slots = line.split()
                _, count = slots.split("=")
                resources[host] = int(count)
            except ValueError:
                raise ValueError(
                    f"hostfile line not of form 'host slots=n': {line!r}")
    return resources


def _parse_inclusion(spec: str) -> Dict[str, Optional[List[int]]]:
    out: Dict[str, Optional[List[int]]] = {}
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host] = [int(s) for s in slots.split(",")]
        else:
            out[part] = None
    return out


def parse_resource_filter(resources: "OrderedDict[str, int]",
                          include_str: str = "", exclude_str: str = ""
                          ) -> "OrderedDict[str, List[int]]":
    """--include/--exclude slot filtering (reference: runner.py:137)."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    full = OrderedDict(
        (h, list(range(n))) for h, n in resources.items())
    if include_str:
        keep = _parse_inclusion(include_str)
        out = OrderedDict()
        for host, slots in keep.items():
            if host not in full:
                raise ValueError(f"included host {host!r} not in hostfile")
            out[host] = slots if slots is not None else full[host]
        return out
    if exclude_str:
        drop = _parse_inclusion(exclude_str)
        out = OrderedDict()
        for host, slots in full.items():
            if host in drop:
                if drop[host] is None:
                    continue
                remaining = [s for s in slots if s not in drop[host]]
                if remaining:
                    out[host] = remaining
            else:
                out[host] = slots
        return out
    return full


def encode_world_info(resources: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(resources).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def build_host_commands(resources: "OrderedDict[str, List[int]]",
                        args) -> List[List[str]]:
    """One command per host: ssh + env + python script (one JAX process per
    host owns all its chips)."""
    hosts = list(resources.keys())
    master = args.master_addr or hosts[0]
    coordinator = f"{master}:{args.master_port}"
    n = len(hosts)
    cmds = []
    exports = [f"{k}={shlex.quote(os.environ[k])}"
               for k in EXPORT_ENVS if k in os.environ]
    for pid, host in enumerate(hosts):
        env = exports + [f"DS_COORDINATOR={coordinator}",
                         f"DS_NUM_PROCESSES={n}",
                         f"DS_PROCESS_ID={pid}",
                         f"DS_LOCAL_CHIPS="
                         f"{','.join(map(str, resources[host]))}"]
        inner = (["env"] + env + [sys.executable, "-u", args.user_script] +
                 args.user_args)
        if n == 1 and not args.force_multi:
            cmds.append(inner)
        else:
            ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
            if args.ssh_port:
                ssh += ["-p", str(args.ssh_port)]
            cmds.append(ssh + [host, " ".join(map(shlex.quote, inner))])
    return cmds


def _pump_lines(stream, sink, prefix: str) -> None:
    """Copy one worker stream line-by-line with a ``[host:rank]`` prefix
    — multi-host logs interleave LABELED instead of as an anonymous
    shuffle.  Line granularity keeps each record intact under
    interleaving (partial lines are only possible at process exit)."""
    try:
        for line in iter(stream.readline, ""):
            sink.write(prefix + line)
            sink.flush()
    except ValueError:  # stream closed mid-read at teardown
        pass
    finally:
        try:
            stream.close()
        except Exception:  # noqa: BLE001
            pass


WATCH_FAIL_RC = 3  # liveness-gate exit code (--watch_fail_after tripped)


class LaunchOutcome:
    """What one launch cycle reports: aggregate rc, the workers that
    exited nonzero, and the workers the --watch liveness gate declared
    dark — the inputs the --elastic relaunch loop (and any external
    supervisor script) shrinks the host list with."""

    def __init__(self):
        self.rc = 0
        self.failed: List[tuple] = []      # (host, rank, exit code)
        self.stale: List[tuple] = []       # (process_index, host_label)

    @property
    def bad_hosts(self) -> set:
        return ({h for h, _, _ in self.failed}
                | {h for _, h in self.stale})


def launch_and_wait(cmds: List[List[str]], hosts: List[str],
                    watch_dir: str = "", watch_interval: float = 10.0,
                    watch_stale_s: float = 60.0,
                    watch_fail_after: int = 0) -> int:
    """Spawn one process per host, label their output, surface failures.

    Multi-host launches pipe each worker's stdout/stderr through a
    ``[host:rank]`` line prefix; a single local process keeps its
    terminal untouched (no pipe between the user and their script).
    With ``watch_dir`` the launcher also renders the heartbeat status
    table (monitor/heartbeat.py) every ``watch_interval`` seconds while
    workers run.  Nonzero worker exits are reported WITH the offending
    host named; the return code is the first nonzero worker rc.
    ``watch_fail_after`` > 0 turns the watch into a liveness GATE: a
    heartbeat that stays STALE for that many consecutive renders
    terminates the workers and returns rc=3 with the worker named."""
    return launch_and_collect(cmds, hosts, watch_dir, watch_interval,
                              watch_stale_s, watch_fail_after).rc


def launch_and_collect(cmds: List[List[str]], hosts: List[str],
                       watch_dir: str = "", watch_interval: float = 10.0,
                       watch_stale_s: float = 60.0,
                       watch_fail_after: int = 0) -> LaunchOutcome:
    prefix_on = len(cmds) > 1
    procs: List[subprocess.Popen] = []
    pumps: List[threading.Thread] = []
    outcome = LaunchOutcome()
    for rank, (host, cmd) in enumerate(zip(hosts, cmds)):
        if prefix_on:
            # errors="replace": a worker emitting non-UTF-8 bytes (a
            # binary progress bar, a core-dump banner) must garble one
            # line, not kill the pump thread and SIGPIPE the worker
            p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True,
                                 errors="replace", bufsize=1)
            for stream, sink in ((p.stdout, sys.stdout),
                                 (p.stderr, sys.stderr)):
                t = threading.Thread(
                    target=_pump_lines, args=(stream, sink,
                                              f"[{host}:{rank}] "),
                    daemon=True, name=f"ds-launch-pump-{host}-{rank}")
                t.start()
                pumps.append(t)
        else:
            p = subprocess.Popen(cmd)
        procs.append(p)

    if watch_dir:
        from ..monitor.heartbeat import (annotate_stale,
                                         format_watch_table,
                                         read_heartbeats,
                                         resolve_heartbeat_dir)
        next_render = time.monotonic()  # render immediately, then every
        stale_streak: Dict[int, int] = {}
        while any(p.poll() is None for p in procs):
            if time.monotonic() >= next_render:
                next_render = time.monotonic() + max(1.0, watch_interval)
                try:
                    # re-resolved every render: the job's
                    # <output_path>/<job_name>/heartbeat dir may only
                    # appear once workers reach their first window
                    hb_dir = resolve_heartbeat_dir(watch_dir)
                    beats = read_heartbeats(hb_dir)
                    table = format_watch_table(
                        beats, stale_after_s=watch_stale_s,
                        expected_procs=len(cmds))
                    print(f"--- dslaunch --watch {hb_dir} ---\n{table}",
                          flush=True)
                    if watch_fail_after > 0:
                        tripped = _track_stale_streaks(
                            annotate_stale(beats, watch_stale_s),
                            stale_streak, watch_fail_after, hosts)
                        if tripped:
                            outcome.stale = tripped
                            for pidx, host in tripped:
                                logger.error(
                                    f"dslaunch --watch_fail_after: "
                                    f"worker {pidx} ({host!r}) heartbeat "
                                    f"stale for {watch_fail_after} "
                                    "consecutive renders — terminating "
                                    "workers")
                            _terminate_all(procs)
                            break
                except Exception as e:  # noqa: BLE001 — a status render
                    # must never take down the launcher (and its
                    # rc-aggregation) while workers are alive
                    logger.warning(f"dslaunch --watch render failed "
                                   f"({e}) — will retry next interval")
            time.sleep(0.5)

    rc = 0
    failed = []
    for rank, (host, p) in enumerate(zip(hosts, procs)):
        p.wait()
        if p.returncode:
            failed.append((host, rank, p.returncode))
            rc = rc or p.returncode
    for t in pumps:
        t.join(timeout=5)
    for host, rank, code in failed:
        logger.error(f"dslaunch: worker on host {host!r} (rank {rank}) "
                     f"exited with rc={code}")
    if failed and len(failed) < len(procs):
        ok = [h for h in hosts
              if h not in {f[0] for f in failed}]
        logger.error(f"dslaunch: {len(failed)}/{len(procs)} worker(s) "
                     f"failed; clean exits on: {ok}")
    if outcome.stale:
        # terminated-by-gate workers exit on our signal: the liveness
        # verdict (not their SIGTERM rc) is the reported failure.  That
        # covers the HEALTHY workers _terminate_all killed too — only
        # the stale hosts are bad; keeping a gate-terminated survivor in
        # `failed` would make --elastic drop the whole fleet.
        rc = WATCH_FAIL_RC
        gate_rcs = {-_signal.SIGTERM, -_signal.SIGKILL}
        failed = [f for f in failed
                  if f[0] not in {h for _, h in outcome.stale}
                  and f[2] not in gate_rcs]
    outcome.rc = rc
    outcome.failed = failed
    return outcome


def _track_stale_streaks(beats, streaks: Dict[int, int],
                         fail_after: int, hosts: List[str]) -> List[tuple]:
    """Consecutive-render stale accounting; returns the (process_index,
    host) pairs whose streak reached `fail_after` this render."""
    stale_now = {hb.get("process_index") for hb in beats
                 if hb.get("stale")
                 and hb.get("process_index") is not None}
    for pidx in list(streaks):
        if pidx not in stale_now:
            del streaks[pidx]
    tripped = []
    for pidx in sorted(stale_now):
        streaks[pidx] = streaks.get(pidx, 0) + 1
        if streaks[pidx] >= fail_after:
            host = hosts[pidx] if pidx < len(hosts) else f"p{pidx}"
            tripped.append((pidx, host))
    return tripped


def _terminate_all(procs: List[subprocess.Popen],
                   grace_s: float = 5.0) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + grace_s
    for p in procs:
        while p.poll() is None and time.monotonic() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def _resolve_active(args):
    """(active resources, labels) for one launch attempt — re-run per
    --elastic relaunch so a --tpu pod re-discovers its CURRENT worker
    set (preempted workers vanish, replacements appear = regrow)."""
    labels: Dict[str, str] = {}
    if args.tpu:
        from .tpu_discovery import discover
        pod = discover(args.tpu, args.tpu_zone, args.tpu_project)
        resources = pod.resources()
        labels = pod.labels()
        logger.info(
            f"dslaunch --tpu {args.tpu}: {len(pod.workers)} worker(s)"
            + (f" [{pod.accelerator_type}]" if pod.accelerator_type
               else ""))
    else:
        resources = fetch_hostfile(args.hostfile)
    if not resources:
        if args.num_nodes > 1:
            raise ValueError("multi-node launch needs a hostfile")
        resources = OrderedDict(localhost=1)
    if args.num_nodes > 0:
        resources = OrderedDict(list(resources.items())[:args.num_nodes])
    return parse_resource_filter(resources, args.include,
                                 args.exclude), labels


def main(argv=None) -> int:
    args = parse_args(argv)
    active, labels = _resolve_active(args)
    logger.info(f"dslaunch world: { {h: s for h, s in active.items()} }")
    if args.dry_run:
        for c in build_host_commands(active, args):
            print(" ".join(map(shlex.quote, c)))
        return 0

    relaunch = 0
    bad_hosts: set = set()  # hosts that failed the PREVIOUS attempt
    while True:
        host_labels = [labels.get(h, h) for h in active]
        outcome = launch_and_collect(
            build_host_commands(active, args), host_labels,
            watch_dir=args.watch, watch_interval=args.watch_interval,
            watch_stale_s=args.watch_stale_s,
            watch_fail_after=args.watch_fail_after)
        if outcome.rc == 0 or not args.elastic:
            return outcome.rc
        if relaunch >= args.max_relaunches:
            logger.error(
                f"dslaunch --elastic: max_relaunches="
                f"{args.max_relaunches} exhausted — exiting "
                f"rc={outcome.rc}")
            return outcome.rc
        relaunch += 1
        # labels back to ssh hosts: ranks line up with `active`'s order
        by_label = {label: host
                    for label, host in zip(host_labels, active)}
        bad_hosts = {by_label.get(h, h) for h in outcome.bad_hosts}
        # regrow: re-discover capacity (a --tpu pod's replacement
        # workers join here); hosts that just failed sit out ONE attempt
        refreshed, labels = _resolve_active(args)
        survivors = OrderedDict(
            (h, s) for h, s in refreshed.items() if h not in bad_hosts)
        if len(survivors) < max(1, args.elastic_min_nodes):
            logger.error(
                f"dslaunch --elastic: only {len(survivors)} host(s) "
                f"survive (min {args.elastic_min_nodes}) after dropping "
                f"{sorted(bad_hosts)} — exiting rc={outcome.rc}")
            return outcome.rc
        logger.error(
            f"dslaunch --elastic: relaunch {relaunch}/"
            f"{args.max_relaunches} on {len(survivors)} host(s) "
            f"(dropped {sorted(bad_hosts)}); the engine resumes from "
            "the newest checkpoint and reshards onto the new world")
        active = survivors


if __name__ == "__main__":
    sys.exit(main())
