"""TPU-pod worker discovery for the launcher.

Reference: deepspeed/launcher/multinode_runner.py:35,78,118 — the PDSH /
OpenMPI / MVAPICH runner family resolves the worker set from hostfiles or
MPI environments.  The TPU-native equivalent resolves a pod's worker
hosts from the platform itself:

  * ON a TPU VM: the GCE metadata server exposes the pod topology —
    `worker-network-endpoints` (comma-separated entries containing each
    worker's IP), `agent-worker-number` (this worker's index) and
    `accelerator-type`.  jax.distributed uses the same source for its
    TPU auto-bootstrap; surfacing it in the launcher lets `dslaunch`
    drive any script across the pod without a hand-written hostfile.
  * OFF the pod (a dev box): `gcloud compute tpus tpu-vm describe`
    returns the workers' `networkEndpoints`, which become the ssh host
    list.

Both backends take injectable fetch/run callables so tests mock the
metadata response and the gcloud JSON without network access (this repo
builds in a zero-egress sandbox; the wire formats follow the public GCP
documentation and are parsed tolerantly — any IPv4 found per entry, in
order).
"""

import json
import re
import subprocess
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, List, Optional

METADATA_ROOT = ("http://metadata.google.internal/computeMetadata/v1/"
                 "instance/attributes/")
_IPV4 = re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b")


@dataclass
class PodInfo:
    """A TPU pod's worker set as the launcher consumes it."""
    workers: List[str]          # one IP/host per worker VM, pod order
    my_index: Optional[int]     # this VM's worker number (None off-pod)
    accelerator_type: str = ""

    def resources(self) -> "OrderedDict[str, int]":
        """hostfile-equivalent resource map: one slot per worker host —
        a TPU host runs ONE process that owns all its local chips
        (multi-controller JAX), matching runner.py's model."""
        return OrderedDict((w, 1) for w in self.workers)

    def labels(self) -> "OrderedDict[str, str]":
        """host -> short display label ("w<N>", pod order) for the
        launcher's ``[host:rank]`` output prefixes and the --watch
        table: a 15-char IP per log line drowns the payload, the pod
        worker number is what an operator actually greps for."""
        return OrderedDict((w, f"w{i}")
                           for i, w in enumerate(self.workers))


def default_metadata_fetch(attribute: str, timeout: float = 5.0) -> str:
    """GET one instance attribute from the GCE metadata server (only
    reachable on a GCP VM; tests inject a fake)."""
    import urllib.request

    req = urllib.request.Request(METADATA_ROOT + attribute,
                                 headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read().decode()


def discover_from_metadata(
        fetch: Callable[[str], str] = default_metadata_fetch) -> PodInfo:
    """Resolve the pod topology from the TPU VM's own metadata.

    `worker-network-endpoints` entries are comma-separated and contain
    each worker's internal IP (exact field layout varies by runtime
    version, so the parser takes any IPv4 per entry, preserving pod
    order — the order defines worker numbering).
    """
    endpoints = fetch("worker-network-endpoints")
    workers: List[str] = []
    for entry in endpoints.split(","):
        m = _IPV4.search(entry)
        if m:
            workers.append(m.group(0))
    if not workers:
        raise RuntimeError(
            f"no worker IPs found in metadata worker-network-endpoints: "
            f"{endpoints!r}")

    def optional(attribute: str) -> Optional[str]:
        """Fetch an OPTIONAL attribute: a genuinely-absent attribute
        (HTTP 404 / KeyError from a fake) returns None; transient
        failures PROPAGATE — a timeout mislabeled as 'absent' would let
        two VMs both claim worker 0."""
        import urllib.error
        try:
            return fetch(attribute)
        except KeyError:
            return None
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    raw_idx = optional("agent-worker-number")
    my_index: Optional[int] = (int(raw_idx.strip())
                               if raw_idx and raw_idx.strip().isdigit()
                               else (0 if len(workers) == 1 else None))
    acc = (optional("accelerator-type") or "").strip()
    return PodInfo(workers=workers, my_index=my_index,
                   accelerator_type=acc)


def discover_from_gcloud(name: str, zone: Optional[str] = None,
                         project: Optional[str] = None,
                         run: Callable[..., "subprocess.CompletedProcess"]
                         = subprocess.run) -> PodInfo:
    """Resolve a pod's workers via `gcloud compute tpus tpu-vm describe`
    (the off-pod path; `run` is injectable for tests)."""
    cmd = ["gcloud", "compute", "tpus", "tpu-vm", "describe", name,
           "--format", "json"]
    if zone:
        cmd += ["--zone", zone]
    if project:
        cmd += ["--project", project]
    proc = run(cmd, capture_output=True, text=True, timeout=60)
    if proc.returncode != 0:
        raise RuntimeError(
            f"gcloud describe {name!r} failed (rc={proc.returncode}): "
            f"{(proc.stderr or '')[-500:]}")
    desc = json.loads(proc.stdout)
    workers = []
    for ep in desc.get("networkEndpoints", []):
        # prefer the EXTERNAL address: this path's use case is launching
        # from outside GCP, where internal 10.x VPC addresses are not
        # routable; fall back to the internal IP for in-VPC dev boxes
        ip = ((ep.get("accessConfig") or {}).get("externalIp")
              or ep.get("ipAddress"))
        if ip:
            workers.append(ip)
    if not workers:
        raise RuntimeError(
            f"TPU {name!r} has no networkEndpoints in gcloud describe "
            "output")
    return PodInfo(workers=workers, my_index=None,
                   accelerator_type=desc.get("acceleratorType", ""))


def discover(tpu: str, zone: Optional[str] = None,
             project: Optional[str] = None) -> PodInfo:
    """`dslaunch --tpu` entry: the reserved names 'metadata' and 'local'
    read this VM's own pod topology from the metadata server; any other
    value is a TPU name resolved via gcloud.  (Backends take injectable
    fetch/run for tests — call them directly to mock.)"""
    if tpu in ("metadata", "local"):
        return discover_from_metadata()
    return discover_from_gcloud(tpu, zone, project)
