"""Config key names and defaults for the deepspeed_tpu JSON config schema.

The key schema intentionally matches the reference DeepSpeed v0.5.2 JSON
surface (reference: deepspeed/runtime/constants.py, deepspeed/runtime/zero/
constants.py, deepspeed/runtime/zero/offload_constants.py) so that reference
configs load unchanged.  Values here are *names and defaults*, i.e. the public
API contract — the implementations behind them are TPU-native.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

# TPU-native addition: bf16 is the natural TPU dtype (no loss scaling needed).
BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False
# Keep gradient buffers in the compute dtype (bf16) instead of fp32 —
# the analog of the reference's fp16 gradient buffers under ZeRO stage
# 1/2 (grads live at half width between backward and the optimizer,
# which upcasts to fp32 at apply).  Halves grad HBM and the stage-2
# reduce-scatter wire width; opt-in because accumulation then rounds
# through bf16 like the reference's fp16 path.
BF16_GRADS_IN_COMPUTE_DTYPE = "grads_in_compute_dtype"
BF16_GRADS_IN_COMPUTE_DTYPE_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient handling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Misc engine knobs
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

# Engine PRNG implementation for the default (no rng= passed) stream.
# "rbg" is the fast TPU choice (~14 ms/step over threefry on the flagship
# bench) but JAX documents rbg streams as NOT stable across backends or
# JAX versions; set "threefry" for bit-reproducible default dropout/noise
# across upgrades and CPU-vs-TPU runs.
PRNG_IMPL = "prng_impl"
PRNG_IMPL_DEFAULT = "rbg"

VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Fused whole-step train program (TPU-native addition; docs/fused_step.md)
#
# One jitted program per optimizer step: gradient accumulation as a
# lax.scan over a leading microbatch axis + the optimizer/loss-scale
# update in the same program — 1 XLA dispatch instead of 2N+1, grad
# buffers never leave the program, and XLA's latency-hiding scheduler
# overlaps microbatch i's grad collective with microbatch i+1's compute.
# Off by default; host-interactive features (offload optimizer,
# eigenvalue/MoQ, sentinel rewind or grad-norm monitoring, PLD,
# curriculum, custom grad programs) automatically fall back to the
# modular forward/backward/step loop.
#############################################
FUSED_STEP = "fused_step"
FUSED_STEP_ENABLED = "enabled"
FUSED_STEP_ENABLED_DEFAULT = False

#############################################
# Program Auditor (TPU-native addition; docs/program_auditor.md)
#
# Static jaxpr lint of the traced train-step programs at engine init /
# in CI: host callbacks in the hot loop, donation misses, collective-
# lockstep signature drift, fp32 upcasts on half wires, comm-budget
# breaches, plus a runtime recompile guard.  mode "off" (default) skips
# everything; "warn" logs findings; "error" raises ProgramAuditError on
# error-severity findings.
#############################################
ANALYSIS = "analysis"
ANALYSIS_MODE = "mode"
ANALYSIS_MODE_DEFAULT = "off"
ANALYSIS_MODES = ("off", "warn", "error")
# per-step wire-byte budget in MiB (trip-count weighted); None = no lint
ANALYSIS_COMM_BUDGET_MB = "comm_budget_mb"
ANALYSIS_COMM_BUDGET_MB_DEFAULT = None
# distinct step-function trace signatures tolerated before the
# recompile guard fires
ANALYSIS_MAX_RETRACES = "max_retraces"
ANALYSIS_MAX_RETRACES_DEFAULT = 16
# donation-audit floor: consumed-but-undonated args smaller than this
# are noise, not HBM leaks
ANALYSIS_DONATION_MIN_MB = "donation_min_mb"
ANALYSIS_DONATION_MIN_MB_DEFAULT = 1.0
# dtype-hazard floor: upcasts on arrays smaller than this are scalars /
# epilogue math, not wires
ANALYSIS_DTYPE_MIN_ELEMENTS = "dtype_min_elements"
ANALYSIS_DTYPE_MIN_ELEMENTS_DEFAULT = 65536
# pin the collective-lockstep signature (hex prefix ok); mismatch is an
# error-severity finding
ANALYSIS_EXPECTED_SIGNATURE = "expected_signature"
ANALYSIS_EXPECTED_SIGNATURE_DEFAULT = None
# Schedule Auditor (overlap / liveness / step-time; docs/program_auditor.md)
#
# static peak-HBM budget in MiB (donation-aware liveness estimate);
# None = report only, no lint
ANALYSIS_HBM_BUDGET_MB = "hbm_budget_mb"
ANALYSIS_HBM_BUDGET_MB_DEFAULT = None
# escalate serialized-collective-in-hot-loop overlap findings from
# warning to error (the CI gate for the double-buffered prefetch work)
ANALYSIS_REQUIRE_OVERLAP = "require_overlap"
ANALYSIS_REQUIRE_OVERLAP_DEFAULT = False
# a collective counts as overlapped when the flop-weighted slack between
# issue and first consume hides at least this fraction of its wire time
ANALYSIS_OVERLAP_MIN_HIDDEN = "overlap_min_hidden_fraction"
ANALYSIS_OVERLAP_MIN_HIDDEN_DEFAULT = 0.5
# hardware model for the static step-time lower bound (defaults: one
# TPU v5e chip — bf16 peak, HBM bandwidth, per-chip ICI bandwidth).
# These THREE names are the canonical hardware-constant vocabulary:
# the analysis config block, the autotuner's calibration file, and the
# cost model's report payload all key off ANALYSIS_HW_KEYS /
# ANALYSIS_HW_DEFAULTS so a constant can never be overridden under one
# spelling and read under another.
ANALYSIS_HW_PEAK_TFLOPS = "hw_peak_tflops"
ANALYSIS_HW_PEAK_TFLOPS_DEFAULT = 197.0
ANALYSIS_HW_HBM_GBPS = "hw_hbm_gbps"
ANALYSIS_HW_HBM_GBPS_DEFAULT = 819.0
ANALYSIS_HW_ICI_GBPS = "hw_ici_gbps"
ANALYSIS_HW_ICI_GBPS_DEFAULT = 90.0
ANALYSIS_HW_KEYS = (ANALYSIS_HW_PEAK_TFLOPS, ANALYSIS_HW_HBM_GBPS,
                    ANALYSIS_HW_ICI_GBPS)
ANALYSIS_HW_DEFAULTS = {
    ANALYSIS_HW_PEAK_TFLOPS: ANALYSIS_HW_PEAK_TFLOPS_DEFAULT,
    ANALYSIS_HW_HBM_GBPS: ANALYSIS_HW_HBM_GBPS_DEFAULT,
    ANALYSIS_HW_ICI_GBPS: ANALYSIS_HW_ICI_GBPS_DEFAULT,
}
# HLO-level SPMD audit (analysis/hlo_audit.py): lower each audited
# program through XLA's SPMD partitioner (compile-only, never executed)
# and cross-check the jaxpr wire story against what the compiler
# actually emitted — GSPMD inserts collectives AFTER tracing, so a
# sharding-annotation mistake can add all-gathers the jaxpr-level
# accounting never sees ("silent resharding").
ANALYSIS_HLO_AUDIT = "hlo_audit"
ANALYSIS_HLO_AUDIT_DEFAULT = False
# escalate silent-reshard + jaxpr/HLO-divergence findings from warning
# to error (the CI posture once a config's compiled wire story is
# pinned)
ANALYSIS_REQUIRE_SPMD_MATCH = "require_spmd_match"
ANALYSIS_REQUIRE_SPMD_MATCH_DEFAULT = False
# floor below which a compiler-inserted gather-family collective is
# waived as "below_floor" instead of flagged: GSPMD legitimately
# inserts small gathers for indexed updates (an embedding grad's
# scatter-add) that are wire the jaxpr never counted but not a
# sharding mistake.  Priced into the exposed-comm lane either way.
ANALYSIS_SPMD_RESHARD_MIN_MB = "spmd_reshard_min_mb"
ANALYSIS_SPMD_RESHARD_MIN_MB_DEFAULT = 1.0
# tolerated relative gap between the jaxpr-predicted wire bytes and the
# HLO-measured bytes of the SAME traced collectives before a
# spmd_divergence finding fires (combiner passes and degenerate-group
# elision move a few percent)
ANALYSIS_SPMD_MATCH_TOLERANCE = "spmd_match_tolerance"
ANALYSIS_SPMD_MATCH_TOLERANCE_DEFAULT = 0.05

#############################################
# Config autotuner (TPU-native addition; docs/autotuner.md)
#
# Offline cost-model-driven search over the real config decision space
# (mesh factorization, ZeRO stage/variant, gas/micro splits, qwZ/qgZ/
# hpZ, fused vs modular, offload tier) — prune on hard constraints,
# trace survivors on a simulated mesh, rank by the static step-time
# lower bound, emit the top-K as bench-ready configs.  The block only
# configures `python -m deepspeed_tpu.analysis tune`; it never changes
# engine behavior.
#############################################
AUTOTUNING = "autotuning"
AUTOTUNING_CHIPS = "chips"
AUTOTUNING_CHIPS_DEFAULT = None          # required via block or --chips
AUTOTUNING_GLOBAL_BATCH = "global_batch"
AUTOTUNING_GLOBAL_BATCH_DEFAULT = None   # default: base config train_batch
AUTOTUNING_TOP_K = "top_k"
AUTOTUNING_TOP_K_DEFAULT = 3
AUTOTUNING_HBM_BUDGET_MB = "hbm_budget_mb"
AUTOTUNING_HBM_BUDGET_MB_DEFAULT = None  # default: analysis.hbm_budget_mb
AUTOTUNING_MAX_CANDIDATES = "max_candidates"
AUTOTUNING_MAX_CANDIDATES_DEFAULT = 64
# search axes: each is the list of values the enumeration sweeps
AUTOTUNING_MESH_MODEL = "mesh_model"
AUTOTUNING_MESH_MODEL_DEFAULT = (1,)
AUTOTUNING_MESH_EXPERT = "mesh_expert"
AUTOTUNING_MESH_EXPERT_DEFAULT = (1,)
AUTOTUNING_ZERO_STAGES = "zero_stages"
AUTOTUNING_ZERO_STAGES_DEFAULT = (1, 2, 3)
AUTOTUNING_STAGE3_VARIANTS = "stage3_variants"
AUTOTUNING_STAGE3_VARIANT_RESIDENT = "resident"
AUTOTUNING_STAGE3_VARIANT_STREAMED = "streamed"
AUTOTUNING_STAGE3_VARIANTS_ALL = (AUTOTUNING_STAGE3_VARIANT_RESIDENT,
                                  AUTOTUNING_STAGE3_VARIANT_STREAMED)
AUTOTUNING_STAGE3_VARIANTS_DEFAULT = AUTOTUNING_STAGE3_VARIANTS_ALL
AUTOTUNING_PREFETCH_MODES = "prefetch_modes"
AUTOTUNING_PREFETCH_MODES_DEFAULT = ("carried", "off")
AUTOTUNING_STAGE3_BUCKET_SIZES = "stage3_bucket_sizes"
AUTOTUNING_STAGE3_BUCKET_SIZES_DEFAULT = (200_000,)
AUTOTUNING_MICRO_BATCHES = "micro_batches"
AUTOTUNING_MICRO_BATCHES_DEFAULT = None  # None = every divisor split
AUTOTUNING_QWZ_BITS = "qwz_bits"
AUTOTUNING_QWZ_BITS_DEFAULT = (0,)
AUTOTUNING_QGZ_BITS = "qgz_bits"
AUTOTUNING_QGZ_BITS_DEFAULT = (0,)
AUTOTUNING_HPZ_GROUP_SIZES = "hpz_group_sizes"
AUTOTUNING_HPZ_GROUP_SIZES_DEFAULT = (0,)
AUTOTUNING_FUSED = "fused"
AUTOTUNING_FUSED_DEFAULT = (False,)
AUTOTUNING_FCM = "fused_collective_matmul"
AUTOTUNING_FCM_DEFAULT = (False,)
AUTOTUNING_ONEBIT = "onebit"
AUTOTUNING_ONEBIT_DEFAULT = (False,)
AUTOTUNING_OFFLOAD_TIERS = "offload"
AUTOTUNING_OFFLOAD_TIER_NONE = "none"
AUTOTUNING_OFFLOAD_TIER_CPU = "cpu"
AUTOTUNING_OFFLOAD_TIER_NVME = "nvme"
AUTOTUNING_OFFLOAD_TIERS_ALL = (AUTOTUNING_OFFLOAD_TIER_NONE,
                                AUTOTUNING_OFFLOAD_TIER_CPU,
                                AUTOTUNING_OFFLOAD_TIER_NVME)
AUTOTUNING_OFFLOAD_TIERS_DEFAULT = (AUTOTUNING_OFFLOAD_TIER_NONE,)
AUTOTUNING_NVME_PREFETCH_DEPTHS = "nvme_prefetch_depths"
AUTOTUNING_NVME_PREFETCH_DEPTHS_DEFAULT = (2,)
AUTOTUNING_OPT_PIPELINE_DEPTHS = "opt_pipeline_depths"
AUTOTUNING_OPT_PIPELINE_DEPTHS_DEFAULT = (2,)
# raw config overlay applied to every candidate (fixed knobs)
AUTOTUNING_FIXED = "fixed"
AUTOTUNING_FIXED_DEFAULT = None
AUTOTUNING_CALIBRATION_FILE = "calibration_file"
AUTOTUNING_CALIBRATION_FILE_DEFAULT = None
# schema tags of the machine-readable artifacts
AUTOTUNE_RESULTS_SCHEMA = "ds_autotune_results_v1"
HW_CALIBRATION_SCHEMA = "ds_hw_calibration_v1"
# NVMe swap-lane fallback bandwidth (GB/s) when no aio sweep ceiling
# artifact exists on this host — deliberately conservative (a cheap
# consumer NVMe read floor) so an uncalibrated search never flatters a
# streamed config
AUTOTUNE_NVME_FALLBACK_GBPS = 3.0

#############################################
# Runtime telemetry monitor (TPU-native addition; docs/telemetry.md)
#
# Structured per-step metric records (JSONL/CSV/TensorBoard writers on a
# background thread), a Chrome/Perfetto trace-event exporter, and a
# measured-vs-predicted reconciliation report against the Program/
# Schedule Auditor's static model.  Off by default; all host reads are
# batched at flush-window boundaries so the async host loop's
# no-hot-loop-sync guarantee holds with the monitor on.
#############################################
MONITOR = "monitor"
MONITOR_ENABLED = "enabled"
MONITOR_ENABLED_DEFAULT = False
MONITOR_OUTPUT_PATH = "output_path"
MONITOR_OUTPUT_PATH_DEFAULT = "./monitor_logs"
MONITOR_JOB_NAME = "job_name"
MONITOR_JOB_NAME_DEFAULT = ""
# writer backends; jsonl is always available (no extra deps), csv is the
# fixed-column projection, tensorboard reuses the engine's own writer
MONITOR_WRITERS = "writers"
MONITOR_WRITERS_DEFAULT = ("jsonl",)
MONITOR_WRITER_KINDS = ("jsonl", "csv", "tensorboard")
# flush-window cadence in optimizer steps; None inherits steps_per_print
# (the same boundary the engine's own coalesced host reads use)
MONITOR_WRITE_INTERVAL = "write_interval"
MONITOR_WRITE_INTERVAL_DEFAULT = None
# Chrome/Perfetto trace-event export (trace.json in the output dir);
# trace_steps bounds the number of optimizer steps traced
MONITOR_TRACE = "trace"
MONITOR_TRACE_DEFAULT = False
MONITOR_TRACE_STEPS = "trace_steps"
MONITOR_TRACE_STEPS_DEFAULT = 128
# measured-vs-predicted reconciliation per flush window, with flag bands:
# measured/predicted step time above step_time_ratio_max flags (and below
# ~1.0 flags model_violation); measured HBM outside
# [1/hbm_ratio_max, hbm_ratio_max] of the liveness estimate flags;
# achieved swap read below swap_min_vs_ceiling of the aio sweep ceiling
# flags
MONITOR_RECONCILE = "reconcile"
MONITOR_RECONCILE_DEFAULT = True
MONITOR_STEP_TIME_RATIO_MAX = "step_time_ratio_max"
MONITOR_STEP_TIME_RATIO_MAX_DEFAULT = 10.0
MONITOR_HBM_RATIO_MAX = "hbm_ratio_max"
MONITOR_HBM_RATIO_MAX_DEFAULT = 2.0
MONITOR_SWAP_MIN_VS_CEILING = "swap_min_vs_ceiling"
MONITOR_SWAP_MIN_VS_CEILING_DEFAULT = 0.25
# ---- fleet observability (monitor/fleet.py, docs/telemetry.md) ------- #
# fleet: every process contributes a window vector to a boundary-only
# allgather; rank 0 emits per-host + fleet-aggregate records and every
# host runs the straggler/divergence detection (monitor/health.py)
MONITOR_FLEET = "fleet"
MONITOR_FLEET_DEFAULT = False
# heartbeat: per-host liveness files under <output_path>/heartbeat,
# written at flush boundaries (dslaunch --watch renders them)
MONITOR_HEARTBEAT = "heartbeat"
MONITOR_HEARTBEAT_DEFAULT = False
MONITOR_STRAGGLER_ZSCORE = "straggler_zscore"
MONITOR_STRAGGLER_ZSCORE_DEFAULT = 3.0
MONITOR_STRAGGLER_MIN_RATIO = "straggler_min_ratio"
MONITOR_STRAGGLER_MIN_RATIO_DEFAULT = 1.15
MONITOR_DIVERGENCE_REL_SPREAD = "divergence_rel_spread"
MONITOR_DIVERGENCE_REL_SPREAD_DEFAULT = 1e-3
MONITOR_HEALTH_WARMUP_WINDOWS = "health_warmup_windows"
MONITOR_HEALTH_WARMUP_WINDOWS_DEFAULT = 2
# Exchange deadline watchdog (monitor/fleet.py): the window allgather
# runs under a timer; on deadline the watchdog names the hosts whose
# heartbeats went dark and raises ExchangeTimeout (the monitor converts
# it into the fleet_disabled diagnostic + supervisor eviction events).
# 0 = off (the allgather may block indefinitely, as before).
MONITOR_FLEET_EXCHANGE_DEADLINE_S = "fleet_exchange_deadline_s"
MONITOR_FLEET_EXCHANGE_DEADLINE_S_DEFAULT = 0.0
# ---- anomaly-triggered deep profiling (monitor/capture.py) ----------- #
MONITOR_CAPTURE = "capture"
MONITOR_CAPTURE_ENABLED = "enabled"
MONITOR_CAPTURE_ENABLED_DEFAULT = False
MONITOR_CAPTURE_STEPS = "steps"
MONITOR_CAPTURE_STEPS_DEFAULT = 8
MONITOR_CAPTURE_MAX_CAPTURES = "max_captures"
MONITOR_CAPTURE_MAX_CAPTURES_DEFAULT = 2
MONITOR_CAPTURE_COOLDOWN_STEPS = "cooldown_steps"
MONITOR_CAPTURE_COOLDOWN_STEPS_DEFAULT = 100
MONITOR_CAPTURE_OUTPUT_PATH = "output_path"
MONITOR_CAPTURE_OUTPUT_PATH_DEFAULT = ""

# ---- MoE routing observability (monitor/moe.py, ISSUE 15) ------------ #
# Off by default; enabling it threads the RoutingStats accumulator
# through the traced step programs (moe/sharded_moe.py) and emits one
# `moe` record per flush window with the ExpertPopularitySnapshot —
# ROADMAP item 6's prefetch oracle.
MONITOR_MOE = "moe"
MONITOR_MOE_ENABLED = "enabled"
MONITOR_MOE_ENABLED_DEFAULT = False
MONITOR_MOE_EWMA_ALPHA = "popularity_ewma_alpha"
MONITOR_MOE_EWMA_ALPHA_DEFAULT = 0.2
MONITOR_MOE_HOT_K = "hot_k"
MONITOR_MOE_HOT_K_DEFAULT = 4
# health rules (health.py): a near-zero expert for K consecutive
# windows, a collapsed router entropy floor, and per-host expert-
# parallel load imbalance vs the leave-one-out peer median
MONITOR_MOE_DEAD_EXPERT_THRESHOLD = "dead_expert_threshold"
MONITOR_MOE_DEAD_EXPERT_THRESHOLD_DEFAULT = 0.02
MONITOR_MOE_DEAD_EXPERT_WINDOWS = "dead_expert_windows"
MONITOR_MOE_DEAD_EXPERT_WINDOWS_DEFAULT = 3
MONITOR_MOE_ENTROPY_FLOOR = "entropy_floor"
MONITOR_MOE_ENTROPY_FLOOR_DEFAULT = 0.05
MONITOR_MOE_COLLAPSE_WINDOWS = "collapse_windows"
MONITOR_MOE_COLLAPSE_WINDOWS_DEFAULT = 3
MONITOR_MOE_EP_IMBALANCE_RATIO = "ep_imbalance_ratio"
MONITOR_MOE_EP_IMBALANCE_RATIO_DEFAULT = 1.5
MONITOR_MOE_EP_IMBALANCE_WINDOWS = "ep_imbalance_windows"
MONITOR_MOE_EP_IMBALANCE_WINDOWS_DEFAULT = 3

#############################################
# Tensorboard
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"
# Summary-writer cadence: scalars are written (and the loss/LR device
# reads forced) only every `write_interval` steps — None inherits
# steps_per_print.  Per-step writes would force a device sync each step
# and drain the dispatch queue (the async-host-loop fix, PR 3).
TENSORBOARD_WRITE_INTERVAL = "write_interval"
TENSORBOARD_WRITE_INTERVAL_DEFAULT = None

#############################################
# ZeRO optimization
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = True

ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = None  # stage-dependent (True for 3)

ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = None  # stage-dependent

ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500_000_000

ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500_000_000

ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False

ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS = "cpu_offload_params"
ZERO_OPTIMIZATION_CPU_OFFLOAD_PARAMS_DEFAULT = False

ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY = "cpu_offload_use_pin_memory"
ZERO_OPTIMIZATION_CPU_OFFLOAD_USE_PIN_MEMORY_DEFAULT = False

ZERO_OPTIMIZATION_OFFLOAD_PARAM = "offload_param"
ZERO_OPTIMIZATION_OFFLOAD_PARAM_DEFAULT = None

ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER = "offload_optimizer"
ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER_DEFAULT = None

ZERO_OPTIMIZATION_SUB_GROUP_SIZE = "sub_group_size"
ZERO_OPTIMIZATION_SUB_GROUP_SIZE_DEFAULT = 1_000_000_000

ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS_DEFAULT = 1_000_000_000

ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
ZERO_OPTIMIZATION_MAX_REUSE_DISTANCE_DEFAULT = 1_000_000_000

ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE_DEFAULT = 50_000_000

# Prefetch program structure for the streamed layer scan
# (zero/stage3_streaming.py): "carried" = double-buffered scan carry,
# gathers verified statically off the critical path in both directions;
# "unrolled" = legacy unroll-2 body (overlap left to XLA's scheduler);
# "off" = gather at use.  Prefetch engages in any mode only when
# stage3_prefetch_bucket_size covers a layer group.
ZERO_OPTIMIZATION_PREFETCH_MODE = "stage3_prefetch_mode"
ZERO_OPTIMIZATION_PREFETCH_MODE_DEFAULT = "carried"
ZERO_OPTIMIZATION_PREFETCH_MODES = ("carried", "unrolled", "off")

ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD_DEFAULT = 100_000

ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE = (
    "stage3_gather_fp16_weights_on_model_save")
ZERO_OPTIMIZATION_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE_DEFAULT = False

ZERO_OPTIMIZATION_IGNORE_UNUSED_PARAMETERS = "ignore_unused_parameters"
ZERO_OPTIMIZATION_IGNORE_UNUSED_PARAMETERS_DEFAULT = True

ZERO_OPTIMIZATION_LEGACY_STAGE1 = "legacy_stage1"
ZERO_OPTIMIZATION_LEGACY_STAGE1_DEFAULT = False

ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

# ZeRO++-style low-bandwidth collectives (arXiv:2306.10209;
# runtime/comm/low_bandwidth.py).  Each knob is independently off by
# default; bits are 0 (off), 4, or 8.
ZERO_OPTIMIZATION_LOW_BANDWIDTH = "low_bandwidth"
LOW_BANDWIDTH_QWZ_BITS = "qwz_bits"            # quantized weight all-gather
LOW_BANDWIDTH_QWZ_BITS_DEFAULT = 0
LOW_BANDWIDTH_QGZ_BITS = "qgz_bits"            # quantized grad reduce-scatter
LOW_BANDWIDTH_QGZ_BITS_DEFAULT = 0
LOW_BANDWIDTH_HPZ_GROUP_SIZE = "hpz_group_size"  # secondary-partition size
LOW_BANDWIDTH_HPZ_GROUP_SIZE_DEFAULT = 0
LOW_BANDWIDTH_BLOCK_SIZE = "block_size"        # quantization block elements
LOW_BANDWIDTH_BLOCK_SIZE_DEFAULT = 256
# T3-style fused collective-matmul (ops/collective_matmul.py,
# docs/fused_collective_matmul.md): the qwZ/qgZ transports move per-TILE
# (quantized shard tiles ride a ring as the producer/consumer GEMM's
# tiles complete) instead of as one monolithic collective
LOW_BANDWIDTH_FCM = "fused_collective_matmul"
LOW_BANDWIDTH_FCM_DEFAULT = False
# 1-bit optimizer wire tier (reference runtime/comm/nccl.py
# compressed_allreduce; docs/onebit.md): after the optimizer's
# freeze_step the data-parallel grad allreduce is removed from the grad
# program and replaced by an error-feedback sign+scale momentum sync on
# a packed int8 wire (comm/compressed.py wire="packed").  Requires a
# onebit optimizer (OneBitAdam/OneBitLamb) and ZeRO stage <= 2.
LOW_BANDWIDTH_ONEBIT = "onebit"
LOW_BANDWIDTH_ONEBIT_DEFAULT = False
# name-scope marker the fused collective-matmul ops trace under; the
# Schedule Auditor's overlap classifier (analysis/overlap.py) reads it
# off eqn name stacks to classify the per-tile transports as
# fused/hidden — single-sourced here so the op and the analyzer can
# never disagree on the spelling
FCM_SCOPE = "fcm_fused"
# name-scope marker the packed 1-bit momentum-sync transport traces
# under (comm/compressed.py wire="packed"); collective_wire_bytes and
# the Schedule Auditor read it off eqn name stacks for attribution —
# single-sourced here like FCM_SCOPE
ONEBIT_SCOPE = "onebit_packed"

#############################################
# Offload (reference: runtime/zero/offload_constants.py)
#############################################
OFFLOAD_CPU_DEVICE = "cpu"
OFFLOAD_NVME_DEVICE = "nvme"

OFFLOAD_PARAM = "offload_param"
OFFLOAD_PARAM_DEVICE = "device"
OFFLOAD_PARAM_DEVICE_DEFAULT = OFFLOAD_CPU_DEVICE
OFFLOAD_PARAM_NVME_PATH = "nvme_path"
OFFLOAD_PARAM_NVME_PATH_DEFAULT = None
OFFLOAD_PARAM_BUFFER_COUNT = "buffer_count"
OFFLOAD_PARAM_BUFFER_COUNT_DEFAULT = 5
OFFLOAD_PARAM_BUFFER_SIZE = "buffer_size"
OFFLOAD_PARAM_BUFFER_SIZE_DEFAULT = 100_000_000
OFFLOAD_PARAM_MAX_IN_CPU = "max_in_cpu"
OFFLOAD_PARAM_MAX_IN_CPU_DEFAULT = 1_000_000_000
OFFLOAD_PARAM_PIN_MEMORY = "pin_memory"
OFFLOAD_PARAM_PIN_MEMORY_DEFAULT = False
# NVMe swap-in look-ahead for the streaming engine (zero/infinity.py):
# number of pinned window buffers the step may hold in flight at once —
# 2 = double buffer (group i computing, group i+1 reading), the carried
# discipline of PR 7 one tier down; < 2 serializes swap-ins at use.
# Must fit in buffer_count.
OFFLOAD_PARAM_PREFETCH_DEPTH = "prefetch_depth"
OFFLOAD_PARAM_PREFETCH_DEPTH_DEFAULT = 2

OFFLOAD_OPTIMIZER = "offload_optimizer"
OFFLOAD_OPTIMIZER_DEVICE = "device"
OFFLOAD_OPTIMIZER_DEVICE_DEFAULT = OFFLOAD_CPU_DEVICE
OFFLOAD_OPTIMIZER_NVME_PATH = "nvme_path"
OFFLOAD_OPTIMIZER_NVME_PATH_DEFAULT = None
OFFLOAD_OPTIMIZER_BUFFER_COUNT = "buffer_count"
OFFLOAD_OPTIMIZER_BUFFER_COUNT_DEFAULT = 4
OFFLOAD_OPTIMIZER_PIN_MEMORY = "pin_memory"
OFFLOAD_OPTIMIZER_PIN_MEMORY_DEFAULT = False
OFFLOAD_OPTIMIZER_PIPELINE_READ = "pipeline_read"
OFFLOAD_OPTIMIZER_PIPELINE_READ_DEFAULT = False
OFFLOAD_OPTIMIZER_PIPELINE_WRITE = "pipeline_write"
OFFLOAD_OPTIMIZER_PIPELINE_WRITE_DEFAULT = False
OFFLOAD_OPTIMIZER_PIPELINE = "pipeline"
OFFLOAD_OPTIMIZER_FAST_INIT = "fast_init"
OFFLOAD_OPTIMIZER_FAST_INIT_DEFAULT = False
# Leaf-pipeline depth of the NVMe optimizer sweep (optimizer_swapper.py):
# number of rotating (param, exp_avg, exp_avg_sq) buffer triples — depth D
# overlaps leaf i's Adam with leaf i+1's read and leaf i-(D-1)'s
# write-back.  >= 2 (the reference PipelinedOptimizerSwapper is depth 2).
OFFLOAD_OPTIMIZER_PIPELINE_DEPTH = "pipeline_depth"
OFFLOAD_OPTIMIZER_PIPELINE_DEPTH_DEFAULT = 2

#############################################
# Async I/O (reference: runtime/swap_tensor/constants.py)
#############################################
AIO = "aio"
AIO_BLOCK_SIZE = "block_size"
AIO_BLOCK_SIZE_DEFAULT = 1048576
AIO_QUEUE_DEPTH = "queue_depth"
AIO_QUEUE_DEPTH_DEFAULT = 8
AIO_THREAD_COUNT = "thread_count"
AIO_THREAD_COUNT_DEFAULT = 1
AIO_SINGLE_SUBMIT = "single_submit"
AIO_SINGLE_SUBMIT_DEFAULT = False
AIO_OVERLAP_EVENTS = "overlap_events"
AIO_OVERLAP_EVENTS_DEFAULT = True
# Engine selection (this repo's addition — the reference hardwires libaio):
#   io_uring   kernel SQ/CQ rings, runtime-probed (csrc/aio/uring_aio.cpp)
#   batched    portable batched-submission preadv/pwritev pool
#   threadpool the original one-syscall-per-chunk pool
#   auto       io_uring when available, else batched
AIO_BACKEND = "backend"
AIO_BACKEND_AUTO = "auto"
AIO_BACKEND_IO_URING = "io_uring"
AIO_BACKEND_BATCHED = "batched"
AIO_BACKEND_THREADPOOL = "threadpool"
AIO_BACKENDS = (AIO_BACKEND_AUTO, AIO_BACKEND_IO_URING,
                AIO_BACKEND_BATCHED, AIO_BACKEND_THREADPOOL)
AIO_BACKEND_DEFAULT = AIO_BACKEND_AUTO
AIO_BLOCK_SIZE_MIN = 4096  # O_DIRECT-friendly floor (engines clamp too)

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_PROFILE_DEFAULT = False
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT = False

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Flops profiler
#############################################
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 1
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True
FLOPS_PROFILER_OUTPUT_FILE = "output_file"
FLOPS_PROFILER_OUTPUT_FILE_DEFAULT = None

#############################################
# Eigenvalue (MoQ support)
#############################################
EIGENVALUE = "eigenvalue"
EIGENVALUE_ENABLED = "enabled"
EIGENVALUE_ENABLED_DEFAULT = False
EIGENVALUE_VERBOSE = "verbose"
EIGENVALUE_VERBOSE_DEFAULT = False
EIGENVALUE_MAX_ITER = "max_iter"
EIGENVALUE_MAX_ITER_DEFAULT = 100
EIGENVALUE_TOL = "tol"
EIGENVALUE_TOL_DEFAULT = 1e-2
EIGENVALUE_STABILITY = "stability"
EIGENVALUE_STABILITY_DEFAULT = 1e-6
EIGENVALUE_GAS_BOUNDARY_RESOLUTION = "gas_boundary_resolution"
EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT = 1
EIGENVALUE_LAYER_NAME = "layer_name"
EIGENVALUE_LAYER_NAME_DEFAULT = "bert.encoder.layer"
EIGENVALUE_LAYER_NUM = "layer_num"
EIGENVALUE_LAYER_NUM_DEFAULT = 0

#############################################
# Progressive layer drop / curriculum
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

CURRICULUM_LEARNING = "curriculum_learning"
CURRICULUM_ENABLED = "enabled"
CURRICULUM_ENABLED_DEFAULT = False

#############################################
# Quantize training (MoQ)
#############################################
QUANTIZE_TRAINING = "quantize_training"
QUANTIZE_BITS = "quantize_bits"
START_BITS = "start_bits"
TARGET_BITS = "target_bits"
QUANTIZER_KERNEL = "quantizer_kernel"
QUANTIZE_SCHEDULE = "quantize_schedule"
QUANTIZE_PERIOD = "quantize_period"
SCHEDULE_OFFSET = "schedule_offset"
QUANTIZE_GROUPS = "quantize_groups"
FP16_MIXED_QUANTIZE = "fp16_mixed_quantize"
QUANTIZE_CHANGE_RATIO = "quantize_change_ratio"
FP16_MIXED_QUANTIZE_ENABLED = "enabled"
QUANTIZE_VERBOSE = "quantize_verbose"
QUANTIZE_ALGO = "quantize_algo"
QUANTIZE_TYPE = "q_type"
QUANTIZE_SYMMETRIC = "symmetric"
QUANTIZE_ASYMMETRIC = "asymmetric"
STOCHASTIC_ROUNDING = "stochastic"
NEAREST_ROUNDING = "nearest"
QUANTIZE_ROUNDING = "rounding"
QUANTIZE_TRAINING_ENABLED = "enabled"
QUANTIZE_TRAINING_ENABLED_DEFAULT = False
QUANTIZE_START_BITS_DEFAULT = 16
QUANTIZE_TARGET_BITS_DEFAULT = 8
QUANTIZER_KERNEL_DEFAULT = False
QUANTIZE_PERIOD_DEFAULT = 1000
QUANTIZE_OFFSET_DEFAULT = 1000
QUANTIZE_GROUPS_DEFAULT = 1
QUANTIZE_TYPE_DEFAULT = 0  # symmetric
QUANTIZE_ROUNDING_DEFAULT = 0  # nearest
FP16_MIXED_QUANTIZE_ENABLED_DEFAULT = False
QUANTIZE_CHANGE_RATIO_DEFAULT = 0.001
QUANTIZE_VERBOSE_DEFAULT = False

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"


class ValidationMode:
    WARN = "WARN"
    IGNORE = "IGNORE"
    FAIL = "FAIL"


CHECKPOINT_TAG_VALIDATION_DEFAULT = ValidationMode.WARN
CHECKPOINT_TAG_VALIDATION_MODES = [
    ValidationMode.WARN, ValidationMode.IGNORE, ValidationMode.FAIL
]

#############################################
# Resilience (fault tolerance; TPU-native addition — preemptible pods
# make checkpoint durability and run-health first-class.  All off by
# default: with the block absent the engine behaves exactly as before.)
#############################################
RESILIENCE = "resilience"
RESILIENCE_ENABLED = "enabled"
RESILIENCE_ENABLED_DEFAULT = False
# Atomic commit protocol: write the tag dir as <tag>.tmp.<nonce>, fsync,
# manifest with per-file size+CRC32, os.replace into place, `latest` last.
RESILIENCE_ATOMIC_CHECKPOINTS = "atomic_checkpoints"
RESILIENCE_ATOMIC_CHECKPOINTS_DEFAULT = True
# Validate the manifest on load; fall back to the newest intact tag.
RESILIENCE_VERIFY_ON_LOAD = "verify_on_load"
RESILIENCE_VERIFY_ON_LOAD_DEFAULT = True
# Bound on how many candidate tags the corruption fallback will scan.
RESILIENCE_MAX_FALLBACK_TAGS = "max_fallback_tags"
RESILIENCE_MAX_FALLBACK_TAGS_DEFAULT = 8
# Retention/GC: keep the newest N tags (0 = no GC); tags whose trailing
# step number is a multiple of keep_every are kept forever.  The tag
# `latest` points to is never deleted.
RESILIENCE_KEEP_LAST_N = "keep_last_n"
RESILIENCE_KEEP_LAST_N_DEFAULT = 0
RESILIENCE_KEEP_EVERY = "keep_every"
RESILIENCE_KEEP_EVERY_DEFAULT = 0
# Retry/backoff wrapper around checkpoint IO (transient FS errors).
RESILIENCE_IO_RETRIES = "io_retries"
RESILIENCE_IO_RETRIES_DEFAULT = 3
RESILIENCE_IO_BACKOFF_SECONDS = "io_backoff_seconds"
RESILIENCE_IO_BACKOFF_SECONDS_DEFAULT = 0.5
# RetryPolicy extras (resilience/retry.py): seeded jitter keeps the
# backoff sequence reproducible; the cap bounds the exponential.
RESILIENCE_RETRY_JITTER = "retry_jitter"
RESILIENCE_RETRY_JITTER_DEFAULT = 0.25
RESILIENCE_RETRY_SEED = "retry_seed"
RESILIENCE_RETRY_SEED_DEFAULT = 0
RESILIENCE_RETRY_MAX_BACKOFF_SECONDS = "retry_max_backoff_seconds"
RESILIENCE_RETRY_MAX_BACKOFF_SECONDS_DEFAULT = 30.0
# Lockstep-signature re-verify on resume (resilience/reshard.py): a
# same-topology resume must reproduce the checkpoint's saved collective
# lockstep signature; a resharded resume re-verifies multihost
# agreement on the new signature instead.
RESILIENCE_VERIFY_LOCKSTEP_ON_RESUME = "verify_lockstep_on_resume"
RESILIENCE_VERIFY_LOCKSTEP_ON_RESUME_DEFAULT = True

# -- preemption sub-block ------------------------------------------- #
RESILIENCE_PREEMPTION = "preemption"
PREEMPTION_ENABLED = "enabled"
PREEMPTION_ENABLED_DEFAULT = False
PREEMPTION_SIGNALS = "signals"            # e.g. ["SIGTERM", "SIGINT"]
PREEMPTION_SIGNALS_DEFAULT = ("SIGTERM", "SIGINT")
PREEMPTION_EMERGENCY_TAG_PREFIX = "emergency_tag_prefix"
PREEMPTION_EMERGENCY_TAG_PREFIX_DEFAULT = "emergency"
PREEMPTION_SAVE_DIR = "save_dir"          # None → last save_checkpoint dir
PREEMPTION_SAVE_DIR_DEFAULT = None
PREEMPTION_RERAISE = "reraise"            # restore handler + re-deliver
PREEMPTION_RERAISE_DEFAULT = True
# Grace deadline: if no step boundary is reached within grace_s of the
# signal, force-save the LAST COMPLETED step from a timer thread (tag
# suffix "_forced") instead of losing the tag entirely.  0 = off.
PREEMPTION_GRACE_S = "grace_s"
PREEMPTION_GRACE_S_DEFAULT = 0.0

# -- training-health sentinel sub-block ----------------------------- #
RESILIENCE_SENTINEL = "sentinel"
SENTINEL_ENABLED = "enabled"
SENTINEL_ENABLED_DEFAULT = False
SENTINEL_EWMA_ALPHA = "ewma_alpha"
SENTINEL_EWMA_ALPHA_DEFAULT = 0.02
SENTINEL_K_SIGMA = "k_sigma"
SENTINEL_K_SIGMA_DEFAULT = 6.0
SENTINEL_WARMUP_STEPS = "warmup_steps"
SENTINEL_WARMUP_STEPS_DEFAULT = 20
SENTINEL_POLICY = "policy"                # warn | skip_step | rewind
SENTINEL_POLICY_DEFAULT = "warn"
SENTINEL_POLICIES = ("warn", "skip_step", "rewind")
SENTINEL_ANOMALY_BUDGET = "anomaly_budget"  # consecutive anomalies → abort
SENTINEL_ANOMALY_BUDGET_DEFAULT = 5
SENTINEL_MONITOR_GRAD_NORM = "monitor_grad_norm"
SENTINEL_MONITOR_GRAD_NORM_DEFAULT = True

# -- chaos sub-block (resilience/chaos.py) --------------------------- #
# Seeded deterministic fault injection, off by default.  `faults` is a
# list of {point, kind, at_call|at_step|after_bytes, repeat, args}
# specs validated against the injection-point catalog at config time.
RESILIENCE_CHAOS = "chaos"
CHAOS_ENABLED = "enabled"
CHAOS_ENABLED_DEFAULT = False
CHAOS_SEED = "seed"
CHAOS_SEED_DEFAULT = 0
CHAOS_FAULTS = "faults"
CHAOS_FAULTS_DEFAULT = ()

#############################################
# Elasticity (reference: deepspeed/elasticity/constants.py)
#############################################
ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]
MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000
MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0
VERSION = "version"
VERSION_DEFAULT = 0.1
LATEST_ELASTICITY_VERSION = 0.1
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False
PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

#############################################
# TPU-native additions (no reference analog)
#############################################
# Mesh shape / named axes: {"data": -1, "model": 1, "pipe": 1, "expert": 1,
#                           "seq": 1}
MESH = "mesh"
MESH_DATA_AXIS = "data"
MESH_MODEL_AXIS = "model"
MESH_PIPE_AXIS = "pipe"
MESH_EXPERT_AXIS = "expert"
MESH_SEQ_AXIS = "seq"

# Sequence parallelism (ring attention / Ulysses) — the modern long-context
# layer the 2021 reference lacks (SURVEY.md §5).
SEQUENCE_PARALLEL = "sequence_parallel"
SEQUENCE_PARALLEL_MODE = "mode"  # "ring" | "ulysses"
SEQUENCE_PARALLEL_MODE_DEFAULT = "ring"
SEQUENCE_PARALLEL_SIZE = "size"
SEQUENCE_PARALLEL_SIZE_DEFAULT = 1

# Pipeline config (reference passes these via PipelineModule kwargs).
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = 1
PIPELINE_PARTITION_METHOD = "partition_method"
PIPELINE_PARTITION_METHOD_DEFAULT = "parameters"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0
