"""Native-op build system — the analog of the reference's op_builder/
(builder.py:112 OpBuilder, load:344/jit_load:356, ALL_OPS registry
op_builder/__init__.py:18-30).

The reference JIT-compiles CUDA extensions with ninja+nvcc; here the native
pieces are host-side C++ (OpenMP/auto-vectorized) compiled with g++ into
shared libraries loaded via ctypes — no torch extension machinery, no
pybind11 dependency.  Pallas kernels need no native build at all; only the
genuinely-host components (Adam/LAMB for offloaded shards, the async file
I/O engine) live here.

Build artifacts land in <repo>/build/<name>-<srchash>.so; a content hash in
the filename makes staleness detection automatic.
"""

import ctypes
import hashlib
import os
import platform
import subprocess
from typing import Dict, List

from ..utils.logging import logger

def _cpu_identity() -> str:
    """CPU model + ISA flags (what -march=native actually binds to)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags", "Features")):
                    return line.strip()
    except OSError:
        pass
    return platform.processor() or "unknown-cpu"


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
CSRC_DIR = os.path.join(_REPO_ROOT, "csrc")
BUILD_DIR = os.environ.get(
    "DS_BUILD_DIR", os.path.join(_REPO_ROOT, "build"))


class OpBuilder:
    """Compile-and-load for one native op (reference: builder.py:112).

    Subclasses define NAME, sources(), and optionally cxx_flags()/ldflags()
    and is_compatible().  load() returns a ctypes.CDLL (cached per-process),
    compiling first if the source hash has no built artifact yet.
    """

    NAME = "base"
    _cache: Dict[str, ctypes.CDLL] = {}

    def sources(self) -> List[str]:
        raise NotImplementedError

    def cxx_flags(self) -> List[str]:
        flags = ["-O3", "-std=c++17", "-fPIC", "-shared", "-fopenmp"]
        if os.environ.get("DS_NATIVE_ARCH", "1") == "1":
            flags.append("-march=native")
        return flags

    def ldflags(self) -> List[str]:
        return []

    def compiler(self) -> str:
        return os.environ.get("CXX", "g++")

    def is_compatible(self) -> bool:
        """Probe the toolchain/OS the way the reference probes libaio/CUDA
        (op_builder/async_io.py:106)."""
        try:
            subprocess.run([self.compiler(), "--version"],
                           capture_output=True, check=True)
            return True
        except (OSError, subprocess.CalledProcessError):
            return False

    # ------------------------------------------------------------------ #
    def hash_inputs(self) -> List[str]:
        """Files whose content keys the build artifact — sources plus any
        private headers (not passed to the compiler, but staleness-
        relevant all the same)."""
        return self.sources()

    def _src_hash(self) -> str:
        h = hashlib.sha256()
        for src in self.hash_inputs():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.cxx_flags() + self.ldflags()).encode())
        # -march=native makes the artifact CPU-specific: key it on the CPU
        # identity so a binary built elsewhere is never loaded (SIGILL risk)
        h.update(platform.machine().encode())
        h.update(_cpu_identity().encode())
        return h.hexdigest()[:16]

    def lib_path(self) -> str:
        return os.path.join(BUILD_DIR, f"{self.NAME}-{self._src_hash()}.so")

    def build(self) -> str:
        path = self.lib_path()
        if os.path.exists(path):
            return path
        os.makedirs(BUILD_DIR, exist_ok=True)
        cmd = ([self.compiler()] + self.cxx_flags() + self.sources() +
               self.ldflags() + ["-o", path + ".tmp"])
        logger.info(f"building native op {self.NAME}: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, capture_output=True, check=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build of {self.NAME} failed:\n{e.stderr}") from e
        os.replace(path + ".tmp", path)  # atomic vs concurrent builders
        return path

    def load(self) -> ctypes.CDLL:
        key = self.lib_path()
        if key not in OpBuilder._cache:
            OpBuilder._cache[key] = ctypes.CDLL(self.build())
        return OpBuilder._cache[key]


class CPUAdamBuilder(OpBuilder):
    """Host Adam/AdamW for offloaded optimizer shards
    (reference: op_builder/cpu_adam.py + csrc/adam/cpu_adam.cpp)."""

    NAME = "cpu_adam"

    def sources(self):
        return [os.path.join(CSRC_DIR, "adam", "host_adam.cpp")]


class AsyncIOBuilder(OpBuilder):
    """Async NVMe file I/O engine (reference: op_builder/async_io.py +
    csrc/aio/).  Two sources: the portable pool engines (threadpool +
    batched-submit preadv/pwritev) and the io_uring ring engine, which is
    compiled everywhere but RUNTIME-probed (ds_uring_probe) — the
    reference probes libaio at build time (async_io.py:106); io_uring
    availability is a kernel/sandbox property, so the probe moves to
    ds_aio_create2 time and aio_handle.py falls back loudly."""

    NAME = "async_io"

    def sources(self):
        return [os.path.join(CSRC_DIR, "aio", "host_aio.cpp"),
                os.path.join(CSRC_DIR, "aio", "uring_aio.cpp")]

    def hash_inputs(self):
        return self.sources() + [os.path.join(CSRC_DIR, "aio",
                                              "aio_backend.h")]

    def ldflags(self):
        return ["-lpthread"]


ALL_OPS: Dict[str, type] = {
    "cpu_adam": CPUAdamBuilder,
    "async_io": AsyncIOBuilder,
}


def op_report() -> Dict[str, Dict[str, object]]:
    """Availability report per op — the `ds_report` data source
    (reference: env_report.py)."""
    report = {}
    for name, cls in ALL_OPS.items():
        builder = cls()
        compatible = builder.is_compatible()
        built = False
        if compatible:
            try:
                built = os.path.exists(builder.lib_path())
            except OSError:
                compatible = False
        report[name] = {"compatible": compatible, "built": built}
    return report
