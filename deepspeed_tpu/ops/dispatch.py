"""Kernel-backend dispatch switch.

Pallas TPU kernels (flash attention, fused LN) must not lower on CPU
(pallas supports only interpret mode there), and the usual gate —
``jax.default_backend() == "tpu"`` — is wrong in one real scenario: a
process that touched the TPU backend first and then forced
``jax_platforms=cpu`` (the multichip CPU-sim dryrun) still reports "tpu".
This module gives such callers an explicit override, also settable via
``DS_FORCE_XLA_OPS=1``.
"""

import os

import jax

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_force_xla = bool(int(os.environ.get("DS_FORCE_XLA_OPS", "0")))


def force_xla_kernels(on: bool = True) -> None:
    """Route all op dispatchers to their XLA reference paths (no Pallas)."""
    global _force_xla
    _force_xla = on


def pallas_available() -> bool:
    """True when Pallas TPU kernels may be used in this process."""
    return (not _force_xla and pltpu is not None
            and jax.default_backend() == "tpu")
