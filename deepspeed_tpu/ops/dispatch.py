"""Kernel-backend dispatch switch.

Pallas TPU kernels (flash attention, fused LN) must not lower on CPU
(pallas supports only interpret mode there), and the usual gate —
``jax.default_backend() == "tpu"`` — is wrong in one real scenario: a
process that touched the TPU backend first and then forced
``jax_platforms=cpu`` (the multichip CPU-sim dryrun) still reports "tpu".
This module gives such callers an explicit override, also settable via
``DS_FORCE_XLA_OPS=1``.
"""

import os

import jax

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_force_xla = bool(int(os.environ.get("DS_FORCE_XLA_OPS", "0")))

# Per-op implementation preferences, where measurement picked a default
# that differs from "pallas wherever possible".  LayerNorm: measured on
# v5e (benchmarks/session_r3/ablations2.log, 2026-07-31) the XLA LN
# beats the Pallas LN kernels by ~2 ms on the flagship step — XLA fuses
# LN into neighboring elementwise work, which a pallas_call is opaque
# to.  DS_LN_IMPL=pallas (or set_ln_impl) re-enables the kernels for
# re-measurement on new hardware/toolchains.
_ln_impl = os.environ.get("DS_LN_IMPL", "xla")


def force_xla_kernels(on: bool = True) -> None:
    """Route all op dispatchers to their XLA reference paths (no Pallas)."""
    global _force_xla
    _force_xla = on


def pallas_available() -> bool:
    """True when Pallas TPU kernels may be used in this process."""
    return (not _force_xla and pltpu is not None
            and jax.default_backend() == "tpu")


def set_ln_impl(impl: str) -> None:
    """Select the LayerNorm implementation: "xla" (measured default) or
    "pallas" (the Pallas kernels, for re-measurement)."""
    if impl not in ("xla", "pallas"):
        raise ValueError(f"ln impl must be 'xla' or 'pallas', got {impl!r}")
    global _ln_impl
    _ln_impl = impl


def ln_impl() -> str:
    """Active LayerNorm implementation ("xla" wins under force_xla)."""
    return "xla" if _force_xla else _ln_impl
