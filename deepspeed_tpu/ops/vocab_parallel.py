"""Vocab-parallel embedding + fused linear cross-entropy for MANUAL TP.

The role of Megatron's VocabParallelEmbedding + vocab-parallel CE (the
reference delegates both to the external Megatron mpu — SURVEY.md §2.3
"TP: integration, not implementation").  The GSPMD engines already
vocab-shard the embedding declaratively (models/gpt2.py
param_partition_specs); THESE ops are for shard_map-manual regions —
the gated 1F1B executor — where GSPMD-placed collectives would land in
divergent control flow (ops/transformer.py tp_axis mode has the full
story, ARCHITECTURE.md invariant 10).

Collective/AD discipline under check_vma=False:
  - the embedding merge is a "g" operator (psum forward, identity
    backward): the arriving output cotangent is already full, and each
    peer's masked scatter-add against it is its exact local wte grad;
  - the cross-entropy is ONE custom_vjp whose backward is local given
    the global softmax statistics (max, sum-exp) — the classic
    vocab-parallel softmax identity dlogits = p - onehot — with the
    input-activation cotangent psum'd INSIDE the backward (the "f"
    position at the head boundary), so LN/residual grads upstream are
    exact per-device with no post-hoc correction.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .tp_collectives import tp_psum


def vocab_parallel_embedding(wte_local, ids, axis):
    """Lookup into a vocab-sharded table inside a manual region.

    wte_local: [V_local, H] — this peer's contiguous vocab slice
    (slice p covers rows [p*V_local, (p+1)*V_local)).
    ids: int [...] global token ids.  Returns [..., H] replicated.
    """
    v_local = wte_local.shape[0]
    start = lax.axis_index(axis) * v_local
    local = ids - start
    mask = (local >= 0) & (local < v_local)
    safe = jnp.where(mask, local, 0)
    part = wte_local[safe] * mask[..., None].astype(wte_local.dtype)
    return tp_psum(part, axis)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def vocab_parallel_linear_cross_entropy(h, w_local, labels, axis):
    """mean softmax-CE of logits = h @ w_local over a vocab-sharded head.

    h: [N, H] replicated; w_local: [H, V_local] this peer's vocab slice;
    labels: int [N] global ids.  Returns the scalar fp32 mean loss
    (identical on every peer).  Numerically matches
    optax.softmax_cross_entropy_with_integer_labels on the full fp32
    logits: loss_i = log(sum_v exp(l_iv)) - l_i,label, computed with the
    global row max subtracted.
    """
    loss, _ = _vp_ce_stats(h, w_local, labels, axis)
    return loss


def _vp_ce_stats(h, w_local, labels, axis):
    v_local = w_local.shape[1]
    start = lax.axis_index(axis) * v_local
    logits = jnp.matmul(h, w_local,
                        preferred_element_type=jnp.float32)  # [N, Vl]
    m = lax.pmax(jnp.max(logits, axis=-1), axis)             # [N] global max
    se = lax.psum(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1), axis)
    local = labels - start
    mask = (local >= 0) & (local < v_local)
    safe = jnp.where(mask, local, 0)
    picked = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
    ll = lax.psum(jnp.where(mask, picked, 0.0), axis)        # label logit
    loss = jnp.mean(jnp.log(se) + m - ll)
    return loss, (m, se)


def _vp_ce_fwd(h, w_local, labels, axis):
    loss, (m, se) = _vp_ce_stats(h, w_local, labels, axis)
    return loss, (h, w_local, labels, m, se)


def _vp_ce_bwd(axis, res, g):
    h, w_local, labels, m, se = res
    v_local = w_local.shape[1]
    start = lax.axis_index(axis) * v_local
    n = h.shape[0]
    # recompute the local logits (cheaper than saving [N, Vl] fp32)
    logits = jnp.matmul(h, w_local, preferred_element_type=jnp.float32)
    p = jnp.exp(logits - m[:, None]) / se[:, None]
    local = labels - start
    mask = (local >= 0) & (local < v_local)
    onehot = (jnp.arange(v_local)[None, :] == local[:, None]) & mask[:, None]
    dlogits = (p - onehot.astype(p.dtype)) * (g / n)
    # "f" position: each peer's dh is only its vocab slice's partial
    dh = lax.psum(jnp.matmul(dlogits, w_local.T.astype(dlogits.dtype)),
                  axis).astype(h.dtype)
    dw = jnp.matmul(h.T.astype(dlogits.dtype), dlogits).astype(w_local.dtype)
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dh, dw, dlabels


vocab_parallel_linear_cross_entropy.defvjp(_vp_ce_fwd, _vp_ce_bwd)
