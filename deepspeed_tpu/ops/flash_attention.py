"""Flash attention for TPU — the Pallas analog of the reference's fused
attention kernels (csrc/transformer/softmax_kernels.cu:595 fused
scale+mask+softmax + cublas strided-batch QK^T/PV matmuls,
csrc/transformer/inference/csrc/softmax.cu).

Instead of materializing the [S, S] score matrix in HBM between three kernel
launches like the CUDA reference, the whole QK^T -> online-softmax -> PV chain
runs in one Pallas kernel, streaming K/V blocks through VMEM with fp32
accumulators (flash-attention style).  The MXU sees two big matmuls per block
pair; HBM traffic is O(S*d) instead of O(S^2).

Backward is the FlashAttention-2 scheme: forward saves only the per-row
logsumexp; two Pallas kernels recompute P block-wise and produce dk/dv
(grid over k blocks) and dq (grid over q blocks) with no [S, S] HBM
materialization.  The XLA reference path serves CPU and the bias/fallback
cases.
"""

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific pallas bits are unavailable on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# Finite mask value: keeps running-max finite for fully-masked rows (an -inf
# row max would turn exp(s - m) into NaN).
DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

_LANES = 128  # TPU lane width; softmax stats are carried at this width
# Row statistics (logsumexp, delta) ride as [B,H,S,8] so their blocks satisfy
# Mosaic's last-two-dims tiling rule; lane 0 holds the value.
_STATS_LANES = 8


# --------------------------------------------------------------------------- #
# Reference implementation (also the backward path and the CPU fallback)
# --------------------------------------------------------------------------- #
def mha_reference(q, k, v, causal: bool = False,
                  sm_scale: Optional[float] = None, bias=None,
                  dropout_rate: float = 0.0, dropout_seed=None):
    """Plain-XLA multi-head attention: q,k,v [B, H, S, D] -> [B, H, S, D].

    fp32 softmax regardless of input dtype (matches the reference kernels,
    which upcast for the softmax — softmax_kernels.cu attn_softmax).
    dropout_rate > 0 applies PROBABILITY dropout (on the normalized
    softmax, the reference's attn-dropout semantics —
    dropout_kernels.cu:868) keyed by the int32 dropout_seed; the mask
    stream differs from the Pallas kernel's in-kernel PRNG, so the two
    paths agree in distribution, not bit-for-bit."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        q_len, k_len = s.shape[-2], s.shape[-1]
        idx_q = jax.lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        idx_k = jax.lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(idx_k > idx_q, DEFAULT_MASK_VALUE, s)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        keep = jax.random.bernoulli(
            jax.random.PRNGKey(jnp.asarray(dropout_seed, jnp.int32)),
            1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# --------------------------------------------------------------------------- #
# Pallas kernel
# --------------------------------------------------------------------------- #
def _ld(ref):
    """Load the [rows, d] tile from a (1, 1, rows, d) block."""
    return ref[0, 0]


def _st(ref, val):
    ref[0, 0] = val


def causal_keep_mask(qi_block, ki_block, block_q, block_k):
    """[block_q, block_k] keep mask (col <= row) from ABSOLUTE block
    indices — the one causal-tile mask shared by the dense fwd/bwd kernels
    and the block-sparse kernels (block_sparse_flash.py)."""
    row = qi_block * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    col = ki_block * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return col <= row


# Dropout PRNG width: 8 (default since r4 session 2) generates one
# random word per FOUR mask positions and compares bytes — 4x fewer
# PRNG words in each of the three kernels that regenerate the mask,
# bias-corrected by the exact quantized keep probability; 32 is one
# word per mask BIT (the conservative fallback, and forced whenever
# block_k % 4 != 0 — _effective_dropout_bits).  Chip-validated r4 at
# both widths (statistics + FD); flagship A/B: 86.99 vs 84.67 TFLOPS
# dropout-on (+2.7%).  Flip with DS_DROPOUT_BITS or set_dropout_bits;
# the mode is read at TRACE time, so fwd and bwd of one step always
# agree (both trace under one jit).
def _parse_dropout_bits(raw: str) -> int:
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"DS_DROPOUT_BITS={raw!r}: must be 8 or 32") from None
    if n not in (8, 32):
        raise ValueError(f"DS_DROPOUT_BITS must be 8 or 32, got {n}")
    return n


_DEFAULT_DROPOUT_BITS = 8
_dropout_bits = _parse_dropout_bits(
    os.environ.get("DS_DROPOUT_BITS", str(_DEFAULT_DROPOUT_BITS)))


def set_dropout_bits(n: int) -> None:
    """Select the in-kernel dropout PRNG width (8 default — 4x cheaper
    mask generation at 1/256 keep-probability granularity, bias-corrected
    by the exact quantized scale; 32 = one word per bit).

    Read at TRACE time: already-jit-compiled functions keep the width
    they were traced with (XLA caches the executable) — re-trace (fresh
    jax.jit, or new shapes) after flipping for the change to take
    effect."""
    if n not in (8, 32):
        raise ValueError(f"dropout bits must be 8 or 32, got {n}")
    global _dropout_bits
    _dropout_bits = n


def dropout_bits() -> int:
    return _dropout_bits


def _quantized_threshold(rate: float, bits: int) -> int:
    """The integer threshold the kernel compares random values against —
    the ONE definition shared by mask generation and its inverse scale
    (two copies drifting apart would bias E[output])."""
    if bits == 8:
        return max(1, min(256, round((1.0 - rate) * 256)))
    return min(int((1.0 - rate) * 2 ** 32), 2 ** 32 - 1)


def _keep_scale(rate: float, bits: int) -> float:
    """Exact inverse keep-probability for the quantized threshold the
    kernel actually compares against — using 1/(1-rate) with the 8-bit
    threshold would bias E[output] by up to ~0.2%."""
    return float(2 ** bits) / _quantized_threshold(rate, bits)


def _dropout_keep(seed_ref, b, h, qi, ki, rate, block_q, block_k,
                  num_k_blocks, bits=32):
    """Regenerable per-tile keep mask: the PRNG is reseeded from the step
    seed and the tile's ABSOLUTE coordinates, so the forward kernel and
    both backward kernels (whose grids order (qi, ki) differently)
    reproduce the identical mask — the TPU analog of the reference's
    philox-offset dropout (dropout_kernels.cu:868).

    Mosaic on current TPUs rejects prng_seed with more than 2 values, so
    the coordinates are folded exactly into two: (seed, b, h) -> value 1
    (grid dim 1 is the head axis in all three kernels, so num_programs(1)
    is the head count) and (qi, ki, seed) -> value 2 via the static
    k-block count.  The seed rides in BOTH values: with value 1 alone,
    sequential per-step seeds (the natural dropout_seed=step usage) would
    alias step s+1/head h with step s/head h+1 and recycle whole mask
    patterns.  Value 2 mixes the seed with the Knuth multiplicative hash
    (2654435761 == -1640531527 as an int32 bit pattern): int32 multiply
    wraps mod 2^32 (MLIR arith has two's-complement semantics, no UB),
    and under that wrap an odd multiplier is a bijection of the seed, so
    the anti-aliasing argument holds for arbitrary step counts — unlike
    the old seed*40503, whose argument silently broke once the product
    first wrapped (seed ~53k).  A collision now needs seed'-seed ==
    bh-bh' AND tile-tile' == (seed'-seed)*2654435761 mod 2^32 —
    vanishingly unlikely while tile counts stay tiny vs 2^32.  All
    arithmetic stays in plain int32: scalar casts/bitcasts are
    Mosaic-illegal ('tpu.bitcast' needs vector operands — measured on
    v5e, round 4)."""
    pltpu.prng_seed(seed_ref[0] + b * pl.num_programs(1) + h,
                    qi * num_k_blocks + ki
                    + seed_ref[0] * np.int32(-1640531527))
    if bits == 8:
        # one 32-bit word per FOUR mask positions: byte j of word w maps
        # to column j*block_k/4 + w (column-GROUP layout — no Mosaic
        # lane interleave needed; each (word, byte) is used exactly
        # once, so positions stay iid uniform bytes).  Callers decide
        # bits where block_k is known (_effective_dropout_bits), so the
        # divisibility precondition holds here by construction.
        assert block_k % 4 == 0, "8-bit dropout requires block_k % 4 == 0"
        w = pltpu.prng_random_bits((block_q, block_k // 4))
        w = w.astype(jnp.uint32)
        t8 = _quantized_threshold(rate, 8)
        m = jnp.concatenate(
            [(w >> np.uint32(8 * j)) & np.uint32(0xFF) for j in range(4)],
            axis=1)
        return m < np.uint32(t8)
    rbits = pltpu.prng_random_bits((block_q, block_k))
    threshold = np.uint32(_quantized_threshold(rate, 32))
    return rbits.astype(jnp.uint32) < threshold


def _effective_dropout_bits(block_k: int) -> int:
    """The width BOTH the mask and the scale must use for this kernel
    call: 8-bit needs four byte-columns per word, so non-multiple-of-4
    k blocks fall back to 32 — decided once here so mask probability and
    inverse scale can never disagree."""
    return _dropout_bits if _dropout_bits == 32 or block_k % 4 == 0 else 32


# --------------------------------------------------------------------------- #
# Dropout mask reuse (store-in-forward / read-in-backward)
# --------------------------------------------------------------------------- #
# The regen scheme above pays the PRNG three times per step (fwd, dq,
# dkv) — measured ~2.6% of the flagship step per kernel at 8-bit
# (docs/ROUND5_NOTES.md).  Mask REUSE stores the keep decisions once in
# the forward and the backward kernels read them: the PRNG runs once,
# and the stored mask costs only 1-bit-per-position of HBM traffic.
#
# Packing rides the SUBLANE axis: 32 q-rows fold into one uint32 word
# row, so the packed tile is [block_q/32, block_k] — the lane dim stays
# the full lane-aligned block_k and the sublane dim is block_q/32 (16 at
# the default 512 block), satisfying Mosaic's (8, 128) int32 tiling
# without any padding.  (Lane-axis packing would shrink the minor dim to
# block_k/32 < 128, which is only legal as a full-extent dim — i.e. a
# single k block — while sublane packing is legal whenever
# block_q % 256 == 0.)  Pack/unpack are 32 aligned sublane slices with
# shift+or — no cross-lane movement, pure VPU work.
#
# The reference's analog is checkpointing the dropout mask with the
# activation (dropout_kernels.cu stores the uint8 mask tensor the
# backward kernels consume); here the mask lives bit-packed in the
# custom-VJP residuals instead.
_MASK_PACK = 32  # q rows per packed uint32 word


def _pack_keep32(keep):
    """[rows, cols] bool -> [rows//32, cols] uint32.  Bit j of word row
    r holds keep[j*(rows//32) + r] (group layout: 32 aligned sublane
    slices, no interleave)."""
    gr = keep.shape[0] // _MASK_PACK
    ku = keep.astype(jnp.uint32)
    packed = ku[0:gr]
    for j in range(1, _MASK_PACK):
        packed = packed | (ku[j * gr:(j + 1) * gr] << np.uint32(j))
    return packed


def _unpack_keep32(packed):
    """Inverse of _pack_keep32: [gr, cols] uint32 -> [gr*32, cols] bool."""
    one = np.uint32(1)
    return jnp.concatenate(
        [(packed >> np.uint32(j)) & one for j in range(_MASK_PACK)],
        axis=0) > 0


def _parse_dropout_reuse(raw: str) -> bool:
    return raw not in ("", "0", "false", "False", "no")


_DEFAULT_DROPOUT_REUSE = False
_dropout_reuse = _parse_dropout_reuse(
    os.environ.get("DS_DROPOUT_REUSE",
                   "1" if _DEFAULT_DROPOUT_REUSE else "0"))


def set_dropout_mask_reuse(on: bool) -> None:
    """Store the forward keep mask (bit-packed) and reuse it in the
    backward kernels instead of regenerating it from the PRNG.  Grads
    are BIT-IDENTICAL either way (the stored mask equals the regenerated
    one); the modes differ only in where the step spends time — regen
    pays the PRNG 3x, reuse pays S^2/8 bytes of residual traffic.  Read
    at TRACE time like set_dropout_bits; falls back to regen when the
    resolved q block is not a multiple of 256 (packed-tile sublane
    alignment)."""
    global _dropout_reuse
    _dropout_reuse = bool(on)


def dropout_mask_reuse() -> bool:
    return _dropout_reuse


def _mask_reuse_usable(block_q: int) -> bool:
    """Packed tile legality: sublane dim block_q/32 must be a multiple
    of 8 -> block_q % 256 == 0 (512-default and 256 blocks qualify;
    smaller resolved blocks regen)."""
    return block_q % 256 == 0


def _fa_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
               *rest,
               causal: bool, sm_scale: float, block_q: int, block_k: int,
               num_k_blocks: int, dropout_rate: float,
               dropout_pbits: int = 32, save_mask: bool = False):
    if save_mask:
        mask_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # With causal masking, blocks strictly above the diagonal contribute
    # nothing — skip their matmuls entirely (the analog of the reference's
    # triangular-launch trick).
    should_compute = True
    if causal:
        should_compute = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(should_compute)
    def _compute():
        # bf16 operands straight into the MXU; fp32 accumulation via
        # preferred_element_type (upcasting first would force an fp32 matmul).
        q = _ld(q_ref)                               # [bq, d]
        k = _ld(k_ref)                               # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk] fp32

        if causal:
            s = jnp.where(causal_keep_mask(qi, ki, block_q, block_k),
                          s, DEFAULT_MASK_VALUE)

        m_prev = m_scr[...]                           # [bq, LANES]
        l_prev = l_scr[...]
        m_curr = jnp.max(s, axis=-1, keepdims=True)   # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)          # [bq, LANES]
        alpha = jnp.exp(m_prev[:, :1] - m_next[:, :1])   # [bq, 1]
        p = jnp.exp(s - m_next[:, :1])                # [bq, bk] fp32
        l_corr = l_prev * alpha
        l_next = l_corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_next
        l_scr[...] = jnp.broadcast_to(l_next[:, :1], l_scr.shape)

        if dropout_rate > 0.0:
            # probability dropout: the PV input is masked+rescaled but the
            # normalizer l accumulates the RAW p (softmax normalizes true
            # probabilities; dropout applies to the normalized P, which
            # commutes with the final /l)
            keep = _dropout_keep(seed_ref, b, h, qi, ki, dropout_rate,
                                 block_q, block_k, num_k_blocks,
                                 bits=dropout_pbits)
            inv = _keep_scale(dropout_rate, dropout_pbits)
            p = jnp.where(keep, p * inv, 0.0)
            if save_mask:
                # bit-packed keep decisions for the backward kernels.
                # Causally-skipped tiles never write (and the backward
                # skips the same tiles, so their garbage is never read).
                mask_ref[0, 0] = _pack_keep32(keep)

        v_blk = _ld(v_ref)                           # [bk, d]
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, d]
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        denom = l_scr[...][:, :1]
        # Fully-masked rows have l == 0; emit zeros not NaN.
        denom = jnp.where(denom == 0.0, 1.0, denom)
        _st(o_ref, (acc_scr[...] / denom).astype(o_ref.dtype))
        # logsumexp residual for the backward pass (FlashAttention-2 style)
        lse = m_scr[...][:, :1] + jnp.log(l_scr[...][:, :1] + 1e-37)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _seed_arg(dropout_seed):
    """int32[1] scalar-prefetch operand (0 when dropout is off)."""
    if dropout_seed is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(dropout_seed, jnp.int32).reshape((1,))


def _fit_block(length: int, target: int, align: int) -> int:
    """Largest divisor of `length` <= `target` that is a multiple of
    `align`; falls back to the largest unaligned divisor (callers judge
    usability).  A short whole length (< align) is its own block."""
    best_unaligned = 1
    b = min(target, length)
    while b >= 1:
        if length % b == 0:
            if b % align == 0 or b == length:
                return b
            if best_unaligned == 1:
                best_unaligned = b
        b -= 1
    return best_unaligned


def _resolve_blocks(q_len, k_len, block_q, block_k):
    """Fit the requested blocks to the sequence lengths.

    Returns (usable, bq, bk): the largest ALIGNED divisors of the lengths
    at most the requested blocks (k lane-aligned, q sublane-aligned), so
    e.g. 1536 fits as 512x768 and 1152 as 384x384.  `usable` requires a
    strictly lane/sublane-aligned tiling: a length with no such divisor
    (primes, 1000, short whole lengths < the 128-lane width) dispatches to
    XLA instead — masked lane reductions on partial tiles are exactly the
    configuration the TPU-path tests cannot cover (interpret-mode tests
    don't exercise lane masking), so the dispatcher never runs them."""
    bq = _fit_block(q_len, block_q, 8)
    bk = _fit_block(k_len, block_k, _LANES)
    usable = bk % _LANES == 0 and bq % 8 == 0
    return usable, bq, bk


def _dims(arr, layout):
    """(batch, heads, seq, d) for either layout."""
    if layout == "bhsd":
        b, h, s, d = arr.shape
    else:  # "bshd": [B, S, heads, d] — head dim indexed in the BlockSpec
        b, s, h, d = arr.shape
    return b, h, s, d


def _tile_spec(rows, d, seq_of):
    """[B, H, S, D] BlockSpec for one [rows, d] tile per (b, h) grid
    cell; `seq_of` picks which grid index walks the sequence dim ('i' or
    'j').  The trailing *_ absorbs the scalar-prefetch ref (the dropout
    seed) that PrefetchScalarGridSpec appends to every index_map.

    (A native [B, S, heads, d] tiling — block (1, rows, 1, d) indexing
    the head dim — is Mosaic-ILLEGAL: the block's last two dims are then
    (1, d) over a (heads, d) axis pair, and 1 is neither a multiple of 8
    nor the full head count.  Measured round 3 on v5e: such specs fail
    Pallas lowering outright, so the "bshd" layout transposes at the
    kernel boundary instead — see flash_attention_pallas.)"""
    if seq_of == "i":
        return pl.BlockSpec((1, 1, rows, d),
                            lambda b, h, i, j, *_: (b, h, i, 0))
    return pl.BlockSpec((1, 1, rows, d),
                        lambda b, h, i, j, *_: (b, h, j, 0))


def flash_attention_pallas(q, k, v, causal: bool = False,
                           sm_scale: Optional[float] = None,
                           block_q: int = 512, block_k: int = 1024,
                           interpret: bool = False, return_lse: bool = False,
                           layout: str = "bhsd", dropout_rate: float = 0.0,
                           dropout_seed=None,
                           save_dropout_mask: bool = False):
    """Pallas flash attention.

    layout="bhsd" (default): q,k,v [B, H, S, D] -> [B, H, S, D].
    layout="bshd": q,k,v [B, S, heads, D] -> [B, S, heads, D], converted
    to the kernel's [B, H, S, D] at this boundary.  (A native bshd
    BlockSpec — (1, rows, 1, d) indexing the head dim — is Mosaic-illegal
    and fails Pallas lowering on real TPUs, measured round 3; the
    transposes here are cheap relative to the attention itself and XLA
    fuses them into neighbors where it can.)
    logsumexp (when return_lse) is [B, H, S] in BOTH layouts.

    save_dropout_mask (requires return_lse and dropout_rate > 0, and a
    resolved q block that is a multiple of 256): additionally returns
    the bit-packed keep mask [B, H, S_q/32, S_k] uint32 — ALWAYS in the
    internal bhsd-derived index space regardless of layout — for
    flash_attention_bwd_pallas(dropout_mask=...)."""
    if pltpu is None:
        raise RuntimeError(
            "pallas TPU support unavailable in this jax install — use "
            "mha_reference / the public flash_attention dispatcher instead")
    batch, heads, q_len, d = _dims(q, layout)
    k_len = _dims(k, layout)[2]
    if layout == "bshd":
        q, k, v = _t_bhsd(q), _t_bhsd(k), _t_bhsd(v)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # fit to the lengths (largest aligned divisors <= requested blocks);
    # explicit small blocks are legal (kernel tests use 64x64) but a
    # degenerate 1-wide tiling (prime-ish length) is rejected loudly —
    # the flash_attention dispatcher falls back to XLA for those
    _, block_q, block_k = _resolve_blocks(q_len, k_len, block_q, block_k)
    if (block_q == 1 and q_len > 1) or (block_k == 1 and k_len > 1):
        raise ValueError(
            f"seq lengths ({q_len},{k_len}) only tile into 1-wide blocks "
            f"— use the flash_attention dispatcher (XLA fallback)")
    nq, nk = q_len // block_q, k_len // block_k
    if dropout_rate > 0.0 and interpret:
        raise ValueError(
            f"in-kernel dropout (dropout_rate={dropout_rate}) needs the "
            "TPU PRNG — pltpu.prng_seed has no CPU lowering, so "
            "interpret mode cannot generate the mask.  Fix: call with "
            "dropout_rate=0 (parity tests compare the dropout-free "
            "kernel), or take the XLA path — flash_attention("
            "impl='xla') / mha_reference — whose jax.random dropout "
            "runs on any backend")
    seed = _seed_arg(dropout_seed)

    if save_dropout_mask:
        if not (return_lse and dropout_rate > 0.0):
            raise ValueError(
                "save_dropout_mask requires return_lse and dropout_rate > 0")
        if not _mask_reuse_usable(block_q):
            raise ValueError(
                f"save_dropout_mask: q_len={q_len} resolved a q block of "
                f"{block_q}, which is not a multiple of 256 (the packed "
                "mask tile needs sublane dim block_q/32 % 8 == 0).  Fix: "
                "pick a block_q whose resolved divisor of q_len is a "
                "multiple of 256 (TransformerConfig.block_q / the block_q "
                "argument), or stay on the regen path by disabling reuse "
                "(set_dropout_mask_reuse(False) / DS_DROPOUT_REUSE=0)")
    kernel = functools.partial(
        _fa_kernel, causal=causal, sm_scale=float(sm_scale),
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
        dropout_rate=float(dropout_rate),
        dropout_pbits=_effective_dropout_bits(block_k),
        save_mask=save_dropout_mask)

    scratch = [
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
        pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
    ]
    out_specs = [
        _tile_spec(block_q, d, "i"),
        pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                     lambda b, h, i, j, *_: (b, h, i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((batch, heads, q_len, _STATS_LANES),
                             jnp.float32),
    ]
    if save_dropout_mask:
        out_specs.append(
            pl.BlockSpec((1, 1, block_q // _MASK_PACK, block_k),
                         lambda b, h, i, j, *_: (b, h, i, j)))
        out_shape.append(
            jax.ShapeDtypeStruct(
                (batch, heads, q_len // _MASK_PACK, k_len), jnp.uint32))
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    res = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, heads, nq, nk),
            in_specs=[
                _tile_spec(block_q, d, "i"),
                _tile_spec(block_k, d, "j"),
                _tile_spec(block_k, d, "j"),
            ],
            out_specs=out_specs,
            scratch_shapes=scratch),
        out_shape=out_shape,
        interpret=interpret,
        **params,
    )(seed, q, k, v)
    out, lse = res[0], res[1]
    if layout == "bshd":
        out = _t_bhsd(out)
    if save_dropout_mask:
        return out, lse[..., 0], res[2]
    return (out, lse[..., 0]) if return_lse else out


# --------------------------------------------------------------------------- #
# Pallas backward kernels (FlashAttention-2 style)
# --------------------------------------------------------------------------- #
def _fa_bwd_dkdv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, *rest, causal, sm_scale, block_q,
                        block_k, num_q_blocks, num_k_blocks, dropout_rate,
                        dropout_pbits=32, reuse_mask: bool = False):
    if reuse_mask:
        mask_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    should_compute = True
    if causal:  # q block fully above the diagonal contributes nothing
        should_compute = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(should_compute)
    def _compute():
        q = _ld(q_ref)                               # [bq, d]
        k = _ld(k_ref)                               # [bk, d]
        v = _ld(v_ref)                               # [bk, d]
        do = _ld(do_ref)                             # [bq, d]
        lse = lse_ref[0, 0][:, :1]                    # [bq, 1]
        delta = delta_ref[0, 0][:, :1]                # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        p = jnp.exp(s - lse)                          # [bq, bk] fp32
        if causal:
            p = jnp.where(causal_keep_mask(qi, ki, block_q, block_k),
                          p, 0.0)

        dp = jax.lax.dot_general(                      # do @ v^T -> [bq, bk]
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # same mask as the forward — regenerated from the tile
            # coordinates, or read back bit-packed (reuse mode; both
            # give the IDENTICAL mask, so grads don't depend on the
            # mode).  dV sees the DROPPED probabilities; dS =
            # P*(D.dp - delta)
            if reuse_mask:
                keep = _unpack_keep32(mask_ref[0, 0])
            else:
                keep = _dropout_keep(seed_ref, b, h, qi, ki, dropout_rate,
                                     block_q, block_k, num_k_blocks,
                                     bits=dropout_pbits)
            inv = _keep_scale(dropout_rate, dropout_pbits)
            p_drop = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_drop = p

        dv_scr[...] += jax.lax.dot_general(            # p^T @ do -> [bk, d]
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale               # [bq, bk] fp32
        dk_scr[...] += jax.lax.dot_general(            # ds^T @ q -> [bk, d]
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        _st(dk_ref, dk_scr[...].astype(dk_ref.dtype))
        _st(dv_ref, dv_scr[...].astype(dv_ref.dtype))


def _fa_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, *rest, causal, sm_scale, block_q,
                      block_k, num_k_blocks, dropout_rate,
                      dropout_pbits=32, reuse_mask: bool = False):
    if reuse_mask:
        mask_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    should_compute = True
    if causal:
        should_compute = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(should_compute)
    def _compute():
        q = _ld(q_ref)
        k = _ld(k_ref)
        v = _ld(v_ref)
        do = _ld(do_ref)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(causal_keep_mask(qi, ki, block_q, block_k),
                          p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            if reuse_mask:
                keep = _unpack_keep32(mask_ref[0, 0])
            else:
                keep = _dropout_keep(seed_ref, b, h, qi, ki, dropout_rate,
                                     block_q, block_k, num_k_blocks,
                                     bits=dropout_pbits)
            inv = _keep_scale(dropout_rate, dropout_pbits)
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(            # ds @ k -> [bq, d]
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        _st(dq_ref, dq_scr[...].astype(dq_ref.dtype))


def flash_attention_bwd_pallas(q, k, v, out, lse, do, causal: bool = False,
                               sm_scale: Optional[float] = None,
                               block_q: int = 512, block_k: int = 1024,
                               interpret: bool = False,
                               layout: str = "bhsd",
                               dropout_rate: float = 0.0,
                               dropout_seed=None, dropout_mask=None,
                               dropout_mask_block_q=None):
    """Block-wise dq, dk, dv — no [S, S] materialization in HBM.  Inputs
    and grads follow `layout` (lse is always [B, H, S]); "bshd" converts
    to the kernel's [B, H, S, D] at this boundary (see
    flash_attention_pallas).

    dropout_mask: the bit-packed [B, H, S_q/32, S_k] uint32 keep mask a
    save_dropout_mask forward stored (always internal-layout).  When
    given, the kernels READ it instead of regenerating from the PRNG —
    identical grads, one PRNG pass per step instead of three.
    dropout_mask_block_q (REQUIRED with dropout_mask): the RESOLVED q
    block the forward packed with — the bit-group layout is a function
    of it, so a fwd/bwd block mismatch would silently permute mask rows;
    this check turns that into a loud error."""
    batch, heads, q_len, d = _dims(q, layout)
    k_len = _dims(k, layout)[2]
    if layout == "bshd":
        q, k, v = _t_bhsd(q), _t_bhsd(k), _t_bhsd(v)
        out, do = _t_bhsd(out), _t_bhsd(do)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # fit to the lengths (largest aligned divisors <= requested blocks);
    # explicit small blocks are legal (kernel tests use 64x64) but a
    # degenerate 1-wide tiling (prime-ish length) is rejected loudly —
    # the flash_attention dispatcher falls back to XLA for those
    _, block_q, block_k = _resolve_blocks(q_len, k_len, block_q, block_k)
    if (block_q == 1 and q_len > 1) or (block_k == 1 and k_len > 1):
        raise ValueError(
            f"seq lengths ({q_len},{k_len}) only tile into 1-wide blocks "
            f"— use the flash_attention dispatcher (XLA fallback)")
    nq, nk = q_len // block_q, k_len // block_k
    if dropout_rate > 0.0 and interpret and dropout_mask is None:
        # reuse-mode (dropout_mask given) backward never touches the PRNG
        # — the unpack is plain vector ops, so interpret mode is legal
        # there (and is how the CPU lane tests the reuse numerics)
        raise ValueError(
            f"in-kernel dropout (dropout_rate={dropout_rate}) needs the "
            "TPU PRNG — pltpu.prng_seed has no CPU lowering, so the "
            "interpret-mode backward cannot regenerate the mask.  Fix: "
            "call with dropout_rate=0, pass the forward's saved "
            "dropout_mask (save_dropout_mask / set_dropout_mask_reuse("
            "True) — the bit-unpack needs no PRNG), or take the XLA "
            "path (flash_attention(impl='xla') / mha_reference)")
    seed = _seed_arg(dropout_seed)

    # delta_i = rowsum(dO_i * O_i)  (cheap elementwise; leave to XLA).
    # With dropout this stays correct: rowsum(dO*O) = sum_j A_ij dA_ij for
    # A = dropout(P), which is exactly the subtrahend in dS = P*(D.dp - δ).
    # The stats ride [B, H, S, lanes] (tiny tensors).
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    stats_shape = (*delta.shape, _STATS_LANES)
    delta = jnp.broadcast_to(delta[..., None], stats_shape)
    lse = jnp.broadcast_to(lse[..., None], stats_shape)

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))

    reuse = dropout_mask is not None
    if reuse:
        if not dropout_rate > 0.0:
            raise ValueError(
                f"dropout_mask given but dropout_rate={dropout_rate} — a "
                "mask only applies to a dropout backward.  Fix: pass the "
                "forward's dropout_rate, or drop the dropout_mask argument")
        if not _mask_reuse_usable(block_q):
            raise ValueError(
                f"dropout_mask given but this backward resolved q block "
                f"{block_q} (from q_len={q_len}, requested block_q), which "
                "is not a multiple of 256 — the forward could not have "
                "packed a mask at this block.  Fix: use the same block_q "
                "in forward and backward (TransformerConfig.block_q), or "
                "disable reuse (set_dropout_mask_reuse(False) / "
                "DS_DROPOUT_REUSE=0) so both sides regen from the PRNG")
        if dropout_mask_block_q != block_q:
            raise ValueError(
                f"dropout_mask was packed with resolved block_q="
                f"{dropout_mask_block_q}, but this backward resolved "
                f"block_q={block_q} — the packed bit layout depends on the "
                "forward's q block, so the grads would be silently wrong.  "
                "Fix: pass dropout_mask_block_q=<the forward's resolved "
                "block> and call with the forward's block_q (the "
                "flash_attention custom_vjp does this automatically; "
                "manual callers must thread it through)")
    mask_in = (dropout_mask,) if reuse else ()

    # dk/dv: grid over k blocks (grid dim 2), inner loop over q blocks
    # (grid dim 3) — _tile_spec's "i"/"j" name grid dims 2/3, so q/do tiles
    # use "j" here
    dkdv_kernel = functools.partial(
        _fa_bwd_dkdv_kernel, causal=causal, sm_scale=float(sm_scale),
        block_q=block_q, block_k=block_k, num_q_blocks=nq, num_k_blocks=nk,
        dropout_rate=float(dropout_rate),
        dropout_pbits=_effective_dropout_bits(block_k), reuse_mask=reuse)
    dkdv_in_specs = [
        _tile_spec(block_q, d, "j"),
        _tile_spec(block_k, d, "i"),
        _tile_spec(block_k, d, "i"),
        _tile_spec(block_q, d, "j"),
        pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                     lambda b, h, j, i, *_: (b, h, i, 0)),
        pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                     lambda b, h, j, i, *_: (b, h, i, 0)),
    ]
    if reuse:  # mask tile (q_block, k_block) = (grid dim 3, grid dim 2)
        dkdv_in_specs.append(
            pl.BlockSpec((1, 1, block_q // _MASK_PACK, block_k),
                         lambda b, h, i, j, *_: (b, h, j, i)))
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, heads, nk, nq),
            in_specs=dkdv_in_specs,
            out_specs=[
                _tile_spec(block_k, d, "i"),
                _tile_spec(block_k, d, "i"),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
        **params,
    )(seed, q, k, v, do, lse, delta, *mask_in)

    # dq: grid over q blocks, inner loop over k blocks
    r_spec = pl.BlockSpec((1, 1, block_q, _STATS_LANES),
                          lambda b, h, i, j, *_: (b, h, i, 0))
    dq_kernel = functools.partial(
        _fa_bwd_dq_kernel, causal=causal, sm_scale=float(sm_scale),
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
        dropout_rate=float(dropout_rate),
        dropout_pbits=_effective_dropout_bits(block_k), reuse_mask=reuse)
    dq_in_specs = [
        _tile_spec(block_q, d, "i"),
        _tile_spec(block_k, d, "j"),
        _tile_spec(block_k, d, "j"),
        _tile_spec(block_q, d, "i"),
        r_spec, r_spec,
    ]
    if reuse:
        dq_in_specs.append(
            pl.BlockSpec((1, 1, block_q // _MASK_PACK, block_k),
                         lambda b, h, i, j, *_: (b, h, i, j)))
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, heads, nq, nk),
            in_specs=dq_in_specs,
            out_specs=_tile_spec(block_q, d, "i"),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        **params,
    )(seed, q, k, v, do, lse, delta, *mask_in)

    if layout == "bshd":
        dq, dk, dv = _t_bhsd(dq), _t_bhsd(dk), _t_bhsd(dv)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# Differentiable public entry point
# --------------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, seed, causal, sm_scale, block_q, block_k,
           layout="bhsd", dropout_rate=0.0):
    return _flash_fwd(q, k, v, seed, causal, sm_scale, block_q, block_k,
                      layout, dropout_rate)[0]


# Auto-dispatch crossover (v5e, 2026-07-31, benchmarks/session_r4/
# bert_ab.log): at S=128 the XLA attention beats the Pallas flash kernel
# by ~25% on the full BERT-large step (90.3 vs 115.5 ms dropout-on) —
# short sequences leave the streaming kernel overhead-bound while XLA
# fuses the whole [S, S] attention in registers/VMEM.  At S=1024 the
# Pallas kernel wins (round-3 2x2).  Sequences shorter than this take
# the XLA path under impl="auto"; impl="pallas" still forces the kernel.
AUTO_MIN_SEQ = 512


def _use_pallas(q_len, k_len, d, block_q, block_k):
    from .dispatch import pallas_available
    if not pallas_available():
        return False
    usable, _, _ = _resolve_blocks(q_len, k_len, block_q, block_k)
    return usable


def _auto_prefers_xla(k_len):
    """impl='auto' short-sequence crossover (measured; see AUTO_MIN_SEQ).
    DS_FLASH_MIN_SEQ is read per call, not at import, so harnesses can
    re-tune the crossover after the module is loaded."""
    return k_len < int(os.environ.get("DS_FLASH_MIN_SEQ", AUTO_MIN_SEQ))


def _t_bhsd(t):
    """[B, S, heads, d] <-> [B, H, S, D] (its own inverse)."""
    return t.transpose(0, 2, 1, 3)


def _ref_in_layout(q, k, v, causal, sm_scale, layout, dropout_rate=0.0,
                   dropout_seed=None):
    """XLA fallback in the caller's layout."""
    if layout == "bhsd":
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed)
    return _t_bhsd(mha_reference(_t_bhsd(q), _t_bhsd(k), _t_bhsd(v),
                                 causal=causal, sm_scale=sm_scale,
                                 dropout_rate=dropout_rate,
                                 dropout_seed=dropout_seed))


def _flash_fwd(q, k, v, seed, causal, sm_scale, block_q, block_k,
               layout="bhsd", dropout_rate=0.0):
    q_len, k_len = _dims(q, layout)[2], _dims(k, layout)[2]
    if _use_pallas(q_len, k_len, q.shape[3], block_q, block_k):
        _, bq, bk = _resolve_blocks(q_len, k_len, block_q, block_k)
        # mask-reuse mode (trace-time, like the PRNG width): store the
        # bit-packed keep mask in the residuals so the backward kernels
        # skip the PRNG — grads identical either way
        if dropout_rate > 0.0 and _dropout_reuse and _mask_reuse_usable(bq):
            out, lse, mask = flash_attention_pallas(
                q, k, v, causal=causal, sm_scale=sm_scale,
                block_q=bq, block_k=bk, return_lse=True, layout=layout,
                dropout_rate=dropout_rate, dropout_seed=seed,
                save_dropout_mask=True)
            return out, (q, k, v, seed, out, lse, mask)
        out, lse = flash_attention_pallas(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_q=bq, block_k=bk, return_lse=True, layout=layout,
            dropout_rate=dropout_rate, dropout_seed=seed)
        return out, (q, k, v, seed, out, lse, None)
    out = _ref_in_layout(q, k, v, causal, sm_scale, layout, dropout_rate,
                         seed[0])
    return out, (q, k, v, seed, None, None, None)


def _flash_bwd(causal, sm_scale, block_q, block_k, layout, dropout_rate,
               res, g):
    q, k, v, seed, out, lse, mask = res
    if lse is not None:
        q_len, k_len = _dims(q, layout)[2], _dims(k, layout)[2]
        _, bq, bk = _resolve_blocks(q_len, k_len, block_q, block_k)
        dq, dk, dv = flash_attention_bwd_pallas(
            q, k, v, out, lse, g, causal=causal, sm_scale=sm_scale,
            block_q=bq, block_k=bk, layout=layout,
            dropout_rate=dropout_rate, dropout_seed=seed,
            dropout_mask=mask, dropout_mask_block_q=bq)
        return dq, dk, dv, None
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _ref_in_layout(q_, k_, v_, causal, sm_scale,
                                          layout, dropout_rate, seed[0]),
        q, k, v)
    return (*vjp(g), None)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Default block sizes, tuned on v5e (benchmarks/profile_flash_blocks.py,
# state-feedback + fetch-sync measurement): large blocks dominate —
# 128x128 is grid-overhead-bound (S=4096 fwd+bwd: 28.1 ms at 128x128 vs
# 6.7 ms at 1024x1024; S=1024: 10.0 -> 4.3 ms).  With these blocks the
# Pallas kernel beats the batched-XLA attention at the kernel level for
# S >= 1024 (S=1024: 4.3 vs 6.3 ms; S=4096: 6.7 vs 23.9 ms) — but at
# SHORT lengths the FULL-STEP measurement goes the other way (round-4
# bert_ab 2x2: S=128 XLA attention wins by ~25%), hence the
# AUTO_MIN_SEQ crossover above.  512x1024 (not 1024x1024, statistically
# tied) keeps the bwd kernel's [bq, bk] fp32 score/ds tiles at 2 MB
# each for VMEM headroom at D>64.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None, bias=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    impl: str = "auto", dropout_rate: float = 0.0,
                    dropout_seed=None):
    """Fused multi-head attention: q,k,v [B, H, S, D] -> [B, H, S, D].

    impl: "auto" (default) runs the Pallas flash kernel with blocks fitted
    to the sequence lengths (_resolve_blocks), falling back to the XLA
    reference on CPU, unaligned lengths, or bias; "pallas" REQUIRES the
    Pallas kernel and raises where auto would fall back (so ablation
    harnesses can never silently measure the XLA path); "xla" forces the
    reference.  Additive-bias attention always takes the XLA path (the
    compiler fuses the bias add into the softmax).

    dropout_rate > 0 applies PROBABILITY dropout to the normalized
    attention (the reference's attn-dropout, dropout_kernels.cu:868) —
    IN-KERNEL on the Pallas path (the mask is regenerated from
    dropout_seed + tile coordinates in the backward, never stored) and via
    jax.random on the XLA path.  dropout_seed is a per-step int32 (array
    or scalar); the two paths use different PRNG streams."""
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    seed = _seed_arg(dropout_seed)
    if impl == "pallas":
        if bias is not None:
            raise ValueError(
                "impl='pallas': the Pallas kernel does not take an additive "
                "bias — use impl='auto'/'xla'")
        if not _use_pallas(q.shape[2], k.shape[2], q.shape[3],
                           block_q, block_k):
            raise ValueError(
                f"impl='pallas': no aligned tiling for seq lengths "
                f"({q.shape[2]},{k.shape[2]}) or Pallas unavailable on this "
                "backend — use impl='auto' for the XLA fallback")
        return _flash(q, k, v, seed, causal, sm_scale, block_q, block_k,
                      "bhsd", dropout_rate)
    if bias is not None:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             bias=bias, dropout_rate=dropout_rate,
                             dropout_seed=seed[0])
    if impl == "xla" or _auto_prefers_xla(k.shape[2]):
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             dropout_rate=dropout_rate,
                             dropout_seed=seed[0])
    return _flash(q, k, v, seed, causal, sm_scale, block_q, block_k,
                  "bhsd", dropout_rate)


def flash_attention_bsh(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None, bias=None,
                        block_q: int = DEFAULT_BLOCK_Q,
                        block_k: int = DEFAULT_BLOCK_K,
                        impl: str = "auto", dropout_rate: float = 0.0,
                        dropout_seed=None):
    """Fused attention over [B, S, heads, d] activations.

    Callers holding [B, S, hidden] activations reshape (free) to
    [B, S, heads, d]; the layout conversion to the kernel's [B, H, S, D]
    happens at the Pallas boundary.  (Round-3 finding: a truly
    transpose-free bshd BlockSpec is Mosaic-illegal — its per-head tile
    puts (1, d) in the last-two-dims position — so this entry point is
    API convenience, not an HBM-traffic optimization; measured, the
    boundary transposes are <1% of step traffic.)  Semantics are
    identical to flash_attention — including impl='pallas' strictness —
    with bias/impl='xla'/unusable lengths falling back to the transposed
    XLA reference.  dropout_rate/dropout_seed as in flash_attention."""
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    seed = _seed_arg(dropout_seed)
    if impl == "pallas":
        if bias is not None:
            raise ValueError(
                "impl='pallas': the Pallas kernel does not take an additive "
                "bias — use impl='auto'/'xla'")
        if not _use_pallas(q.shape[1], k.shape[1], q.shape[3],
                           block_q, block_k):
            raise ValueError(
                f"impl='pallas': no aligned tiling for seq lengths "
                f"({q.shape[1]},{k.shape[1]}) or Pallas unavailable on this "
                "backend — use impl='auto' for the XLA fallback")
    if (bias is not None or impl == "xla"
            or (impl == "auto" and _auto_prefers_xla(k.shape[1]))):
        return _t_bhsd(mha_reference(_t_bhsd(q), _t_bhsd(k), _t_bhsd(v),
                                     causal=causal, sm_scale=sm_scale,
                                     bias=bias, dropout_rate=dropout_rate,
                                     dropout_seed=seed[0]))
    return _flash(q, k, v, seed, causal, sm_scale, block_q, block_k,
                  "bshd", dropout_rate)
