"""Flash attention for TPU — the Pallas analog of the reference's fused
attention kernels (csrc/transformer/softmax_kernels.cu:595 fused
scale+mask+softmax + cublas strided-batch QK^T/PV matmuls,
csrc/transformer/inference/csrc/softmax.cu).

Instead of materializing the [S, S] score matrix in HBM between three kernel
launches like the CUDA reference, the whole QK^T -> online-softmax -> PV chain
runs in one Pallas kernel, streaming K/V blocks through VMEM with fp32
accumulators (flash-attention style).  The MXU sees two big matmuls per block
pair; HBM traffic is O(S*d) instead of O(S^2).

Backward currently recomputes attention with the XLA reference path (exact
same math, fp32 softmax) via custom_vjp; a dedicated Pallas backward kernel is
a later optimization.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific pallas bits are unavailable on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# Finite mask value: keeps running-max finite for fully-masked rows (an -inf
# row max would turn exp(s - m) into NaN).
DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

_LANES = 128  # TPU lane width; softmax stats are carried at this width


# --------------------------------------------------------------------------- #
# Reference implementation (also the backward path and the CPU fallback)
# --------------------------------------------------------------------------- #
def mha_reference(q, k, v, causal: bool = False,
                  sm_scale: Optional[float] = None, bias=None):
    """Plain-XLA multi-head attention: q,k,v [B, H, S, D] -> [B, H, S, D].

    fp32 softmax regardless of input dtype (matches the reference kernels,
    which upcast for the softmax — softmax_kernels.cu attn_softmax)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        q_len, k_len = s.shape[-2], s.shape[-1]
        idx_q = jax.lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
        idx_k = jax.lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
        s = jnp.where(idx_k > idx_q, DEFAULT_MASK_VALUE, s)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# --------------------------------------------------------------------------- #
# Pallas kernel
# --------------------------------------------------------------------------- #
def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
               causal: bool, sm_scale: float, block_q: int, block_k: int,
               num_k_blocks: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # With causal masking, blocks strictly above the diagonal contribute
    # nothing — skip their matmuls entirely (the analog of the reference's
    # triangular-launch trick).
    should_compute = True
    if causal:
        should_compute = qi * block_q + block_q - 1 >= ki * block_k

    @pl.when(should_compute)
    def _compute():
        # bf16 operands straight into the MXU; fp32 accumulation via
        # preferred_element_type (upcasting first would force an fp32 matmul).
        q = q_ref[0, 0]                               # [bq, d]
        k = k_ref[0, 0]                               # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk] fp32

        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col > row, DEFAULT_MASK_VALUE, s)

        m_prev = m_scr[...]                           # [bq, LANES]
        l_prev = l_scr[...]
        m_curr = jnp.max(s, axis=-1, keepdims=True)   # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)          # [bq, LANES]
        alpha = jnp.exp(m_prev[:, :1] - m_next[:, :1])   # [bq, 1]
        p = jnp.exp(s - m_next[:, :1])                # [bq, bk] fp32
        l_corr = l_prev * alpha
        l_next = l_corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_next
        l_scr[...] = jnp.broadcast_to(l_next[:, :1], l_scr.shape)

        v_blk = v_ref[0, 0]                           # [bk, d]
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, d]
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        # Fully-masked rows have l == 0; emit zeros not NaN.
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


def flash_attention_pallas(q, k, v, causal: bool = False,
                           sm_scale: Optional[float] = None,
                           block_q: int = 128, block_k: int = 128,
                           interpret: bool = False):
    """Pallas flash attention. q,k,v: [B, H, S, D] -> [B, H, S, D]."""
    if pltpu is None:
        raise RuntimeError(
            "pallas TPU support unavailable in this jax install — use "
            "mha_reference / the public flash_attention dispatcher instead")
    batch, heads, q_len, d = q.shape
    k_len = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    if q_len % block_q or k_len % block_k:
        raise ValueError(
            f"seq lengths ({q_len},{k_len}) must divide into blocks "
            f"({block_q},{block_k})")
    nq, nk = q_len // block_q, k_len // block_k

    kernel = functools.partial(
        _fa_kernel, causal=causal, sm_scale=float(sm_scale),
        block_q=block_q, block_k=block_k, num_k_blocks=nk)

    grid = (batch, heads, nq, nk)
    scratch = [
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
        pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
    ]
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        **params,
    )(q, k, v)


# --------------------------------------------------------------------------- #
# Differentiable public entry point
# --------------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)[0]


def _use_pallas(q_len, k_len, d, block_q, block_k):
    if pltpu is None or jax.default_backend() != "tpu":
        return False
    bq, bk = min(block_q, q_len), min(block_k, k_len)
    return q_len % bq == 0 and k_len % bk == 0


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    if _use_pallas(q.shape[2], k.shape[2], q.shape[3], block_q, block_k):
        out = flash_attention_pallas(q, k, v, causal=causal,
                                     sm_scale=sm_scale,
                                     block_q=block_q, block_k=block_k)
    else:
        out = mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: mha_reference(q_, k_, v_, causal=causal,
                                         sm_scale=sm_scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None, bias=None,
                    block_q: int = 128, block_k: int = 128):
    """Fused multi-head attention: q,k,v [B, H, S, D] -> [B, H, S, D].

    Dispatches to the Pallas kernel on TPU (bias-free paths); additive-bias
    attention falls back to the XLA path, which the compiler still fuses into
    few kernels."""
    if bias is not None:
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             bias=bias)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k)
