"""Fused inference transformer layer with KV cache.

Reference: deepspeed/ops/transformer/inference/transformer_inference.py
(DeepSpeedSelfAttentionFunction/DeepSpeedMLPFunction/
DeepSpeedTransformerInference with `layer_past`), backed by the CUDA
kernels of csrc/transformer/inference/ (softmax.cu, gelu.cu, normalize.cu,
dequantize.cu).

TPU-native: prefill runs the training layer's flash path on the full
prompt and emits the K/V cache; decode is a single-token step whose
attention reads a static-shape cache updated in place with
`lax.dynamic_update_slice` (jit-stable: position is a traced scalar, shapes
never change).  Int8 weights ride as (int8, per-group scale) pairs and are
dequantized at the matmul (the dequantize.cu role); XLA fuses the
dequant-multiply into the gemm epilogue.

Weight layout is identical to DeepSpeedTransformerLayer (ops/transformer.py)
so training checkpoints serve directly.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .flash_attention import DEFAULT_MASK_VALUE, flash_attention
from .normalize import fused_layer_norm
from .activations import bias_gelu
from .quant import matmul_maybe_int8
from .transformer import DeepSpeedTransformerConfig


class KVCache(NamedTuple):
    k: jnp.ndarray  # [B, heads, max_len, head_dim]
    v: jnp.ndarray


def init_kv_cache(batch: int, heads: int, max_len: int, head_dim: int,
                  dtype=jnp.bfloat16) -> KVCache:
    shape = (batch, heads, max_len, head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


class DeepSpeedTransformerInference:
    """Inference twin of DeepSpeedTransformerLayer: same params, plus KV
    cache plumbing (reference transformer_inference.py:647 layer_past)."""

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config

    # -- shared blocks -------------------------------------------------- #
    def _attn_proj(self, params, x):
        cfg = self.config
        b, s, _ = x.shape
        qkv = matmul_maybe_int8(x, params["attn_qkvw"]) + \
            params["attn_qkvb"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def to_heads(t):
            return t.reshape(b, s, cfg.heads, -1).transpose(0, 2, 1, 3)
        return to_heads(q), to_heads(k), to_heads(v)

    def _mlp(self, params, x, residual):
        cfg = self.config
        mlp_in = fused_layer_norm(x, params["attn_nw"], params["attn_nb"],
                                  cfg.layer_norm_eps)
        inter = bias_gelu(matmul_maybe_int8(mlp_in, params["inter_w"]),
                          params["inter_b"].astype(mlp_in.dtype),
                          approximate=cfg.gelu_approximate)
        out = matmul_maybe_int8(inter, params["output_w"]) + \
            params["output_b"].astype(inter.dtype)
        return out + residual

    # -- prefill -------------------------------------------------------- #
    def prefill(self, params, x, cache: KVCache,
                attn_mask: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, KVCache]:
        """Full-prompt forward.  x: [B, S, H]; returns (out, cache) with
        K/V written at positions [0, S)."""
        cfg = self.config
        x = x.astype(cfg.dtype)
        residual = x
        attn_in = fused_layer_norm(x, params["norm_w"], params["norm_b"],
                                   cfg.layer_norm_eps)
        q, k, v = self._attn_proj(params, attn_in)
        ctx = flash_attention(q, k, v, causal=cfg.causal, bias=attn_mask,
                              block_q=cfg.block_q, block_k=cfg.block_k)
        b, heads, s, d = ctx.shape
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, heads * d)
        attn_out = matmul_maybe_int8(ctx, params["attn_ow"]) + \
            params["attn_ob"].astype(ctx.dtype)
        attn_out = attn_out + residual
        out = self._mlp(params, attn_out, attn_out)
        cache = KVCache(
            jax.lax.dynamic_update_slice(
                cache.k, k.astype(cache.k.dtype), (0, 0, 0, 0)),
            jax.lax.dynamic_update_slice(
                cache.v, v.astype(cache.v.dtype), (0, 0, 0, 0)))
        return out, cache

    # -- decode --------------------------------------------------------- #
    def decode(self, params, x, cache: KVCache, pos
               ) -> Tuple[jnp.ndarray, KVCache]:
        """One-token step.  x: [B, 1, H]; pos: traced scalar index of this
        token.  Attention reads cache[0..pos] with a static-shape mask."""
        cfg = self.config
        x = x.astype(cfg.dtype)
        residual = x
        attn_in = fused_layer_norm(x, params["norm_w"], params["norm_b"],
                                   cfg.layer_norm_eps)
        q, k, v = self._attn_proj(params, attn_in)  # [B, heads, 1, d]
        cache = KVCache(
            jax.lax.dynamic_update_slice(
                cache.k, k.astype(cache.k.dtype), (0, 0, pos, 0)),
            jax.lax.dynamic_update_slice(
                cache.v, v.astype(cache.v.dtype), (0, 0, pos, 0)))
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       cache.k.astype(jnp.float32)) / jnp.sqrt(
                           jnp.float32(d))
        max_len = cache.k.shape[2]
        valid = jnp.arange(max_len) <= pos
        s = jnp.where(valid[None, None, None, :], s, DEFAULT_MASK_VALUE)
        p = jax.nn.softmax(s, axis=-1).astype(cache.v.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", p, cache.v)
        b, heads, _, _ = ctx.shape
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, 1, heads * d)
        attn_out = matmul_maybe_int8(ctx, params["attn_ow"]) + \
            params["attn_ob"].astype(ctx.dtype)
        attn_out = attn_out + residual
        out = self._mlp(params, attn_out, attn_out)
        return out, cache
