"""Pallas block-sparse flash attention over a SparsityConfig layout.

Reference: deepspeed/ops/sparse_attention/matmul.py:749 (Triton SDD/DSD/DDS
block-sparse matmuls) + softmax.py:315 (block softmax) — the reference
composes three Triton kernels, materializing the block-sparse score tensor
in HBM between them.

TPU-native design: ONE kernel per direction, flash-style.  The static
layout becomes scalar-prefetched gather indices — for grid cell
(b, h, qi, j) the BlockSpec index_map reads idx[h, qi, j] to DMA exactly
the j-th allowed k-block of query block qi, so HBM traffic and MXU work
are O(S · deg · block) and the softmax is the streaming online softmax
(no score materialization anywhere, unlike the gather-einsum path in
sparse_self_attention.py which builds an O(S · deg · block) fp32 score
tensor in HBM).  Padded entries repeat the row's last valid k-block —
the Pallas pipeline skips the DMA when the mapped block is unchanged —
and are masked off with `@pl.when`.

Backward is FlashAttention-2 over the sparse layout: dq walks the same
forward indices; dk/dv walk the TRANSPOSED layout (for each k-block, the
q-blocks that attend to it).  Both recompute P block-wise from the saved
logsumexp.
"""

import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..flash_attention import (DEFAULT_MASK_VALUE, _STATS_LANES, _LANES,
                               causal_keep_mask)


def layout_gather(layout: np.ndarray, transpose: bool = False
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """[H, nb, nb] bool -> (idx [H, nb, max_deg] int32, valid int32).

    Rows pad by REPEATING the last valid index (or 0 for empty rows) so
    consecutive grid steps map the same block and the pipeline elides the
    DMA.  transpose=True gathers over the first block axis instead (the
    dk/dv direction: for k-block i, the q-blocks attending to it).  Shares
    its gather core with layout_to_gather_indices
    (sparse_self_attention.py) — one builder, two pad policies."""
    from .sparse_self_attention import _gather_core
    if transpose:
        layout = layout.transpose(0, 2, 1)
    idx, valid = _gather_core(layout, pad_last_valid=True,
                              allow_empty_rows=True)
    return idx, valid.astype(np.int32)


def _bsf_fwd_kernel(idx_ref, val_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                    m_scr, l_scr, acc_scr, *, causal, sm_scale, block,
                    max_deg):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, DEFAULT_MASK_VALUE)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    ki = idx_ref[h, qi, j]
    live = val_ref[h, qi, j] == 1
    if causal:  # a fully-above-diagonal block contributes nothing
        live = jnp.logical_and(live, ki * block <= qi * block + block - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]                                   # [block, d]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [block, block]
        if causal:
            s = jnp.where(causal_keep_mask(qi, ki, block, block), s,
                          DEFAULT_MASK_VALUE)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_curr = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev[:, :1] - m_next[:, :1])
        p = jnp.exp(s - m_next[:, :1])
        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_next
        l_scr[...] = jnp.broadcast_to(l_next[:, :1], l_scr.shape)
        v_blk = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(j == max_deg - 1)
    def _finalize():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0, 0] = (acc_scr[...] / denom).astype(o_ref.dtype)
        lse = m_scr[...][:, :1] + jnp.log(l_scr[...][:, :1] + 1e-37)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _bsf_dq_kernel(idx_ref, val_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, causal, sm_scale, block,
                   max_deg):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    ki = idx_ref[h, qi, j]
    live = val_ref[h, qi, j] == 1
    if causal:
        live = jnp.logical_and(live, ki * block <= qi * block + block - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(causal_keep_mask(qi, ki, block, block), p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == max_deg - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bsf_dkdv_kernel(idx_ref, val_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, causal,
                     sm_scale, block, max_deg):
    h = pl.program_id(1)
    ki = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    qi = idx_ref[h, ki, j]
    live = val_ref[h, ki, j] == 1
    if causal:
        live = jnp.logical_and(live, ki * block <= qi * block + block - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(causal_keep_mask(qi, ki, block, block), p, 0.0)
        pt = p.astype(do.dtype)
        dv_scr[...] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == max_deg - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _q_spec(block, d):
    return pl.BlockSpec((1, 1, block, d),
                        lambda b, h, i, j, *refs: (b, h, i, 0))


def _gathered_spec(block, d):
    return pl.BlockSpec((1, 1, block, d),
                        lambda b, h, i, j, idx, val: (b, h, idx[h, i, j], 0))


def _stats_spec(block):
    return pl.BlockSpec((1, 1, block, _STATS_LANES),
                        lambda b, h, i, j, *refs: (b, h, i, 0))


def sparse_tiling_ok(block: int) -> bool:
    """The kernel tiles at layout-block granularity: Mosaic needs the lane
    dim (k block) % 128 and sublane (q block) % 8."""
    return block % _LANES == 0


def block_sparse_flash_fwd(q, k, v, idx, valid, block: int, causal: bool,
                           sm_scale: Optional[float] = None,
                           interpret: bool = False,
                           return_lse: bool = False):
    """q,k,v [B, H, S, D]; idx/valid [H, nb, max_deg] (layout_gather)."""
    if pltpu is None:
        raise RuntimeError("pallas TPU support unavailable")
    batch, heads, s, d = q.shape
    if s % block:
        raise ValueError(f"seq len {s} not divisible by block {block}")
    nb = s // block
    max_deg = idx.shape[-1]
    scale = float(sm_scale if sm_scale is not None else 1.0 / math.sqrt(d))
    kernel = functools.partial(_bsf_fwd_kernel, causal=causal,
                               sm_scale=scale, block=block, max_deg=max_deg)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, heads, nb, max_deg),
        in_specs=[
            _q_spec(block, d),
            _gathered_spec(block, d),
            _gathered_spec(block, d),
        ],
        out_specs=[
            _q_spec(block, d),
            _stats_spec(block),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, _LANES), jnp.float32),
            pltpu.VMEM((block, _LANES), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ])
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, s, _STATS_LANES),
                                 jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(idx, valid, q, k, v)
    return (out, lse[..., 0]) if return_lse else out


def block_sparse_flash_bwd(q, k, v, out, lse, do, idx, valid, idx_t, valid_t,
                           block: int, causal: bool,
                           sm_scale: Optional[float] = None,
                           interpret: bool = False):
    batch, heads, s, d = q.shape
    nb = s // block
    max_deg = idx.shape[-1]
    max_deg_t = idx_t.shape[-1]
    scale = float(sm_scale if sm_scale is not None else 1.0 / math.sqrt(d))

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    stats_shape = (*delta.shape, _STATS_LANES)
    delta = jnp.broadcast_to(delta[..., None], stats_shape)
    lse = jnp.broadcast_to(lse[..., None], stats_shape)

    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))

    def gathered_stats_spec(blk):
        return pl.BlockSpec((1, 1, blk, _STATS_LANES),
                            lambda b, h, i, j, idx, val:
                            (b, h, idx[h, i, j], 0))

    # dq: grid over q blocks, walking the forward gather indices
    dq_kernel = functools.partial(_bsf_dq_kernel, causal=causal,
                                  sm_scale=scale, block=block,
                                  max_deg=max_deg)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, heads, nb, max_deg),
            in_specs=[
                _q_spec(block, d),            # q
                _gathered_spec(block, d),     # k via idx
                _gathered_spec(block, d),     # v via idx
                _q_spec(block, d),            # do
                _stats_spec(block),           # lse
                _stats_spec(block),           # delta
            ],
            out_specs=_q_spec(block, d),
            scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
        **params,
    )(idx, valid, q, k, v, do, lse, delta)

    # dk/dv: grid over k blocks, walking the transposed gather indices —
    # q/do/lse/delta tiles are gathered by q-block index
    dkdv_kernel = functools.partial(_bsf_dkdv_kernel, causal=causal,
                                    sm_scale=scale, block=block,
                                    max_deg=max_deg_t)
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, heads, nb, max_deg_t),
            in_specs=[
                _gathered_spec(block, d),     # q via idx_t
                _q_spec(block, d),            # k (this grid's row)
                _q_spec(block, d),            # v
                _gathered_spec(block, d),     # do via idx_t
                gathered_stats_spec(block),   # lse via idx_t
                gathered_stats_spec(block),   # delta via idx_t
            ],
            out_specs=[
                _q_spec(block, d),
                _q_spec(block, d),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, d), jnp.float32),
                pltpu.VMEM((block, d), jnp.float32),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
        **params,
    )(idx_t, valid_t, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _bsf(q, k, v, idx, valid, idx_t, valid_t, block, causal, sm_scale,
         interpret):
    return _bsf_fwd(q, k, v, idx, valid, idx_t, valid_t, block, causal,
                    sm_scale, interpret)[0]


def _bsf_fwd(q, k, v, idx, valid, idx_t, valid_t, block, causal, sm_scale,
             interpret):
    out, lse = block_sparse_flash_fwd(
        q, k, v, idx, valid, block, causal, sm_scale, interpret=interpret,
        return_lse=True)
    return out, (q, k, v, out, lse, idx, valid, idx_t, valid_t)


def _bsf_bwd(block, causal, sm_scale, interpret, res, g):
    q, k, v, out, lse, idx, valid, idx_t, valid_t = res
    dq, dk, dv = block_sparse_flash_bwd(
        q, k, v, out, lse, g, idx, valid, idx_t, valid_t, block, causal,
        sm_scale, interpret=interpret)
    return dq, dk, dv, None, None, None, None


_bsf.defvjp(_bsf_fwd, _bsf_bwd)


def block_sparse_flash_attention(q, k, v, idx, valid, idx_t, valid_t,
                                 block: int, causal: bool = False,
                                 sm_scale: Optional[float] = None,
                                 interpret: bool = False):
    """Differentiable block-sparse flash attention.

    q,k,v: [B, H, S, D]; idx/valid from layout_gather(layout),
    idx_t/valid_t from layout_gather(layout, transpose=True); block is the
    SparsityConfig block size (must satisfy sparse_tiling_ok on TPU)."""
    return _bsf(q, k, v, jnp.asarray(idx), jnp.asarray(valid),
                jnp.asarray(idx_t), jnp.asarray(valid_t), int(block),
                bool(causal), sm_scale, interpret)
