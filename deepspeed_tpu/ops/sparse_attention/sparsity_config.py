"""Block-sparse attention layouts.

Reference: deepspeed/ops/sparse_attention/sparsity_config.py —
SparsityConfig:9 (base, block size + per-head layouts), Dense:63, Fixed:94
(Sparse-Transformers-style local windows + global summary columns),
Variable:243 (custom window sizes, random + global blocks), BigBird:421
(random + sliding window + global), BSLongformer:544 (sliding window +
selected global tokens).

A layout is a boolean array [num_heads, num_blocks, num_blocks]; entry
(h, i, j) allows query block i to attend key block j for head h.  Layouts
are built in NumPy at trace time (static shapes) — the TPU analog of the
reference's torch-tensor layout construction; the consuming kernel turns
them into gather indices (see sparse_self_attention.py).
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base layout config (reference: sparsity_config.py:9)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} must be divisible by block {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), bool)

    def check_and_propagate_first_head_layout(
            self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0:1]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend all blocks (reference: Dense:63) — debugging /
    parity baseline."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[...] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers 'fixed' pattern (reference: Fixed:94).

    Blocks attend their local window of `num_local_blocks`; the last
    `num_global_blocks` blocks of each window are global columns (attended
    by everyone); optional horizontal global rows.  `attention`
    'unidirectional' lower-triangles everything for causal LMs.
    `num_different_global_patterns` rotates which window-slice acts global
    across heads (requires different_layout_per_head)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention!r}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError(
                "horizontal global attention needs bidirectional attention")
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be a multiple of "
                f"num_global_blocks {num_global_blocks}")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "num_different_global_patterns > 1 needs "
                "different_layout_per_head")
        if num_different_global_patterns > (num_local_blocks //
                                            num_global_blocks):
            raise ValueError("too many global patterns for the window size")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        uni = self.attention == "unidirectional"
        for h in range(self.num_heads):
            # local windows
            for start in range(0, nb, self.num_local_blocks):
                end = min(start + self.num_local_blocks, nb)
                for i in range(start, end):
                    hi = (i + 1) if uni else end
                    layout[h, i, start:hi] = True
            # global slice index rotates across heads
            pattern = (h % self.num_different_global_patterns)
            first = (self.num_local_blocks -
                     (pattern + 1) * self.num_global_blocks)
            for start in range(0, nb, self.num_local_blocks):
                g0 = start + first
                g1 = g0 + self.num_global_blocks
                if g1 > nb:
                    continue
                # vertical: everyone (after, if unidirectional) sees globals
                lo = g1 if uni else 0
                layout[h, lo:, g0:g1] = True
                if uni:
                    # within-window causality already covers rows < g1
                    pass
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = True
        if uni:
            tril = np.tril(np.ones((nb, nb), bool))
            layout &= tril[None]
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Custom local windows + random + global blocks (reference:
    Variable:243).  local_window_blocks lists successive window sizes (last
    repeats); global_block_indices/end_indices choose global columns."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"attention {attention!r}")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and len(
                global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global block start/end lists differ in length")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        uni = self.attention == "unidirectional"
        rng = np.random.RandomState(self.seed)
        # local windows of varying size
        sizes = list(self.local_window_blocks)
        for h in range(self.num_heads):
            start = 0
            k = 0
            while start < nb:
                w = sizes[min(k, len(sizes) - 1)]
                end = min(start + w, nb)
                for i in range(start, end):
                    hi = (i + 1) if uni else end
                    layout[h, i, start:hi] = True
                start = end
                k += 1
            # random blocks (per head when different_layout_per_head)
            for i in range(nb):
                if self.num_random_blocks > 0:
                    cols = rng.choice(nb, self.num_random_blocks,
                                      replace=False)
                    for c in cols:
                        if not uni or c <= i:
                            layout[h, i, c] = True
            # global columns/rows
            for gi, g0 in enumerate(self.global_block_indices):
                if self.global_block_end_indices is not None:
                    g1 = self.global_block_end_indices[gi]
                else:
                    g1 = g0 + 1
                g0, g1 = min(g0, nb), min(g1, nb)
                lo = g1 if uni else 0
                layout[h, lo:, g0:g1] = True
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = True
        if uni:
            layout &= np.tril(np.ones((nb, nb), bool))[None]
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global (reference: BigBird:421)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, seed: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"{nb} blocks < sliding window "
                f"{self.num_sliding_window_blocks}")
        rng = np.random.RandomState(self.seed)
        w = self.num_sliding_window_blocks // 2
        g = self.num_global_blocks
        for h in range(self.num_heads):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = True  # band
                cols = rng.choice(nb, self.num_random_blocks, replace=False)
                layout[h, i, cols] = True                              # rand
            layout[h, :, :g] = True   # first blocks global (columns)
            layout[h, :g, :] = True   # ...and rows
            layout[h, :, nb - g:] = True
            layout[h, nb - g:, :] = True
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + selected global blocks
    (reference: BSLongformer:544)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and len(
                global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global block start/end lists differ in length")

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_heads):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = True
            for gi, g0 in enumerate(self.global_block_indices):
                if self.global_block_end_indices is not None:
                    g1 = self.global_block_end_indices[gi]
                else:
                    g1 = g0 + 1
                g0, g1 = min(g0, nb), min(g1, nb)
                layout[h, :, g0:g1] = True
                layout[h, g0:g1, :] = True
        return self.check_and_propagate_first_head_layout(layout)
