"""Standalone block-sparse matmul (SDD / DSD / DDS) over a layout.

Reference: deepspeed/ops/sparse_attention/matmul.py:749 `MatMul` — the
Triton block-sparse GEMMs behind SparseSelfAttention (sdd: dense×dense →
sparse scores; dsd: sparse×dense → dense context) plus the dds mode their
backward uses.  The reference hand-writes forward + two backward kernels
per mode and a LUT builder with segmenting/locks for the scatter.

TPU recasting: the layout is static at trace time, so every mode compiles
to gather → batched einsum (→ scatter for sdd): static shapes, MXU-sized
[block × block] tiles, and XLA autodiff differentiates straight through —
the reference's hand-written backward kernels and locking LUTs have no
analog here because gather/einsum transpose mechanically.

Sparse operand format (mirrors the reference's torch-blocksparse layout):
``[B, nnz, block, block]`` where ``nnz = layout.sum()`` and row ``n``
holds the block at the n-th nonzero of ``layout [H, nb, nb]`` in
row-major (h, i, j) order — `block_coords` returns those coordinates.
"""

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp


def block_coords(layout: np.ndarray) -> Tuple[np.ndarray, ...]:
    """(h, i, j) int32 coordinate arrays of layout's nonzeros, row-major —
    the order of the sparse format's nnz dimension."""
    layout = np.asarray(layout, bool)
    hs, is_, js = np.nonzero(layout)
    return hs.astype(np.int32), is_.astype(np.int32), js.astype(np.int32)


def _group_index(layout: np.ndarray, transpose: bool
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-(head, row-block) gather tables into the nnz dimension.

    Returns (n_idx [H, nb, max_deg], other [H, nb, max_deg], valid): for
    q-block i of head h, n_idx lists the positions in the nnz list of its
    allowed blocks and `other` the k-block ids (transpose=False); with
    transpose=True the grouping is by k-block j and `other` lists i."""
    layout = np.asarray(layout, bool)
    h, nb, _ = layout.shape
    nnz_of = -np.ones_like(layout, np.int32)
    nnz_of[np.nonzero(layout)] = np.arange(int(layout.sum()), dtype=np.int32)
    lay = layout.transpose(0, 2, 1) if transpose else layout
    deg = lay.sum(-1)
    max_deg = max(int(deg.max()), 1)
    n_idx = np.zeros((h, nb, max_deg), np.int32)
    other = np.zeros((h, nb, max_deg), np.int32)
    valid = np.zeros((h, nb, max_deg), bool)
    for hh in range(h):
        for i in range(nb):
            cols = np.nonzero(lay[hh, i])[0]
            other[hh, i, :len(cols)] = cols
            n_idx[hh, i, :len(cols)] = (nnz_of[hh, cols, i] if transpose
                                        else nnz_of[hh, i, cols])
            valid[hh, i, :len(cols)] = True
    return n_idx, other, valid


class MatMul:
    """`MatMul(layout, block, mode, trans_a, trans_b)` — API parity with
    the reference's triton ops (matmul.py:749).

    mode='sdd': c_sparse = a_dense @ b_dense at the layout's blocks
                (a, b: [B, H, S, D]-style; trans flags transpose the last
                two dims first, so the attention call sdd(q, k,
                trans_b=True) computes q @ k^T).
    mode='dsd': c_dense = a_sparse @ b_dense (trans_a transposes each
                stored block AND the layout).
    mode='dds': c_dense = a_dense @ b_sparse.
    """

    def __init__(self, layout, block: int, mode: str,
                 trans_a: bool = False, trans_b: bool = False):
        if mode not in ("sdd", "dsd", "dds"):
            raise ValueError(f"mode={mode!r} not in sdd|dsd|dds")
        self.layout = np.asarray(layout, bool)
        if self.layout.ndim != 3:
            raise ValueError("layout must be [H, nb, nb]")
        self.block = int(block)
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b
        self.nnz = int(self.layout.sum())
        hs, is_, js = block_coords(self.layout)
        self._hs, self._is, self._js = (jnp.asarray(hs), jnp.asarray(is_),
                                        jnp.asarray(js))
        # row-grouped (and col-grouped) views for the dense-output modes
        self._by_row = tuple(map(jnp.asarray,
                                 _group_index(self.layout, False)))
        self._by_col = tuple(map(jnp.asarray,
                                 _group_index(self.layout, True)))

    # ------------------------------------------------------------------ #
    def _check_heads(self, x):
        """Out-of-range head gathers would CLAMP (JAX semantics), reading
        the wrong head's data silently — guard like SparseSelfAttention."""
        h = self.layout.shape[0]
        if x.shape[1] not in (1, h):
            raise ValueError(
                f"operand has {x.shape[1]} heads, layout built for {h} "
                "(1 broadcasts)")

    def _blocked(self, x, trans):
        """[B, H, S, D] (optionally pre-transposing the trailing dims) ->
        [B, H, nb, block, D]."""
        if trans:
            x = jnp.swapaxes(x, -1, -2)
        b, h, s, d = x.shape
        if s % self.block:
            raise ValueError(f"S={s} not a multiple of block={self.block}")
        return x.reshape(b, h, s // self.block, self.block, d)

    def _sdd(self, a, b):
        self._check_heads(a)
        self._check_heads(b)
        ab = self._blocked(a, self.trans_a)
        bb = self._blocked(b, not self.trans_b)  # contract over D
        if ab.shape[1] == 1:  # head-broadcast operands (reference allows)
            ab = jnp.broadcast_to(ab, (ab.shape[0], self.layout.shape[0])
                                  + ab.shape[2:])
        if bb.shape[1] == 1:
            bb = jnp.broadcast_to(bb, (bb.shape[0], self.layout.shape[0])
                                  + bb.shape[2:])
        a_g = ab[:, self._hs, self._is]          # [B, nnz, block, D]
        b_g = bb[:, self._hs, self._js]          # [B, nnz, block, D]
        return jnp.einsum("bnqd,bnkd->bnqk", a_g, b_g,
                          preferred_element_type=jnp.float32
                          ).astype(a.dtype)

    def _dsd(self, a_sparse, b):
        self._check_heads(b)
        n_idx, other, valid = self._by_row if not self.trans_a \
            else self._by_col
        w = a_sparse
        if self.trans_a:
            w = jnp.swapaxes(w, -1, -2)
        bb = self._blocked(b, self.trans_b)
        h, nb, max_deg = n_idx.shape
        w_g = w[:, n_idx]                  # [B, H, nb, deg, block, block]
        w_g = jnp.where(valid[None, :, :, :, None, None], w_g, 0)
        b_g = bb[:, jnp.arange(h)[:, None, None], other]
        out = jnp.einsum("bhijqk,bhijkd->bhiqd", w_g, b_g,
                         preferred_element_type=jnp.float32)
        bsz, _, _, _, _, d = b_g.shape
        return out.reshape(bsz, h, nb * self.block, d).astype(b.dtype)

    def _dds(self, a, b_sparse):
        self._check_heads(a)
        # c[.., m, j·block+k] = sum_i a[.., m, i·block+q] · w[n(h,i,j),q,k]
        n_idx, other, valid = self._by_col if not self.trans_b \
            else self._by_row
        w = b_sparse
        if self.trans_b:
            w = jnp.swapaxes(w, -1, -2)
        a2 = a if not self.trans_a else jnp.swapaxes(a, -1, -2)
        bsz, h, m, s = a2.shape
        a_blk = a2.reshape(bsz, h, m, s // self.block, self.block)
        a_g = a_blk[:, jnp.arange(h)[:, None, None], :, other]
        # a_g: [H, nb_j, deg, B, m, block_q] (numpy-style advanced-index
        # reordering); move batch back
        a_g = jnp.moveaxis(a_g, 3, 0)      # [B, H, nb_j, deg, m, block_q]
        w_g = w[:, n_idx]                  # [B, H, nb_j, deg, blk_q, blk_k]
        w_g = jnp.where(valid[None, :, :, :, None, None], w_g, 0)
        out = jnp.einsum("bhjimq,bhjiqk->bhjmk", a_g, w_g,
                         preferred_element_type=jnp.float32)
        nb = n_idx.shape[1]
        out = jnp.moveaxis(out, 2, 3).reshape(bsz, h, m, nb * self.block)
        return out.astype(a.dtype)

    def __call__(self, a, b):
        if self.mode == "sdd":
            return self._sdd(a, b)
        if self.mode == "dsd":
            return self._dsd(a, b)
        return self._dds(a, b)


class Softmax:
    """Block-sparse softmax with scale / rpe / key-padding / attention
    masks — API parity with reference softmax.py:315 (same application
    order as trsrc/softmax_fwd.tr: x·scale + rpe + kp_mask + attn_mask,
    then a rowwise softmax over the row's allowed blocks).

    x: the sparse format [B, nnz, block, block].
    rpe: [S, S], [H, S, S] or [B, H, S, S] fp tensor, gathered at the
         layout blocks and ADDED (reference loads it per (head, row,
         col)).
    key_padding_mask: [B, S] over keys; mode 'add' adds the values, mode
         'mul' turns zero entries into -inf (softmax_fwd.tr:102).
    attn_mask: [S, S]; same two modes.
    Fully-masked rows produce 0 rather than the reference's NaN.
    """

    def __init__(self, layout, block: int):
        self.layout = np.asarray(layout, bool)
        self.block = int(block)
        self.nnz = int(self.layout.sum())
        self._by_row = tuple(map(jnp.asarray,
                                 _group_index(self.layout, False)))

    @functools.partial(jax.jit, static_argnames=("self", "kp_mode",
                                                 "attn_mode", "have"))
    def _impl(self, x, scale, rpe, kp, attn, kp_mode, attn_mode, have):
        from .sparse_self_attention import gathered_mask_terms

        n_idx, other, valid = self._by_row
        h, nb, max_deg = n_idx.shape
        blk = self.block
        bsz = x.shape[0]
        w = x[:, n_idx].astype(jnp.float32)  # [B, H, nb, deg, bq, bk]
        w = w * scale
        # one shared gather for rpe/kp/attn so this op and the fused
        # attention impl cannot drift (sparse_self_attention.py)
        for term in gathered_mask_terms(other, nb, blk, have, rpe, kp,
                                        attn, kp_mode, attn_mode, bsz):
            w = w + term
        neg = jnp.float32(-1e30)
        w = jnp.where(valid[None, :, :, :, None, None], w, neg)
        w = jnp.maximum(w, neg)  # -inf + -inf stays finite for the max
        flat = jnp.moveaxis(w, -2, -3)       # [B, H, nb, bq, deg, bk]
        flat = flat.reshape(bsz, h, nb, blk, max_deg * blk)
        m = jnp.max(flat, -1, keepdims=True)
        p = jnp.exp(flat - m)
        p = p * (flat > neg / 2)             # drop masked lanes exactly
        denom = jnp.maximum(p.sum(-1, keepdims=True), 1e-30)
        p = (p / denom).reshape(bsz, h, nb, blk, max_deg, blk)
        p = jnp.moveaxis(p, -2, -3)          # [B, H, nb, deg, bq, bk]
        # scatter back to the sparse format; padding entries route to a
        # dummy slot so they cannot clobber real blocks
        slot = jnp.where(valid, n_idx, self.nnz)
        out = jnp.zeros((bsz, self.nnz + 1, blk, blk), x.dtype)
        out = out.at[:, slot].set(p.astype(x.dtype))
        return out[:, :self.nnz]

    def __call__(self, x, scale=1.0, rpe=None, key_padding_mask=None,
                 attn_mask=None, key_padding_mask_mode="add",
                 attn_mask_mode="add"):
        have = tuple(name for name, v in
                     (("rpe", rpe), ("kp", key_padding_mask),
                      ("attn", attn_mask)) if v is not None)
        zero = jnp.zeros((), jnp.float32)
        return self._impl(x, jnp.float32(scale),
                          rpe if rpe is not None else zero,
                          key_padding_mask if key_padding_mask is not None
                          else zero,
                          attn_mask if attn_mask is not None else zero,
                          key_padding_mask_mode, attn_mask_mode, have)
