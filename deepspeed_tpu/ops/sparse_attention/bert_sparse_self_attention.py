"""BERT-style sparse self-attention module.

Reference: deepspeed/ops/sparse_attention/bert_sparse_self_attention.py:78
— q/k/v Linear projections + SparseSelfAttention with the BERT attention
mask as key_padding_mask, returning the merged [B, S, hidden] context.

Functional-JAX form (init_params/apply) matching the repo's model
convention; the q/k/v projections are plain matmuls so XLA fuses them
with neighbors, and the attention itself dispatches through
SparseSelfAttention (Pallas streaming kernel when the mask-free fast
path applies, gather-einsum otherwise).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import FixedSparsityConfig, SparsityConfig


class BertSparseSelfAttention:
    """`BertSparseSelfAttention(config, sparsity_config)` — config needs
    `hidden_size` and `num_attention_heads` (or `num_heads`), like the
    reference's BERT config contract."""

    def __init__(self, config, sparsity_config: Optional[SparsityConfig]
                 = None, key_padding_mask_mode: str = "add"):
        hidden = getattr(config, "hidden_size")
        heads = getattr(config, "num_attention_heads",
                        getattr(config, "num_heads", None))
        if heads is None:
            raise ValueError("config needs num_attention_heads/num_heads")
        if hidden % heads:
            raise ValueError(
                f"The hidden size ({hidden}) is not a multiple of the "
                f"number of attention heads ({heads})")
        self.num_attention_heads = heads
        self.attention_head_size = hidden // heads
        self.all_head_size = hidden
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(num_heads=heads)
        if sparsity_config.num_heads != heads:
            raise ValueError(
                f"sparsity_config built for {sparsity_config.num_heads} "
                f"heads, model has {heads}")
        self.sparse_self_attention = SparseSelfAttention(
            sparsity_config, key_padding_mask_mode=key_padding_mask_mode)

    def init_params(self, rng):
        h = self.all_head_size
        ks = jax.random.split(rng, 3)
        init = lambda k: (jax.random.normal(k, (h, h), jnp.float32)  # noqa: E731
                          * 0.02)
        return {
            "query": {"kernel": init(ks[0]), "bias": jnp.zeros((h,))},
            "key": {"kernel": init(ks[1]), "bias": jnp.zeros((h,))},
            "value": {"kernel": init(ks[2]), "bias": jnp.zeros((h,))},
        }

    def _transpose_for_scores(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_attention_heads,
                         self.attention_head_size).transpose(0, 2, 1, 3)

    def apply(self, params, hidden_states, attention_mask=None):
        """hidden_states [B, S, hidden]; attention_mask [B, S] routed as
        the key-padding mask exactly like the reference forward
        (bert_sparse_self_attention.py:78).  Its VALUES follow this
        module's key_padding_mask_mode (reference softmax.py semantics):
        the default 'add' expects an ADDITIVE mask (0 = keep, a large
        negative like -10000 = pad — the HF/BERT extended-mask
        convention); 'mul' expects 1 = keep / 0 = pad.  Returns the
        dense [B, S, hidden] context."""
        q = hidden_states @ params["query"]["kernel"] + \
            params["query"]["bias"]
        k = hidden_states @ params["key"]["kernel"] + params["key"]["bias"]
        v = hidden_states @ params["value"]["kernel"] + \
            params["value"]["bias"]
        qh = self._transpose_for_scores(q)
        kh = self._transpose_for_scores(k)
        vh = self._transpose_for_scores(v)
        ctx = self.sparse_self_attention(
            qh, kh, vh, key_padding_mask=attention_mask)
        b, _, s, _ = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(b, s, self.all_head_size)

    __call__ = apply
