"""Sparse-attention integration helpers.

Reference: deepspeed/ops/sparse_attention/sparse_attention_utils.py:225 —
pad/unpad sequences to the block size and wire SparseSelfAttention into
BERT-style models (bert_sparse_self_attention.py:78).

TPU integration point: DeepSpeedTransformerConfig.sparsity_config makes
DeepSpeedTransformerLayer route its attention through SparseSelfAttention
(ops/transformer.py), so any model built on the layer — BertModel,
GPT2Model — becomes block-sparse by config alone.
"""

import jax.numpy as jnp

from .sparse_self_attention import SparseSelfAttention


def pad_to_block_size(block: int, input_ids, pad_token_id: int,
                      attention_mask=None):
    """Right-pad [B, S] ids (and mask) so S divides the block size; returns
    (pad_len, ids, mask) like the reference's pad_to_block_size."""
    seq_len = input_ids.shape[1]
    pad_len = (block - seq_len % block) % block
    if pad_len == 0:
        return 0, input_ids, attention_mask
    ids = jnp.pad(input_ids, ((0, 0), (0, pad_len)),
                  constant_values=pad_token_id)
    if attention_mask is not None:
        attention_mask = jnp.pad(attention_mask, ((0, 0), (0, pad_len)),
                                 constant_values=0)
    return pad_len, ids, attention_mask


def unpad_sequence_output(pad_len: int, sequence_output):
    """Drop the padding added by pad_to_block_size."""
    if pad_len == 0:
        return sequence_output
    return sequence_output[:, :-pad_len]


def extend_position_embedding(params: dict, new_max_positions: int):
    """Grow a trained checkpoint's position-embedding table ("wpe") to
    support longer sparse-attention sequences by tiling the trained rows
    (reference: sparse_attention_utils.py extend_position_embedding — it
    replicates the learned table until the new length is covered, which
    preserves the local positional geometry the model trained on).

    Returns a NEW param dict; requires new_max_positions to be a multiple
    of the current table length, like the reference."""
    if "wpe" not in params:
        raise ValueError("params has no 'wpe' position-embedding table")
    wpe = params["wpe"]
    cur = wpe.shape[0]
    if new_max_positions % cur:
        raise ValueError(
            f"new_max_positions {new_max_positions} must be a multiple of "
            f"the trained length {cur} (reference semantics)")
    reps = new_max_positions // cur
    out = dict(params)
    out["wpe"] = jnp.tile(wpe, (reps, 1))
    return out
