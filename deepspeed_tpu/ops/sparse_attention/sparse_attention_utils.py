"""Sparse-attention integration helpers.

Reference: deepspeed/ops/sparse_attention/sparse_attention_utils.py:225 —
pad/unpad sequences to the block size and wire SparseSelfAttention into
BERT-style models (bert_sparse_self_attention.py:78).

TPU integration point: DeepSpeedTransformerConfig.sparsity_config makes
DeepSpeedTransformerLayer route its attention through SparseSelfAttention
(ops/transformer.py), so any model built on the layer — BertModel,
GPT2Model — becomes block-sparse by config alone.
"""

from typing import Optional, Tuple

import jax.numpy as jnp

from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import SparsityConfig


def pad_to_block_size(block: int, input_ids, pad_token_id: int,
                      attention_mask=None):
    """Right-pad [B, S] ids (and mask) so S divides the block size; returns
    (pad_len, ids, mask) like the reference's pad_to_block_size."""
    seq_len = input_ids.shape[1]
    pad_len = (block - seq_len % block) % block
    if pad_len == 0:
        return 0, input_ids, attention_mask
    ids = jnp.pad(input_ids, ((0, 0), (0, pad_len)),
                  constant_values=pad_token_id)
    if attention_mask is not None:
        attention_mask = jnp.pad(attention_mask, ((0, 0), (0, pad_len)),
                                 constant_values=0)
    return pad_len, ids, attention_mask


def unpad_sequence_output(pad_len: int, sequence_output):
    """Drop the padding added by pad_to_block_size."""
    if pad_len == 0:
        return sequence_output
    return sequence_output[:, :-pad_len]
