from .sparsity_config import (BigBirdSparsityConfig,
                              BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)
from .sparse_self_attention import (SparseSelfAttention,
                                    layout_to_gather_indices)
from .block_sparse_flash import (block_sparse_flash_attention,
                                 layout_gather)
from .sparse_attention_utils import (extend_position_embedding,
                                     pad_to_block_size,
                                     unpad_sequence_output)
from .matmul import MatMul, Softmax, block_coords
from .bert_sparse_self_attention import BertSparseSelfAttention
