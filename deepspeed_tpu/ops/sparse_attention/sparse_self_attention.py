"""Block-sparse self-attention over a SparsityConfig layout.

Reference: deepspeed/ops/sparse_attention/sparse_self_attention.py:14
(QK^T -> masked block softmax -> ·V over the layout) built on Triton
block-sparse SDD/DSD/DDS matmuls (matmul.py:749) and block softmax
(softmax.py:315).

TPU-native: the layout is static at trace time, so it compiles into gather
indices — for every (head, q-block) the set of allowed k-blocks, padded to
the layout's max degree.  Attention then runs as dense einsums over the
gathered [max_deg * block] keys: compute and memory are O(S · w) like the
Triton kernels (w = max_deg · block), but everything is static-shape XLA
that tiles straight onto the MXU; no scalar-indexed DMA needed.  Rows pad
with `valid=False` entries masked to DEFAULT_MASK_VALUE before the fp32
softmax.
"""

import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..flash_attention import DEFAULT_MASK_VALUE
from .sparsity_config import SparsityConfig


def layout_to_gather_indices(layout: np.ndarray
                             ) -> Tuple[np.ndarray, np.ndarray]:
    """[H, nb, nb] bool -> (idx [H, nb, max_deg] int32, valid bool).

    idx[h, i, j] is the j-th allowed k-block of q-block i (padded with 0
    where valid is False)."""
    h, nb, _ = layout.shape
    degrees = layout.sum(-1)
    if (degrees == 0).any():
        raise ValueError("layout has a query block with no allowed k-blocks")
    max_deg = int(degrees.max())
    idx = np.zeros((h, nb, max_deg), np.int32)
    valid = np.zeros((h, nb, max_deg), bool)
    for hh in range(h):
        for i in range(nb):
            cols = np.nonzero(layout[hh, i])[0]
            idx[hh, i, :len(cols)] = cols
            valid[hh, i, :len(cols)] = True
    return idx, valid


@functools.partial(jax.jit, static_argnames=("block", "causal", "sm_scale"))
def _sparse_attention_impl(q, k, v, idx, valid, block: int,
                           causal: bool, sm_scale: Optional[float]):
    b, h, s, d = q.shape
    nb = s // block
    max_deg = idx.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    qb = q.reshape(b, h, nb, block, d)
    kb = k.reshape(b, h, nb, block, d)
    vb = v.reshape(b, h, nb, block, d)
    heads = jnp.arange(h)[:, None, None]
    kg = kb[:, heads, idx]                    # [B, H, nb, max_deg, block, d]
    vg = vb[:, heads, idx]

    scores = jnp.einsum("bhiqd,bhijkd->bhiqjk", qb.astype(jnp.float32),
                        kg.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale

    mask = valid[:, :, None, :, None]         # [H, nb, 1, max_deg, 1]
    if causal:
        q_pos = (jnp.arange(nb)[:, None] * block +
                 jnp.arange(block)[None, :])             # [nb, block]
        k_pos = (idx[..., None] * block +
                 jnp.arange(block))                      # [H, nb, deg, blk]
        causal_ok = (k_pos[:, :, None, :, :] <=
                     q_pos[None, :, :, None, None])      # [H,nb,blk,deg,blk]
        mask = mask & causal_ok
    mask = jnp.broadcast_to(mask, (h, nb, block, max_deg, block))
    scores = jnp.where(mask[None], scores, DEFAULT_MASK_VALUE)

    flat = scores.reshape(b, h, nb, block, max_deg * block)
    m = jnp.max(flat, axis=-1, keepdims=True)
    p = jnp.exp(flat - m)
    p = p * mask.reshape(1, h, nb, block, max_deg * block)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    p = (p / l).reshape(b, h, nb, block, max_deg, block)

    out = jnp.einsum("bhiqjk,bhijkd->bhiqd", p.astype(v.dtype), vg)
    return out.reshape(b, h, s, d)


class SparseSelfAttention:
    """Layout-driven attention module (reference:
    sparse_self_attention.py:14).  Layout/gather indices are cached per
    sequence length."""

    def __init__(self, sparsity_config: SparsityConfig,
                 attn_mask_mode: str = "add"):
        self.sparsity_config = sparsity_config
        self.attn_mask_mode = attn_mask_mode
        self._cache = {}

    def layout_for(self, seq_len: int):
        if seq_len not in self._cache:
            layout = self.sparsity_config.make_layout(seq_len)
            idx, valid = layout_to_gather_indices(layout)
            self._cache[seq_len] = (layout, jnp.asarray(idx),
                                    jnp.asarray(valid))
        return self._cache[seq_len]

    def density(self, seq_len: int) -> float:
        layout, _, _ = self.layout_for(seq_len)
        return float(layout.mean())

    def __call__(self, q, k, v, causal: bool = False,
                 sm_scale: Optional[float] = None):
        """q, k, v: [B, H, S, D] -> [B, H, S, D]."""
        s = q.shape[2]
        block = self.sparsity_config.block
        _, idx, valid = self.layout_for(s)
        if q.shape[1] != self.sparsity_config.num_heads:
            raise ValueError(
                f"q has {q.shape[1]} heads, layout built for "
                f"{self.sparsity_config.num_heads}")
        return _sparse_attention_impl(q, k, v, idx, valid, block, causal,
                                      sm_scale)
