"""Block-sparse self-attention over a SparsityConfig layout.

Reference: deepspeed/ops/sparse_attention/sparse_self_attention.py:14
(QK^T -> masked block softmax -> ·V over the layout) built on Triton
block-sparse SDD/DSD/DDS matmuls (matmul.py:749) and block softmax
(softmax.py:315).

TPU-native: the layout is static at trace time, so it compiles into gather
indices — for every (head, q-block) the set of allowed k-blocks, padded to
the layout's max degree.  Attention then runs as dense einsums over the
gathered [max_deg * block] keys: compute and memory are O(S · w) like the
Triton kernels (w = max_deg · block), but everything is static-shape XLA
that tiles straight onto the MXU; no scalar-indexed DMA needed.  Rows pad
with `valid=False` entries masked to DEFAULT_MASK_VALUE before the fp32
softmax.
"""

import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..flash_attention import DEFAULT_MASK_VALUE
from .sparsity_config import SparsityConfig


def _gather_core(layout: np.ndarray, pad_last_valid: bool,
                 allow_empty_rows: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Shared gather-index builder: [H, nb, nb] bool ->
    (idx [H, nb, max_deg] int32, valid bool).  pad_last_valid repeats the
    row's last allowed block into the padding (so a sequential consumer
    revisits the same block and elides the DMA); otherwise padding is 0."""
    h, nb, _ = layout.shape
    degrees = layout.sum(-1)
    if not allow_empty_rows and (degrees == 0).any():
        raise ValueError("layout has a query block with no allowed k-blocks")
    max_deg = max(int(degrees.max()), 1)
    idx = np.zeros((h, nb, max_deg), np.int32)
    valid = np.zeros((h, nb, max_deg), bool)
    for hh in range(h):
        for i in range(nb):
            cols = np.nonzero(layout[hh, i])[0]
            idx[hh, i, :len(cols)] = cols
            valid[hh, i, :len(cols)] = True
            if pad_last_valid and len(cols):
                idx[hh, i, len(cols):] = cols[-1]
    return idx, valid


def layout_to_gather_indices(layout: np.ndarray
                             ) -> Tuple[np.ndarray, np.ndarray]:
    """[H, nb, nb] bool -> (idx [H, nb, max_deg] int32, valid bool).

    idx[h, i, j] is the j-th allowed k-block of q-block i (padded with 0
    where valid is False)."""
    return _gather_core(layout, pad_last_valid=False, allow_empty_rows=False)


def gathered_mask_terms(kcols, nb, block, have, rpe, key_padding_mask,
                        attn_mask, kp_mode, attn_mode, batch):
    """Block-gathered additive mask terms, shared by the fused attention
    impl below and the standalone Softmax op (matmul.py) so the two
    paths cannot drift.  kcols [H, nb, deg] holds each row-block's
    allowed k-block ids; every returned term broadcasts against the
    [B, H, nb, deg, bq, bk] score layout (callers in [.., bq, deg, bk]
    moveaxis(-2, -3) each term).  Semantics mirror trsrc/softmax_fwd.tr:
    rpe added; mul-mode masks convert zero entries to DEFAULT_MASK_VALUE,
    add-mode values pass through."""
    h = kcols.shape[0]
    heads = jnp.arange(h)[:, None, None]
    rows = jnp.arange(nb)[None, :, None]
    terms = []
    if "rpe" in have:
        r = rpe.astype(jnp.float32)
        if r.ndim == 2:
            r = r[None, None]
        elif r.ndim == 3:
            r = r[None]
        rb = r.reshape(r.shape[0], r.shape[1], nb, block, nb, block)
        rb = jnp.moveaxis(rb, 4, 3)          # [b?, h?, nb_i, nb_j, bq, bk]
        rb = jnp.broadcast_to(rb, (rb.shape[0], h, nb, nb, block, block))
        terms.append(rb[:, heads, rows, kcols])  # [B?, H, nb, deg, bq, bk]
    if "kp" in have:
        kpf = key_padding_mask.astype(jnp.float32)
        if kp_mode == "mul":
            kpf = jnp.where(kpf == 0, DEFAULT_MASK_VALUE, 0.0)
        kp_g = kpf.reshape(batch, nb, block)[:, kcols]  # [B, H, nb, deg, bk]
        terms.append(kp_g[:, :, :, :, None, :])
    if "attn" in have:
        am = attn_mask.astype(jnp.float32)
        if attn_mode == "mul":
            am = jnp.where(am == 0, DEFAULT_MASK_VALUE, 0.0)
        ab = am.reshape(nb, block, nb, block)
        ab = jnp.moveaxis(ab, 2, 1)          # [nb_i, nb_j, bq, bk]
        terms.append(ab[rows, kcols][None])  # [1, H, nb, deg, bq, bk]
    return terms


@functools.partial(jax.jit, static_argnames=("block", "causal", "sm_scale",
                                             "kp_mode", "attn_mode",
                                             "have"))
def _sparse_attention_impl(q, k, v, idx, valid, block: int,
                           causal: bool, sm_scale: Optional[float],
                           rpe=None, key_padding_mask=None, attn_mask=None,
                           kp_mode: str = "add", attn_mode: str = "add",
                           have: tuple = ()):
    b, h, s, d = q.shape
    nb = s // block
    max_deg = idx.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    qb = q.reshape(b, h, nb, block, d)
    kb = k.reshape(b, h, nb, block, d)
    vb = v.reshape(b, h, nb, block, d)
    heads = jnp.arange(h)[:, None, None]
    kg = kb[:, heads, idx]                    # [B, H, nb, max_deg, block, d]
    vg = vb[:, heads, idx]

    scores = jnp.einsum("bhiqd,bhijkd->bhiqjk", qb.astype(jnp.float32),
                        kg.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale

    # reference mask-application order (trsrc/softmax_fwd.tr): x·scale
    # + rpe + key_padding_mask + attn_mask, then the masked softmax
    for term in gathered_mask_terms(idx, nb, block, have, rpe,
                                    key_padding_mask, attn_mask,
                                    kp_mode, attn_mode, b):
        scores = scores + jnp.moveaxis(term, -2, -3)  # -> [.., bq, deg, bk]
    if have:
        # two stacked mul-mode masks would overflow fp32 to -inf and the
        # exp below would then produce NaN on fully-masked rows; clamping
        # keeps the max finite (same guard as matmul.Softmax)
        scores = jnp.maximum(scores, DEFAULT_MASK_VALUE)

    mask = valid[:, :, None, :, None]         # [H, nb, 1, max_deg, 1]
    if causal:
        q_pos = (jnp.arange(nb)[:, None] * block +
                 jnp.arange(block)[None, :])             # [nb, block]
        k_pos = (idx[..., None] * block +
                 jnp.arange(block))                      # [H, nb, deg, blk]
        causal_ok = (k_pos[:, :, None, :, :] <=
                     q_pos[None, :, :, None, None])      # [H,nb,blk,deg,blk]
        mask = mask & causal_ok
    mask = jnp.broadcast_to(mask, (h, nb, block, max_deg, block))
    scores = jnp.where(mask[None], scores, DEFAULT_MASK_VALUE)

    flat = scores.reshape(b, h, nb, block, max_deg * block)
    m = jnp.max(flat, axis=-1, keepdims=True)
    p = jnp.exp(flat - m)
    # exclude layout padding AND mul-mode-masked lanes (their scores sit
    # at ~DEFAULT_MASK_VALUE); a fully-masked row then outputs 0 instead
    # of the reference kernel's NaN
    p = p * (flat > DEFAULT_MASK_VALUE / 2)
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    p = (p / denom).reshape(b, h, nb, block, max_deg, block)

    out = jnp.einsum("bhiqjk,bhijkd->bhiqd", p.astype(v.dtype), vg)
    return out.reshape(b, h, s, d)


class SparseSelfAttention:
    """Layout-driven attention module (reference:
    sparse_self_attention.py:14).  Layout/gather indices are cached per
    sequence length.

    Two execution paths, dispatched per call:
    - the Pallas block-sparse flash kernel (block_sparse_flash.py) when the
      layout block is lane-aligned and Pallas is available — streaming
      softmax, no score materialization;
    - the gather-einsum path (_sparse_attention_impl) elsewhere (CPU, odd
      block sizes) — same O(S·deg·block) compute, but scores materialize.
    """

    def __init__(self, sparsity_config: SparsityConfig,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "add", impl: str = "auto"):
        if impl not in ("auto", "pallas", "gather"):
            raise ValueError(f"impl={impl!r} not in auto|pallas|gather")
        for mode in (key_padding_mask_mode, attn_mask_mode):
            if mode not in ("add", "mul"):
                raise ValueError(f"mask mode {mode!r} not in add|mul")
        self.sparsity_config = sparsity_config
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.impl = impl
        self._cache = {}

    def layout_for(self, seq_len: int):
        if seq_len not in self._cache:
            from .block_sparse_flash import layout_gather
            layout = self.sparsity_config.make_layout(seq_len)
            idx, valid = layout_to_gather_indices(layout)
            fidx, fvalid = layout_gather(layout)
            tidx, tvalid = layout_gather(layout, transpose=True)
            self._cache[seq_len] = (layout, jnp.asarray(idx),
                                    jnp.asarray(valid),
                                    tuple(jnp.asarray(a) for a in
                                          (fidx, fvalid, tidx, tvalid)))
        return self._cache[seq_len]

    def density(self, seq_len: int) -> float:
        layout = self.layout_for(seq_len)[0]
        return float(layout.mean())

    def _use_pallas(self) -> bool:
        if self.impl == "gather":
            return False
        from ..dispatch import pallas_available
        from .block_sparse_flash import sparse_tiling_ok
        ok = pallas_available() and sparse_tiling_ok(
            self.sparsity_config.block)
        if self.impl == "pallas" and not ok:
            raise ValueError(
                f"impl='pallas': block={self.sparsity_config.block} not "
                "lane-aligned or Pallas unavailable on this backend")
        return ok

    def __call__(self, q, k, v, causal: bool = False,
                 sm_scale: Optional[float] = None, rpe=None,
                 key_padding_mask=None, attn_mask=None):
        """q, k, v: [B, H, S, D] -> [B, H, S, D].

        rpe / key_padding_mask / attn_mask follow the reference forward
        (sparse_self_attention.py:105): rpe is [S, S] / [H, S, S] /
        [B, H, S, S] added to the scores; key_padding_mask is [B, S]
        over keys; attn_mask is [S, S]; each mask honors this module's
        add/mul mode (softmax.py semantics).  Masked calls run on the
        gather path — the Pallas streaming kernel covers the plain
        layout+causal cases (impl='pallas' raises rather than silently
        degrading)."""
        s = q.shape[2]
        block = self.sparsity_config.block
        _, idx, valid, flash_idx = self.layout_for(s)
        if q.shape[1] != self.sparsity_config.num_heads:
            raise ValueError(
                f"q has {q.shape[1]} heads, layout built for "
                f"{self.sparsity_config.num_heads}")
        have = tuple(name for name, t in
                     (("rpe", rpe), ("kp", key_padding_mask),
                      ("attn", attn_mask)) if t is not None)
        if have:
            if self.impl == "pallas":
                raise ValueError(
                    "impl='pallas': rpe/key_padding_mask/attn_mask run on "
                    "the gather path — use impl='auto' or 'gather'")
            return _sparse_attention_impl(
                q, k, v, idx, valid, block, causal, sm_scale,
                rpe=rpe, key_padding_mask=key_padding_mask,
                attn_mask=attn_mask,
                kp_mode=self.key_padding_mask_mode,
                attn_mode=self.attn_mask_mode, have=have)
        if self._use_pallas():
            from .block_sparse_flash import block_sparse_flash_attention
            fidx, fvalid, tidx, tvalid = flash_idx
            return block_sparse_flash_attention(
                q, k, v, fidx, fvalid, tidx, tvalid, block, causal=causal,
                sm_scale=sm_scale)
        return _sparse_attention_impl(q, k, v, idx, valid, block, causal,
                                      sm_scale)
