"""deepspeed_tpu.ops — the TPU kernel layer.

Plays the role of the reference's `deepspeed/ops/` (Python wrappers over
csrc/ CUDA kernels).  On TPU the hot ops are Pallas kernels feeding the MXU;
everything XLA already fuses well (bias+gelu, bias+dropout+residual, Adam
elementwise math) is expressed as plain jnp and left to the compiler.
"""

from .flash_attention import (flash_attention,
                              flash_attention_bsh,
                              mha_reference)
from .normalize import fused_layer_norm, layer_norm_reference
from .activations import bias_gelu, bias_dropout_residual, gelu
from .transformer import (DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)

__all__ = [
    "flash_attention", "flash_attention_bsh", "mha_reference",
    "fused_layer_norm",
    "layer_norm_reference", "bias_gelu", "bias_dropout_residual", "gelu",
    "DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer",
]
