"""Megatron's f/g collective operators for MANUAL-TP regions.

Used by every explicit-collective tensor-parallel path (the transformer
layer's tp_axis mode, the vocab-parallel embedding/CE) inside
shard_map-manual regions compiled with check_vma=False — where shard_map
cannot track the replicated/varying boundary, so plain lax.psum
transposes to psum and multiplies upstream cotangents by tp_size.  The
custom VJPs encode the boundary instead (ARCHITECTURE.md invariant 10):

  tp_psum  ("g"): all-reduce forward, IDENTITY backward — placed where
      row-parallel partial outputs merge; the output cotangent arriving
      from replicated downstream compute is already full.
  tp_fcast ("f"): IDENTITY forward, all-reduce backward — placed at each
      replicated->column-parallel input boundary; the per-peer cotangent
      there is only that peer's partial (it flowed through the peer's own
      weight shards) and the backward psum restores the full cotangent,
      so every upstream grad is exact per-device with no post-hoc
      correction.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _psum_compilable(x, axis):
    """lax.psum that compiles on every backend.

    XLA CPU's AllReducePromotion pass CRASHES (hlo_instruction.cc
    "Invalid binary instruction opcode copy") cloning the sub-f32
    all-reduces these manual regions emit, so promote them explicitly
    there — the same discipline the ZeRO-3 streamed region adopted in
    round 3 (ARCHITECTURE.md invariant 4).  TPU keeps the native width
    on the wire."""
    if (x.dtype in (jnp.bfloat16, jnp.float16)
            and jax.default_backend() == "cpu"):
        return lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return lax.psum(x, axis)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_psum(x, axis):
    """All-reduce forward, identity backward (Megatron "g")."""
    return _psum_compilable(x, axis)


def _tp_psum_fwd(x, axis):
    return _psum_compilable(x, axis), None


def _tp_psum_bwd(axis, _, ct):
    return (ct,)


tp_psum.defvjp(_tp_psum_fwd, _tp_psum_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_fcast(x, axis):
    """Identity forward, all-reduce backward (Megatron "f")."""
    return x


def _tp_fcast_fwd(x, axis):
    return x, None


def _tp_fcast_bwd(axis, _, ct):
    return (_psum_compilable(ct, axis),)


tp_fcast.defvjp(_tp_fcast_fwd, _tp_fcast_bwd)
