"""Megatron's f/g collective operators for MANUAL-TP regions.

Used by every explicit-collective tensor-parallel path (the transformer
layer's tp_axis mode, the vocab-parallel embedding/CE) inside
shard_map-manual regions compiled with check_vma=False — where shard_map
cannot track the replicated/varying boundary, so plain lax.psum
transposes to psum and multiplies upstream cotangents by tp_size.  The
custom VJPs encode the boundary instead (ARCHITECTURE.md invariant 10):

  tp_psum  ("g"): all-reduce forward, IDENTITY backward — placed where
      row-parallel partial outputs merge; the output cotangent arriving
      from replicated downstream compute is already full.
  tp_fcast ("f"): IDENTITY forward, all-reduce backward — placed at each
      replicated->column-parallel input boundary; the per-peer cotangent
      there is only that peer's partial (it flowed through the peer's own
      weight shards) and the backward psum restores the full cotangent,
      so every upstream grad is exact per-device with no post-hoc
      correction.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _psum_compilable(x, axis):
    """lax.psum with sub-f32 inputs promoted to f32 BY DEFAULT.

    Two reasons, same as the ZeRO-3 streamed region's round-3 rule
    (ARCHITECTURE.md invariant 4: manual regions run every reduction
    collective they emit in fp32): XLA CPU's AllReducePromotion pass
    CRASHES (hlo_instruction.cc "Invalid binary instruction opcode
    copy") cloning these manual-region bf16 all-reduces, and a
    backend-conditional gate cannot be trusted here —
    jax.default_backend() misreports "tpu" in the CPU-sim dryrun
    scenario dispatch.py documents.  Cost on real TPU: 2x wire bytes on
    these boundaries.

    DS_TP_PSUM_NATIVE=1 is the measured native-width mode (VERDICT r4
    weak #5): an EXPLICIT opt-in for real multi-chip TPU runs — halves
    the manual-TP wire bytes, reduces the partial sums in bf16 (a
    precision change, like the reference's fp16 allreduce default),
    and must never be set where a CPU backend might compile the region.
    Read at trace time: set it before the engine builds its programs."""
    if (x.dtype in (jnp.bfloat16, jnp.float16)
            and os.environ.get("DS_TP_PSUM_NATIVE", "0") != "1"):
        return lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return lax.psum(x, axis)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_psum(x, axis):
    """All-reduce forward, identity backward (Megatron "g")."""
    return _psum_compilable(x, axis)


def _tp_psum_fwd(x, axis):
    return _psum_compilable(x, axis), None


def _tp_psum_bwd(axis, _, ct):
    return (ct,)


tp_psum.defvjp(_tp_psum_fwd, _tp_psum_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_fcast(x, axis):
    """Identity forward, all-reduce backward (Megatron "f")."""
    return x


def _tp_fcast_fwd(x, axis):
    return x, None


def _tp_fcast_bwd(axis, _, ct):
    return (_psum_compilable(ct, axis),)


tp_fcast.defvjp(_tp_fcast_fwd, _tp_fcast_bwd)
