"""Megatron's f/g collective operators for MANUAL-TP regions.

Used by every explicit-collective tensor-parallel path (the transformer
layer's tp_axis mode, the vocab-parallel embedding/CE) inside
shard_map-manual regions compiled with check_vma=False — where shard_map
cannot track the replicated/varying boundary, so plain lax.psum
transposes to psum and multiplies upstream cotangents by tp_size.  The
custom VJPs encode the boundary instead (ARCHITECTURE.md invariant 10):

  tp_psum  ("g"): all-reduce forward, IDENTITY backward — placed where
      row-parallel partial outputs merge; the output cotangent arriving
      from replicated downstream compute is already full.
  tp_fcast ("f"): IDENTITY forward, all-reduce backward — placed at each
      replicated->column-parallel input boundary; the per-peer cotangent
      there is only that peer's partial (it flowed through the peer's own
      weight shards) and the backward psum restores the full cotangent,
      so every upstream grad is exact per-device with no post-hoc
      correction.
"""

from functools import partial

import jax
from jax import lax


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_psum(x, axis):
    """All-reduce forward, identity backward (Megatron "g")."""
    return lax.psum(x, axis)


def _tp_psum_fwd(x, axis):
    return lax.psum(x, axis), None


def _tp_psum_bwd(axis, _, ct):
    return (ct,)


tp_psum.defvjp(_tp_psum_fwd, _tp_psum_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_fcast(x, axis):
    """Identity forward, all-reduce backward (Megatron "f")."""
    return x


def _tp_fcast_fwd(x, axis):
    return x, None


def _tp_fcast_bwd(axis, _, ct):
    return (lax.psum(ct, axis),)


tp_fcast.defvjp(_tp_fcast_fwd, _tp_fcast_bwd)
