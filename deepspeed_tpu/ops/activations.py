"""Fused elementwise transformer ops — gelu, bias+gelu, bias+dropout+residual.

Reference: csrc/transformer/gelu_kernels.cu:330 (fused bias-gelu fwd/bwd) and
csrc/transformer/dropout_kernels.cu:868 (fused bias+dropout+residual).

On TPU these are expressed as plain jnp: XLA fuses the whole chain into the
neighbouring matmul's epilogue, which is exactly what the hand-written CUDA
kernels buy on GPU.  Dropout uses the JAX counter-based PRNG (threefry),
giving reproducible masks under jit/shard_map — the role of the reference's
per-kernel curand states (dropout_kernels.cu Dropout<T>::SetMask).
"""

import jax
import jax.numpy as jnp


def gelu(x):
    """tanh-approximation gelu, matching gelu_kernels.cu:10
    (0.5 * x * (1 + tanh(sqrt(2/pi) * (x + 0.044715 x^3))))."""
    xf = x.astype(jnp.float32)
    out = 0.5 * xf * (1.0 + jnp.tanh(0.7978845608028654 *
                                     (xf + 0.044715 * xf * xf * xf)))
    return out.astype(x.dtype)


def gelu_exact(x):
    """Exact (erf) gelu — HF BERT's default hidden_act="gelu"."""
    xf = x.astype(jnp.float32)
    return (xf * 0.5 * (1.0 + jax.lax.erf(
        xf / jnp.sqrt(jnp.float32(2.0))))).astype(x.dtype)


def bias_gelu(x, bias, approximate: bool = True):
    """Fused bias-add + gelu (gelu_kernels.cu fused_bias_gelu)."""
    y = x + bias
    return gelu(y) if approximate else gelu_exact(y)


def dropout(x, rate: float, rng, deterministic: bool = False):
    if deterministic or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, p=keep, shape=x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def bias_dropout_residual(x, bias, residual, rate: float, rng,
                          deterministic: bool = False):
    """Fused bias-add + dropout + residual-add
    (dropout_kernels.cu dropout_kernel + bias/residual variants)."""
    return dropout(x + bias, rate, rng, deterministic) + residual
