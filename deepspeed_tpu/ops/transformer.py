"""DeepSpeedTransformerLayer — the fused transformer block.

Reference: deepspeed/ops/transformer/transformer.py — DeepSpeedTransformerConfig
(:39), DeepSpeedTransformerLayer (:462, owns attn_qkvw/attn_qkvb/attn_ow/...),
backed by the csrc/transformer CUDA kernels.

TPU-native: the layer is a pure function over a param pytree (same weight
names as the reference for checkpoint parity).  Attention runs the Pallas
flash kernel; LN the fused LN; bias/gelu/dropout chains are left to XLA
fusion.  Tensor parallelism is declared, not coded: `param_partition_specs`
returns the Megatron-style column/row split over the "model" mesh axis and
GSPMD inserts the per-layer collectives.  (Exception: inside shard_map-manual
regions — the gated 1F1B executor — `__call__(tp_axis=...)` runs the same
split with EXPLICIT collectives, the f/g operator pair of
ops/tp_collectives.py, so they stay out of divergent control flow.)
"""

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MODEL_AXIS
from .activations import bias_gelu, bias_dropout_residual, dropout
from .flash_attention import flash_attention, flash_attention_bsh
from .normalize import fused_layer_norm
from .quant import matmul_maybe_int8
from .tp_collectives import tp_fcast, tp_psum


@dataclass
class DeepSpeedTransformerConfig:
    """Mirror of ops/transformer/transformer.py:39 (CUDA-only knobs dropped,
    TPU knobs added)."""
    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-5
    seed: int = -1
    fp16: bool = False
    bf16: bool = True
    pre_layer_norm: bool = True
    layer_id: int = 0
    # TPU additions
    causal: bool = False
    # v5e-tuned flash blocks (ops/flash_attention.DEFAULT_BLOCK_*)
    block_q: int = 512
    block_k: int = 1024
    # "auto" = Pallas flash when usable, XLA reference otherwise
    attn_impl: str = "auto"
    # "bhsd" (default): classic [B,H,S,D] kernel layout.  "bshd": API
    # convenience for [B,S,H,D] callers — NOT transpose-free: a native
    # bshd BlockSpec is Mosaic-illegal (measured round 3, v5e), so the
    # layout converts at the Pallas boundary; the transposes are <1% of
    # step traffic.
    attn_layout: str = "bhsd"
    # "kernel" = in-kernel attention-probability dropout (reference
    # semantics, ~10% step cost at S=1024); "ctx" = cheap dropout on the
    # attention output (different regularizer) — see __call__
    attn_dropout_impl: str = "kernel"
    # "gelu_new"/"gelu_pytorch_tanh" = tanh approx (the reference kernel's
    # flavor, gelu_kernels.cu:10); "gelu" = exact erf (HF BERT default)
    activation: str = "gelu_new"
    # block-sparse attention: a SparsityConfig routes the layer's attention
    # through SparseSelfAttention (the reference wires this via
    # bert_sparse_self_attention.py:78; here it's one config field)
    sparsity_config: Optional[object] = None
    # "dense" (default) = the fused inter/output FFN; "none" = attention
    # sublayer only (no FFN params) — the GShard/Megatron-MoE pattern
    # replaces the FFN of alternating layers with an expert layer
    # (reference: moe/layer.py MoE wraps the FFN position), so the MoE
    # model composes [attention-only layer] + [gated expert FFN block]
    ffn: str = "dense"

    @property
    def gelu_approximate(self) -> bool:
        if self.activation in ("gelu_new", "gelu_pytorch_tanh",
                               "gelu_python", "gelu_fast"):
            return True
        if self.activation == "gelu":
            return False
        raise ValueError(f"unsupported activation {self.activation!r} — "
                         f"gelu variants only (reference kernel parity)")

    def __post_init__(self):
        if self.intermediate_size == -1 and self.hidden_size != -1:
            self.intermediate_size = 4 * self.hidden_size
        if self.ffn not in ("dense", "none"):
            raise ValueError(
                f"ffn={self.ffn!r}: must be 'dense' or 'none' "
                "(init/forward/specs all key on it)")
        if self.attn_dropout_impl not in ("kernel", "ctx"):
            raise ValueError(
                f"attn_dropout_impl={self.attn_dropout_impl!r}: must be "
                "'kernel' (in-kernel probability dropout, reference "
                "semantics) or 'ctx' (output dropout)")

    @property
    def dtype(self):
        if self.bf16:
            return jnp.bfloat16
        if self.fp16:
            return jnp.float16
        return jnp.float32


class DeepSpeedTransformerLayer:
    """Fused transformer layer (reference: transformer.py:462).

    Weight names follow the reference exactly:
      attn_qkvw [H, 3H], attn_qkvb [3H], attn_ow [H, H], attn_ob [H],
      attn_nw/attn_nb [H] (post-attention LN), inter_w [H, I], inter_b [I],
      output_w [I, H], output_b [H], norm_w/norm_b [H].
    """

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config
        self._sparse_attn = None
        if config.sparsity_config is not None:
            from .sparse_attention import SparseSelfAttention
            self._sparse_attn = SparseSelfAttention(config.sparsity_config)

    # -- parameters ---------------------------------------------------- #
    def init_params(self, rng):
        cfg = self.config
        h, inter = cfg.hidden_size, cfg.intermediate_size
        std = cfg.initializer_range
        keys = jax.random.split(rng, 4)
        init = jax.nn.initializers.normal(std)
        params = {
            "attn_qkvw": init(keys[0], (h, 3 * h), jnp.float32),
            "attn_qkvb": jnp.zeros((3 * h,), jnp.float32),
            "attn_ow": init(keys[1], (h, h), jnp.float32),
            "attn_ob": jnp.zeros((h,), jnp.float32),
            "norm_w": jnp.ones((h,), jnp.float32),
            "norm_b": jnp.zeros((h,), jnp.float32),
        }
        if cfg.ffn == "dense":
            params.update({
                "attn_nw": jnp.ones((h,), jnp.float32),
                "attn_nb": jnp.zeros((h,), jnp.float32),
                "inter_w": init(keys[2], (h, inter), jnp.float32),
                "inter_b": jnp.zeros((inter,), jnp.float32),
                "output_w": init(keys[3], (inter, h), jnp.float32),
                "output_b": jnp.zeros((h,), jnp.float32),
            })
        return params

    @staticmethod
    def param_partition_specs(ffn: str = "dense"):
        """Megatron-style TP: qkv/inter column-split, out/output row-split
        over the "model" axis (the role the external Megatron mpu plays in
        the reference — engine.py:739-770)."""
        specs = {
            "attn_qkvw": P(None, MODEL_AXIS),
            "attn_qkvb": P(MODEL_AXIS),
            "attn_ow": P(MODEL_AXIS, None),
            "attn_ob": P(),
            "norm_w": P(), "norm_b": P(),
        }
        if ffn == "dense":
            specs.update({
                "attn_nw": P(), "attn_nb": P(),
                "inter_w": P(None, MODEL_AXIS),
                "inter_b": P(MODEL_AXIS),
                "output_w": P(MODEL_AXIS, None),
                "output_b": P(),
            })
        return specs

    @staticmethod
    def tp_manual_views(params, heads: int):
        """Rearrange the fused qkv leaves head-major for MANUAL TP.

        Storage keeps the reference's blocked [q|k|v] layout (attn_qkvw
        [..., H, 3H], attn_qkvb [..., 3H]) — HF policy imports, the MP
        resize merge/split (state_dict_factory) and inference all assume
        it.  A contiguous model-axis shard of that layout holds
        MISmatched q/k/v pieces, so the gated executor views them as
        [..., H, heads, 3, d] / [..., heads, 3, d] (a free in-graph
        reshape+swap applied OUTSIDE the shard_map; AD transposes it) —
        any contiguous shard of the heads dim then carries matched head
        groups.  Returns the viewed tree; `tp_manual_unview` restores
        storage layout (for the grads)."""
        p = dict(params)
        w = p["attn_qkvw"]
        d = w.shape[-2] // heads
        p["attn_qkvw"] = w.reshape(
            w.shape[:-1] + (3, heads, d)).swapaxes(-3, -2)
        bias = p["attn_qkvb"]
        p["attn_qkvb"] = bias.reshape(
            bias.shape[:-1] + (3, heads, d)).swapaxes(-3, -2)
        return p

    @staticmethod
    def tp_manual_unview(params):
        """Inverse of tp_manual_views (applied to the grads)."""
        p = dict(params)
        w = p["attn_qkvw"]  # [..., H, heads, 3, d]
        heads, _, d = w.shape[-3:]
        p["attn_qkvw"] = w.swapaxes(-3, -2).reshape(
            w.shape[:-3] + (3 * heads * d,))
        bias = p["attn_qkvb"]
        p["attn_qkvb"] = bias.swapaxes(-3, -2).reshape(
            bias.shape[:-3] + (3 * heads * d,))
        return p

    @staticmethod
    def tp_manual_view_specs(ffn: str = "dense"):
        """param_partition_specs in the tp_manual_views layout: the qkv
        leaves shard on their heads dim; everything else is unchanged
        (attn_ow's row shard is already head-contiguous)."""
        specs = DeepSpeedTransformerLayer.param_partition_specs(ffn)
        specs["attn_qkvw"] = P(None, MODEL_AXIS, None, None)
        specs["attn_qkvb"] = P(MODEL_AXIS, None, None)
        return specs

    def num_params(self):
        h, i = self.config.hidden_size, self.config.intermediate_size
        if self.config.ffn != "dense":
            # qkvw+ow (4h^2) + qkvb+ob (4h) + pre-attn LN (2h)
            return 4 * h * h + 6 * h
        return 4 * h * h + 2 * h * i + 9 * h + i

    # -- forward ------------------------------------------------------- #
    def __call__(self, params, x, attn_mask=None, rng=None,
                 deterministic: bool = False, tp_axis: Optional[str] = None,
                 seq_axis: Optional[str] = None, sp_mode: str = "auto"):
        """x: [B, S, H] -> [B, S, H].  attn_mask: additive [B, 1, 1, S] or
        [B, 1, S, S] bias, like the reference's input_mask.

        tp_axis: MANUAL tensor parallelism — params are LOCAL Megatron
        shards (param_partition_specs layout over that mesh axis) and the
        row-parallel matmul outputs are psum'd explicitly here, instead of
        GSPMD inserting the collectives from sharding annotations.  Used
        inside shard_map-manual regions where GSPMD-placed collectives
        would land in divergent control flow (the gated 1F1B executor's
        per-stage lax.cond branches — one_f_one_b.py).  x and the returned
        activation are replicated over tp_axis.

        seq_axis: MANUAL sequence parallelism — x is the LOCAL sequence
        chunk [B, S_local, H] (global order follows the axis index) and
        attention runs ring or Ulysses over that axis
        (parallel/sequence.py *_inner), with explicit collectives for the
        same divergent-control-flow reason as tp_axis.  Composes with
        tp_axis: local heads × local sequence, ring/all-to-all over seq,
        psums over model.  Restrictions: no sparse attention, no additive
        attn_mask, and the attention-probability ('kernel') dropout falls
        back to output ('ctx') dropout — the ring accumulator has no PRNG
        path.  sp_mode: 'ring' | 'ulysses' | 'allgather' | 'auto'
        (Ulysses when the seq degree divides the local head count —
        heads redistribute across seq peers)."""
        cfg = self.config
        eps = cfg.layer_norm_eps
        heads = cfg.heads
        b, s, h = x.shape
        d = h // heads
        if tp_axis is not None:
            # local heads from the head-major qkv view [H, hl, 3, d]
            # (tp_manual_views — a contiguous model-axis shard of the
            # blocked [q|k|v] layout would hold MISmatched q/k/v pieces)
            heads = params["attn_qkvw"].shape[-3]
        hw = heads * d  # local attention width (== h without tp_axis)
        if seq_axis is not None:
            if self._sparse_attn is not None:
                raise ValueError(
                    "manual sequence parallelism does not support sparse "
                    "attention (layouts are built for the full sequence)")
            if attn_mask is not None:
                raise NotImplementedError(
                    "manual sequence parallelism supports causal masking "
                    "only (additive attn_mask has no ring form here)")
        has_dropout = (cfg.attn_dropout_ratio > 0.0 or
                       cfg.hidden_dropout_ratio > 0.0)
        if rng is None:
            if not deterministic and has_dropout:
                raise ValueError(
                    "transformer layer called in training mode with dropout "
                    "configured but no rng — pass rng= or deterministic=True")
            rng = jax.random.PRNGKey(0)
            deterministic = True
        r_attn, r_hid1, r_hid2 = jax.random.split(rng, 3)
        if tp_axis is not None:
            # decorrelate the attention-probability dropout across head
            # shards (each peer sees only its local heads); the hidden
            # dropouts run AFTER the psums on replicated values and must
            # keep the shared key
            r_attn = jax.random.fold_in(r_attn, lax.axis_index(tp_axis))
        if seq_axis is not None:
            # every dropout acts on chunk-LOCAL values: decorrelate all
            # three keys across sequence peers (a shared key would repeat
            # one mask pattern every S_local positions)
            sidx = lax.axis_index(seq_axis)
            r_attn = jax.random.fold_in(r_attn, sidx)
            r_hid1 = jax.random.fold_in(r_hid1, sidx)
            r_hid2 = jax.random.fold_in(r_hid2, sidx)

        x = x.astype(cfg.dtype)
        residual = x
        if cfg.pre_layer_norm:
            attn_in = fused_layer_norm(x, params["norm_w"], params["norm_b"],
                                       eps)
        else:
            attn_in = x
        if tp_axis is not None:
            attn_in = tp_fcast(attn_in, tp_axis)

        with jax.named_scope("attn"):
            if tp_axis is None:
                qkv = matmul_maybe_int8(attn_in, params["attn_qkvw"]) + \
                    params["attn_qkvb"].astype(attn_in.dtype)
                q, k, v = jnp.split(qkv, 3, axis=-1)
            else:
                # head-major local view: w [H, hl, 3, d], b [hl, 3, d]
                qkv = jnp.einsum(
                    "bsh,hjcd->bsjcd", attn_in,
                    params["attn_qkvw"].astype(attn_in.dtype)) + \
                    params["attn_qkvb"].astype(attn_in.dtype)
                q, k, v = (qkv[..., 0, :].reshape(b, s, hw),
                           qkv[..., 1, :].reshape(b, s, hw),
                           qkv[..., 2, :].reshape(b, s, hw))

        # attention dropout placement (attn_dropout_impl):
        #   "kernel" (default) — probability dropout INSIDE the flash
        #     kernel, the reference's semantics (dropout_kernels.cu
        #     attn-dropout on the softmax output).  Costs O(S^2) PRNG
        #     bits regenerated in all three kernels: measured ~10% of
        #     the flagship step on v5e (94.3 nodrop vs 84.7 TFLOPS).
        #   "ctx" — cheap dropout on the attention OUTPUT (O(S*d) bits,
        #     one pass).  Different regularizer than the reference's;
        #     choose it when dropout semantics need not match.
        # Sparse attention always uses ctx dropout (its kernel has no
        # PRNG path yet); r_attn is consumed exactly once on every path.
        kernel_drop = (cfg.attn_dropout_impl == "kernel"
                       and seq_axis is None)
        attn_rate = (0.0 if deterministic or not kernel_drop
                     else cfg.attn_dropout_ratio)

        def attn_seed():
            if attn_rate == 0.0:
                return None
            return jax.random.randint(r_attn, (), 0, 2 ** 31 - 1, jnp.int32)

        if seq_axis is not None:
            # ring / Ulysses attention over the manual seq axis on the
            # local chunk (lazy import: parallel.sequence pulls in
            # flash_attention at module load)
            from ..parallel.sequence import sp_attention_inner

            sp = lax.psum(1, seq_axis)  # static under shard_map
            mode = sp_mode
            if mode == "auto":
                mode = "ulysses" if heads % sp == 0 else "ring"

            def to_heads(t):
                return t.reshape(b, s, heads, d).transpose(0, 2, 1, 3)

            with jax.named_scope("attn"):
                ctx = sp_attention_inner(to_heads(q), to_heads(k),
                                         to_heads(v), mode=mode,
                                         axis_name=seq_axis,
                                         causal=cfg.causal)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, hw)
            # kernel-dropout fallback: output ('ctx') dropout on the chunk
            ctx = dropout(ctx, cfg.attn_dropout_ratio, r_attn, deterministic)
        elif self._sparse_attn is not None:
            # route the layer's additive mask into SparseSelfAttention's
            # mask features (added round 4): [B,1,1,S] (key padding) ->
            # key_padding_mask 'add'; [1,1,S,S] / [S,S] -> attn_mask
            # 'add'.  A per-batch full [B,1,S,S] mask has no sparse
            # analog (the reference softmax supports 2D attn masks only).
            sparse_kp = sparse_am = None
            if attn_mask is not None:
                if attn_mask.ndim == 4 and attn_mask.shape[1:3] == (1, 1):
                    sparse_kp = attn_mask.reshape(attn_mask.shape[0], s)
                elif (attn_mask.ndim == 4 and attn_mask.shape[0] == 1
                      and attn_mask.shape[1] == 1):
                    sparse_am = attn_mask.reshape(s, s)
                elif attn_mask.ndim == 2:
                    sparse_am = attn_mask
                else:
                    raise NotImplementedError(
                        "sparse attention supports [B,1,1,S] key-padding "
                        "or 2D [S,S] additive masks (reference "
                        "softmax.py:attn_mask is 2D-only); got shape "
                        f"{attn_mask.shape}")

            def to_heads(t):
                return t.reshape(b, s, heads, d).transpose(0, 2, 1, 3)

            with jax.named_scope("attn"):
                ctx = self._sparse_attn(to_heads(q), to_heads(k),
                                        to_heads(v), causal=cfg.causal,
                                        key_padding_mask=sparse_kp,
                                        attn_mask=sparse_am)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, hw)
            ctx = dropout(ctx, cfg.attn_dropout_ratio, r_attn, deterministic)
        elif cfg.attn_layout == "bshd":
            # [B,S,H] -> [B,S,heads,d] is a free view; the layout
            # conversion to the kernel's [B,H,S,D] happens at the Pallas
            # boundary (a native bshd BlockSpec is Mosaic-illegal —
            # measured round 3; see flash_attention.py::_tile_spec)
            def split_heads(t):
                return t.reshape(b, s, heads, d)

            with jax.named_scope("attn"):
                ctx = flash_attention_bsh(
                    split_heads(q), split_heads(k), split_heads(v),
                    causal=cfg.causal, bias=attn_mask,
                    block_q=cfg.block_q, block_k=cfg.block_k,
                    impl=cfg.attn_impl, dropout_rate=attn_rate,
                    dropout_seed=attn_seed())
            ctx = ctx.reshape(b, s, hw)
            if not kernel_drop:
                ctx = dropout(ctx, cfg.attn_dropout_ratio, r_attn,
                              deterministic)
        else:
            def to_heads(t):
                return t.reshape(b, s, heads, d).transpose(0, 2, 1, 3)

            with jax.named_scope("attn"):
                ctx = flash_attention(
                    to_heads(q), to_heads(k), to_heads(v),
                    causal=cfg.causal, bias=attn_mask,
                    block_q=cfg.block_q, block_k=cfg.block_k,
                    impl=cfg.attn_impl, dropout_rate=attn_rate,
                    dropout_seed=attn_seed())
            ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, hw)
            if not kernel_drop:
                ctx = dropout(ctx, cfg.attn_dropout_ratio, r_attn,
                              deterministic)

        # NOTE: "attn" opens as several blocks (the dispatch branches
        # prevent one contiguous region); the scope KEY is identical so
        # module_tree merges them — only free reshapes/transposes between
        # blocks fall to the parent "layer" scope.
        with jax.named_scope("attn"):
            attn_out = matmul_maybe_int8(ctx, params["attn_ow"])
            if tp_axis is not None:
                # row-parallel output projection: merge the per-peer
                # partials BEFORE bias/dropout/residual (replicated on)
                attn_out = tp_psum(attn_out, tp_axis)
            attn_out = bias_dropout_residual(
                attn_out, params["attn_ob"].astype(attn_out.dtype),
                residual, cfg.hidden_dropout_ratio, r_hid1, deterministic)

        if cfg.ffn == "none":
            # attention sublayer only — the caller owns the FFN position
            # (MoE expert block); pre-LN residual form required
            if not cfg.pre_layer_norm:
                raise ValueError("ffn='none' requires pre_layer_norm")
            return attn_out

        if cfg.pre_layer_norm:
            mlp_in = fused_layer_norm(attn_out, params["attn_nw"],
                                      params["attn_nb"], eps)
            mlp_residual = attn_out
        else:
            attn_out = fused_layer_norm(attn_out, params["attn_nw"],
                                        params["attn_nb"], eps)
            mlp_in = attn_out
            mlp_residual = attn_out
        if tp_axis is not None:
            mlp_in = tp_fcast(mlp_in, tp_axis)

        with jax.named_scope("mlp"):
            inter = bias_gelu(matmul_maybe_int8(mlp_in, params["inter_w"]),
                              params["inter_b"].astype(mlp_in.dtype),
                              approximate=cfg.gelu_approximate)
            out = matmul_maybe_int8(inter, params["output_w"])
            if tp_axis is not None:
                out = tp_psum(out, tp_axis)
            out = bias_dropout_residual(
                out, params["output_b"].astype(out.dtype), mlp_residual,
                cfg.hidden_dropout_ratio, r_hid2, deterministic)

        if not cfg.pre_layer_norm:
            out = fused_layer_norm(out, params["norm_w"], params["norm_b"],
                                   eps)
        return out
