from .cpu_adam import DeepSpeedCPUAdam
