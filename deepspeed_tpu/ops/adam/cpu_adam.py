"""DeepSpeedCPUAdam — host-memory Adam/AdamW over a pytree of fp32 shards.

Reference: deepspeed/ops/adam/cpu_adam.py:186 (DeepSpeedCPUAdam) backed by
csrc/adam/cpu_adam.cpp.  Role in ZeRO-Offload: fp32 master params and m/v
moments live in host DRAM; each step consumes device gradients and produces
updated parameters, optionally fused with the fp32→bf16 cast for the
device-bound copy (the reference's `adam_update_copy` overlapping H2D path).

The native kernel is csrc/adam/host_adam.cpp loaded via ctypes
(CPUAdamBuilder); when no toolchain is available a vectorized NumPy fallback
keeps the API usable (slower, same numerics).
"""

import ctypes
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax

from ...utils.logging import logger
from ..op_builder import CPUAdamBuilder


def _load_native():
    builder = CPUAdamBuilder()
    if not builder.is_compatible():
        return None
    try:
        lib = builder.load()
    except RuntimeError as e:  # pragma: no cover - toolchain-specific
        logger.warning(f"cpu_adam native build failed, using NumPy: {e}")
        return None
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    lib.ds_adam_step.argtypes = [
        f32p, f32p, f32p, f32p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_int64, ctypes.c_int]
    lib.ds_adam_step.restype = None
    lib.ds_adam_step_bf16.argtypes = lib.ds_adam_step.argtypes + [u16p]
    lib.ds_adam_step_bf16.restype = None
    lib.ds_adam_num_threads.restype = ctypes.c_int
    return lib


_NATIVE: Optional[ctypes.CDLL] = None
_NATIVE_TRIED = False


def get_native_lib():
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE = _load_native()
        _NATIVE_TRIED = True
    return _NATIVE


def _as_f32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def adam_step_buffers(p: np.ndarray, m: np.ndarray, v: np.ndarray,
                      g: np.ndarray, *, lr: float, beta1: float,
                      beta2: float, eps: float, weight_decay: float,
                      step: int, adamw_mode: bool,
                      bf16_out: Optional[np.ndarray] = None,
                      lib="auto") -> None:
    """One fused Adam/AdamW update over flat fp32 buffers, in place.

    Shared by DeepSpeedCPUAdam (RAM-resident states) and the NVMe optimizer
    swapper (states paged through these buffers).  Uses the native kernel
    when available, NumPy otherwise."""
    if lib == "auto":
        lib = get_native_lib()
    if lib is not None:
        args = (_as_f32_ptr(p.reshape(-1)), _as_f32_ptr(m.reshape(-1)),
                _as_f32_ptr(v.reshape(-1)), _as_f32_ptr(g.reshape(-1)),
                ctypes.c_int64(p.size), ctypes.c_float(lr),
                ctypes.c_float(beta1), ctypes.c_float(beta2),
                ctypes.c_float(eps), ctypes.c_float(weight_decay),
                ctypes.c_int64(step), ctypes.c_int(1 if adamw_mode else 0))
        if bf16_out is None:
            lib.ds_adam_step(*args)
        else:
            lib.ds_adam_step_bf16(
                *args, bf16_out.reshape(-1).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint16)))
        return
    _adam_step_numpy(p, m, v, g, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
                     weight_decay=weight_decay, step=step,
                     adamw_mode=adamw_mode, bf16_out=bf16_out)


def _adam_step_numpy(p, m, v, g, *, lr, beta1, beta2, eps, weight_decay,
                     step, adamw_mode, bf16_out=None):
    bias1 = 1.0 - beta1 ** step
    bias2 = 1.0 - beta2 ** step
    if not adamw_mode and weight_decay > 0:
        g = g + weight_decay * p
    m *= beta1
    m += (1 - beta1) * g
    v *= beta2
    v += (1 - beta2) * g * g
    denom = np.sqrt(v) / np.sqrt(bias2) + eps
    if adamw_mode and weight_decay > 0:
        p *= 1.0 - lr * weight_decay
    p -= (lr / bias1) * (m / denom)
    if bf16_out is not None:
        import ml_dtypes
        bf16_out[...] = p.astype(ml_dtypes.bfloat16).view(np.uint16)


class DeepSpeedCPUAdam:
    """Adam/AdamW stepping fp32 host shards in place.

    params: a pytree of numpy fp32 arrays (the host master copy).  step()
    takes a matching pytree of gradients (any float dtype; converted to
    fp32), updates params/m/v in place, and can emit a bf16 copy-out tree
    for the device upload.
    """

    def __init__(self, params: Any, lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adamw_mode: bool = True):
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.adamw_mode = bool(adamw_mode)
        self.step_count = 0

        def _host_master(x):
            arr = np.asarray(x)
            if not np.issubdtype(arr.dtype, np.floating) and arr.dtype != \
                    np.dtype("bfloat16"):
                return np.array(arr, copy=True)  # int leaves pass through
            return np.ascontiguousarray(
                np.array(arr, dtype=np.float32, copy=True))
        self.params = jax.tree.map(_host_master, params)
        # Moments as flat lists aligned with tree_leaves(self.params); None
        # for non-float (pass-through) leaves.  Kept out of pytree form so
        # None entries don't collapse the tree structure.
        self._p_leaves, self._treedef = jax.tree_util.tree_flatten(
            self.params)
        self.exp_avg = [np.zeros_like(p) if p.dtype == np.float32 else None
                        for p in self._p_leaves]
        self.exp_avg_sq = [np.zeros_like(p) if p.dtype == np.float32
                           else None for p in self._p_leaves]
        self._lib = get_native_lib()

    @property
    def using_native(self) -> bool:
        return self._lib is not None

    # ------------------------------------------------------------------ #
    def _step_leaf(self, p, m, v, g, bf16_out):
        adam_step_buffers(
            p, m, v, g, lr=self.lr, beta1=self.betas[0],
            beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay, step=self.step_count,
            adamw_mode=self.adamw_mode, bf16_out=bf16_out, lib=self._lib)

    def step(self, grads: Any = None, lr: Optional[float] = None,
             emit_bf16: bool = False, *,
             leaf_list: Optional[list] = None) -> Optional[Any]:
        """One fused update; returns the bf16 copy-out tree if emit_bf16.

        Pass either `grads` (a pytree matching params — NEVER mutated) or
        `leaf_list` (an already-flattened leaf list in param order, which
        is CONSUMED: each entry is set to None right after its leaf
        update, so a caller holding only the list sees its grad memory
        released during the sweep — the offload/infinity tiers pass tens
        of GB here at multi-B-param scale)."""
        if (grads is None) == (leaf_list is None):
            raise ValueError("pass exactly one of grads / leaf_list")
        if lr is not None:
            self.lr = float(lr)
        self.step_count += 1
        g_leaves = (leaf_list if leaf_list is not None
                    else self._treedef.flatten_up_to(grads))
        out_leaves = []
        for idx, (p, m, v) in enumerate(zip(self._p_leaves, self.exp_avg,
                                            self.exp_avg_sq)):
            if m is None:  # non-float leaf: pass through untouched
                out_leaves.append(p)
                continue
            g = np.ascontiguousarray(np.asarray(g_leaves[idx],
                                                dtype=np.float32))
            g_leaves[idx] = None  # consume: free the caller-side leaf
            if g.shape != p.shape:
                raise ValueError(
                    f"grad shape {g.shape} != param shape {p.shape}")
            bf16_out = (np.empty(p.shape, dtype=np.uint16)
                        if emit_bf16 else None)
            self._step_leaf(p, m, v, g, bf16_out)
            g = None
            out_leaves.append(bf16_out)
        if emit_bf16:
            import ml_dtypes
            return jax.tree_util.tree_unflatten(
                self._treedef,
                [o.view(ml_dtypes.bfloat16) if isinstance(o, np.ndarray)
                 and o.dtype == np.uint16 else o for o in out_leaves])
        return None

    # -- checkpoint support -------------------------------------------- #
    def state_dict(self) -> Dict[str, Any]:
        placeholder = np.zeros(0, np.float32)
        return {
            "step": self.step_count,
            "exp_avg": {str(i): (m if m is not None else placeholder)
                        for i, m in enumerate(self.exp_avg)},
            "exp_avg_sq": {str(i): (v if v is not None else placeholder)
                           for i, v in enumerate(self.exp_avg_sq)},
            "params": self.params,
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.step_count = int(sd["step"])
        for i, (m, v) in enumerate(zip(self.exp_avg, self.exp_avg_sq)):
            if m is None:
                continue
            m[...] = np.asarray(sd["exp_avg"][str(i)], dtype=np.float32)
            v[...] = np.asarray(sd["exp_avg_sq"][str(i)], dtype=np.float32)
        src_leaves = self._treedef.flatten_up_to(sd["params"])
        for dst, src in zip(self._p_leaves, src_leaves):
            dst[...] = np.asarray(src, dtype=dst.dtype)
