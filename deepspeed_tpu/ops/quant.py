"""Quantized-weight carrier shared by the training and inference layers.

Reference: the int8 weight path of
csrc/transformer/inference/csrc/dequantize.cu + pt_binding.cpp (vector_matmul
int8 variants): weights live in HBM as int8 with per-group fp scales and are
dequantized into the gemm.  On TPU the dequant-multiply fuses into the
matmul epilogue under XLA, so this is a NamedTuple + one helper rather than
a kernel.
"""

from typing import Any, NamedTuple

import jax.numpy as jnp


class QuantizedWeight(NamedTuple):
    """Per-group symmetric int8 weight (reference: weight_quantizer.py:5).

    scale groups split the leading (input) dimension; scale shape is
    [groups, 1] (per layer) or [L, groups, 1] when layers are stacked."""
    qweight: jnp.ndarray
    scale: jnp.ndarray

    @property
    def shape(self):
        return self.qweight.shape

    @property
    def dtype(self):
        return self.qweight.dtype


def matmul_maybe_int8(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """x @ w with just-in-time dequantization for QuantizedWeight."""
    if isinstance(w, QuantizedWeight):
        rows = w.qweight.shape[0]
        groups = w.scale.shape[0]
        qw = w.qweight.reshape(groups, rows // groups, -1)
        deq = (qw.astype(x.dtype) *
               w.scale.astype(x.dtype)[:, :, None]).reshape(rows, -1)
        return x @ deq
    return x @ w.astype(x.dtype)
