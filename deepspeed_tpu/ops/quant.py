"""Quantized-weight carrier + fused dequant-matmul.

Reference: the int8 weight path of
csrc/transformer/inference/csrc/dequantize.cu + pt_binding.cpp (vector_matmul
int8 variants): weights live in HBM as int8 with per-group fp scales and are
dequantized into the gemm, so HBM sees ONE int8 read per token — never a
materialized fp copy.

TPU equivalents, in dispatch order:
  1. a Pallas kernel (fused_dequant_matmul) that DMAs int8 tiles into VMEM,
     converts + scales there, and feeds the MXU — int8 HBM traffic by
     construction (the dequantize.cu role);
  2. a reshape-free XLA path whose dequant producer (convert + per-row
     scale multiply) is a plain elementwise chain XLA can fuse into the
     dot operand read.  (The earlier group-reshape -> multiply -> reshape
     chain defeated that fusion, which is why int8 decode measured SLOWER
     than bf16 in round 3.)
"""

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


class QuantizedWeight(NamedTuple):
    """Per-group symmetric int8 weight (reference: weight_quantizer.py:5).

    scale groups split the leading (input) dimension; scale shape is
    [groups, 1] (per layer) or [L, groups, 1] when layers are stacked."""
    qweight: jnp.ndarray
    scale: jnp.ndarray

    @property
    def shape(self):
        return self.qweight.shape

    @property
    def dtype(self):
        return self.qweight.dtype


def _row_scales(w: QuantizedWeight, dtype):
    """[rows] per-row scale vector from the per-group scales."""
    rows = w.qweight.shape[0]
    groups = w.scale.shape[0]
    return jnp.repeat(w.scale.reshape(groups).astype(dtype),
                      rows // groups)


def _dq_kernel(x_ref, qw_ref, s_ref, o_ref, acc, *, num_k_blocks):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    x = x_ref[...]                                    # [bm, bk]
    # dequant in VMEM: int8 -> fp32, per-row (K-dim) scale, then down to
    # the compute dtype — HBM only ever saw the int8 bytes.  The scale
    # multiply stays in fp32: s_ref is a [bk, 1] fp32 tile (a 1-D vector
    # operand trips Mosaic's layout verifier when bk < K, and a bf16
    # minor-dim insert is rejected outright).
    qw = (qw_ref[...].astype(jnp.float32) * s_ref[...]).astype(x.dtype)
    acc[...] += jax.lax.dot_general(
        x, qw, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _fin():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def _fit_blocks(m, k, n, block_m, block_n, block_k):
    """Largest aligned divisors <= the targets (sublane for M, lane for
    K/N; a block equal to a short full dim is always legal)."""
    from .flash_attention import _fit_block
    return (_fit_block(m, block_m, 8), _fit_block(n, block_n, 128),
            _fit_block(k, block_k, 128))


def fused_dequant_matmul(x, w: QuantizedWeight, block_m: int = 256,
                         block_n: int = 512, block_k: int = 512,
                         interpret: bool = False):
    """x [M, K] @ dequant(w) [K, N] -> [M, N] with int8-only HBM reads.

    Blocks are fitted to the shapes (callers go through
    matmul_maybe_int8, which falls back to the XLA path when no aligned
    tiling exists)."""
    if pltpu is None:
        raise RuntimeError("pallas TPU support unavailable")
    m, k = x.shape
    k2, n = w.qweight.shape
    assert k == k2, (x.shape, w.qweight.shape)
    fit = _dq_fit_or_none(m, k, n, block_m, block_n, block_k)
    if fit is None:
        raise ValueError(f"shapes ({m},{k},{n}) have no legal tiling — "
                         "use the XLA dequant path")
    bm, bn, bk = fit
    scales = _row_scales(w, jnp.float32)[:, None]     # [K, 1]
    grid = (m // bm, n // bn, k // bk)
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        functools.partial(_dq_kernel, num_k_blocks=k // bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bk, 1), lambda i, j, kk: (kk, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
        **params,
    )(x, w.qweight, scales)


def _dq_fit_or_none(m, k, n, block_m=256, block_n=512, block_k=512):
    """The ONE tiling decision for the fused kernel: returns the fitted
    (bm, bn, bk) when a legal Mosaic tiling exists (sublane/lane-aligned,
    or block == full short dim; no degenerate 1-wide tiling), else None.
    Callers pass the result straight into fused_dequant_matmul so the
    gate and the kernel can never disagree."""
    bm, bn, bk = _fit_blocks(m, k, n, block_m, block_n, block_k)

    def legal(b, length, lane):
        return ((b % lane == 0 or b == length) and b > 1) or length == 1

    if legal(bm, m, 8) and legal(bn, n, 128) and legal(bk, k, 128):
        return bm, bn, bk
    return None


@jax.custom_vjp
def _fused_dq(x, qweight, scales):
    """Differentiable wrapper: forward = Pallas fused kernel; backward =
    one XLA matmul against the (fusably) dequantized transpose.  The int8
    weight is non-differentiable; the scale cotangent IS computed (so the
    fused path and the XLA fallback produce the same gradients — e.g. for
    learned scales), but XLA dead-code-eliminates its extra matmul
    whenever the caller doesn't use it."""
    return fused_dequant_matmul(x, QuantizedWeight(qweight, scales))


def _fused_dq_fwd(x, qweight, scales):
    return _fused_dq(x, qweight, scales), (x, qweight, scales)


def _fused_dq_bwd(res, g):
    x, qweight, scales = res
    w = QuantizedWeight(qweight, scales)
    # dL/dW = x^T g; dL/dscale_group = sum over the group's rows of
    # (x^T g) * float(qweight), matching d/ds [x @ (s * qf)].
    gw = jnp.einsum("mk,mn->kn", x.astype(jnp.float32),
                    g.astype(jnp.float32))
    per_row = jnp.sum(gw * qweight.astype(jnp.float32), axis=1)   # [K]
    groups = scales.shape[0]
    dscale = per_row.reshape(groups, -1).sum(axis=1).reshape(scales.shape)
    return (g @ dequant(w, g.dtype).T, None, dscale.astype(scales.dtype))


_fused_dq.defvjp(_fused_dq_fwd, _fused_dq_bwd)


def dequant(w: QuantizedWeight, dtype):
    """Reshape-free dequantization: convert + per-row scale, a fusable
    elementwise producer for the XLA dot path."""
    if w.qweight.ndim != 2:
        raise ValueError(
            f"QuantizedWeight matmul expects a 2-D weight, got "
            f"{w.qweight.shape} — unstack layer-stacked weights first")
    return w.qweight.astype(dtype) * _row_scales(w, dtype)[:, None]


def matmul_maybe_int8(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """x @ w with just-in-time dequantization for QuantizedWeight.

    2-D x on the Pallas-capable backend takes the fused kernel; other
    ranks/backends use the XLA path, whose dequant producer XLA fuses
    into the dot operand read."""
    if isinstance(w, QuantizedWeight):
        from .dispatch import pallas_available
        shape = x.shape
        x2 = x.reshape(-1, shape[-1])
        if (w.qweight.ndim == 2 and pallas_available()
                and _dq_fit_or_none(x2.shape[0],
                                    *w.qweight.shape) is not None):
            out = _fused_dq(x2, w.qweight, w.scale)
        else:
            out = x2 @ dequant(w, x.dtype)
        return out.reshape(*shape[:-1], -1)
    return x @ w.astype(x.dtype)
