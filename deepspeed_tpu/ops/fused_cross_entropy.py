"""Fused (chunked) linear + softmax cross-entropy — the LM-head memory fix.

The naive path materializes fp32 logits [B, S, V] (GPT-2 124M at B=8,
S=1024: 1.6 GB) and reads them again for the softmax — pure HBM traffic
the MXU waits on.  This op never materializes more than one vocab CHUNK of
logits: the forward streams logsumexp over chunks (online softmax), and
the custom VJP recomputes each chunk to emit dh and dW incrementally —
O(B·S·chunk) live instead of O(B·S·V).

Non-divisible vocabularies (e.g. GPT-2's unpadded 50257) are padded up to
a whole number of chunks; padded columns are masked to -inf in the
forward (zero probability) so they contribute nothing to the loss or the
gradients, and the dW pad columns are sliced away.

Reference counterpart: the training softmax kernels
(csrc/transformer/softmax_kernels.cu) fuse scale+mask+softmax for the same
reason — do not round-trip the big tensor through HBM.  (The chunked
linear-CE formulation matches public "fused linear cross entropy" practice
in TPU/GPU LM stacks.)
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


# Auto chunk policy: bound the transient [N, chunk] fp32 logits block.
# Measured on v5e (benchmarks/profile_ce_sweep.py): larger chunks are
# faster (fewer scan steps, bigger matmuls) — 105ms vs 111ms full-step at
# the flagship shape for whole-vocab vs 8192 — so "auto" picks the largest
# chunk whose transient stays under this budget.
_CE_CHUNK_ELEM_BUDGET = 1 << 29  # 512M fp32 elements = 2 GB transient


def _plan(vocab: int, chunk_size, n_tokens: int):
    """(chunk, n_chunks, padded_vocab) with chunk*n_chunks == padded."""
    if chunk_size is None:
        chunk_size = max(4096, _CE_CHUNK_ELEM_BUDGET // max(1, n_tokens))
    c = max(1, min(chunk_size, vocab))
    n_chunks = -(-vocab // c)
    return c, n_chunks, c * n_chunks


def _padded_w(w, padded_vocab):
    hid, vocab = w.shape
    if padded_vocab == vocab:
        return w
    return jnp.pad(w, ((0, 0), (0, padded_vocab - vocab)))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(h, w, labels, chunk_size=None,
                               ignore_index=None):
    """mean over (valid) tokens of CE(softmax(h @ w), labels).

    h: [N, H] hidden states (any float dtype; matmuls accumulate fp32)
    w: [H, V] head projection
    labels: [N] int
    ignore_index: labels equal to this contribute nothing to the loss or
      gradients and are excluded from the mean (the masked-LM convention,
      reference bing_bert objective / torch F.cross_entropy semantics).
    """
    loss, _ = _forward(h, w, labels, chunk_size, ignore_index)
    return loss


def _valid_mask(labels, ignore_index):
    if ignore_index is None:
        return jnp.ones(labels.shape, jnp.float32), jnp.float32(
            labels.shape[0])
    valid = (labels != ignore_index).astype(jnp.float32)
    return valid, jnp.maximum(valid.sum(), 1.0)


def _forward(h, w, labels, chunk_size, ignore_index):
    n, hid = h.shape
    vocab = w.shape[1]
    c, n_chunks, padded = _plan(vocab, chunk_size, n)
    wc = _padded_w(w, padded).reshape(hid, n_chunks, c).transpose(1, 0, 2)
    valid, denom = _valid_mask(labels, ignore_index)

    def body(carry, w_i):
        m, s, idx = carry
        logits = jnp.einsum(
            "nh,hc->nc", h, w_i.astype(h.dtype),
            preferred_element_type=jnp.float32)  # [N, c] fp32
        cols = idx * c + jnp.arange(c)
        logits = jnp.where(cols[None, :] < vocab, logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=1)
        # label logit if it falls in this chunk
        local = labels - idx * c
        in_chunk = (local >= 0) & (local < c)
        lab = jnp.take_along_axis(
            logits, jnp.clip(local, 0, c - 1)[:, None], axis=1)[:, 0]
        return (m_new, s, idx + 1), jnp.where(in_chunk, lab, 0.0)

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    (m, s, _), lab_parts = lax.scan(body, (m0, s0, jnp.int32(0)), wc)
    lse = m + jnp.log(s)
    label_logit = lab_parts.sum(axis=0)
    loss = ((lse - label_logit) * valid).sum() / denom
    return loss.astype(jnp.float32), (lse,)


def _fwd(h, w, labels, chunk_size, ignore_index):
    loss, (lse,) = _forward(h, w, labels, chunk_size, ignore_index)
    return loss, (h, w, labels, lse)


def _bwd(chunk_size, ignore_index, res, g):
    h, w, labels, lse = res
    n, hid = h.shape
    vocab = w.shape[1]
    c, n_chunks, padded = _plan(vocab, chunk_size, n)
    wc = _padded_w(w, padded).reshape(hid, n_chunks, c).transpose(1, 0, 2)
    valid, denom = _valid_mask(labels, ignore_index)
    scale = (g / denom) * valid  # [N] d mean / d token (0 on ignored)

    def body(carry, w_i):
        dh, idx = carry
        logits = jnp.einsum("nh,hc->nc", h, w_i.astype(h.dtype),
                            preferred_element_type=jnp.float32)
        cols = idx * c + jnp.arange(c)
        logits = jnp.where(cols[None, :] < vocab, logits, -jnp.inf)
        p = jnp.exp(logits - lse[:, None])   # softmax chunk (0 on padding)
        local = labels - idx * c
        onehot = (local[:, None] == jnp.arange(c)[None, :])
        grad_logits = (p - onehot.astype(p.dtype)) * scale[:, None]
        # dh accumulates fp32 across chunks — rounding per-chunk to bf16
        # would compound error the unchunked path doesn't have
        dh = dh + jnp.einsum("nc,hc->nh", grad_logits, w_i,
                             preferred_element_type=jnp.float32)
        dw_i = jnp.einsum("nh,nc->hc", h, grad_logits,
                          preferred_element_type=jnp.float32)
        return (dh, idx + 1), dw_i

    dh0 = jnp.zeros(h.shape, jnp.float32)
    (dh, _), dw_chunks = lax.scan(body, (dh0, jnp.int32(0)), wc)
    dw = dw_chunks.transpose(1, 0, 2).reshape(hid, padded)[:, :vocab]
    return dh.astype(h.dtype), dw.astype(w.dtype), None


fused_linear_cross_entropy.defvjp(_fwd, _bwd)
