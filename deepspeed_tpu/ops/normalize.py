"""Fused LayerNorm — the analog of the reference's fused LN kernels
(csrc/transformer/normalize_kernels.cu:2103, fwd/bwd incl. the "invertible"
variant that recomputes the input from the output).

On TPU, XLA already fuses mean/var/normalize/scale into one loop nest, so the
default path is plain jnp (fp32 statistics).  A Pallas row-block kernel is
provided for the hot transformer path where we want LN fused into the
surrounding kernel schedule explicitly.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def layer_norm_reference(x, gamma, beta, eps: float = 1e-5):
    """LN over the last dim with fp32 statistics (normalize_kernels.cu
    fused_bias_residual_layer_norm semantics, minus the fused residual which
    callers express as x + residual before the call)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) +
            beta.astype(jnp.float32)).astype(x.dtype)


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * g_ref[...].astype(jnp.float32) +
                  b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def layer_norm_pallas(x, gamma, beta, eps: float = 1e-5,
                      block_rows: int = 256, interpret: bool = False):
    """Pallas LN over the last dim of a 2-D [rows, hidden] view."""
    orig_shape = x.shape
    hidden = orig_shape[-1]
    x2 = x.reshape(-1, hidden)
    rows = x2.shape[0]
    block_rows = min(block_rows, rows)
    while rows % block_rows:  # largest divisor of rows <= block_rows keeps
        block_rows -= 1       # each block VMEM-sized (never one giant block)
    kernel = functools.partial(_ln_kernel, eps=eps)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, gamma, beta)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x, gamma, beta, eps):
    return _fused_ln_fwd(x, gamma, beta, eps)[0]


def _fused_ln_fwd(x, gamma, beta, eps):
    from .dispatch import pallas_available
    if pallas_available():
        out = layer_norm_pallas(x, gamma, beta, eps)
    else:
        out = layer_norm_reference(x, gamma, beta, eps)
    return out, (x, gamma, beta)


def _fused_ln_bwd(eps, res, g):
    x, gamma, beta = res
    _, vjp = jax.vjp(
        lambda x_, g_, b_: layer_norm_reference(x_, g_, b_, eps),
        x, gamma, beta)
    return vjp(g)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(x, gamma, beta, eps: float = 1e-5):
    """Differentiable fused LayerNorm (Pallas on TPU, XLA elsewhere)."""
    return _fused_ln(x, gamma, beta, eps)
