"""Fused LayerNorm — the analog of the reference's fused LN kernels
(csrc/transformer/normalize_kernels.cu:2103, fwd/bwd incl. the "invertible"
variant that recomputes the input from the output).

On TPU, XLA already fuses mean/var/normalize/scale into one loop nest, so the
default path is plain jnp (fp32 statistics).  A Pallas row-block kernel is
provided for the hot transformer path where we want LN fused into the
surrounding kernel schedule explicitly.
"""

import functools

import jax
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def layer_norm_reference(x, gamma, beta, eps: float = 1e-5):
    """LN over the last dim with fp32 statistics (normalize_kernels.cu
    fused_bias_residual_layer_norm semantics, minus the fused residual which
    callers express as x + residual before the call)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) +
            beta.astype(jnp.float32)).astype(x.dtype)


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * g_ref[...].astype(jnp.float32) +
                  b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _pick_block_rows(rows: int, block_rows: int) -> int:
    """Largest divisor of rows <= block_rows — keeps each block VMEM-sized
    (never one giant block).  Shared by the forward and backward kernels
    so their block policies cannot diverge."""
    if rows <= 0:
        return 0
    block_rows = min(block_rows, rows)
    while rows % block_rows:
        block_rows -= 1
    return block_rows


def _ln_tiling_ok(rows: int, hidden: int, block_rows: int) -> bool:
    """Mosaic requires the last two block dims divisible by (8, 128) or
    equal to the respective array dims; reject shapes that would fail
    lowering so the dispatcher can fall back to the XLA vjp instead of
    erroring.  Every block here spans the full hidden dim (== array dim,
    always legal), so only the row tiling needs checking."""
    del hidden
    return rows > 0 and (block_rows % 8 == 0 or block_rows == rows)


def layer_norm_pallas(x, gamma, beta, eps: float = 1e-5,
                      block_rows: int = 256, interpret: bool = False):
    """Pallas LN over the last dim of a 2-D [rows, hidden] view."""
    orig_shape = x.shape
    hidden = orig_shape[-1]
    x2 = x.reshape(-1, hidden)
    rows = x2.shape[0]
    block_rows = _pick_block_rows(rows, block_rows)
    if not _ln_tiling_ok(rows, hidden, block_rows):
        raise ValueError(
            f"layer_norm_pallas: rows={rows}, hidden={hidden} has no "
            "usable block tiling — use layer_norm_reference")
    kernel = functools.partial(_ln_kernel, eps=eps)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, gamma, beta)
    return out.reshape(orig_shape)


def _ln_bwd_kernel(x_ref, g_ref, dy_ref, dx_ref, dg_ref, db_ref, *, eps):
    """One-pass LN backward per row block (the normalize_kernels.cu
    backward's role): recompute the fp32 statistics, produce dx, and
    accumulate dgamma/dbeta row sums across the sequential TPU grid into
    a single [1, hidden] block (block == array dims, which satisfies the
    Mosaic tiling rule that a (1, hidden) window over an (nb, hidden)
    array does not)."""
    x = x_ref[...].astype(jnp.float32)                 # [rows, hidden]
    dy = dy_ref[...].astype(jnp.float32)
    gamma = g_ref[...].astype(jnp.float32)             # [hidden]
    n = x.shape[-1]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    dyg = dy * gamma
    m1 = jnp.sum(dyg, axis=-1, keepdims=True) / n
    m2 = jnp.sum(dyg * xhat, axis=-1, keepdims=True) / n
    dx = (dyg - m1 - xhat * m2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def layer_norm_bwd_pallas(x, gamma, dy, eps: float = 1e-5,
                          block_rows: int = 256, interpret: bool = False):
    """Pallas LN backward over the last dim: returns (dx, dgamma, dbeta)
    with fp32 gamma/beta grads (their accumulation dtype)."""
    orig_shape = x.shape
    hidden = orig_shape[-1]
    x2 = x.reshape(-1, hidden)
    dy2 = dy.reshape(-1, hidden)
    rows = x2.shape[0]
    block_rows = _pick_block_rows(rows, block_rows)
    if not _ln_tiling_ok(rows, hidden, block_rows):
        # awkward row counts would fail Mosaic lowering — the XLA vjp is
        # strictly better there
        raise ValueError(
            f"layer_norm_bwd_pallas: rows={rows}, hidden={hidden} has no "
            "usable block tiling — use the XLA backward")
    nb = rows // block_rows
    kernel = functools.partial(_ln_bwd_kernel, eps=eps)
    dx, dg, db = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, x.dtype),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma, dy2)
    return (dx.reshape(orig_shape), dg[0], db[0])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ln(x, gamma, beta, eps):
    return _fused_ln_fwd(x, gamma, beta, eps)[0]


def _fused_ln_usable(x) -> bool:
    # The default LN impl is XLA, by measurement — see dispatch.ln_impl
    # (v5e: XLA LN beats the Pallas kernels by ~2 ms/step because a
    # pallas_call is opaque to XLA's elementwise fusion).
    from .dispatch import ln_impl, pallas_available
    if ln_impl() != "pallas":
        return False
    if not pallas_available():
        return False
    rows = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 1
    hidden = x.shape[-1]
    return _ln_tiling_ok(rows, hidden, _pick_block_rows(rows, 256))


def _fused_ln_fwd(x, gamma, beta, eps):
    if _fused_ln_usable(x):
        out = layer_norm_pallas(x, gamma, beta, eps)
    else:
        out = layer_norm_reference(x, gamma, beta, eps)
    return out, (x, gamma, beta)


def _fused_ln_bwd(eps, res, g):
    x, gamma, beta = res
    if _fused_ln_usable(x):
        dx, dgamma, dbeta = layer_norm_bwd_pallas(x, gamma, g, eps)
        return (dx, dgamma.astype(jnp.asarray(gamma).dtype),
                dbeta.astype(jnp.asarray(beta).dtype))
    _, vjp = jax.vjp(
        lambda x_, g_, b_: layer_norm_reference(x_, g_, b_, eps),
        x, gamma, beta)
    return vjp(g)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(x, gamma, beta, eps: float = 1e-5):
    """Differentiable fused LayerNorm.  Default implementation is the
    XLA reference (the measured winner on v5e — see dispatch.ln_impl);
    DS_LN_IMPL=pallas / dispatch.set_ln_impl("pallas") selects the
    Pallas kernels."""
    return _fused_ln(x, gamma, beta, eps)
