"""Fused collective-matmul — T3-style per-tile fusion of the qwZ/qgZ
transports with the GEMMs that produce/consume them (arXiv:2401.16677).

The modular low-bandwidth path (runtime/comm/low_bandwidth.py) moves each
layer group's quantized weights as ONE all-gather and each gradient as ONE
all-to-all: the wire is a monolithic event the scheduler must hide under
*other* work.  T3's observation is that the producer/consumer GEMM itself
is the natural cover — track the GEMM's tiles and trigger communication
per tile as tiles complete, so the transport is structurally interleaved
with the matmul instead of scheduled around it.  Two fused pairs:

  forward   the qwZ dequant-all-gather fused into the consuming GEMM's
            PROLOGUE: remote shard tiles (int8/int4 payload + fp32 block
            scales) arrive over a ring, double-buffered against the MXU's
            current tile, with the dequant epilogue folded in per tile
            (``fused_allgather_matmul``);
  backward  the qgZ grad reduce-scatter fused into the producer GEMM's
            EPILOGUE: as each output tile of dW = x^T @ dy completes it is
            blockwise-int8 quantized (error-feedback residual intact) and
            sent straight to its owner — a ring-scheduled all-to-all
            (``fused_matmul_reduce_scatter``).

Two implementation layers:

  1. The GEMM-fused ops above, for callers that hand us the matmul.  On
     TPU they are single Pallas kernels whose ring transport rides
     ``pltpu.make_async_remote_copy`` between per-step MXU tiles
     (UNVALIDATED on real chips — the on-chip numbers fold into ROADMAP
     item 1's measured sweep).  In interpret mode (CPU tier-1 coverage)
     the same per-tile GEMM kernels run under ``pallas_call(interpret=
     True)`` with the remote-copy path swapped for a mesh-simulated
     permute (``lax.ppermute``) — the flash_attention.py pattern.

  2. Per-tile TRANSPORT drop-ins for the streamed-ZeRO-3 scan, whose
     consumer/producer is an arbitrary model body rather than one GEMM
     we control: ``fcm_all_gather`` (drop-in for
     ``low_bandwidth_all_gather`` / ``_all_gather_f32grad``) and
     ``fcm_reduce_scatter`` (drop-in for ``quantized_psum_scatter`` /
     ``f32_psum_scatter``) realize the same per-tile schedule at program
     granularity: W-1 independent quantize -> ppermute -> dequant tile
     chains replace the monolithic collective, giving the scheduler
     tile-level freedom and the Schedule Auditor a statically-checkable
     property.  Enabled via ``zero_optimization.low_bandwidth.
     fused_collective_matmul`` (docs/fused_collective_matmul.md).

Every transport here traces under ``jax.named_scope(constants.FCM_SCOPE)``
— the Schedule Auditor's overlap classifier (analysis/overlap.py) reads
the marker off equation name stacks and classifies the per-tile wire as
``fused`` (hidden by construction, the carried-like static property),
and the cost model prices it in the hidden-comm lane.

Numerics contract (pinned by tests/unit/test_collective_matmul.py):

  - the fused qwZ gather is BITWISE-identical to the modular path — the
    same blockwise quantization runs once at the source and the same
    per-tile dequant math runs at each receiver, only the transport
    schedule differs;
  - the fused qgZ scatter keeps the modular path's accumulation-order
    contract — every receiver dequantizes the full source table and
    reduces in shard-index order (``jnp.sum(deq, axis=0)``), bitwise
    matching ``quantized_psum_scatter`` / ``qgz_reduce_scatter_inner``;
  - the error-feedback residual is computed from the same compensated
    quantization (``new_error = (x + error) - deq(quant(x + error))``).

The qgz_bits=0 fallback reduces through the same per-tile table in fp32
(promote half -> accumulate fp32 -> demote), which matches
``f32_psum_scatter``'s accumulation DTYPE but fixes the accumulation
ORDER (shard-index) where ``lax.psum_scatter`` leaves it to XLA — equal
up to fp reassociation, exactly equal when qgZ is on.
"""

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-specific pallas bits are unavailable on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .. import constants as C
from ..runtime.comm.low_bandwidth import (DEFAULT_BLOCK, blockwise_dequantize,
                                          blockwise_quantize)

FCM_SCOPE = C.FCM_SCOPE


def _fcm_scope():
    """The name scope every fused transport traces under — the single
    handle the Schedule Auditor keys its ``fused`` classification on."""
    return jax.named_scope(FCM_SCOPE)


def _axes_tuple(axes) -> Tuple[str, ...]:
    return (axes,) if isinstance(axes, str) else tuple(axes)


# --------------------------------------------------------------------- #
# per-tile ring transport (the mesh-level schedule both layers share)
# --------------------------------------------------------------------- #
def _ring_tiles(payloads, axis_name):
    """Ring-circulate per-device payload tiles and return them in SOURCE
    order.

    ``payloads`` is a tuple of arrays (one shard tile each, e.g. the
    quantized payload and its scales).  Devices forward along a
    send-left ring (device d sends to d-1, receives from d+1), so after
    step ``t`` device ``d`` holds the tile originated at ``(d+t) % W``
    — W-1 hops total, the same wire volume as a tiled all-gather, but
    as W-1 INDEPENDENT per-tile transfers the scheduler can interleave
    with the consuming compute.  The returned tables are stacked
    ``[W, ...]`` in source-index order (``jnp.roll`` by the device's own
    index converts arrival order to source order)."""
    world = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i - 1) % world) for i in range(world)]
    rows = [list(payloads)]
    cur = list(payloads)
    for _t in range(1, world):
        cur = [None if p is None else lax.ppermute(p, axis_name, perm)
               for p in cur]
        rows.append(cur)
    tables = []
    for k, p in enumerate(payloads):
        if p is None:
            tables.append(None)
            continue
        stacked = jnp.stack([row[k] for row in rows], axis=0)
        tables.append(jnp.roll(stacked, my, axis=0))
    return tables


def _scatter_tiles(payloads, axis_name):
    """Ring-scheduled all-to-all of per-destination tiles, returning
    each device's received tiles in SOURCE order.

    ``payloads`` is a tuple of ``[W, ...]`` tables where row ``j`` is the
    tile this device owes destination ``j``.  Round ``t`` (t=1..W-1)
    moves every device's distance-``t`` tile in one shifted permutation
    (a ring-scheduled all-to-all: balanced link use, one tile per round
    — per-tile communication as the producer's output tiles complete).
    Row ``my`` stays local.  Returns ``[W, ...]`` tables where row ``s``
    is the tile SOURCE ``s`` sent here."""
    world = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    # rolled[t] = my tile for destination (my + t) % W
    rolled = [None if p is None else jnp.roll(p, -my, axis=0)
              for p in payloads]
    arrivals = [[None if r is None else r[0] for r in rolled]]
    for t in range(1, world):
        perm = [(i, (i + t) % world) for i in range(world)]
        arrivals.append([
            None if r is None else lax.ppermute(r[t], axis_name, perm)
            for r in rolled])
    tables = []
    for k, p in enumerate(payloads):
        if p is None:
            tables.append(None)
            continue
        # arrivals[t][k] came from source (my - t) % W; reversing gives a
        # rotation of source order, fixed up by one roll
        rev = jnp.stack([arrivals[t][k] for t in range(world)][::-1],
                        axis=0)
        tables.append(jnp.roll(rev, my + 1, axis=0))
    return tables


def _quantize_scatter_reduce(chunk_tab, axis_name, bits, block,
                             applied_dtype=None):
    """The fused scatter's ONE accumulation pipeline (shared by every
    reduce-scatter entry point so the bitwise contract cannot fork):
    quantize the destination-index chunk table once (per-chunk scales —
    the modular qgZ layout), move each tile in a ring-scheduled
    all-to-all round, dequantize the received source table and reduce
    in SHARD-INDEX order (``jnp.sum(axis=0)`` — the modular
    accumulation contract, bitwise).  bits=0 moves fp32 chunks
    unquantized.

    Returns ``(reduced, applied)``: ``applied`` is
    ``deq(quant(chunk_tab))`` in ``applied_dtype`` for error-feedback
    callers (None when not requested; bits=0 quantizes nothing, so
    ``applied == chunk_tab``)."""
    if bits:
        q, s = blockwise_quantize(chunk_tab, dim=0, bits=bits,
                                  block=block)
        applied = (blockwise_dequantize(q, s, chunk_tab.shape, dim=0,
                                        dtype=applied_dtype, bits=bits)
                   if applied_dtype is not None else None)
        q_tab, s_tab = _scatter_tiles((q, s), axis_name)
        deq = blockwise_dequantize(q_tab, s_tab, chunk_tab.shape,
                                   dim=0, dtype=jnp.float32, bits=bits)
    else:
        applied = (chunk_tab.astype(applied_dtype)
                   if applied_dtype is not None else None)
        (deq,) = _scatter_tiles((chunk_tab.astype(jnp.float32),),
                                axis_name)
    return jnp.sum(deq, axis=0), applied


# --------------------------------------------------------------------- #
# layer 2: per-tile transport drop-ins for the streamed-ZeRO-3 scan
# --------------------------------------------------------------------- #
def _fcm_gather_one_axis(parts, axis_name, cdim):
    """One axis of the fused gather: ring the payload tiles gathered so
    far (concatenated along ``cdim`` for transport) and return the new
    per-source tile lists.  ``parts`` is a tuple of lists, one list per
    payload kind (e.g. quantized values and their scales), each in
    source order along the axes already rung."""
    world = lax.axis_size(axis_name)
    cats = tuple(jnp.concatenate(pl, axis=cdim) if len(pl) > 1 else pl[0]
                 for pl in parts)
    tabs = _ring_tiles(cats, axis_name)
    return tuple([tab[p] for p in range(world)] for tab in tabs)


def _fcm_gather_impl(x, axes, dim, bits, block):
    """Per-tile ring gather over one or more mesh axes.  The shard is
    quantized ONCE at the source (identical to the modular qwZ path —
    re-quantizing a partially-gathered result would change the block
    boundaries and break bitwise parity); the (payload, scales) tiles
    then ride the rings — innermost axis first, so the final source
    order matches the joint tiled all_gather's axis-major layout — and
    each final tile gets its own dequant epilogue."""
    if bits:
        q, s = blockwise_quantize(x, dim=dim, bits=bits, block=block)
        pq, ps = [q], [s]
        for ax in reversed(axes):
            pq, ps = _fcm_gather_one_axis((pq, ps), ax, 0)
        shard_m = x.shape[dim]
        tiles = []
        for qt, st in zip(pq, ps):
            mult = st.shape[0] // s.shape[0]
            tshape = (tuple(x.shape[:dim]) + (shard_m * mult,)
                      + tuple(x.shape[dim + 1:]))
            tiles.append(blockwise_dequantize(qt, st, tshape, dim=dim,
                                              dtype=x.dtype, bits=bits))
        return jnp.concatenate(tiles, axis=dim) if len(tiles) > 1 \
            else tiles[0]
    px = [x]
    for ax in reversed(axes):
        (px,) = _fcm_gather_one_axis((px,), ax, dim)
    return jnp.concatenate(px, axis=dim) if len(px) > 1 else px[0]


def _fcm_scatter_one_axis(x, axis_name, dim, bits, block):
    """One axis of the fused scatter: split into per-owner chunks,
    quantize the compensated chunk table (per-chunk scales — identical
    to the modular qgZ quantization), move each tile in a ring-scheduled
    all-to-all round, dequantize the received source table and reduce in
    shard-index order (``jnp.sum(axis=0)`` — the modular accumulation
    contract, bitwise).  bits=0 moves native chunks promoted to fp32
    (the ``f32_psum_scatter`` dtype contract with a FIXED shard-index
    accumulation order)."""
    world = lax.axis_size(axis_name)
    xt = jnp.moveaxis(x, dim, 0)
    m = xt.shape[0]
    if m % world != 0:
        raise ValueError(
            f"fused reduce-scatter: dim {dim} (size {m}) must be "
            f"divisible by the {axis_name!r} axis size {world}")
    tail = xt.shape[1:]
    chunks = xt.reshape((world, m // world) + tail)
    red, _ = _quantize_scatter_reduce(chunks, axis_name, bits, block)
    return jnp.moveaxis(red.astype(x.dtype), 0, dim)


def fcm_reduce_scatter(x, axes, dim, bits: int = 0,
                       block: int = DEFAULT_BLOCK):
    """Per-tile drop-in for ``quantized_psum_scatter`` (bits=4/8) and
    ``f32_psum_scatter`` (bits=0): the backward GEMM's gradient leaves
    as per-owner tiles on a ring-scheduled all-to-all instead of one
    monolithic collective.  Multiple axes reduce sequentially in tuple
    order, matching the modular path's staging."""
    axes = _axes_tuple(axes)
    with _fcm_scope():
        for ax in axes:
            x = _fcm_scatter_one_axis(x, ax, dim, bits, block)
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def fcm_all_gather(x, axes, dim, qwz_bits=0, qgz_bits=0,
                   block=DEFAULT_BLOCK):
    """Per-tile drop-in for ``low_bandwidth_all_gather`` (and, at
    qwz_bits=0, for ``_all_gather_f32grad``): the consuming GEMM's
    weights arrive tile-by-tile over a ring with the dequant folded in
    per tile.  Forward values are BITWISE-identical to the modular
    path; the transpose reduce-scatters through
    :func:`fcm_reduce_scatter` (qgZ-quantized when ``qgz_bits``, the
    fp32-accumulation table otherwise — the straight-through-quantizer
    contract of the modular custom_vjp, preserved)."""
    axes = _axes_tuple(axes)
    with _fcm_scope():
        return _fcm_gather_impl(x, axes, dim, qwz_bits, block)


def _fcm_ag_fwd(x, axes, dim, qwz_bits, qgz_bits, block):
    return fcm_all_gather(x, axes, dim, qwz_bits, qgz_bits, block), None


def _fcm_ag_bwd(axes, dim, qwz_bits, qgz_bits, block, _, g):
    del qwz_bits  # straight-through: the forward quantizer is identity
    return (fcm_reduce_scatter(g, axes, dim, bits=qgz_bits, block=block),)


fcm_all_gather.defvjp(_fcm_ag_fwd, _fcm_ag_bwd)


def fcm_qgz_reduce_scatter_inner(x, error, axis_name: str, dim: int = 0,
                                 bits: int = 8,
                                 block: int = DEFAULT_BLOCK):
    """Error-compensated fused reduce-scatter; call inside shard_map.

    The per-tile analog of ``qgz_reduce_scatter_inner`` with the
    identical error-feedback contract: the persistent ``error`` buffer
    absorbs this step's quantization residual (``new_error = (x +
    error) - deq(quant(x + error))``), so repeated reductions of a
    persistent signal converge on the exact mean.  Returns
    ``(reduced_chunk, new_error)`` — both bitwise-equal to the modular
    variant's (same quantization, same shard-order accumulation), only
    the transport is per-tile."""
    from ..runtime.comm.low_bandwidth import _check_bits
    _check_bits(bits, "qgz_bits")
    world = lax.axis_size(axis_name)
    compensated = x + error
    xt = jnp.moveaxis(compensated, dim, 0)
    m = xt.shape[0]
    if m % world != 0:
        raise ValueError(
            f"fused qgz reduce-scatter: dim {dim} (size {m}) must be "
            f"divisible by the {axis_name!r} axis size {world}")
    tail = xt.shape[1:]
    chunks = xt.reshape((world, m // world) + tail)
    with _fcm_scope():
        red, applied = _quantize_scatter_reduce(
            chunks, axis_name, bits, block,
            applied_dtype=compensated.dtype)
        reduced = jnp.moveaxis(red.astype(x.dtype), 0, dim)
    new_error = compensated - jnp.moveaxis(
        applied.reshape((m,) + tail), 0, dim)
    return reduced, new_error


# --------------------------------------------------------------------- #
# layer 1: the GEMM-fused kernels
# --------------------------------------------------------------------- #
def _use_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return bool(interpret)
    from .dispatch import pallas_available
    return not pallas_available()


def _dequant_tile(q, s, kc, n, bits):
    """In-kernel dequant prologue: [kc, nb, bs(/2)] int8 payload + fp32
    block scales -> [kc, n] fp32 weight tile (bits=0: native tile, no
    scales)."""
    if not bits:
        return q.astype(jnp.float32).reshape(kc, n)
    if bits == 4 and 2 * int(np.prod(q.shape)) == kc * n:
        from ..runtime.comm.low_bandwidth import unpack_int4
        q = unpack_int4(q)
    return (q.astype(jnp.float32) * s[..., None]).reshape(kc, n)


def _ag_mm_tile_kernel(x_ref, q_ref, s_ref, o_ref, *, bits, kc, n):
    """One ring step's MXU tile: dequantize the arrived shard (prologue)
    and accumulate its partial product.  ``x_ref`` is the [m, kc] column
    block matching the shard's rows."""
    w = _dequant_tile(q_ref[...], s_ref[...], kc, n, bits)
    o_ref[...] = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _ag_mm_tile_t_kernel(g_ref, q_ref, s_ref, o_ref, *, bits, kc, n):
    """Transposed tile for the dx backward: g @ deq(q)^T."""
    w = _dequant_tile(q_ref[...], s_ref[...], kc, n, bits)
    o_ref[...] = jax.lax.dot_general(
        g_ref[...].astype(jnp.float32), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _rs_mm_tile_kernel(a_ref, b_ref, o_ref):
    """One producer-GEMM output tile of dW = a^T @ b (the tile about to
    be quantized and sent in the epilogue)."""
    o_ref[...] = jax.lax.dot_general(
        a_ref[...].astype(jnp.float32), b_ref[...].astype(jnp.float32),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _tile_call(kernel, out_shape, interpret, *args, **static):
    return pl.pallas_call(
        functools.partial(kernel, **static),
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        interpret=interpret,
    )(*args)


def _ag_matmul_interp(x, q, s, axis_name, bits, out_dtype, transpose):
    """Interpret-mode fused allgather-matmul: the per-tile GEMM kernels
    run under ``pallas_call(interpret=True)`` while the remote-copy ring
    is mesh-simulated with ``lax.ppermute`` (the flash_attention.py
    pattern: same kernel math, swappable transport).  Tile t's GEMM
    consumes the shard that arrived at hop t — the arriving tile t+1 is
    independent of it, which is exactly the double-buffering the TPU
    kernel realizes in VMEM."""
    world = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    kc = q.shape[0]
    n = _tile_n(q, kc, bits) if bits else q.shape[1]
    m = x.shape[0]
    ones = jnp.ones((kc, 1), jnp.float32)
    perm = [(i, (i - 1) % world) for i in range(world)]
    cq, cs = q, s
    acc = jnp.zeros((m, kc * world), jnp.float32) if transpose else None
    for t in range(world):
        if t > 0:
            cq = lax.ppermute(cq, axis_name, perm)
            if cs is not None:
                cs = lax.ppermute(cs, axis_name, perm)
        src = lax.rem(my + t, world)
        if transpose:
            # dx backward: the OUTPUT's column block selects the source
            part = _tile_call(_ag_mm_tile_t_kernel, (m, kc), True,
                              x, cq, cs if cs is not None else ones,
                              bits=bits, kc=kc, n=n)
            acc = lax.dynamic_update_slice(acc, part, (0, src * kc))
        else:
            xcols = lax.dynamic_slice_in_dim(x, src * kc, kc, axis=1)
            part = _tile_call(_ag_mm_tile_kernel, (m, n), True,
                              xcols, cq, cs if cs is not None else ones,
                              bits=bits, kc=kc, n=n)
            acc = part if acc is None else acc + part
    return acc.astype(out_dtype)


def _tile_n(q, kc, bits):
    """Columns of the dequantized weight tile for a quantized payload."""
    elems = int(np.prod(q.shape))
    if bits == 4:
        elems *= 2
    return elems // kc


def _quantize_shard(w_shard, bits, block):
    if not bits:
        return w_shard, None
    return blockwise_quantize(w_shard, dim=0, bits=bits, block=block)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def fused_allgather_matmul(x, w_shard, axis_name, qwz_bits=8,
                           qgz_bits=0, block=DEFAULT_BLOCK,
                           interpret=None):
    """``x @ all_gather(w_shard, axis=0)`` with the qwZ dequant-all-gather
    fused into the GEMM's prologue.  Call inside shard_map over
    ``axis_name``; ``w_shard`` is this device's ``[K/W, N]`` row shard,
    ``x`` is ``[M, K]`` (replicated or batch-sharded rows).

    The shard is blockwise-quantized ONCE at the source; the ring then
    moves int8 payload + fp32 scales per tile while the MXU multiplies
    the tile that already arrived — remote arrival double-buffered
    against the current tile, dequant folded into each tile's prologue.
    Backward: dx re-rings the quantized shards through the transposed
    tile GEMM; dW takes :func:`fused_matmul_reduce_scatter` — the qgZ
    scatter fused into the producer GEMM's epilogue (straight-through
    quantizer: with qgz_bits=0 the dW wire is fp32, matching the
    modular custom_vjp's contract)."""
    return _fused_ag_matmul_fwd_impl(x, w_shard, axis_name, qwz_bits,
                                     block, interpret)


def _fused_ag_matmul_fwd_impl(x, w_shard, axis_name, qwz_bits, block,
                              interpret):
    kc = w_shard.shape[0]
    if x.shape[-1] != kc * lax.axis_size(axis_name):
        raise ValueError(
            f"fused_allgather_matmul: x has K={x.shape[-1]} but the "
            f"gathered weight has {kc * lax.axis_size(axis_name)} rows "
            f"({kc} x {lax.axis_size(axis_name)} shards)")
    q, s = _quantize_shard(w_shard, qwz_bits, block)
    with _fcm_scope():
        if _use_interpret(interpret):
            return _ag_matmul_interp(x, q, s, axis_name, qwz_bits,
                                     x.dtype, transpose=False)
        return _ag_matmul_tpu(x, q, s, axis_name, qwz_bits, x.dtype)


def _fused_ag_mm_fwd(x, w_shard, axis_name, qwz_bits, qgz_bits, block,
                     interpret):
    y = _fused_ag_matmul_fwd_impl(x, w_shard, axis_name, qwz_bits, block,
                                  interpret)
    return y, (x, w_shard)


def _fused_ag_mm_bwd(axis_name, qwz_bits, qgz_bits, block, interpret,
                     res, g):
    x, w_shard = res
    q, s = _quantize_shard(w_shard, qwz_bits, block)
    with _fcm_scope():
        if _use_interpret(interpret):
            dx = _ag_matmul_interp(g, q, s, axis_name, qwz_bits, x.dtype,
                                   transpose=True)
        else:
            dx = _ag_matmul_tpu(g, q, s, axis_name, qwz_bits, x.dtype,
                                transpose=True)
    dw, _ = fused_matmul_reduce_scatter(
        x, g, None, axis_name, qgz_bits=qgz_bits, block=block,
        interpret=interpret)
    return dx, dw.astype(w_shard.dtype)


fused_allgather_matmul.defvjp(_fused_ag_mm_fwd, _fused_ag_mm_bwd)


def fused_matmul_reduce_scatter(lhs, rhs, error, axis_name,
                                qgz_bits: int = 8,
                                block: int = DEFAULT_BLOCK,
                                interpret: Optional[bool] = None):
    """``reduce_scatter(lhs^T @ rhs, dim=0)`` with the qgZ transport
    fused into the producer GEMM's epilogue.  Call inside shard_map over
    ``axis_name``; returns ``(my_chunk, new_error)`` where ``my_chunk``
    is this device's ``[K/W, N]`` row chunk of the summed gradient.

    The output tiles of dW = lhs^T @ rhs are computed per DESTINATION in
    ring order (distance-1 neighbor first); as each tile completes it is
    compensated with its ``error`` slice, blockwise-quantized and sent
    straight to its owner (per-tile communication as tiles complete).
    Receivers dequantize the full source table and reduce in shard-index
    order — bitwise-matching ``qgz_reduce_scatter_inner``'s accumulation
    contract, with the identical error-feedback residual
    (``new_error = compensated - deq(quant(compensated))``).  ``error``
    may be None (straight-through, no feedback — the dW wire of
    :func:`fused_allgather_matmul`'s backward); qgz_bits=0 sends fp32
    tiles (no quantization, error passes through zero).

    On TPU with qgz_bits=8 the whole pipeline runs as ONE Pallas kernel
    whose per-tile sends ride ``pltpu.make_async_remote_copy``
    (:func:`_matmul_rs_tpu`); other widths keep the per-tile structure
    below with compiled tile GEMMs and mesh-level transport."""
    world = lax.axis_size(axis_name)
    k, n = lhs.shape[1], rhs.shape[1]
    if k % world != 0:
        raise ValueError(
            f"fused_matmul_reduce_scatter: K={k} must be divisible by "
            f"the {axis_name!r} axis size {world}")
    kc = k // world
    use_interp = _use_interpret(interpret)
    if not use_interp and qgz_bits == 8:
        with _fcm_scope():
            return _matmul_rs_tpu(lhs, rhs, error, axis_name, block)
    with _fcm_scope():
        my = lax.axis_index(axis_name)
        tiles = []
        for t in range(world):
            dst = lax.rem(my + t, world)
            a_cols = lax.dynamic_slice_in_dim(lhs, dst * kc, kc, axis=1)
            tile = _tile_call(_rs_mm_tile_kernel, (kc, n), use_interp,
                              a_cols, rhs)
            if error is not None:
                tile = tile + lax.dynamic_slice_in_dim(
                    error.astype(jnp.float32), dst * kc, kc, axis=0)
            tiles.append(tile)
        # destination-order [W, kc, n] table (row t -> dst (my + t) % W);
        # roll to destination-index order for the quantizer (per-chunk
        # scales, identical to the modular chunk-table quantization)
        dest_tab = jnp.roll(jnp.stack(tiles, axis=0), my, axis=0)
        my_chunk, applied = _quantize_scatter_reduce(
            dest_tab, axis_name, qgz_bits, block,
            applied_dtype=jnp.float32 if error is not None else None)
    if error is not None:
        new_error = (dest_tab - applied).reshape(k, n)
        return my_chunk, new_error.astype(error.dtype)
    return my_chunk, None


# --------------------------------------------------------------------- #
# TPU path: in-kernel RDMA ring (UNVALIDATED on chip — ROADMAP item 1)
# --------------------------------------------------------------------- #
def _ag_matmul_tpu(x, q, s, axis_name, bits, out_dtype,
                   transpose: bool = False):  # pragma: no cover - TPU only
    """Single-kernel fused dequant-all-gather-matmul: the quantized
    shard circulates the ring via ``pltpu.make_async_remote_copy`` into
    double-buffered VMEM slots while the MXU multiplies the tile that
    arrived last step — the T3 schedule realized in-kernel.

    UNVALIDATED on real chips (this host has none): written against the
    Pallas TPU RDMA contract (neighbor barrier before the first remote
    write, per-slot DMA semaphores, send-wait before slot reuse) and
    folded into ROADMAP item 1's measured sweep.  Interpret-mode callers
    take :func:`_ag_matmul_interp`, which pins the identical numerics
    with the transport mesh-simulated."""
    if pltpu is None:
        raise RuntimeError(
            "fused_allgather_matmul: pallas TPU support unavailable — "
            "pass interpret=True (mesh-simulated transport) on CPU")
    world = int(lax.axis_size(axis_name))
    kc = q.shape[0]
    n = _tile_n(q, kc, bits)
    m = x.shape[0]
    if s is None:
        s = jnp.ones((kc, 1), jnp.float32)
    me = lax.axis_index(axis_name).astype(jnp.int32).reshape((1,))

    def kernel(me_ref, x_ref, q0_ref, s0_ref, o_ref, qbuf, sbuf, acc,
               qsend, qrecv, ssend, srecv):
        me_i = me_ref[0]
        left = lax.rem(me_i - 1 + world, world)
        right = lax.rem(me_i + 1, world)
        # stage my own payload in slot 0
        qbuf[0] = q0_ref[...]
        sbuf[0] = s0_ref[...]
        acc[...] = jnp.zeros_like(acc)
        # both neighbors must have staged before any remote write lands
        barrier = pltpu.get_barrier_semaphore()
        for nb in (left, right):
            pltpu.semaphore_signal(barrier, inc=1, device_id=(nb,))
        pltpu.semaphore_wait(barrier, 2)

        def step(t, _):
            slot = lax.rem(t, 2)
            nxt = lax.rem(t + 1, 2)

            @pl.when(t < world - 1)
            def _send():
                # forward the current tile to the left neighbor while
                # the MXU works on it — the double buffer
                for buf, snd, rcv in ((qbuf, qsend, qrecv),
                                      (sbuf, ssend, srecv)):
                    pltpu.make_async_remote_copy(
                        src_ref=buf.at[slot], dst_ref=buf.at[nxt],
                        send_sem=snd.at[slot], recv_sem=rcv.at[nxt],
                        device_id=(left,),
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).start()

            src = lax.rem(me_i + t, world)
            w = _dequant_tile(qbuf[slot], sbuf[slot], kc, n, bits)
            if transpose:
                part = jax.lax.dot_general(
                    x_ref[...].astype(jnp.float32), w,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc[:, pl.ds(src * kc, kc)] = part
            else:
                xc = x_ref[:, pl.ds(src * kc, kc)]
                acc[...] += jax.lax.dot_general(
                    xc.astype(jnp.float32), w, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            @pl.when(t < world - 1)
            def _wait():
                for snd, rcv in ((qsend, qrecv), (ssend, srecv)):
                    pltpu.semaphore_wait(rcv.at[nxt], 1)
                    pltpu.semaphore_wait(snd.at[slot], 1)
            return 0

        lax.fori_loop(0, world, step, 0)
        o_ref[...] = acc[...].astype(o_ref.dtype)

    out_shape = (m, kc * world) if transpose else (m, n)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(),
            in_specs=[pl.BlockSpec(x.shape, lambda *_: (0, 0)),
                      pl.BlockSpec(q.shape, lambda *_: (0,) * q.ndim),
                      pl.BlockSpec(s.shape, lambda *_: (0,) * s.ndim)],
            out_specs=pl.BlockSpec(out_shape, lambda *_: (0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2,) + q.shape, q.dtype),
                pltpu.VMEM((2,) + s.shape, s.dtype),
                pltpu.VMEM(out_shape, jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ]),
        out_shape=jax.ShapeDtypeStruct(out_shape, out_dtype),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0),
    )(me, x, q, s)


def _matmul_rs_tpu(lhs, rhs, error, axis_name,
                   block):  # pragma: no cover - TPU only
    """Single-kernel fused GEMM + qgZ reduce-scatter (int8): each output
    tile of dW = lhs^T @ rhs is computed per DESTINATION in ring order,
    compensated with its error slice, blockwise-int8 quantized in the
    epilogue and sent straight to its owner via
    ``pltpu.make_async_remote_copy`` (a ring-scheduled all-to-all:
    round t sends the distance-t tile while the MXU computes the next
    one); the receiver dequantizes the source table and reduces in
    shard-index order — the modular accumulation contract.

    UNVALIDATED on real chips (this host has none) — folded into
    ROADMAP item 1's measured sweep; interpret-mode callers take the
    per-tile path in :func:`fused_matmul_reduce_scatter`, which pins
    the identical numerics with the transport mesh-simulated."""
    if pltpu is None:
        raise RuntimeError(
            "fused_matmul_reduce_scatter: pallas TPU support "
            "unavailable — pass interpret=True on CPU")
    from ..runtime.comm.low_bandwidth import largest_divisor_at_most
    world = int(lax.axis_size(axis_name))
    k, n = lhs.shape[1], rhs.shape[1]
    kc = k // world
    rest = kc * n
    bs = largest_divisor_at_most(rest, block)
    nb = rest // bs
    qmax = 127.0
    track_error = error is not None
    err_in = (error.astype(jnp.float32) if track_error
              else jnp.zeros((k, n), jnp.float32))
    me = lax.axis_index(axis_name).astype(jnp.int32).reshape((1,))

    def kernel(me_ref, lhs_ref, rhs_ref, err_ref, out_ref, nerr_ref,
               qtab, stab, qstage, sstage, qsend, ssend, qrecv, srecv):
        me_i = me_ref[0]
        barrier = pltpu.get_barrier_semaphore()
        for d in range(world):
            if d != 0:  # every peer must arrive before remote writes
                pltpu.semaphore_signal(
                    barrier, inc=1,
                    device_id=(lax.rem(me_i + d, world),))
        pltpu.semaphore_wait(barrier, world - 1)

        def quantize(tile):
            g = tile.reshape(nb, bs)
            amax = jnp.max(jnp.abs(g), axis=-1)
            scale = jnp.where(amax > 0, amax / qmax, 1.0)
            q = jnp.clip(jnp.round(g / scale[:, None]), -qmax, qmax
                         ).astype(jnp.int8)
            return q, scale.reshape(1, nb)

        def one_tile(t):
            """producer-GEMM tile for destination (me + t) % W, with the
            error-feedback epilogue."""
            dst = lax.rem(me_i + t, world)
            a = lhs_ref[:, pl.ds(dst * kc, kc)]
            tile = jax.lax.dot_general(
                a.astype(jnp.float32), rhs_ref[...].astype(jnp.float32),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            comp = tile + err_ref[pl.ds(dst * kc, kc), :]
            q, scale = quantize(comp)
            deq = (q.astype(jnp.float32)
                   * scale.reshape(nb, 1)).reshape(kc, n)
            nerr_ref[pl.ds(dst * kc, kc), :] = comp - deq
            return dst, q, scale

        def step(t, _):
            slot = lax.rem(t, 2)
            dst, q, scale = one_tile(t)
            qstage[slot] = q
            sstage[slot] = scale

            @pl.when(t >= 3)
            def _reuse():  # the slot's previous send must have landed
                pltpu.semaphore_wait(qsend.at[slot], 1)
                pltpu.semaphore_wait(ssend.at[slot], 1)
            # remote tables are indexed by SOURCE: my row is `me_i`
            pltpu.make_async_remote_copy(
                src_ref=qstage.at[slot], dst_ref=qtab.at[me_i],
                send_sem=qsend.at[slot], recv_sem=qrecv.at[me_i],
                device_id=(dst,),
                device_id_type=pltpu.DeviceIdType.LOGICAL).start()
            pltpu.make_async_remote_copy(
                src_ref=sstage.at[slot], dst_ref=stab.at[me_i],
                send_sem=ssend.at[slot], recv_sem=srecv.at[me_i],
                device_id=(dst,),
                device_id_type=pltpu.DeviceIdType.LOGICAL).start()
            return 0

        # rounds 1..W-1: send each tile as it completes; own tile last
        lax.fori_loop(1, world, step, 0)
        dst0, q0, s0 = one_tile(0)
        del dst0
        qtab[me_i] = q0
        stab[me_i] = s0

        def collect(s_idx, acc):
            @pl.when(s_idx != me_i)
            def _wait():
                pltpu.semaphore_wait(qrecv.at[s_idx], 1)
                pltpu.semaphore_wait(srecv.at[s_idx], 1)
            deq = (qtab[s_idx].astype(jnp.float32)
                   * stab[s_idx].reshape(nb, 1)).reshape(kc, n)
            return acc + deq  # shard-index order: the modular contract

        acc = lax.fori_loop(0, world, collect,
                            jnp.zeros((kc, n), jnp.float32))
        out_ref[...] = acc.astype(out_ref.dtype)
        # drain outstanding sends before kernel exit: the step loop only
        # waits a slot's send when REUSING it (t >= 3), so the last two
        # rounds' sends (one round when world == 2) were never waited
        for t in range(max(1, world - 2), world):
            pltpu.semaphore_wait(qsend.at[t % 2], 1)
            pltpu.semaphore_wait(ssend.at[t % 2], 1)

    chunk, nerr = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(),
            in_specs=[pl.BlockSpec(lhs.shape, lambda *_: (0, 0)),
                      pl.BlockSpec(rhs.shape, lambda *_: (0, 0)),
                      pl.BlockSpec((k, n), lambda *_: (0, 0))],
            out_specs=[pl.BlockSpec((kc, n), lambda *_: (0, 0)),
                       pl.BlockSpec((k, n), lambda *_: (0, 0))],
            scratch_shapes=[
                pltpu.VMEM((world, nb, bs), jnp.int8),
                pltpu.VMEM((world, 1, nb), jnp.float32),
                pltpu.VMEM((2, nb, bs), jnp.int8),
                pltpu.VMEM((2, 1, nb), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((world,)),
                pltpu.SemaphoreType.DMA((world,)),
            ]),
        out_shape=[jax.ShapeDtypeStruct((kc, n), jnp.float32),
                   jax.ShapeDtypeStruct((k, n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=1),
    )(me, lhs, rhs, err_in)
    if track_error:
        return chunk, nerr.astype(error.dtype)
    return chunk, None
