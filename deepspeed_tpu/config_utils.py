"""Helpers for parsing the JSON config (reference: deepspeed/runtime/config_utils.py)."""

import json


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys while JSON-parsing (reference: config_utils.py:23)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d


def load_config_dict(config):
    """Accept a path to a JSON file or an already-parsed dict."""
    if isinstance(config, dict):
        return config
    if isinstance(config, str):
        with open(config, "r") as f:
            return json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
    raise TypeError(
        "Expected a dict or a path to a JSON config file, got {}".format(type(config)))
