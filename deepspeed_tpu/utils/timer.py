"""Wall-clock and throughput timers (reference: deepspeed/utils/timer.py:19).

Where the reference synchronizes CUDA streams, we synchronize XLA's async
dispatch queue: `_device_sync` runs a trivial computation and blocks on it,
which (by in-order execution per device) drains previously dispatched work.
"""

import time
from typing import Dict, List, Optional

from .logging import log_dist, logger

# one debug line per process, not one per timed step
_sync_failure_logged = False


def _device_sync():
    """Drain the XLA dispatch queue.  Failures are narrowed: only the
    expected benign cases (no jax installed: ImportError; backend not
    initialized / torn down mid-exit: RuntimeError) are swallowed — and
    even those are logged once at debug, because a sync that silently
    fails times the queue depth as ~0 and every derived number lies."""
    global _sync_failure_logged
    try:
        import jax
        import jax.numpy as jnp
        jnp.zeros(()).block_until_ready()
        # effects_barrier waits for any outstanding host callbacks too;
        # older jax versions lack it (AttributeError is a version fact,
        # not a sync failure)
        barrier = getattr(jax, "effects_barrier", None)
        if barrier is not None:
            barrier()
    except (ImportError, RuntimeError) as e:
        if not _sync_failure_logged:
            _sync_failure_logged = True
            logger.debug(f"timer device sync unavailable "
                         f"({type(e).__name__}: {e}) — timings will not "
                         "drain the dispatch queue")


class SynchronizedWallClockTimer:
    """Named timer group; `elapsed` drains the device queue before reading."""

    class Timer:
        def __init__(self, name: str):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.time()

        def start(self):
            assert not self.started_, f"timer {self.name_} has already been started"
            _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, "timer is not started"
            _device_sync()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

        def mean(self):
            return self.elapsed(reset=False)

    def __init__(self):
        self.timers: Dict[str, "SynchronizedWallClockTimer.Timer"] = {}

    def __call__(self, name: str):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0)
            peak = stats.get("peak_bytes_in_use", 0)
            return (f"MemAllocated={in_use / 2**30:.2f} GB "
                    f"MaxMemAllocated={peak / 2**30:.2f} GB")
        except Exception:
            return "MemAllocated=? MaxMemAllocated=?"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(
                    reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        log_dist(string, ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec tracking (reference: deepspeed/utils/timer.py
    ThroughputTimer).

    Unlike the reference (which cuda-synchronizes every step), the device
    queue is drained only at `steps_per_output` window boundaries: a per-step
    sync through a remote-TPU tunnel serializes host dispatch against device
    compute and was measured to add ~150 ms/step to the flagship bench.
    Two semantic consequences: per-step variance is lost, and the window
    includes inter-step host time (dataloader etc.) the reference's
    start/stop bracketing excluded — i.e. this reports DELIVERED end-to-end
    throughput, which is lower than the reference's device-only number when
    a slow input pipeline isn't hidden by the dispatch queue.
    """

    def __init__(self, batch_size, num_workers, start_step=2,
                 steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.total_timed_steps = 0
        self.window_steps = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step and self.start_time == 0.0:
            # first timed step: drain the queue once so the window starts
            # from an idle device, then let dispatch run free
            _device_sync()
            self.start_time = time.time()
            self.window_steps = 0

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if not global_step:
            return
        self.global_step_count += 1
        if self.start_time <= 0:
            return
        self.window_steps += 1
        if self.global_step_count % self.steps_per_output != 0:
            return
        window_rate = self._close_window()
        if report_speed:
            self.logging(
                "epoch={}/micro_step={}/global_step={}, "
                "RunningAvgSamplesPerSec={:.6g}, CurrSamplesPerSec={:.6g}".format(
                    self.epoch_count, self.micro_step_count,
                    self.global_step_count, self.avg_samples_per_sec(),
                    window_rate))

    def _close_window(self):
        """Drain the device queue, fold the open window into the running
        totals, and start the next window.  Returns the closed window's
        global samples/sec (all workers, same units as the running avg)."""
        _device_sync()
        self.end_time = time.time()
        duration = self.end_time - self.start_time
        self.total_elapsed_time += duration
        self.total_timed_steps += self.window_steps
        rate = (self.batch_size * self.num_workers * self.window_steps /
                max(duration, 1e-12))
        self.start_time = self.end_time  # next window starts synced
        self.window_steps = 0
        return rate

    def avg_samples_per_sec(self):
        if self.window_steps > 0:
            # fold the open partial window in — otherwise short runs
            # (< steps_per_output steps) would have no data at all
            self._close_window()
        if self.total_timed_steps > 0:
            samples_per_step = self.batch_size * self.num_workers
            avg_time_per_step = (self.total_elapsed_time /
                                 self.total_timed_steps)
            return samples_per_step / avg_time_per_step
        return float("-inf")
