"""Multi-controller bootstrap — the init_distributed analog.

Reference: deepspeed/utils/distributed.py:12 (init_distributed: env-var /
MPI rank discovery, then torch.distributed.init_process_group(nccl)).

TPU recasting: discovery order is (1) dslaunch's DS_* env, (2) torch-style
MASTER_ADDR/RANK/WORLD_SIZE env, (3) OMPI_COMM_WORLD_* (mpirun), then
`jax.distributed.initialize` wires the coordinator.  On Cloud TPU with no
env at all, jax.distributed.initialize() autodetects from metadata — the
AzureML-patch role of the reference (:108).
"""

import os
from typing import Optional

import jax

from .logging import logger

_INITIALIZED = False


def mpi_discovery() -> Optional[dict]:
    """OpenMPI env discovery (reference: distributed.py:54)."""
    if "OMPI_COMM_WORLD_SIZE" not in os.environ:
        return None
    return {
        "num_processes": int(os.environ["OMPI_COMM_WORLD_SIZE"]),
        "process_id": int(os.environ["OMPI_COMM_WORLD_RANK"]),
        "coordinator_address": os.environ.get("MASTER_ADDR", "") and
        f"{os.environ['MASTER_ADDR']}:"
        f"{os.environ.get('MASTER_PORT', 29500)}",
    }


def init_distributed(dist_backend: str = "xla", auto_mpi_discovery=True,
                     init_method: Optional[str] = None, rank: int = -1,
                     world_size: int = -1) -> None:
    """Initialize multi-controller JAX from launcher/MPI/torch-style env."""
    global _INITIALIZED
    if _INITIALIZED or jax.process_count() > 1:
        _INITIALIZED = True
        return

    coordinator = num = pid = None
    if "DS_COORDINATOR" in os.environ:  # dslaunch
        coordinator = os.environ["DS_COORDINATOR"]
        num = int(os.environ["DS_NUM_PROCESSES"])
        pid = int(os.environ["DS_PROCESS_ID"])
    elif "MASTER_ADDR" in os.environ and "RANK" in os.environ:
        coordinator = (f"{os.environ['MASTER_ADDR']}:"
                       f"{os.environ.get('MASTER_PORT', 29500)}")
        num = int(os.environ.get("WORLD_SIZE", world_size))
        pid = int(os.environ["RANK"])
    elif auto_mpi_discovery:
        found = mpi_discovery()
        if found and found["coordinator_address"]:
            coordinator = found["coordinator_address"]
            num, pid = found["num_processes"], found["process_id"]

    if rank >= 0:
        pid = rank
    if world_size > 0:
        num = world_size

    if coordinator is None or num is None or num <= 1:
        logger.info("init_distributed: single-process (no coordinator env)")
        _INITIALIZED = True
        return
    logger.info(f"init_distributed: coordinator={coordinator} "
                f"process {pid}/{num}")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num, process_id=pid)
    _INITIALIZED = True
