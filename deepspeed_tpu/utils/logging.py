"""Rank-aware logging (reference: deepspeed/utils/logging.py)."""

import logging
import sys

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name="DeepSpeedTPU", level=logging.INFO):
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] "
            "%(message)s")
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(formatter)
        logger_.addHandler(handler)
    return logger_


logger = _create_logger()


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log from the listed process ranks only; None ⇒ rank 0, -1 in the list
    ⇒ every rank.  Reference: deepspeed/utils/logging.py log_dist."""
    my_rank = _process_index()
    ranks = ranks or [0]
    if my_rank in ranks or -1 in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")
