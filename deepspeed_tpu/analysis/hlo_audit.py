"""HLO-level SPMD audit — cross-check the jaxpr wire story against the
program XLA actually compiled.

Every number the repo stakes its honesty on — ``collective_wire_bytes``,
the lockstep signature, ``predicted_step_time_lb``, the
``require_overlap`` CI gate — is computed from the **jaxpr**.  But under
pjit/GSPMD, XLA inserts collectives *after* tracing: the partitioner
adds the data-parallel partial-sum reductions, re-gathers ZeRO-sharded
params at the optimizer boundary, and — when a sharding annotation is
wrong — silently reshards tensors with all-gathers the entire
jaxpr-level analysis stack never sees (the exact failure mode the T3
paper, arXiv:2401.16677, fuses kernels to avoid).  This module closes
the blind spot:

  1. lower each ``AuditTarget`` through XLA's SPMD partitioner on the
     simulated mesh (compile-only on CPU, never executed — the same
     contract as the rest of the auditor),
  2. walk the optimized post-SPMD HLO for collective ops (all-gather /
     all-reduce / reduce-scatter / collective-permute / all-to-all;
     async ``-start``/``-done`` pairs deduped to the start),
  3. price each collective with replica-group-aware sizing and while-
     loop trip-count weighting (``known_trip_count`` backend config),
  4. reconcile against the jaxpr-level prediction: collectives whose op
     metadata names a traced jax collective primitive confirm the
     accounting; compiler-inserted reductions are the partial-sum
     combine GSPMD must insert (explained, priced); compiler-inserted
     GATHER-family collectives are resharding — waived when a declared
     sharding contract predicts them (ZeRO's param re-gather) or when
     below the configured floor, otherwise a ``silent_reshard`` finding
     with op-metadata source provenance (warning by default, error
     under ``analysis.require_spmd_match``).

The HLO-only wire (everything the jaxpr never counted) feeds the cost
model's exposed-comm lane so ``predicted_step_time_lb`` stops
undercounting — see ``cost_model.build_step_time_model``.

Parsing note: the walk reads the optimized HLO **text**
(``lowered.compile().as_text()``), the one stable surface jax exposes
across jaxlib versions for the post-optimization program.  The parser
is deliberately structural — computations, instructions, called
computations, replica groups — and every quantity it extracts is pinned
by fixture tests against real XLA output (tests/unit/test_hlo_audit.py).
"""

import re
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from .. import constants as C
from .findings import Finding, RULE_SILENT_RESHARD, RULE_SPMD_DIVERGENCE
from .jaxpr_walk import scope_has_component

# HLO collective opcodes (sync + async-start forms).  ``-done`` halves
# of async pairs are skipped: the wire moves once per start.
GATHER_OPCODES = ("all-gather",)
REDUCE_OPCODES = ("all-reduce", "reduce-scatter")
PERMUTE_OPCODES = ("collective-permute", "all-to-all")
COLLECTIVE_OPCODES = GATHER_OPCODES + REDUCE_OPCODES + PERMUTE_OPCODES
# gather-family = compiler-inserted instances are resharding, not the
# mathematically-required partial-sum combine
RESHARD_OPCODES = GATHER_OPCODES + PERMUTE_OPCODES

# traced jax collective primitives an HLO op's metadata op_name ends in
# when the collective came from the traced program (signature.py's
# COLLECTIVE_PRIMS vocabulary).  GSPMD-inserted collectives carry the
# CAUSING op's metadata (dot_general, scatter-add) or none at all.
_TRACED_PRIMS = ("all_gather", "psum_scatter", "reduce_scatter",
                 "all_to_all", "ppermute", "psum2", "psum", "pmax",
                 "pmin")
# the subset whose wire the jaxpr accounting (rules.step_wire_bytes)
# actually counts — ppermute only inside the fused-collective-matmul
# scope, pmax/pmin never (lockstep-relevant, wire-irrelevant)
_COUNTED_PRIMS = ("all_gather", "psum_scatter", "reduce_scatter",
                  "all_to_all", "psum2", "psum")

_DTYPE_BITS = {
    "pred": 8, "token": 0, "opaque": 0,
    "s2": 2, "u2": 2, "s4": 4, "u4": 4,
    "s8": 8, "u8": 8, "s16": 16, "u16": 16, "s32": 32, "u32": 32,
    "s64": 64, "u64": 64,
    "f8e4m3fn": 8, "f8e5m2": 8, "f8e4m3b11fnuz": 8, "f8e4m3fnuz": 8,
    "f8e5m2fnuz": 8, "f8e3m4": 8, "f8e4m3": 8, "f8e8m0fnu": 8,
    "bf16": 16, "f16": 16, "f32": 32, "f64": 64,
    "c64": 64, "c128": 128,
}

_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\](?:\{[^}]*\})?")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[^\s=]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|\S+)\s+(?P<opcode>[\w\-]+)\(")
_COMP_RE = re.compile(r"^\s*(?P<entry>ENTRY\s+)?%(?P<name>[^\s(]+)\s*\(")
_METADATA_RE = re.compile(
    r'metadata=\{[^}]*?op_name="(?P<op_name>[^"]*)"'
    r'(?:[^}]*?source_file="(?P<file>[^"]*)")?'
    r'(?:[^}]*?source_line=(?P<line>\d+))?')
_TRIP_RE = re.compile(r'"known_trip_count"\s*:\s*\{\s*"n"\s*:\s*"(\d+)"')
# greedy digits/braces body (the lazy form would stop at the FIRST
# inner '}' of {{0,1},{2,3}}); the [^a-z=] class halts at the next
# lowercase attribute name either way
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(?P<body>[^a-z=]*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(?P<ngroups>\d+),(?P<size>\d+)\]<=\[")
_CALLED_RE = {
    "body": re.compile(r"body=%(\S+?)(?=[,)\s]|$)"),
    "condition": re.compile(r"condition=%(\S+?)(?=[,)\s]|$)"),
    "calls": re.compile(r"calls=%(\S+?)(?=[,)\s]|$)"),
    "to_apply": re.compile(r"to_apply=%(\S+?)(?=[,)\s]|$)"),
    "true": re.compile(r"true_computation=%(\S+?)(?=[,)\s]|$)"),
    "false": re.compile(r"false_computation=%(\S+?)(?=[,)\s]|$)"),
    "branches": re.compile(r"branch_computations=\{([^}]*)\}"),
}
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


def _shape_bytes(text: str) -> int:
    """Total bytes of every shape token in ``text`` (sub-byte dtypes
    round up per array, matching numpy's int4 itemsize convention)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        bits = _DTYPE_BITS.get(dtype)
        if bits is None or bits == 0:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += max(1, (n * bits) // 8) if n else 0
    return total


def _paren_operands(line: str, opcode: str) -> str:
    """The operand list of the instruction call: text between the
    opcode's '(' and its matching ')'."""
    start = line.index(opcode + "(") + len(opcode)
    depth = 0
    for i in range(start, len(line)):
        ch = line[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i]
    return line[start + 1:]


class HloInstr(NamedTuple):
    name: str
    opcode: str
    shape: str
    line: str


@dataclass
class HloCollective:
    """One collective instruction of the optimized post-SPMD program."""
    opcode: str             # canonical (async -start folded in)
    name: str               # HLO instruction name
    target: str             # audited program label
    wire_bytes: int         # one execution's wire (gather: group-sized
                            # output, reduce/permute: operand bytes)
    mult: int               # enclosing while-loop trip multiplier
    group_size: int         # replica-group participant count
    n_groups: int
    op_name: str            # metadata op_name ("" when absent)
    source: str             # "file:line" provenance ("" when absent)
    traced: bool            # produced by a traced jax collective prim
    counted: bool           # traced AND in the jaxpr wire accounting
    degenerate: bool        # single-participant group: no wire moves
    in_branch: bool = False  # under a conditional (may not execute)
    # False for records in a non-worst conditional branch: excluded
    # from every byte total (only one branch executes; totals take the
    # worst branch, mirroring the jaxpr-side walkers) but still
    # CLASSIFIED — a silent reshard in the cheaper branch must flag
    charged: bool = True
    waived_by: str = ""     # waiver name for inserted gathers ("" = none)


class HloProgram:
    """Parsed optimized-HLO module: computations, entry, partitions."""

    def __init__(self, text: str):
        self.computations: Dict[str, List[HloInstr]] = {}
        self.entry: Optional[str] = None
        m = _NUM_PARTITIONS_RE.search(text)
        self.num_partitions = int(m.group(1)) if m else 1
        current: Optional[List[HloInstr]] = None
        for raw in text.splitlines():
            instr = _INSTR_RE.match(raw)
            if instr is not None and current is not None:
                current.append(HloInstr(instr.group("name"),
                                        instr.group("opcode"),
                                        instr.group("shape"), raw))
                continue
            comp = _COMP_RE.match(raw)
            if comp is not None and "->" in raw and raw.rstrip().endswith("{"):
                current = []
                self.computations[comp.group("name")] = current
                if comp.group("entry"):
                    self.entry = comp.group("name")


def _replica_group(line: str, num_partitions: int) -> Tuple[int, int]:
    """(group_size, n_groups) of a collective instruction."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group("size")), int(m.group("ngroups"))
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        groups = [g for g in m.group("body").split("},{") if g.strip()]
        if not groups:
            # replica_groups={} — all participants in one group
            return num_partitions, 1
        first = groups[0].strip("{} ")
        size = len([x for x in first.split(",") if x.strip()])
        return max(1, size), len(groups)
    return num_partitions, 1


def _canonical_opcode(opcode: str) -> Optional[str]:
    """Map sync/async spellings onto the canonical collective opcode;
    None for non-collectives and for the -done halves of async pairs."""
    if opcode.endswith("-done") or opcode.endswith("-update"):
        return None
    base = opcode[:-6] if opcode.endswith("-start") else opcode
    return base if base in COLLECTIVE_OPCODES else None


def _collective_wire_bytes(instr: HloInstr, opcode: str,
                           group_size: int) -> int:
    """Replica-group-aware wire bytes, on the jaxpr accounting's
    conventions: a gather is priced at its group-sized OUTPUT (operand
    bytes x participants — matches `step_wire_bytes` counting gather
    outvars), reductions/permutes at their operand bytes."""
    operands = _shape_bytes(_paren_operands(instr.line, instr.opcode))
    if opcode in GATHER_OPCODES:
        return operands * group_size
    return operands


def walk_hlo_collectives(program: HloProgram,
                         target_label: str = "") -> List[HloCollective]:
    """Trip-count-weighted collective records of one compiled program.

    Walks from ENTRY through while bodies (mult x known_trip_count),
    conditional branches (marked ``in_branch``; totals take the worst
    branch like the jaxpr-side walkers), and call/async computations.
    Fusion computations are skipped — XLA never fuses collectives.
    """
    out: List[HloCollective] = []
    visiting: List[str] = []

    def visit(comp_name: str, mult: int, in_branch: bool,
              sink: List[HloCollective]) -> None:
        comp = program.computations.get(comp_name)
        if comp is None or comp_name in visiting:
            return
        visiting.append(comp_name)
        for instr in comp:
            opcode = _canonical_opcode(instr.opcode)
            if opcode is not None:
                size, n_groups = _replica_group(instr.line,
                                                program.num_partitions)
                meta = _METADATA_RE.search(instr.line)
                op_name = meta.group("op_name") if meta else ""
                source = ""
                if meta and meta.group("file"):
                    source = meta.group("file")
                    if meta.group("line"):
                        source += f":{meta.group('line')}"
                last = op_name.rsplit("/", 1)[-1]
                prim = next((p for p in _TRACED_PRIMS
                             if re.search(rf"\b{p}\b", last)), None)
                counted = prim in _COUNTED_PRIMS
                if prim == "ppermute":
                    # the jaxpr accounting prices ppermute only as a
                    # fused-collective-matmul transport (rules.py)
                    counted = scope_has_component(op_name, C.FCM_SCOPE)
                degenerate = size <= 1
                sink.append(HloCollective(
                    opcode=opcode, name=instr.name, target=target_label,
                    wire_bytes=(0 if degenerate else
                                _collective_wire_bytes(instr, opcode,
                                                       size)),
                    mult=mult, group_size=size, n_groups=n_groups,
                    op_name=op_name, source=source,
                    traced=prim is not None, counted=counted,
                    degenerate=degenerate, in_branch=in_branch))
                continue
            if instr.opcode == "while":
                trip = _TRIP_RE.search(instr.line)
                n = int(trip.group(1)) if trip else 1
                for key in ("body", "condition"):
                    m = _CALLED_RE[key].search(instr.line)
                    if m:
                        visit(m.group(1), mult * n, in_branch, sink)
            elif instr.opcode == "conditional":
                branches = []
                m = _CALLED_RE["branches"].search(instr.line)
                if m:
                    branches = [b.strip().lstrip("%")
                                for b in m.group(1).split(",") if b.strip()]
                else:
                    for key in ("true", "false"):
                        mm = _CALLED_RE[key].search(instr.line)
                        if mm:
                            branches.append(mm.group(1))
                probes: List[List[HloCollective]] = []
                for b in branches:
                    probe: List[HloCollective] = []
                    visit(b, mult, True, probe)
                    probes.append(probe)
                if probes:
                    # worst branch feeds the totals (only one executes);
                    # every branch's records are kept for findings —
                    # uncharged, wire intact, so the reshard classifier
                    # still sees their true bytes
                    best = max(probes, key=lambda p: sum(
                        r.wire_bytes * r.mult for r in p))
                    for p in probes:
                        for r in p:
                            if p is not best:
                                r.charged = False
                            sink.append(r)
            elif instr.opcode in ("call", "async-start"):
                for key in ("to_apply", "calls"):
                    m = _CALLED_RE[key].search(instr.line)
                    if m:
                        visit(m.group(1), mult, in_branch, sink)
        visiting.pop()

    if program.entry is not None:
        visit(program.entry, 1, False, out)
    return out


@dataclass
class SpmdWaiver:
    """A declared expectation for compiler-inserted gather-family wire:
    the sharding contract predicts up to ``byte_budget`` bytes/step of
    ``opcodes`` resharding (ZeRO stage >= 1 re-gathers the updated
    params at the optimizer boundary).  Absorbed bytes are reported per
    waiver so tests can pin WHY a config's divergence is explained."""
    name: str
    byte_budget: int
    opcodes: Tuple[str, ...] = RESHARD_OPCODES
    absorbed_bytes: int = 0


@dataclass
class HloTargetAudit:
    """Reconciliation of one compiled program against its jaxpr."""
    target: str
    collectives: List[HloCollective] = field(default_factory=list)
    error: str = ""             # lowering/compile failure (audit skipped)
    skipped: bool = False       # target had no lowering hook
    # accounting (all trip-count weighted, one dispatch of the program)
    jaxpr_wire_bytes: int = 0   # rules.step_wire_bytes prediction
    matched_wire_bytes: int = 0  # traced+counted collectives, HLO-sized
    uncounted_traced_bytes: int = 0  # traced but outside jaxpr accounting
    reduction_bytes: int = 0    # inserted all-reduce/reduce-scatter
    waived_reshard_bytes: int = 0
    reshard_bytes: int = 0      # inserted, unwaived — the finding bytes
    n_silent_reshards: int = 0
    waivers: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def hlo_wire_bytes(self) -> int:
        return (self.matched_wire_bytes + self.uncounted_traced_bytes
                + self.reduction_bytes + self.waived_reshard_bytes
                + self.reshard_bytes)

    @property
    def hlo_only_bytes(self) -> int:
        """COMPILER-INSERTED wire the jaxpr accounting never saw —
        priced fully exposed by the cost model (no overlap record
        exists for it).  Traced-but-uncounted wire (a ring attention's
        ppermute, pmax/pmin) is deliberately NOT here: the jaxpr side
        excludes it because it is overlap-managed by construction, and
        pricing it exposed would push the 'lower bound' above
        achievable step time."""
        return (self.reduction_bytes + self.waived_reshard_bytes
                + self.reshard_bytes)

    @property
    def verified(self) -> bool:
        """The cross-check actually ran for this target."""
        return not self.error and not self.skipped

    @property
    def divergence_ratio(self) -> Optional[float]:
        """None when the target was never cross-checked — an errored
        target must not masquerade as a measured-zero-wire one."""
        if not self.verified:
            return None
        if self.jaxpr_wire_bytes <= 0:
            return 1.0 if self.matched_wire_bytes == 0 else float("inf")
        return self.matched_wire_bytes / self.jaxpr_wire_bytes


def audit_target_hlo(target, cfg, jaxpr_wire_bytes: int
                     ) -> Tuple[HloTargetAudit, List[Finding]]:
    """Lower one AuditTarget through the SPMD partitioner and reconcile
    (compile-only; returns an error-carrying audit when XLA refuses —
    the PartitionId seed-xfail class must not crash the auditor)."""
    audit = HloTargetAudit(target=target.label,
                           jaxpr_wire_bytes=int(jaxpr_wire_bytes))
    severity = "error" if cfg.require_spmd_match else "warning"
    if target.lower is None:
        audit.skipped = True
        if cfg.require_spmd_match and jaxpr_wire_bytes > 0:
            # under the gate posture, a wire-carrying target that
            # cannot be cross-checked must not silently pass
            return audit, [Finding(
                rule=RULE_SPMD_DIVERGENCE, severity=severity,
                message=(f"target carries {jaxpr_wire_bytes} B of "
                         "traced wire but has no lowering hook — its "
                         "compiled wire story is UNVERIFIED under "
                         "require_spmd_match"),
                target=target.label,
                fix_hint="give the AuditTarget a `lower` thunk (the "
                         "engine targets wire theirs automatically)")]
        return audit, []
    try:
        text = target.lower()
    except Exception as e:  # noqa: BLE001 — surface, never crash
        audit.error = f"{type(e).__name__}: {e}"
        # escalates with require_spmd_match: the gate must fail rather
        # than pass with a target's cross-check silently disabled
        return audit, [Finding(
            rule=RULE_SPMD_DIVERGENCE, severity=severity,
            message=("HLO audit could not compile the program through "
                     f"the SPMD partitioner: {audit.error[:200]} — the "
                     "compiled wire story is UNVERIFIED for this target"),
            target=target.label,
            fix_hint="see the seed-xfail ledger (docs/COVERAGE.md) for "
                     "known partitioner rejections on this backend")]

    program = HloProgram(text)
    records = walk_hlo_collectives(program, target.label)
    audit.collectives = records

    # fresh copies: absorbed_bytes accumulates per audit run
    waivers = [SpmdWaiver(w.name, int(w.byte_budget), tuple(w.opcodes))
               for w in target.spmd_waivers]
    floor = int(cfg.spmd_reshard_min_mb * 1024 * 1024)
    floor_waiver = SpmdWaiver("below_floor", 0)
    findings: List[Finding] = []
    flagged: set = set()
    for rec in records:
        weighted = rec.wire_bytes * rec.mult
        if rec.degenerate:
            continue
        if rec.traced:
            if not rec.charged:
                continue
            if rec.counted:
                audit.matched_wire_bytes += weighted
            else:
                audit.uncounted_traced_bytes += weighted
            continue
        if rec.opcode in REDUCE_OPCODES:
            if rec.charged:
                audit.reduction_bytes += weighted
            continue
        # compiler-inserted gather-family: resharding.  Named waivers
        # (largest budget first) absorb the wire the sharding contract
        # predicts; the configured floor absorbs small indexed-update
        # gathers; the remainder is a silent reshard.  Records in a
        # non-worst conditional branch (charged=False) go through the
        # SAME classification — a reshard there still flags — but
        # consume no waiver budget and add to no byte total.
        waiver = next(
            (w for w in sorted(waivers, key=lambda w: -w.byte_budget)
             if rec.opcode in w.opcodes
             and w.absorbed_bytes + weighted <= w.byte_budget), None)
        if waiver is None and weighted < floor:
            waiver = floor_waiver
        if waiver is not None:
            if rec.charged:
                waiver.absorbed_bytes += weighted
                audit.waived_reshard_bytes += weighted
            rec.waived_by = waiver.name
            continue
        if rec.charged:
            audit.reshard_bytes += weighted
        audit.n_silent_reshards += 1
        key = (rec.opcode, rec.op_name, rec.wire_bytes)
        if key in flagged:
            continue
        flagged.add(key)
        cause = (f"inserted for `{rec.op_name.rsplit('/', 1)[-1]}`"
                 if rec.op_name else
                 "inserted at a sharding boundary (no causing op — an "
                 "in/out sharding annotation disagrees with the data's "
                 "actual placement)")
        findings.append(Finding(
            rule=RULE_SILENT_RESHARD, severity=severity,
            message=(f"compiler-inserted `{rec.opcode}` moves "
                     f"{rec.wire_bytes} B x{rec.mult} "
                     f"(groups of {rec.group_size}) that the jaxpr-level "
                     f"wire accounting never saw — {cause}"),
            target=target.label,
            scope=rec.source or rec.op_name,
            fix_hint=("align the sharding annotation with the intended "
                      "layout (pjit out_shardings / NamedSharding on "
                      "the weight), or declare the wire with an "
                      "explicit collective so every analysis layer "
                      "prices it; raise analysis.spmd_reshard_min_mb "
                      "only if this gather is intended")))

    audit.waivers = [{"name": w.name, "byte_budget": int(w.byte_budget),
                      "absorbed_bytes": int(w.absorbed_bytes)}
                     for w in waivers + [floor_waiver]
                     if w.absorbed_bytes > 0]

    ratio = audit.divergence_ratio
    if (audit.jaxpr_wire_bytes > 0 or audit.matched_wire_bytes > 0) \
            and abs(ratio - 1.0) > cfg.spmd_match_tolerance:
        direction = (
            "the compiled program moves LESS traced wire than the "
            "jaxpr predicts (an OVERPREDICTION: XLA CSE'd duplicate "
            "gathers or strength-reduced an all-reduce of replicated "
            "data to a multiply)" if ratio < 1.0 else
            "the compiled program moves MORE traced wire than the "
            "jaxpr predicts (an UNDERPREDICTION — the honesty gap "
            "this audit exists to catch)")
        findings.append(Finding(
            rule=RULE_SPMD_DIVERGENCE, severity=severity,
            message=(f"jaxpr-predicted wire ({audit.jaxpr_wire_bytes} B) "
                     f"and HLO-measured wire of the SAME traced "
                     f"collectives ({audit.matched_wire_bytes} B) "
                     f"diverge by {abs(ratio - 1.0) * 100:.1f}% "
                     f"(tolerance {cfg.spmd_match_tolerance * 100:.0f}%)"
                     f" — {direction}"),
            target=target.label,
            fix_hint=("diff the collective lists (--json reports both "
                      "sides per target); re-pin analysis."
                      "spmd_match_tolerance (or waive the config in the "
                      "cross-check regression) only once the gap is "
                      "understood and named")))
    return audit, findings


def summarize_hlo(audits: List[Tuple[HloTargetAudit, int]]
                  ) -> Dict[str, Any]:
    """Report payload over every audited target.  ``audits`` pairs each
    target's reconciliation with its per-step repeat count (the modular
    grad program dispatches gas times, matching the jaxpr accounting).
    """
    total_hlo = sum(a.hlo_wire_bytes * rep for a, rep in audits)
    total_jaxpr = sum(a.jaxpr_wire_bytes * rep for a, rep in audits)
    total_matched = sum(a.matched_wire_bytes * rep for a, rep in audits)
    n_coll = sum(sum(r.mult for r in a.collectives
                     if not r.degenerate and r.charged) * rep
                 for a, rep in audits)
    # the divergence ratio compares VERIFIED targets only: an errored
    # or skipped target contributed no matched bytes, and folding its
    # jaxpr wire into the denominator would read as "XLA optimized it
    # away" when the truth is "never cross-checked" (its own finding
    # carries that)
    v_jaxpr = sum(a.jaxpr_wire_bytes * rep for a, rep in audits
                  if a.verified)
    if v_jaxpr > 0:
        ratio = total_matched / v_jaxpr
    else:
        ratio = 1.0 if total_matched == 0 else float("inf")
    return {
        "hlo_wire_bytes_per_step": int(total_hlo),
        "hlo_collective_count": int(n_coll),
        "jaxpr_wire_bytes_per_step": int(total_jaxpr),
        "matched_wire_bytes_per_step": int(total_matched),
        "hlo_only_wire_bytes_per_step": int(
            sum(a.hlo_only_bytes * rep for a, rep in audits)),
        "reshard_bytes_per_step": int(
            sum(a.reshard_bytes * rep for a, rep in audits)),
        "n_silent_reshards": int(
            sum(a.n_silent_reshards for a, _ in audits)),
        "divergence_ratio": ratio,
        "n_unverified_targets": sum(
            1 for a, _ in audits if not a.verified),
        "targets": {
            a.target: {
                "error": a.error,
                "verified": a.verified,
                "jaxpr_wire_bytes": a.jaxpr_wire_bytes,
                "hlo_wire_bytes": a.hlo_wire_bytes,
                "matched_wire_bytes": a.matched_wire_bytes,
                "uncounted_traced_bytes": a.uncounted_traced_bytes,
                "reduction_bytes": a.reduction_bytes,
                "waived_reshard_bytes": a.waived_reshard_bytes,
                "reshard_bytes": a.reshard_bytes,
                "n_silent_reshards": a.n_silent_reshards,
                "divergence_ratio": a.divergence_ratio,
                "waivers": a.waivers,
                "collectives": [asdict(r) for r in a.collectives],
            } for a, _ in audits},
    }
