"""Collective-lockstep signature: the ordered collective sequence of a
program, hashed into a per-config fingerprint.

Why: on a multihost pod every process must issue the SAME collectives in
the SAME order or the pod deadlocks — and the failure mode is a hang, not
a stack trace (PR 2's multihost resilience work had to hand-audit exactly
this).  The sequence of collective equations is a static property of the
traced program, so config drift (one host with qwZ on, another off; a
skinny-leaf gate flipping a gather dense on one host) is catchable BEFORE
dispatch by comparing signatures instead of burning a pod to find out.

Scope note: this sees EXPLICIT collectives (shard_map regions, the ZeRO-3
streamed gathers, qwZ/qgZ) — the same surface `collective_wire_bytes`
accounts.  GSPMD-inserted collectives (jit + shardings) are compiled per
identical HLO on every host and cannot drift independently of the traced
program, so hashing the traced sequence is the right invariant.
"""

import hashlib
from typing import List, Optional, Tuple

from .jaxpr_walk import iter_eqns

# collective primitives, by wire direction (superset of
# low_bandwidth.collective_wire_bytes's families: psum2 is what a psum
# inside shard_map traces to on jax 0.4.x, and ppermute/pmax/pmin matter
# for lockstep even though the wire accounting ignores them)
GATHER_PRIMS = ("all_gather",)
REDUCE_PRIMS = ("psum_scatter", "reduce_scatter", "all_to_all", "psum",
                "psum2", "ppermute", "pmax", "pmin")
COLLECTIVE_PRIMS = GATHER_PRIMS + REDUCE_PRIMS


def _axes_of(eqn) -> str:
    axes = eqn.params.get("axis_name", eqn.params.get("axes", ()))
    if isinstance(axes, (tuple, list)):
        return ",".join(str(a) for a in axes)
    return str(axes)


def collective_sequence(jaxpr) -> List[str]:
    """Ordered, canonical description of every collective equation —
    primitive, mesh axes, operand shape/dtype, and the static trip
    multiplier (a collective inside the gas=4 scan runs 4x and must stay
    in lockstep on every iteration)."""
    seq = []
    for ctx in iter_eqns(jaxpr):
        name = ctx.eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        operand = next((v for v in ctx.eqn.invars
                        if hasattr(v, "aval")), None)
        aval = getattr(operand, "aval", None)
        shape = tuple(getattr(aval, "shape", ()))
        dtype = str(getattr(aval, "dtype", "?"))
        seq.append(f"{name}[{_axes_of(ctx.eqn)}]"
                   f"{list(shape)}:{dtype}x{ctx.mult}")
    return seq


def lockstep_signature(jaxpr) -> Tuple[str, List[str]]:
    """(hex digest, sequence) for a traced program."""
    seq = collective_sequence(jaxpr)
    return signature_of_sequence(seq), seq


def signature_of_sequence(seq: List[str]) -> str:
    h = hashlib.sha256()
    for item in seq:
        h.update(item.encode())
        h.update(b"\n")
    return h.hexdigest()


def combine_signatures(sigs: List[str]) -> str:
    """Per-engine signature over several traced programs (grad + apply,
    or the fused whole-step): order-sensitive, like the dispatch order."""
    h = hashlib.sha256()
    for s in sigs:
        h.update(s.encode())
        h.update(b"\n")
    return h.hexdigest()


def first_divergence(a: List[str], b: List[str]) -> Optional[str]:
    """Human-readable description of where two collective sequences
    diverge (None when identical) — the message a hung pod never gives."""
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return f"position {i}: {x!r} vs {y!r}"
    if len(a) != len(b):
        longer, n = (a, len(b)) if len(a) > len(b) else (b, len(a))
        return (f"length {len(a)} vs {len(b)} — first extra collective: "
                f"{longer[n]!r}")
    return None
