"""CLI: lint a config + model pair on CPU without touching an accelerator.

    python -m deepspeed_tpu.analysis --config ds_config.json \
        [--model gpt2] [--hidden 64 --layers 2 --heads 4 --seq 64 \
         --vocab 256] [--json] [--dump-sequence]

Builds the model and engine on the CPU backend, traces the step
program(s) abstractly, runs every static lint rule plus the lockstep
signature, prints the findings, and exits nonzero when the config's
``analysis.mode`` is ``"error"`` and error-severity findings exist — the
CI contract.  The model defaults to a tiny GPT-2 shape: the lint is
about PROGRAM STRUCTURE (which the config decides), not model scale.
"""

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis",
        description="Static Program Auditor: lint a DeepSpeed-TPU config "
                    "+ model pair (host-syncs, donation misses, "
                    "collective lockstep, dtype hazards, comm budget).")
    p.add_argument("--config", required=True,
                   help="DeepSpeed JSON config path")
    p.add_argument("--model", default="gpt2", choices=("gpt2",),
                   help="model family to trace (default gpt2)")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON on stdout")
    p.add_argument("--dump-sequence", action="store_true",
                   help="print the ordered collective sequence (what "
                        "the lockstep signature hashes)")
    return p


def main(argv=None) -> int:
    # lint runs on CPU regardless of what accelerators are attached —
    # must be decided before jax initializes a backend
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    args = build_parser().parse_args(argv)

    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.config import AnalysisConfig
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu import constants as C
    from .auditor import audit_engine

    with open(args.config) as f:
        raw = json.load(f)
    analysis_cfg = AnalysisConfig.from_dict(raw.get(C.ANALYSIS))

    # The engine is built with analysis off so a mode:"error" config
    # still produces a full printed report here (instead of the
    # constructor raising mid-build); the CLI then applies the mode.
    engine_raw = dict(raw)
    engine_raw[C.ANALYSIS] = dict(raw.get(C.ANALYSIS) or {},
                                  **{C.ANALYSIS_MODE: "off"})

    cfg = GPT2Config(
        hidden_size=args.hidden, num_layers=args.layers,
        num_heads=args.heads, n_positions=args.seq, vocab_size=args.vocab,
        bf16=bool(engine_raw.get("bf16", {}).get("enabled", False)))
    model = GPT2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    engine, _, _, _ = ds.initialize(model=model, config=engine_raw,
                                    model_parameters=params)

    report = audit_engine(engine, cfg=analysis_cfg)

    if args.json:
        print(report.to_json())
    else:
        print(report.summary_line())
        for finding in report.findings:
            print("  " + finding.format())
        if args.dump_sequence:
            for item in report.collective_sequence:
                print("  seq: " + item)
        print(f"lockstep signature: {report.signature}")
    mode = analysis_cfg.mode
    if mode == "error" and report.has_errors:
        print("program audit: FAILED (error-severity findings, "
              "analysis.mode=error)", file=sys.stderr)
        return 1
    return 0
