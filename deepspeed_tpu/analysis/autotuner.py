"""Cost-model-driven config autotuner — search offline, validate on
chip once (ROADMAP item 5).

Chip time is the scarcest resource: every config this repo has ever
run was hand-picked, and the acceptance sweeps wedge when they try to
cover the space.  The Schedule Auditor already computes everything a
search needs to rank a config WITHOUT executing it — roofline step-time
lower bound, donation-aware peak-HBM liveness, trip-weighted wire
bytes, overlap efficiency — in seconds per candidate on CPU.  This
module closes the loop:

  enumerate   the real decision space (analysis/search_space.py)
  prune       hard constraints BEFORE tracing: batch-triple validity
              (elasticity solver reuse), a sound static HBM floor
              (param + optimizer residency under the ZeRO stage) vs the
              budget
  trace       each survivor's step program on the simulated mesh (the
              --devices machinery) and drop candidates the auditor
              rejects (error findings: liveness over budget, serialized
              hot-loop collectives under require_overlap, lockstep
              drift, ...)
  rank        by predicted_step_time_lb with per-lane attribution
              (compute / memory / hidden-comm / exposed-comm / swap) so
              the report says WHY each winner wins
  emit        the top-K as bench-ready config JSONs — each must pass
              the same `cli.main --mode error` gate CI runs before it
              is written — plus a machine-readable leaderboard
              (autotune_results.json) bench.py ingests as ladder rows
  calibrate   fit the hw_{peak_tflops,hbm_gbps,ici_gbps} constants from
              measured-vs-predicted reconciliation windows (the
              monitor's records or a bench row's embedded summary), so
              the next search ranks with THIS hardware's numbers

Mirrors the reference DeepSpeed's config-sweep culture and the
interconnect-aware partitioning search of arXiv:2501.04266, applied to
the ZeRO++-style transport knobs (arXiv:2306.10209) this repo
implements.  An empty search FAILS LOUDLY naming the binding
constraint — never an empty leaderboard with exit 0.
"""

import copy
import json
import os
import statistics
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import constants as C
from ..config import AnalysisConfig, validate_hw_constants
from .cost_model import hw_constants, per_lane_predictions
from .search_space import (AutotuneError, Candidate, Pruned, SearchSpace,
                           enumerate_candidates, nearest_divisor_worlds)

RESULTS_FILENAME = "autotune_results.json"

# the tiny trace model: the lint is about PROGRAM STRUCTURE (which the
# config decides), not model scale — same defaults as the lint CLI
DEFAULT_MODEL_KW = {"hidden": 64, "layers": 2, "heads": 4,
                    "seq": 64, "vocab": 256}

_LANE_KEYS = ("compute", "memory", "hidden_comm", "exposed_comm", "swap")


class AutotuneEmptySearch(AutotuneError):
    """Every candidate was pruned; the message names the binding
    constraint (the CLI exits nonzero with it)."""


@dataclass
class RankedCandidate:
    """A survivor with its audit evidence."""
    candidate: Candidate
    report: Any  # AuditReport

    @property
    def predicted_step_time_lb_s(self) -> float:
        return float(self.report.predicted_step_time_lb_s)


@dataclass
class SearchOutcome:
    """Everything one search run learned."""
    space: SearchSpace
    ranked: List[RankedCandidate]
    analysis_cfg: AnalysisConfig
    chips: int
    global_batch: int
    hbm_budget_mb: Optional[float]
    model_kw: Dict[str, int]
    calibration_file: Optional[str] = None
    base_config_path: Optional[str] = None
    # (name, floor_bytes) of hbm_floor prunes — empty-search diagnosis
    floor_prunes: List[Tuple[str, int]] = field(default_factory=list)
    # (name, liveness_bytes) of auditor hbm_budget prunes
    liveness_prunes: List[Tuple[str, int]] = field(default_factory=list)


# --------------------------------------------------------------------- #
# pre-trace pruning
# --------------------------------------------------------------------- #
def static_hbm_floor_bytes(knobs: Dict[str, Any], param_bytes: int,
                           opt_state_bytes: int, dp_world: int) -> int:
    """A SOUND lower bound on any step program's resident HBM for this
    candidate: parameter + optimizer-state residency under the ZeRO
    stage and offload tier, ignoring activations/grads entirely.  It can
    only prune true budget violations — the traced liveness estimate is
    the authoritative (and larger) number for survivors."""
    stage = int(knobs.get("zero_stage") or 0)
    offload = knobs.get("offload") or C.AUTOTUNING_OFFLOAD_TIER_NONE
    p = param_bytes
    if offload == C.AUTOTUNING_OFFLOAD_TIER_NVME:
        p = 0  # window buffers only
    elif stage >= 3:
        p //= max(1, dp_world)
    o = opt_state_bytes
    if offload != C.AUTOTUNING_OFFLOAD_TIER_NONE:
        o = 0  # host / NVMe resident
    elif stage >= 1:
        o //= max(1, dp_world)
    return p + o


def _optimizer_moments(opt_name: str) -> int:
    """Per-param moment count the configured optimizer MUST carry — a
    sound floor may only assume state the step cannot avoid (Adam
    family: two moments; momentum-SGD: one; plain SGD: none)."""
    opt_name = (opt_name or "").lower()
    if "adam" in opt_name:
        return 2
    if "momentum" in opt_name:
        return 1
    return 0


def _model_param_bytes(model_kw: Dict[str, int]) -> int:
    """Byte size of the tiny trace model's param tree, computed
    abstractly (eval_shape — no allocation).  Master params are fp32
    regardless of bf16 compute (GPT2Model casts at use), so this IS the
    resident size."""
    import jax
    from ..models import GPT2Config, GPT2Model
    cfg = GPT2Config(hidden_size=model_kw["hidden"],
                     num_layers=model_kw["layers"],
                     num_heads=model_kw["heads"],
                     n_positions=model_kw["seq"],
                     vocab_size=model_kw["vocab"])
    model = GPT2Model(cfg)
    shapes = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    from .auditor import _tree_bytes
    return _tree_bytes(shapes)


# --------------------------------------------------------------------- #
# per-candidate trace + audit
# --------------------------------------------------------------------- #
def _auditable_config(raw: Dict[str, Any]) -> Tuple[Dict[str, Any], bool]:
    """The config the auditor can trace.  NVMe-param candidates route to
    the ZeRO-Infinity layer-streaming engine, which the static auditor
    cannot trace — audit their RESIDENT TWIN (offload stripped) for the
    on-chip program shape, and charge the disk trips via the swap lane
    instead.  Returns (config, is_twin)."""
    zo = raw.get(C.ZERO_OPTIMIZATION) or {}
    op = zo.get(C.ZERO_OPTIMIZATION_OFFLOAD_PARAM) or {}
    if (op.get(C.OFFLOAD_PARAM_DEVICE) or "none") == "none":
        return raw, False
    twin = copy.deepcopy(raw)
    tzo = twin[C.ZERO_OPTIMIZATION]
    tzo.pop(C.ZERO_OPTIMIZATION_OFFLOAD_PARAM, None)
    tzo.pop(C.ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER, None)
    return twin, True


def audit_candidate(candidate: Candidate, model_kw: Dict[str, int],
                    analysis_cfg: AnalysisConfig):
    """Build the candidate's engine on the simulated mesh, trace its
    step program(s) abstractly, and return the full AuditReport (never
    executes a step).  NVMe candidates audit their resident twin with
    the swap lane folded in."""
    import jax
    import deepspeed_tpu as ds
    from ..models import GPT2Config, GPT2Model
    from .auditor import _tree_bytes, audit_engine

    raw = copy.deepcopy(candidate.config)
    # the engine is built with analysis off so an error-mode base config
    # cannot raise mid-build; the search applies findings itself
    raw[C.ANALYSIS] = dict(raw.get(C.ANALYSIS) or {},
                           **{C.ANALYSIS_MODE: "off"})
    traced_raw, is_twin = _auditable_config(raw)

    mcfg = GPT2Config(
        hidden_size=model_kw["hidden"], num_layers=model_kw["layers"],
        num_heads=model_kw["heads"], n_positions=model_kw["seq"],
        vocab_size=model_kw["vocab"],
        bf16=bool(raw.get(C.BF16, {}).get(C.BF16_ENABLED, False)))
    model = GPT2Model(mcfg)
    params = model.init_params(jax.random.PRNGKey(0))

    ds.reset_mesh_context()
    engine = None
    try:
        engine, _, _, _ = ds.initialize(model=model, config=traced_raw,
                                        model_parameters=params)
        swap = None
        if is_twin:
            from ..config import ZeroConfig
            from .cost_model import swap_lane
            orig_zero = ZeroConfig.from_dict(
                candidate.config.get(C.ZERO_OPTIMIZATION))
            swap = swap_lane(orig_zero, engine.config.aio_config,
                             param_bytes=_tree_bytes(engine.params),
                             opt_state_bytes=_tree_bytes(engine.opt_state))
        # 1-bit candidates are ranked on their STEADY-STATE program: the
        # post-freeze compressed phase is what the run spends its life
        # in (the warmup program is the dense twin, already enumerated)
        lb = (traced_raw.get(C.ZERO_OPTIMIZATION) or {}).get(
            C.ZERO_OPTIMIZATION_LOW_BANDWIDTH) or {}
        phase = ("compressed" if lb.get(C.LOW_BANDWIDTH_ONEBIT)
                 else None)
        return audit_engine(engine, cfg=analysis_cfg, multihost=False,
                            swap=swap, phase=phase)
    finally:
        if engine is not None and getattr(engine, "_preemption",
                                          None) is not None:
            engine._preemption.uninstall()
        ds.reset_mesh_context()


# --------------------------------------------------------------------- #
# the search
# --------------------------------------------------------------------- #
def run_search(base_raw: Dict[str, Any], tune_cfg=None, *,
               chips: Optional[int] = None,
               global_batch: Optional[int] = None,
               hbm_budget_mb: Optional[float] = None,
               model_kw: Optional[Dict[str, int]] = None,
               calibration: Optional[Any] = None,
               base_config_path: Optional[str] = None) -> SearchOutcome:
    """Run the full offline search.  CLI flags (the keyword args) win
    over the config's ``autotuning`` block; ``calibration`` is a path or
    an already-loaded hw mapping.  Raises AutotuneEmptySearch when
    pruning eliminates every candidate."""
    import jax
    from ..config import AutotuningConfig

    if tune_cfg is None:
        tune_cfg = AutotuningConfig.from_dict(base_raw.get(C.AUTOTUNING))
    chips = chips if chips is not None else tune_cfg.chips
    if chips is None:
        raise AutotuneError(
            "the chip count is required: set autotuning.chips or pass "
            "--chips")
    if jax.device_count() != chips:
        raise AutotuneError(
            f"search wants a {chips}-device mesh but jax initialized "
            f"{jax.device_count()} device(s) — the tune CLI sets "
            "xla_force_host_platform_device_count before jax import; "
            "unset any conflicting XLA_FLAGS and rerun")
    if global_batch is None:
        global_batch = tune_cfg.global_batch
    if global_batch is None:
        global_batch = base_raw.get(C.TRAIN_BATCH_SIZE)
    if global_batch is None:
        micro = int(base_raw.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU) or 1)
        gas = int(base_raw.get(C.GRADIENT_ACCUMULATION_STEPS) or 1)
        global_batch = micro * gas * chips
    global_batch = int(global_batch)
    model_kw = dict(DEFAULT_MODEL_KW, **(model_kw or {}))

    analysis_raw = dict(base_raw.get(C.ANALYSIS) or {})
    analysis_raw[C.ANALYSIS_MODE] = "off"  # search applies findings
    if hbm_budget_mb is None:
        hbm_budget_mb = tune_cfg.hbm_budget_mb
    if hbm_budget_mb is None:
        b = analysis_raw.get(C.ANALYSIS_HBM_BUDGET_MB)
        hbm_budget_mb = None if b is None else float(b)
    if hbm_budget_mb is not None:
        analysis_raw[C.ANALYSIS_HBM_BUDGET_MB] = hbm_budget_mb
    analysis_cfg = AnalysisConfig.from_dict(analysis_raw)

    calibration_file = None
    if calibration is None:
        calibration = tune_cfg.calibration_file
    if isinstance(calibration, str):
        calibration_file = calibration
        calibration = load_calibration(calibration)
    if calibration:
        analysis_cfg = analysis_cfg.hw_overridden(calibration)

    space = enumerate_candidates(base_raw, tune_cfg, chips, global_batch)
    outcome = SearchOutcome(
        space=space, ranked=[], analysis_cfg=analysis_cfg, chips=chips,
        global_batch=global_batch, hbm_budget_mb=hbm_budget_mb,
        model_kw=model_kw, calibration_file=calibration_file,
        base_config_path=base_config_path)

    # ---- pre-trace HBM-floor prune -------------------------------- #
    survivors: List[Candidate] = []
    if hbm_budget_mb is not None:
        budget_bytes = int(hbm_budget_mb * 1024 * 1024)
        param_bytes = _model_param_bytes(model_kw)
        # moment count from the CONFIGURED optimizer — a sound floor
        # may only assume state the step cannot avoid (the old
        # hardcoded Adam 2x over-pruned SGD searches)
        opt_bytes = _optimizer_moments(
            (base_raw.get(C.OPTIMIZER) or {}).get("type")) * param_bytes
        for cand in space.candidates:
            mesh = cand.knobs["mesh"]
            dp = mesh["data"] * mesh["expert"]
            floor = static_hbm_floor_bytes(cand.knobs, param_bytes,
                                           opt_bytes, dp)
            if floor > budget_bytes:
                space.pruned.append(Pruned(
                    name=cand.name, stage="hbm_floor",
                    reason=(f"static param+optimizer residency floor "
                            f"{floor} B exceeds hbm_budget_mb="
                            f"{hbm_budget_mb} ({budget_bytes} B) before "
                            "tracing")))
                outcome.floor_prunes.append((cand.name, floor))
            else:
                survivors.append(cand)
    else:
        survivors = list(space.candidates)

    # ---- trace + audit + rank ------------------------------------- #
    for cand in survivors:
        try:
            report = audit_candidate(cand, model_kw, analysis_cfg)
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # even build/trace is pruned with provenance, not fatal
            space.pruned.append(Pruned(
                name=cand.name, stage="trace",
                reason=f"{type(e).__name__}: {e}"[:300]))
            continue
        if report.has_errors:
            first = next(f for f in report.findings
                         if f.severity == "error")
            space.pruned.append(Pruned(
                name=cand.name, stage="auditor",
                reason=f"[{first.rule}] {first.message}"[:300]))
            if first.rule == "hbm_budget":
                outcome.liveness_prunes.append(
                    (cand.name, int(report.peak_hbm_bytes)))
            continue
        outcome.ranked.append(RankedCandidate(cand, report))

    if not outcome.ranked:
        raise AutotuneEmptySearch(_empty_search_message(outcome))
    outcome.ranked.sort(
        key=lambda r: (r.predicted_step_time_lb_s, r.candidate.name))
    return outcome


def _empty_search_message(outcome: SearchOutcome) -> str:
    """Name the binding constraint instead of printing an empty
    leaderboard."""
    space = outcome.space
    stages = [p.stage for p in space.pruned]
    header = (f"autotune search pruned all {space.n_enumerated} "
              "enumerated candidate(s): ")
    if stages and all(s == "batch" for s in stages):
        worlds = nearest_divisor_worlds(outcome.global_batch,
                                        outcome.chips)
        return (header + "batch-triple infeasibility — global batch "
                f"{outcome.global_batch} admits no (micro, gas) split "
                f"on any enumerated mesh of {outcome.chips} chips. "
                f"Nearest chip counts whose data world divides the "
                f"batch: {worlds}. First reason: "
                f"{space.pruned[0].reason}")
    hbm_prunes = outcome.floor_prunes + outcome.liveness_prunes
    # the HBM diagnosis may only fire when every traced prune actually
    # WAS an hbm_budget finding — an auditor prune for a different rule
    # (overlap, lockstep, ...) would survive any budget raise
    hbm_auditor_names = {name for name, _ in outcome.liveness_prunes}
    if hbm_prunes and all(
            p.stage in ("hbm_floor", "batch")
            or (p.stage == "auditor" and p.name in hbm_auditor_names)
            for p in space.pruned):
        name, smallest = min(hbm_prunes, key=lambda kv: kv[1])
        mib = smallest / (1024 * 1024)
        return (header + "HBM budget is the binding constraint — "
                f"hbm_budget_mb={outcome.hbm_budget_mb} is below the "
                f"smallest feasible estimate {mib:.1f} MiB (candidate "
                f"{name}). Raise the budget, stream params (zero stage "
                "3 + streamed variant), or add an offload tier to the "
                "search axes")
    lines = "; ".join(f"{p.name}[{p.stage}]: {p.reason}"
                      for p in space.pruned[:5])
    return header + f"first reasons: {lines}"


# --------------------------------------------------------------------- #
# emission: bench-ready configs + machine-readable leaderboard
# --------------------------------------------------------------------- #
def _leaderboard_entry(rank: int, rc: RankedCandidate,
                       config_file: Optional[str]) -> Dict[str, Any]:
    report = rc.report
    st = report.step_time
    lanes = {k: round(float(v), 9)
             for k, v in per_lane_predictions(st).items()
             if isinstance(v, (int, float))}
    entry = {
        "rank": rank,
        "name": rc.candidate.name,
        "predicted_step_time_lb_s": round(
            rc.predicted_step_time_lb_s, 9),
        "bound": st["bound"],
        "lanes": lanes,
        "wire_bytes_per_step": int(report.wire_bytes_per_step),
        "peak_hbm_bytes": int(report.peak_hbm_bytes),
        "overlap_efficiency": round(float(report.overlap_efficiency), 4),
        "findings": report.counts(),
        "knobs": rc.candidate.knobs,
        "config_file": config_file,
    }
    if st.get("swap") is not None:
        entry["swap"] = st["swap"]
    return entry


def results_payload(outcome: SearchOutcome, top_k: int,
                    entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "schema": C.AUTOTUNE_RESULTS_SCHEMA,
        "base_config": outcome.base_config_path,
        "chips": outcome.chips,
        "global_batch": outcome.global_batch,
        "hbm_budget_mb": outcome.hbm_budget_mb,
        "top_k": top_k,
        "model": dict(outcome.model_kw),
        "hw": hw_constants(outcome.analysis_cfg),
        "calibration_file": outcome.calibration_file,
        "n_enumerated": outcome.space.n_enumerated,
        "n_candidates": len(outcome.space.candidates),
        "n_survivors": len(outcome.ranked),
        "pruned": [{"name": p.name, "stage": p.stage,
                    "reason": p.reason} for p in outcome.space.pruned],
        "leaderboard": entries,
    }


def emit_results(outcome: SearchOutcome, out_dir: str,
                 top_k: int) -> Dict[str, Any]:
    """Write the top-K bench-ready configs plus autotune_results.json.

    Every emitted config must itself pass the SAME ``cli.main --mode
    error`` gate CI runs over docs/examples — a config the auditor
    rejects is never written (it is recorded as an ``emit_gate`` prune
    and the next ranked candidate is promoted)."""
    os.makedirs(out_dir, exist_ok=True)
    entries: List[Dict[str, Any]] = []
    for rc in outcome.ranked:
        if len(entries) >= top_k:
            break
        rank = len(entries) + 1
        cfg = copy.deepcopy(rc.candidate.config)
        # the emitted config self-enforces the search's HBM budget
        analysis = dict(cfg.get(C.ANALYSIS) or {})
        if outcome.hbm_budget_mb is not None:
            analysis[C.ANALYSIS_HBM_BUDGET_MB] = outcome.hbm_budget_mb
        if analysis:
            cfg[C.ANALYSIS] = analysis
        cfg["_autotune"] = {
            "rank": rank, "name": rc.candidate.name,
            "predicted_step_time_lb_s": round(
                rc.predicted_step_time_lb_s, 9),
            "chips": outcome.chips,
            "global_batch": outcome.global_batch,
            "base_config": outcome.base_config_path,
            "model": dict(outcome.model_kw),
        }
        fname = f"autotune_rank{rank}_{rc.candidate.name}.json"
        ok, gate_tail = _emit_gate(cfg, outcome, out_dir)
        if not ok:
            outcome.space.pruned.append(Pruned(
                name=rc.candidate.name, stage="emit_gate",
                reason=("emitted config failed cli.main --mode error — "
                        "never emitting a config the auditor rejects: "
                        + gate_tail)[:300]))
            continue
        with open(os.path.join(out_dir, fname), "w") as f:
            json.dump(cfg, f, indent=2)
            f.write("\n")
        entries.append(_leaderboard_entry(rank, rc, fname))
    if not entries:
        raise AutotuneEmptySearch(
            "every ranked candidate failed the emit gate "
            "(cli.main --mode error) — the search and the gate disagree; "
            "rerun with --json and inspect the pruned records")
    payload = results_payload(outcome, top_k, entries)
    validate_results(payload)
    with open(os.path.join(out_dir, RESULTS_FILENAME), "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return payload


def _emit_gate(cfg: Dict[str, Any], outcome: SearchOutcome,
               out_dir: str) -> Tuple[bool, str]:
    """Run the literal CI lint (cli.main --mode error) over the
    candidate config before it is written.  NVMe candidates gate their
    resident twin — the exact program the search ranked.  The lint's
    own stdout/stderr is captured (the tune CLI's --json contract keeps
    stdout parseable); the tail rides the prune reason on failure."""
    import contextlib
    import io

    import deepspeed_tpu as ds
    from .cli import main as cli_main
    gated, is_twin = _auditable_config(cfg)
    if is_twin:
        gated = copy.deepcopy(gated)
        gated.setdefault("_autotune", {})["emit_gate"] = "resident_twin"
    pending = os.path.join(out_dir, ".pending_emit_gate.json")
    with open(pending, "w") as f:
        json.dump(gated, f)
    buf = io.StringIO()
    try:
        argv = ["--config", pending, "--mode", "error",
                "--hidden", str(outcome.model_kw["hidden"]),
                "--layers", str(outcome.model_kw["layers"]),
                "--heads", str(outcome.model_kw["heads"]),
                "--seq", str(outcome.model_kw["seq"]),
                "--vocab", str(outcome.model_kw["vocab"])]
        if outcome.chips > 1:
            argv += ["--devices", str(outcome.chips)]
        ds.reset_mesh_context()
        try:
            with contextlib.redirect_stdout(buf), \
                    contextlib.redirect_stderr(buf):
                ok = cli_main(argv) == 0
            return ok, buf.getvalue()[-200:]
        finally:
            ds.reset_mesh_context()
    finally:
        try:
            os.remove(pending)
        except OSError:
            pass


def validate_results(payload: Dict[str, Any]) -> None:
    """Schema check for autotune_results.json — shared by the writer,
    the bench-ladder ingester, and the CI smoke test, so a malformed
    artifact fails at the boundary with a named defect."""
    def _fail(msg):
        raise AutotuneError(f"invalid autotune results: {msg}")

    if not isinstance(payload, dict):
        _fail(f"payload must be a dict, got {type(payload).__name__}")
    if payload.get("schema") != C.AUTOTUNE_RESULTS_SCHEMA:
        _fail(f"schema tag {payload.get('schema')!r} != "
              f"{C.AUTOTUNE_RESULTS_SCHEMA!r}")
    for key in ("chips", "global_batch", "model", "hw", "leaderboard",
                "pruned", "n_enumerated", "n_candidates", "n_survivors"):
        if key not in payload:
            _fail(f"missing key {key!r}")
    board = payload["leaderboard"]
    if not isinstance(board, list) or not board:
        _fail("leaderboard must be a non-empty list")
    for i, entry in enumerate(board):
        if entry.get("rank") != i + 1:
            _fail(f"leaderboard ranks must be consecutive from 1, got "
                  f"{entry.get('rank')} at index {i}")
        for key in ("name", "predicted_step_time_lb_s", "bound",
                    "lanes", "knobs", "config_file"):
            if key not in entry:
                _fail(f"leaderboard[{i}] missing {key!r}")
        if not (isinstance(entry["predicted_step_time_lb_s"],
                           (int, float))
                and entry["predicted_step_time_lb_s"] > 0):
            _fail(f"leaderboard[{i}].predicted_step_time_lb_s must be "
                  f"> 0, got {entry['predicted_step_time_lb_s']}")
        missing = [k for k in _LANE_KEYS if k not in entry["lanes"]]
        if missing:
            _fail(f"leaderboard[{i}].lanes missing {missing}")
    lbs = [e["predicted_step_time_lb_s"] for e in board]
    if lbs != sorted(lbs):
        _fail("leaderboard is not sorted by predicted_step_time_lb_s")
    for key in ("hw",):
        hw = payload[key]
        if not all(k in hw for k in C.ANALYSIS_HW_KEYS):
            _fail(f"hw block missing canonical keys "
                  f"{list(C.ANALYSIS_HW_KEYS)}")


# --------------------------------------------------------------------- #
# calibration: reconciliation windows -> fitted hardware constants
# --------------------------------------------------------------------- #
def extract_reconciliation_windows(path: str) -> List[Dict[str, Any]]:
    """Pull (measured step time, predicted lanes) pairs out of a
    records artifact: a monitor JSONL stream (kind == "reconcile"
    records), a bench JSON line/file with an embedded "reconciliation"
    summary (stale-marked rows included — the reconciliation is real
    even when the row is stale), or a bare list of window dicts."""
    objs: List[Any] = []
    with open(path) as f:
        text = f.read()
    try:
        top = json.loads(text)
        objs = top if isinstance(top, list) else [top]
    except ValueError:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                objs.append(json.loads(line))
            except ValueError:
                continue
    windows = []
    for obj in objs:
        if not isinstance(obj, dict):
            continue
        if isinstance(obj.get("reconciliation"), dict):
            obj = obj["reconciliation"]
        m = obj.get("measured_step_time_s")
        lanes = obj.get("lanes")
        if m is None or not isinstance(lanes, dict):
            continue
        windows.append({"measured_step_time_s": float(m),
                        "lanes": lanes})
    return windows


_BINDING_TO_HW = {"compute": C.ANALYSIS_HW_PEAK_TFLOPS,
                  "memory": C.ANALYSIS_HW_HBM_GBPS,
                  "hidden_comm": C.ANALYSIS_HW_ICI_GBPS}


def fit_hw_calibration(windows: List[Dict[str, Any]],
                       base_hw: Dict[str, float],
                       source: Optional[str] = None) -> Dict[str, Any]:
    """Fit the canonical hardware constants from measured windows.

    Per window: the binding roofline lane (largest of compute / memory /
    hidden_comm) absorbs the measured time net of exposed comm —
    ``scale = (measured - exposed) / t_binding`` — and its constant is
    divided by the median scale across windows (t = work / constant).
    Comm-exposed windows (exposed > binding) fit the ICI constant from
    the exposed term instead.  Swap-tier windows (a nonzero ``swap``
    lane) are SKIPPED entirely: the disk time is already priced at the
    measured aio sweep ceiling, and a summary window cannot separate it
    back out of the measured step — attributing it to a roofline lane
    would corrupt that lane's constant (an NVMe row's serialized disk
    seconds would read as "compute is 6x slower").  Constants with no
    evidence keep their base values and are marked unfitted."""
    scales: Dict[str, List[float]] = {k: [] for k in C.ANALYSIS_HW_KEYS}
    used = skipped = 0
    for w in windows:
        m = float(w.get("measured_step_time_s") or 0.0)
        lanes = w.get("lanes") or {}
        if m <= 0 or not lanes:
            skipped += 1
            continue
        if float(lanes.get("swap") or 0.0) > 0.0:
            skipped += 1
            continue
        binding = max(_BINDING_TO_HW,
                      key=lambda k: float(lanes.get(k) or 0.0))
        t_b = float(lanes.get(binding) or 0.0)
        exposed = float(lanes.get("exposed_comm") or 0.0)
        if exposed > t_b and exposed > 0:
            scale = (m - t_b) / exposed
            key = C.ANALYSIS_HW_ICI_GBPS
        elif t_b > 0:
            scale = (m - exposed) / t_b
            key = _BINDING_TO_HW[binding]
        else:
            skipped += 1
            continue
        if scale <= 0:
            skipped += 1
            continue
        scales[key].append(scale)
        used += 1
    hw = {k: float(base_hw[k]) for k in C.ANALYSIS_HW_KEYS}
    fitted = {k: False for k in C.ANALYSIS_HW_KEYS}
    for key, ss in scales.items():
        if ss:
            hw[key] = float(base_hw[key]) / statistics.median(ss)
            fitted[key] = True
    validate_hw_constants(hw, context="calibration")
    return {
        "schema": C.HW_CALIBRATION_SCHEMA,
        "hw": hw,
        "fitted": fitted,
        "base_hw": {k: float(base_hw[k]) for k in C.ANALYSIS_HW_KEYS},
        "windows_used": used,
        "windows_skipped": skipped,
        "source": source,
    }


def load_calibration(path: str) -> Dict[str, float]:
    """Load + validate a calibration file written by ``calibrate`` —
    returns the hw mapping under the canonical names."""
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or \
            payload.get("schema") != C.HW_CALIBRATION_SCHEMA:
        raise AutotuneError(
            f"{path}: not a calibration file (expected schema "
            f"{C.HW_CALIBRATION_SCHEMA!r}, got "
            f"{payload.get('schema') if isinstance(payload, dict) else type(payload).__name__!r})")
    hw = payload.get("hw") or {}
    missing = [k for k in C.ANALYSIS_HW_KEYS if k not in hw]
    if missing:
        raise AutotuneError(
            f"{path}: calibration hw block missing {missing}")
    return validate_hw_constants(hw, context="calibration")
