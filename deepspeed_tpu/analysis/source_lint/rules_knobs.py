"""knob-tri-sourcing: every config knob exists in three places.

A *knob* is a ``constants.py`` key constant (``NAME = "json_key"``)
that ships a ``NAME_DEFAULT`` sibling — the repo's convention for "this
is a user-facing config field".  Each knob must be:

1. **declared** in ``constants.py`` (that's how it enters the set),
2. **validated** — the constant is referenced by a declared validator
   module (``manifest.VALIDATOR_MODULES``: config.py parses/validates
   engine blocks, elasticity.py its own), and
3. **documented** — the JSON key string appears in ``docs/``
   or ``README.md``.

Orphans (declared but never validated: dead surface or a typo'd
rename) and doc-drift (validated but undocumented) are named per key.
Constants reserved for upstream-config parity can be waived by prefix
in ``manifest.RESERVED_KNOB_PREFIXES`` with a reason.
"""

import ast
import os
import re
from typing import Dict, List

from . import manifest
from .core import (
    RULE_KNOB_TRI_SOURCING,
    LintContext,
    SourceFinding,
    register,
)

_CONSTANTS = "deepspeed_tpu/constants.py"


def _knobs(pf) -> Dict[str, tuple]:
    """NAME -> (json_key, lineno) for every constant with a _DEFAULT
    sibling."""
    assigns: Dict[str, tuple] = {}
    names = set()
    for node in pf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            names.add(name)
            if (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                assigns[name] = (node.value.value, node.lineno)
    return {n: v for n, v in assigns.items()
            if not n.endswith("_DEFAULT") and f"{n}_DEFAULT" in names}


def _read(ctx: LintContext, rel: str) -> str:
    pf = ctx.get(rel)
    if pf is not None:
        return "\n".join(pf.lines)
    try:
        with open(os.path.join(ctx.root, rel)) as f:
            return f.read()
    except OSError:
        return ""


def _docs_corpus(ctx: LintContext) -> str:
    chunks: List[str] = []
    docs_dir = os.path.join(ctx.root, "docs")
    for dirpath, _dirnames, filenames in os.walk(docs_dir):
        for fn in sorted(filenames):
            if fn.endswith(".md"):
                try:
                    with open(os.path.join(dirpath, fn)) as f:
                        chunks.append(f.read())
                except OSError:
                    pass
    readme = os.path.join(ctx.root, "README.md")
    try:
        with open(readme) as f:
            chunks.append(f.read())
    except OSError:
        pass
    return "\n".join(chunks)


def _waived(name: str) -> str:
    for prefix, reason in manifest.RESERVED_KNOB_PREFIXES.items():
        if name.startswith(prefix):
            return reason
    return ""


@register(RULE_KNOB_TRI_SOURCING)
def check(ctx: LintContext) -> List[SourceFinding]:
    pf = ctx.get(_CONSTANTS)
    if pf is None:
        return []
    knobs = _knobs(pf)
    validators = "\n".join(_read(ctx, m)
                           for m in manifest.VALIDATOR_MODULES)
    docs = _docs_corpus(ctx)

    findings: List[SourceFinding] = []
    for name in sorted(knobs):
        key, lineno = knobs[name]
        if _waived(name):
            continue
        if not re.search(rf"\b{re.escape(name)}\b", validators):
            findings.append(SourceFinding(
                RULE_KNOB_TRI_SOURCING, "error",
                f"knob {name} (json key {key!r}) is declared in "
                "constants.py but referenced by no validator module",
                path=_CONSTANTS, line=lineno,
                fix_hint="validate it in config.py (or another "
                         "manifest.VALIDATOR_MODULES entry), delete the "
                         "dead constant, or reserve its prefix with a "
                         "reason in RESERVED_KNOB_PREFIXES"))
            continue
        if not re.search(rf"\b{re.escape(key)}\b", docs):
            findings.append(SourceFinding(
                RULE_KNOB_TRI_SOURCING, "error",
                f"knob {name}: json key {key!r} appears nowhere in "
                "docs/ or README.md",
                path=_CONSTANTS, line=lineno,
                fix_hint="document the key (docs/config_reference.md "
                         "is the catalog of last resort)"))
    return findings
