"""Declared repo invariants the source-lint rules check against.

This file is the single place where the repo says OUT LOUD which files
form the deterministic planes, which cross-thread attributes are
intentionally lock-free, which except-and-continue ladders are waived
from degradation-registry coverage (and WHY — a reason string is
mandatory for every waiver, same contract as the per-file suppression
comments), which modules count as config validators, and which classes
carry checkpointed counters.  Rules read these tables; humans review
them in diffs — adding a waiver is a visible act.
"""

# ---------------------------------------------------------------- #
# determinism rule: the deterministic planes
# ---------------------------------------------------------------- #
# The chaos plane's bitwise-fired-log contract (docs/resilience.md):
# same seed + same schedule => byte-identical fired logs, so these
# files may not read wall clocks or the process-global random state.
# Seeded ``random.Random(seed)`` instances and ``time.sleep`` (which
# delays but never *decides*) are allowed.
DETERMINISTIC_PLANES = (
    "deepspeed_tpu/runtime/resilience/chaos.py",
    "deepspeed_tpu/runtime/resilience/retry.py",
    "deepspeed_tpu/monitor/health.py",
)

# ---------------------------------------------------------------- #
# thread-discipline rule: the declared lock map
# ---------------------------------------------------------------- #
# (path, ClassName) -> {attr: reason}.  Attributes written inside a
# thread target and read outside it must appear here — the reason
# documents why the access is safe without a lock (GIL-atomic store of
# an immutable value, or ordered by a join/Event).
LOCK_MAP = {
    ("deepspeed_tpu/runtime/resilience/preemption.py",
     "PreemptionHandler"): {
        "deadline_fired": (
            "grace-deadline timer callback stores an immutable bool; "
            "the step loop only polls it (GIL-atomic, one writer)"),
        "forced_tag": (
            "set once by the timer callback before deadline_fired, "
            "read only after deadline_fired observes True"),
    },
    ("deepspeed_tpu/monitor/writers.py", "WriterThread"): {
        "_errored": (
            "one-shot failure latch stored by the writer thread; "
            "readers only poll the immutable bool (GIL-atomic)"),
    },
}

# ---------------------------------------------------------------- #
# degradation-coverage rule: waived except-and-continue ladders
# ---------------------------------------------------------------- #
# (path, enclosing-qualname) -> reason.  A broad except that swallows
# without registering in resilience/degradation.py is only legal when
# listed here; the reason must say why the registry is the wrong tool
# (per-window transient, best-effort cleanup, or the registry itself).
DEGRADATION_WAIVERS = {
    ("deepspeed_tpu/runtime/resilience/degradation.py",
     "record"): "the registry's own never-raise guard cannot recurse "
                "into itself",
    ("deepspeed_tpu/analysis/auditor.py", "engine_swap_lane"):
        "the swap lane is optional provenance; a None lane is visible "
        "in the audit report, not a silent tier change",
    ("deepspeed_tpu/analysis/autotuner.py", "run_search"):
        "an untraceable candidate is pruned WITH provenance into "
        "space.pruned and shows up in the leaderboard output",
    ("deepspeed_tpu/analysis/hlo_audit.py", "audit_target_hlo"):
        "the compile failure becomes an audit Finding that escalates "
        "under require_spmd_match — louder than the registry",
    ("deepspeed_tpu/compat.py", "_install_name_replication_rule"):
        "jax-version layout probe: newer jax needs no patch, nothing "
        "degrades",
    ("deepspeed_tpu/config.py", "PreemptionConfig.from_dict"):
        "jax import probe at config-parse time; the guarded multihost "
        "path RAISES DeepSpeedConfigError, it never falls back",
    ("deepspeed_tpu/launcher/runner.py", "_pump_lines"):
        "a garbled worker output line is per-line transient; the "
        "worker's exit code is still collected and aggregated",
    ("deepspeed_tpu/launcher/runner.py", "launch_and_collect"):
        "the --watch status render retries next interval and says so; "
        "rc aggregation is unaffected",
    ("deepspeed_tpu/moe/sharded_moe.py", "sum_routing_stats"):
        "one-shot-warned inner-scan tracer case; missing moe records "
        "are visible in the monitor stream",
    ("deepspeed_tpu/monitor/capture.py", "ProfileCapture.disarm"):
        "stop_trace cleanup is best-effort teardown; the persistent "
        "case (arm failure) registers in the handler above it",
    ("deepspeed_tpu/monitor/fleet.py", "FleetAggregator._missing_hosts"):
        "heartbeat attribution is advisory diagnosis inside an "
        "already-raising ExchangeTimeout path",
    ("deepspeed_tpu/monitor/fleet.py", "FleetAggregator._gather_window"):
        "guarded chaos-plane import probe (partial install): chaos off "
        "means no injection, not a tier change",
    ("deepspeed_tpu/monitor/fleet.py",
     "FleetAggregator._gather_under_deadline.work"):
        "the worker catches only to RETHROW on the calling thread via "
        "box['exc'] — nothing is swallowed",
    ("deepspeed_tpu/monitor/heartbeat.py", "HeartbeatWriter._chaos_fire"):
        "guarded chaos-plane import probe (partial install)",
    ("deepspeed_tpu/monitor/heartbeat.py", "read_heartbeats"):
        "a torn/unreadable beat file is per-read transient; staleness "
        "math treats it as missing and the watch table shows it",
    ("deepspeed_tpu/monitor/monitor.py", "_batched_loss_fetch"):
        "per-window device fetch; the window record visibly carries "
        "whatever was fetched",
    ("deepspeed_tpu/monitor/monitor.py", "MetricsStream.flush"):
        "per-window best-effort reads (loss/memory/fleet); the next "
        "window retries — no persistent tier change",
    ("deepspeed_tpu/monitor/monitor.py", "TrainingMonitor._fleet_window"):
        "fleet exchange failures feed the supervisor/eviction path, "
        "which owns the loud reporting",
    ("deepspeed_tpu/monitor/monitor.py", "TrainingMonitor.close"):
        "teardown is best-effort; after close there is nothing left "
        "to degrade",
    ("deepspeed_tpu/monitor/record.py", "device_memory"):
        "backend memory_stats probe, per-call; records carry nulls "
        "visibly when it fails",
    ("deepspeed_tpu/monitor/record.py", "identity"):
        "hostname/pid label probes — cosmetic record fields",
    ("deepspeed_tpu/monitor/writers.py", "TensorBoardWriter.flush"):
        "per-call flush cleanup; write failures latch _warned in the "
        "write handler, which registers",
    ("deepspeed_tpu/monitor/writers.py", "_json_default"):
        "repr() fallback for one unserializable record field",
    ("deepspeed_tpu/monitor/writers.py", "WriterThread._run"):
        "per-batch flush is best-effort; a failing WRITER registers "
        "via the _errored latch in the write loop above",
    ("deepspeed_tpu/monitor/writers.py", "WriterThread.close"):
        "teardown close after drain (or after the loud drain-timeout "
        "warning) is best-effort",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._configure_tensorboard"):
        "these handlers only probe importability down the tb ladder; "
        "the chosen tier is registered via degrade() at the ladder "
        "foot in the same method",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._monitor_boundary_reads"):
        "per-step telemetry read; next boundary retries",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._moe_local_expert_slice"):
        "optional moe expert-slice probe; absence is visible as "
        "missing moe records",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._monitor_moe_stats"):
        "per-window moe stat fetch; next window retries",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._resolve_verified_tag"):
        "an unreadable latest file falls through to the directory "
        "scan; a truly broken checkpoint raises on load",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._maybe_handle_preemption"):
        "emergency save on the signal path: failure is logged loudly "
        "and the run is already ending — the registry summary would "
        "never be read",
    ("deepspeed_tpu/runtime/engine.py",
     "DeepSpeedEngine._forced_emergency_save"):
        "forced save during teardown; loud log, process is dying",
    ("deepspeed_tpu/runtime/engine.py", "DeepSpeedEngine.load_checkpoint"):
        "engine_rng restore from an old/foreign checkpoint is skipped "
        "with a per-rank log; training state itself loaded fine",
    ("deepspeed_tpu/runtime/resilience/preemption.py",
     "PreemptionHandler._deadline_expired"):
        "forced-save failure on the timer thread is logged at error "
        "level mid-teardown; the process is being preempted",
    ("deepspeed_tpu/runtime/resilience/retry.py", "RetryPolicy.run"):
        "stamping retry_attempts on a foreign (possibly slotted) "
        "exception is diagnostic garnish; the original error re-raises",
    ("deepspeed_tpu/runtime/swap_tensor/aio_handle.py", "_chaos_fire"):
        "guarded chaos-plane import probe (partial install)",
    ("deepspeed_tpu/runtime/swap_tensor/aio_handle.py", "_degraded"):
        "this IS the registry shim: a guarded import of degradation "
        "itself cannot register its own absence",
    ("deepspeed_tpu/runtime/swap_tensor/aio_handle.py",
     "AsyncIOHandle.__del__"):
        "interpreter-teardown destructor; modules may already be gone",
    ("deepspeed_tpu/runtime/utils.py", "see_memory_usage"):
        "debug memory-print probes; output says n/a when they fail",
    ("deepspeed_tpu/runtime/zero/infinity.py",
     "ZeroInfinityEngine._monitor_boundary_reads"):
        "per-step telemetry read; next boundary retries",
    ("deepspeed_tpu/runtime/zero/infinity.py",
     "ZeroInfinityEngine.load_checkpoint"):
        "engine_rng restore from an old/foreign checkpoint is skipped "
        "with a per-rank log; training state itself loaded fine",
    ("deepspeed_tpu/runtime/zero/stage3_streaming.py", "_body_uses_pallas"):
        "static jaxpr probe; an unprobeable body is treated as "
        "pallas-free, which only affects a log line",
    ("deepspeed_tpu/runtime/zero/stage3_streaming.py",
     "_body_closes_over_tracers.has_tracer"):
        "static closure probe during trace-error diagnosis",
    ("deepspeed_tpu/runtime/zero/stage3_streaming.py",
     "Zero3StreamContext.scan"):
        "the guarded import protects the degrade() call itself "
        "(partial install) — the fallback IS being registered there",
    ("deepspeed_tpu/utils/logging.py", "_process_index"):
        "jax absent or uninitialized at log-format time; rank label "
        "defaults to 0",
    ("deepspeed_tpu/utils/timer.py",
     "SynchronizedWallClockTimer.memory_usage"):
        "debug memory probe for a log line",
}

# ---------------------------------------------------------------- #
# knob tri-sourcing rule
# ---------------------------------------------------------------- #
# modules (repo-relative) that count as the validation surface for
# constants.py keys — a knob referenced by none of them is an orphan
VALIDATOR_MODULES = (
    "deepspeed_tpu/config.py",
    "deepspeed_tpu/elasticity.py",
)

# constant-name prefixes reserved for upstream-parity surfaces that are
# intentionally accepted-but-unvalidated (config blocks we parse for
# upstream config compatibility but do not yet act on) -> reason
RESERVED_KNOB_PREFIXES = {
    "SPARSE_": (
        "sparse-attention block: upstream-DeepSpeed config parity "
        "surface; no TPU sparse-attention kernels exist yet, so the "
        "keys are declared but deliberately unvalidated (ROADMAP)"),
    "PIPELINE_": (
        "pipeline-parallel block: reserved for the ROADMAP pipeline "
        "direction; the engine does not consume these keys yet"),
}

# ---------------------------------------------------------------- #
# checkpoint-state coverage rule
# ---------------------------------------------------------------- #
# Classes whose counter/state attributes must round-trip through the
# declared save/load pair (the PR 16 onebit_phase bug class).
# Candidate attrs: public attributes initialized in __init__ to an int
# or dict literal AND mutated outside __init__/save/load; extra_attrs
# forces private attrs into the candidate set; exempt_attrs documents
# deliberate non-persistence (reason per attr).
STATE_CLASSES = (
    {
        "path": "deepspeed_tpu/runtime/resilience/sentinel.py",
        "cls": "TrainingSentinel",
        "save": "state_dict",
        "load": "load_state_dict",
        "extra_attrs": (),
        "exempt_attrs": {},
    },
    {
        "path": "deepspeed_tpu/runtime/resilience/retry.py",
        "cls": "RetryPolicy",
        "save": "snapshot",
        "load": "restore",
        "extra_attrs": (),
        "exempt_attrs": {},
    },
    {
        "path": "deepspeed_tpu/analysis/recompile.py",
        "cls": "RecompileGuard",
        "save": "counters",
        "load": "load_counters",
        "extra_attrs": (),
        "exempt_attrs": {},
    },
    {
        "path": "deepspeed_tpu/runtime/engine.py",
        "cls": "DeepSpeedEngine",
        "save": "save_checkpoint",
        "load": "load_checkpoint",
        "extra_attrs": ("_onebit_phase",),
        "exempt_attrs": {},
    },
)
