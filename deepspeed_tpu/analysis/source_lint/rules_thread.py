"""thread-discipline: lifecycle + naming + lock hygiene for host threads.

Three checks over every ``threading.Thread`` / ``threading.Timer``
creation site (the bug classes behind PR 11's grace-deadline double-save
and PR 10's pump-thread SIGPIPE):

1. Every thread must be *daemon'd and named* with a ``ds-`` prefix (so
   py-spy dumps and stack traces attribute them to this package), OR
   *provably joined* — an unconditional ``t.join()`` with no timeout in
   the creating function.  A timed join can return with the thread
   still alive, so it does not count.
2. ``Lock`` / ``RLock`` / ``Condition`` acquisition only via ``with`` —
   a bare ``.acquire()`` orphans the lock on any exception between it
   and the ``release()``.
3. Attributes written inside a thread target and read outside it are
   cross-thread shared state: each must appear in the declared lock map
   (``manifest.LOCK_MAP``) with a reason, or the rule fires.
"""

import ast
from typing import Dict, List, Optional, Set

from . import manifest
from .core import (
    RULE_THREAD_DISCIPLINE,
    LintContext,
    ParsedFile,
    SourceFinding,
    call_name,
    const_str,
    dotted,
    enclosing_class,
    enclosing_function,
    register,
)

_THREAD_CTORS = {"threading.Thread", "threading.Timer"}
_BARE_CTORS = {"Thread", "Timer"}
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}


def _threading_imports(pf: ParsedFile) -> Set[str]:
    """Names imported *from* threading in this file (so a bare
    ``Thread(...)`` is only a thread ctor if it came from threading)."""
    out: Set[str] = set()
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                out.add(alias.asname or alias.name)
    return out


def _is_thread_ctor(name: str, bare_ok: Set[str]) -> bool:
    return name in _THREAD_CTORS or (name in _BARE_CTORS
                                     and name in bare_ok)


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _assigned_name(call: ast.Call) -> Optional[str]:
    """``t = threading.Thread(...)`` -> ``"t"`` (simple Name targets
    only; attribute targets like self._thread return the dotted path)."""
    parent = getattr(call, "_ds_parent", None)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        return dotted(parent.targets[0]) or None
    return None


def _body_of_scope(call: ast.Call) -> List[ast.stmt]:
    fn = enclosing_function(call)
    return fn.body if fn is not None else []


def _post_creation_facts(var: str, body: List[ast.stmt],
                         after_line: int) -> Dict[str, object]:
    """Scan the creating scope for ``var.daemon = True``,
    ``var.name = "..."``, and ``var.join()`` (timeout-free)."""
    facts: Dict[str, object] = {"daemon": False, "name": None,
                                "joined": False}
    for node in ast.walk(ast.Module(body=body, type_ignores=[])):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and dotted(node.targets[0].value) == var):
            attr = node.targets[0].attr
            if attr == "daemon" and isinstance(node.value, ast.Constant):
                facts["daemon"] = node.value.value is True
            elif attr == "name":
                facts["name"] = const_str(node.value)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and dotted(node.func.value) == var
                and not node.args
                and not any(kw.arg == "timeout" for kw in node.keywords)):
            facts["joined"] = True
    return facts


def _target_callable(call: ast.Call, name: str) -> Optional[ast.AST]:
    """The thread's entry callable: ``target=`` kwarg for Thread, second
    positional (the function) for Timer."""
    tgt = _kwarg(call, "target")
    if tgt is None and name.endswith("Timer") and len(call.args) >= 2:
        tgt = call.args[1]
    if tgt is None and name.endswith("Timer"):
        tgt = _kwarg(call, "function")
    return tgt


def _self_attr_writes(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
    return out


def _self_attr_reads(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            out.add(node.attr)
    return out


def _check_creation(pf: ParsedFile, call: ast.Call, name: str,
                    findings: List[SourceFinding]) -> None:
    qual = pf.qualname_of(call)
    daemon_kw = _kwarg(call, "daemon")
    name_kw = _kwarg(call, "name")
    daemon = (isinstance(daemon_kw, ast.Constant)
              and daemon_kw.value is True)
    tname = const_str(name_kw) if name_kw is not None else None

    var = _assigned_name(call)
    if var is not None and "." not in var:
        facts = _post_creation_facts(var, _body_of_scope(call),
                                     call.lineno)
        daemon = daemon or bool(facts["daemon"])
        tname = tname if tname is not None else facts["name"]
        if facts["joined"]:
            return  # provably joined: lifecycle is bounded by the scope

    if daemon and tname is not None and tname.startswith("ds-"):
        return
    if not daemon:
        findings.append(SourceFinding(
            RULE_THREAD_DISCIPLINE, "error",
            f"{name} is neither daemon'd nor provably joined "
            "(an unconditional timeout-free join in the creating scope)",
            path=pf.path, line=call.lineno, scope=qual,
            fix_hint="pass daemon=True (or set t.daemon = True before "
                     "start) so a wedged thread cannot block process "
                     "exit, or join it unconditionally"))
    if tname is None or not tname.startswith("ds-"):
        have = f"name {tname!r}" if tname is not None else "no name"
        findings.append(SourceFinding(
            RULE_THREAD_DISCIPLINE, "error",
            f"{name} has {have}; host-plane threads must be named "
            "with the ds- prefix",
            path=pf.path, line=call.lineno, scope=qual,
            fix_hint="name it 'ds-<subsystem>-<role>' so py-spy/stack "
                     "dumps attribute it to this package"))


def _check_shared_attrs(pf: ParsedFile, call: ast.Call, name: str,
                        findings: List[SourceFinding]) -> None:
    tgt = _target_callable(call, name)
    if tgt is None:
        return
    cls = enclosing_class(call)
    target_fn: Optional[ast.AST] = None
    if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self" and cls is not None):
        for node in cls.body:
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == tgt.attr):
                target_fn = node
                break
    elif isinstance(tgt, ast.Name):
        fn = enclosing_function(call)
        for node in ast.walk(fn) if fn is not None else []:
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == tgt.id):
                target_fn = node
                break
    if target_fn is None or cls is None:
        return

    written = _self_attr_writes(target_fn)
    if not written:
        return
    read_outside: Set[str] = set()
    for node in cls.body:
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not target_fn):
            read_outside |= _self_attr_reads(node)
    shared = written & read_outside
    declared = manifest.LOCK_MAP.get((pf.path, cls.name), {})
    for attr in sorted(shared - set(declared)):
        findings.append(SourceFinding(
            RULE_THREAD_DISCIPLINE, "error",
            f"attribute self.{attr} is written inside thread target "
            f"{cls.name}.{target_fn.name} and read outside it, but is "
            "not in the declared lock map",
            path=pf.path, line=target_fn.lineno,
            scope=f"{cls.name}.{target_fn.name}",
            fix_hint="guard it with a lock or declare it (with the "
                     "safety argument) in source_lint/manifest.py "
                     "LOCK_MAP"))


def _lock_vars(pf: ParsedFile, bare_ok: Set[str]) -> Set[str]:
    """Dotted names assigned from a threading lock/condition ctor."""
    out: Set[str] = set()
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)):
            cn = call_name(node.value)
            if cn in _LOCK_CTORS and (cn.startswith("threading.")
                                      or cn in bare_ok):
                name = dotted(node.targets[0])
                if name:
                    out.add(name)
    return out


def _check_acquire(pf: ParsedFile, bare_ok: Set[str],
                   findings: List[SourceFinding]) -> None:
    known_locks = _lock_vars(pf, bare_ok)
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"):
            continue
        recv = dotted(node.func.value)
        leaf = recv.rsplit(".", 1)[-1].lower()
        if recv in known_locks or "lock" in leaf or "cond" in leaf:
            findings.append(SourceFinding(
                RULE_THREAD_DISCIPLINE, "error",
                f"bare {recv}.acquire() — lock acquisition only via "
                "`with`",
                path=pf.path, line=node.lineno,
                scope=pf.qualname_of(node),
                fix_hint="use `with <lock>:` so the lock releases on "
                         "every exception path"))


@register(RULE_THREAD_DISCIPLINE)
def check(ctx: LintContext) -> List[SourceFinding]:
    findings: List[SourceFinding] = []
    for pf in ctx.files:
        bare_ok = _threading_imports(pf)
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if _is_thread_ctor(cn, bare_ok):
                    _check_creation(pf, node, cn, findings)
                    _check_shared_attrs(pf, node, cn, findings)
        _check_acquire(pf, bare_ok, findings)
    return findings
