"""Walker + runner + CLI entry for the source lint.

``run_source_lint(root)`` walks every ``deepspeed_tpu/**/*.py`` under
``root`` (default: this repo), runs the registered rules, applies the
per-file suppression tables, and returns a ``SourceLintReport``.

``lint_source_main(argv)`` is the CLI behind

    python -m deepspeed_tpu.analysis lint-source [--json] [--root DIR]

exit code 1 when error-severity findings survive, 0 otherwise — the
tier1.yml gate contract, twinned in-process by
tests/unit/test_source_lint.py.
"""

import argparse
import os
from typing import List, Optional

from .core import (
    RULE_CHECKS,
    RULE_PARSE,
    RULE_SUPPRESSION,
    LintContext,
    SourceFinding,
    SourceLintReport,
    parse_file,
)

# rule modules register themselves on import (order = report order)
from . import rules_thread  # noqa: F401  (registration side effect)
from . import rules_determinism  # noqa: F401
from . import rules_degradation  # noqa: F401
from . import rules_knobs  # noqa: F401
from . import rules_checkpoint  # noqa: F401

_EXCLUDED_DIRS = {"__pycache__", "build", ".git"}
_PACKAGE_DIR = "deepspeed_tpu"


def default_root() -> str:
    # .../repo/deepspeed_tpu/analysis/source_lint/runner.py -> repo
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def build_context(root: Optional[str] = None) -> LintContext:
    root = os.path.abspath(root or default_root())
    ctx = LintContext(root=root)
    pkg = os.path.join(root, _PACKAGE_DIR)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _EXCLUDED_DIRS)
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            try:
                with open(full, encoding="utf-8") as f:
                    text = f.read()
                ctx.files.append(parse_file(rel, text))
            except (OSError, SyntaxError) as e:
                ctx.parse_errors.append((rel, str(e)))
    return ctx


def run_source_lint(root: Optional[str] = None) -> SourceLintReport:
    ctx = build_context(root)
    report = SourceLintReport(files_scanned=len(ctx.files))

    # suppression-contract violations are never themselves suppressible
    for pf in ctx.files:
        report.findings.extend(getattr(pf, "_contract_findings", []))
    for rel, msg in ctx.parse_errors:
        report.findings.append(SourceFinding(
            RULE_PARSE, "error", f"file failed to parse: {msg}",
            path=rel, fix_hint="fix the syntax error"))

    raw: List[SourceFinding] = []
    for rule_id, check in RULE_CHECKS.items():
        raw.extend(check(ctx))

    for f in raw:
        pf = ctx.get(f.path)
        sup = pf.suppressed(f.rule) if pf is not None else None
        if sup is not None and f.rule not in (RULE_SUPPRESSION,
                                              RULE_PARSE):
            sup.used = True
            report.suppressed.append((f.path, f.rule, sup.reason))
        else:
            report.findings.append(f)

    # a suppression that ate nothing is stale — warn so waivers cannot
    # quietly outlive the finding they excused
    for pf in ctx.files:
        for sup in pf.suppressions:
            if not sup.used:
                report.findings.append(SourceFinding(
                    RULE_SUPPRESSION, "warning",
                    f"stale suppression: {sup.rule!r} has no finding "
                    "left to suppress in this file",
                    path=pf.path, line=sup.line,
                    fix_hint="delete the ds-lint comment"))

    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report


def build_lint_source_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis lint-source",
        description="AST-based source lint of the host plane "
                    "(docs/source_lint.md): thread discipline, "
                    "deterministic-plane clock/random bans, degradation-"
                    "registry coverage, knob tri-sourcing, checkpoint-"
                    "state round-trips.")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: this checkout)")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON on stdout")
    return p


def lint_source_main(argv=None) -> int:
    args = build_lint_source_parser().parse_args(argv)
    report = run_source_lint(args.root)
    if args.json:
        print(report.to_json())
    else:
        for f in report.findings:
            print(f.format())
        print(report.summary_line())
    return 1 if report.has_errors else 0
