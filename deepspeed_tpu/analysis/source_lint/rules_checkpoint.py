"""checkpoint-state: counter attributes must round-trip save/load.

The PR 16 bug class: the engine grew ``_onebit_phase`` but
``save_checkpoint`` didn't persist it, so a resumed run silently
restarted the 1-bit warmup.  For every class declared in
``manifest.STATE_CLASSES`` this rule derives the candidate state set —
public attributes initialized in ``__init__`` to an int or dict
literal AND mutated outside ``__init__``/save/load (a literal-int attr
nobody mutates is config, not state) plus the manifest's
``extra_attrs`` — and requires each to be *visible* in BOTH the save
and the load method: referenced as ``self.<attr>`` or named in a
string constant (client-state keys drop a leading underscore, so
``_onebit_phase`` matches ``"onebit_phase"``).  Same-class helper
methods called from save/load are searched too (one level), so a
``state_dict`` that returns ``self.counters()`` still counts.
"""

import ast
from typing import List, Optional, Set

from . import manifest
from .core import (
    RULE_CHECKPOINT_STATE,
    LintContext,
    ParsedFile,
    SourceFinding,
    register,
)


def _find_class(pf: ParsedFile, name: str) -> Optional[ast.ClassDef]:
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _method(cls: ast.ClassDef, name: str) -> Optional[ast.AST]:
    for node in cls.body:
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == name):
            return node
    return None


def _is_state_literal(value: ast.AST) -> bool:
    if isinstance(value, ast.Constant) and isinstance(value.value, int) \
            and not isinstance(value.value, bool):
        return True
    if (isinstance(value, ast.UnaryOp) and isinstance(value.op, ast.USub)
            and isinstance(value.operand, ast.Constant)
            and isinstance(value.operand.value, int)):
        return True
    if isinstance(value, ast.Dict):
        # {} (a tally filled at runtime) or an all-int dict (a counters
        # table) is state; a populated mixed dict is a static table
        return not value.values or all(
            isinstance(v, ast.Constant) and isinstance(v.value, int)
            for v in value.values)
    return False


def _self_assign_targets(node: ast.stmt) -> List[str]:
    out: List[str] = []
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    for t in targets:
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            out.append(t.attr)
    return out


def _candidates(cls: ast.ClassDef, save: str, load: str) -> Set[str]:
    init = _method(cls, "__init__")
    if init is None:
        return set()
    literal_inits: Set[str] = set()
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and _is_state_literal(node.value):
            literal_inits.update(a for a in _self_assign_targets(node)
                                 if not a.startswith("_"))
    mutated: Set[str] = set()
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if meth.name in ("__init__", save, load):
            continue
        for node in ast.walk(meth):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                mutated.update(_self_assign_targets(node))
            # dict-state mutation: self.counts[k] = / .update( / +=
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Store)
                    and isinstance(node.value, ast.Attribute)
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id == "self"):
                mutated.add(node.value.attr)
    return literal_inits & mutated


def _visible_names(cls: ast.ClassDef, method: ast.AST) -> Set[str]:
    """Attribute names + string constants visible from a method body,
    expanding one level of same-class ``self.helper()`` calls."""
    seen_methods = {method}
    for node in ast.walk(method):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            helper = _method(cls, node.func.attr)
            if helper is not None:
                seen_methods.add(helper)
    out: Set[str] = set()
    for meth in seen_methods:
        for node in ast.walk(meth):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                out.add(node.attr)
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                out.add(node.value)
    return out


def _covered(attr: str, names: Set[str]) -> bool:
    return attr in names or attr.lstrip("_") in names


@register(RULE_CHECKPOINT_STATE)
def check(ctx: LintContext) -> List[SourceFinding]:
    findings: List[SourceFinding] = []
    for decl in manifest.STATE_CLASSES:
        pf = ctx.get(decl["path"])
        if pf is None:
            continue
        cls = _find_class(pf, decl["cls"])
        if cls is None:
            findings.append(SourceFinding(
                RULE_CHECKPOINT_STATE, "error",
                f"manifest declares class {decl['cls']} but it is not "
                f"in {decl['path']}",
                path=decl["path"],
                fix_hint="update source_lint/manifest.py STATE_CLASSES"))
            continue
        save = _method(cls, decl["save"])
        load = _method(cls, decl["load"])
        if save is None or load is None:
            missing = decl["save"] if save is None else decl["load"]
            findings.append(SourceFinding(
                RULE_CHECKPOINT_STATE, "error",
                f"{decl['cls']} has no method {missing!r} declared as "
                "its checkpoint surface",
                path=decl["path"], line=cls.lineno, scope=decl["cls"],
                fix_hint="update source_lint/manifest.py STATE_CLASSES"))
            continue
        attrs = _candidates(cls, decl["save"], decl["load"])
        attrs.update(decl.get("extra_attrs", ()))
        exempt = decl.get("exempt_attrs", {})
        save_names = _visible_names(cls, save)
        load_names = _visible_names(cls, load)
        for attr in sorted(attrs):
            if attr in exempt:
                continue
            for side, names in (("save", save_names),
                                ("load", load_names)):
                if not _covered(attr, names):
                    findings.append(SourceFinding(
                        RULE_CHECKPOINT_STATE, "error",
                        f"{decl['cls']}.{attr} looks like mutable "
                        f"counter state but does not round-trip: not "
                        f"visible in {side} method {decl[side]!r}",
                        path=decl["path"], line=cls.lineno,
                        scope=f"{decl['cls']}.{attr}",
                        fix_hint="persist it through the declared "
                                 "save/load pair, or exempt it with a "
                                 "reason in STATE_CLASSES "
                                 "exempt_attrs (the onebit_phase bug "
                                 "class, PR 16)"))
    return findings
