"""Source-level invariant auditor for the host plane (ISSUE 20).

The analysis stack audits what we trace (jaxpr lint), what we schedule
(overlap/liveness), and what XLA compiles (HLO cross-check) — this
subpackage audits the SOURCE that grew around it: writer threads,
watchdog timers, the chaos plane's determinism contract, the
degradation registry, and checkpoint client-state round-trips.  It is a
dependency-free ``ast`` walker + rule registry whose findings mirror
the Program Auditor's ``rule_id/severity/provenance`` shape
(docs/source_lint.md).

Entry points:

    python -m deepspeed_tpu.analysis lint-source [--json]

and in-process (the fast-lane twin in tests/unit/test_source_lint.py):

    from deepspeed_tpu.analysis.source_lint import run_source_lint
    report = run_source_lint()
    assert not report.has_errors
"""

from .core import (  # noqa: F401
    ALL_SOURCE_RULES,
    RULE_CHECKPOINT_STATE,
    RULE_DEGRADATION_COVERAGE,
    RULE_DETERMINISM,
    RULE_KNOB_TRI_SOURCING,
    RULE_SUPPRESSION,
    RULE_THREAD_DISCIPLINE,
    LintContext,
    ParsedFile,
    SourceFinding,
    SourceLintReport,
    Suppression,
)
from .runner import lint_source_main, run_source_lint  # noqa: F401
