"""Core of the source lint: finding schema, suppressions, file walker.

Everything here is dependency-free stdlib (``ast`` + ``re``): the lint
must run in CI before anything is installed beyond the package itself,
and in-process from the fast lane without importing jax.

Finding schema mirrors ``analysis/findings.py`` (rule / severity /
message / provenance / fix_hint) so CI, tests, and the CLI consume the
same shape at every audit altitude — jaxpr, schedule, HLO, and now
source (docs/program_auditor.md's altitude table).

Suppression contract: a finding is suppressed for one file by a comment

    # ds-lint: disable=<rule>(<reason>)

anywhere in that file.  The reason is MANDATORY — a reasonless
``disable=`` is itself an error-severity finding (``suppression``), so
the shipped tree can never accumulate unexplained waivers.  Multiple
rules: ``disable=rule-a(why),rule-b(why)``.
"""

import ast
import io
import re
import tokenize
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

SEVERITIES = ("error", "warning", "info")

# stable rule ids (tests, docs, and suppression comments key off these)
RULE_THREAD_DISCIPLINE = "thread-discipline"
RULE_DETERMINISM = "determinism"
RULE_DEGRADATION_COVERAGE = "degradation-coverage"
RULE_KNOB_TRI_SOURCING = "knob-tri-sourcing"
RULE_CHECKPOINT_STATE = "checkpoint-state"
# meta-rule: malformed / reasonless / unknown-rule suppression comments
RULE_SUPPRESSION = "suppression"
# meta-rule: a walked file failed to parse at all
RULE_PARSE = "parse"

ALL_SOURCE_RULES = (
    RULE_THREAD_DISCIPLINE,
    RULE_DETERMINISM,
    RULE_DEGRADATION_COVERAGE,
    RULE_KNOB_TRI_SOURCING,
    RULE_CHECKPOINT_STATE,
    RULE_SUPPRESSION,
    RULE_PARSE,
)


@dataclass
class SourceFinding:
    """One source-lint hit: what rule fired, how bad, and exactly where
    (file:line provenance plus the enclosing def/class qualname)."""
    rule: str                 # one of ALL_SOURCE_RULES
    severity: str             # "error" | "warning" | "info"
    message: str              # human-readable defect statement
    path: str = ""            # repo-relative file path
    line: int = 0             # 1-based line number (0 = whole file)
    scope: str = ""           # enclosing qualname ("Class.method")
    fix_hint: str = ""        # one actionable sentence

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in "
                             f"{SEVERITIES}")
        if self.rule not in ALL_SOURCE_RULES:
            raise ValueError(f"unknown source rule id {self.rule!r}")

    @property
    def provenance(self) -> str:
        where = self.path + (f":{self.line}" if self.line else "")
        return where + (f" @ {self.scope}" if self.scope else "")

    def format(self) -> str:
        hint = f"  hint: {self.fix_hint}" if self.fix_hint else ""
        return (f"[{self.severity.upper():7s}] {self.rule}: "
                f"{self.message} ({self.provenance}){hint}")


@dataclass
class Suppression:
    """One parsed ``# ds-lint: disable=rule(reason)`` entry."""
    rule: str
    reason: str
    path: str
    line: int
    used: bool = False


# everything after the disable marker is the entry list; entries are
# rule(reason) pairs separated by commas OUTSIDE parens (reasons may
# contain commas).  Only real COMMENT tokens are scanned — a docstring
# quoting the syntax is not a suppression.
_SUPPRESS_RE = re.compile(r"#\s*ds-lint:\s*disable=(.*)$")
_ENTRY_RE = re.compile(r"^\s*([A-Za-z0-9_-]+)\s*(?:\(([^()]*)\))?\s*$")


def _split_entries(raw: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in raw:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return [e for e in (x.strip() for x in out) if e]


def parse_suppressions(path: str, lines: List[str]
                       ) -> Tuple[List[Suppression], List[SourceFinding]]:
    """Parse every ds-lint disable comment in one file.  Returns the
    valid suppressions plus findings for contract violations (missing
    reason, unparseable entry, unknown rule id)."""
    sups: List[Suppression] = []
    findings: List[SourceFinding] = []
    comments: List[Tuple[int, str]] = []
    try:
        toks = tokenize.generate_tokens(
            io.StringIO("\n".join(lines) + "\n").readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string))
    except tokenize.TokenizeError:
        pass
    for lineno, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        for entry in _split_entries(m.group(1)):
            em = _ENTRY_RE.match(entry)
            if not em:
                findings.append(SourceFinding(
                    RULE_SUPPRESSION, "error",
                    f"unparseable suppression entry {entry!r}",
                    path=path, line=lineno,
                    fix_hint="write `# ds-lint: disable=<rule>(<reason>)`"))
                continue
            rule, reason = em.group(1), (em.group(2) or "").strip()
            if rule not in ALL_SOURCE_RULES:
                findings.append(SourceFinding(
                    RULE_SUPPRESSION, "warning",
                    f"suppression names unknown rule {rule!r}",
                    path=path, line=lineno,
                    fix_hint=f"known rules: {', '.join(ALL_SOURCE_RULES)}"))
                continue
            if not reason:
                findings.append(SourceFinding(
                    RULE_SUPPRESSION, "error",
                    f"suppression of {rule!r} carries no reason",
                    path=path, line=lineno,
                    fix_hint="a reason is mandatory: "
                             f"`# ds-lint: disable={rule}(<why>)`"))
                continue
            sups.append(Suppression(rule=rule, reason=reason,
                                    path=path, line=lineno))
    return sups, findings


class _QualnameVisitor(ast.NodeVisitor):
    """Annotates every node with ``_ds_qualname`` (enclosing
    Class.method path) and ``_ds_parent`` so rules can report scope
    provenance and walk upward without re-deriving it."""

    def __init__(self):
        self._stack: List[str] = []

    def visit(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            self._stack.append(node.name)
            node._ds_qualname = ".".join(self._stack)
            for child in ast.iter_child_nodes(node):
                child._ds_parent = node
                self.visit(child)
            self._stack.pop()
        else:
            node._ds_qualname = ".".join(self._stack)
            for child in ast.iter_child_nodes(node):
                child._ds_parent = node
                self.visit(child)


@dataclass
class ParsedFile:
    """One source file the walker loaded: path, text, AST (annotated
    with qualname/parent), and its suppression table."""
    path: str                       # repo-relative, forward slashes
    lines: List[str]
    tree: ast.AST
    suppressions: List[Suppression] = field(default_factory=list)

    def qualname_of(self, node: ast.AST) -> str:
        return getattr(node, "_ds_qualname", "")

    def suppressed(self, rule: str) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.rule == rule:
                return s
        return None


@dataclass
class LintContext:
    """Everything a rule sees: the parsed package files plus the repo
    root (rules that read docs/ or README reach through it)."""
    root: str
    files: List[ParsedFile] = field(default_factory=list)
    # parse failures (path, message) — reported as findings by runner
    parse_errors: List[Tuple[str, str]] = field(default_factory=list)

    def get(self, path: str) -> Optional[ParsedFile]:
        for pf in self.files:
            if pf.path == path:
                return pf
        return None


def parse_file(path: str, text: str) -> ParsedFile:
    lines = text.splitlines()
    tree = ast.parse(text, filename=path)
    _QualnameVisitor().visit(tree)
    sups, sup_findings = parse_suppressions(path, lines)
    pf = ParsedFile(path=path, lines=lines, tree=tree, suppressions=sups)
    # stash the contract-violation findings on the file so the runner
    # folds them into the report (they are never themselves
    # suppressible — that would defeat the contract)
    pf._contract_findings = sup_findings
    return pf


@dataclass
class SourceLintReport:
    """Everything one source-lint pass learned about the tree."""
    findings: List[SourceFinding] = field(default_factory=list)
    files_scanned: int = 0
    # suppressions that actually ate a finding: (path, rule, reason)
    suppressed: List[Tuple[str, str, str]] = field(default_factory=list)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    @property
    def has_errors(self) -> bool:
        return any(f.severity == "error" for f in self.findings)

    def summary_line(self) -> str:
        c = self.counts()
        return (f"source lint: {c['error']} error(s), "
                f"{c['warning']} warning(s), {c['info']} info over "
                f"{self.files_scanned} file(s); "
                f"{len(self.suppressed)} finding(s) suppressed "
                f"with reasons")

    def to_json(self, indent: Optional[int] = 2) -> str:
        import json
        return json.dumps({
            "findings": [asdict(f) for f in self.findings],
            "files_scanned": self.files_scanned,
            "suppressed": [list(s) for s in self.suppressed],
            "counts": self.counts(),
        }, indent=indent)


# ---------------------------------------------------------------- #
# rule registry
# ---------------------------------------------------------------- #

# rule_id -> check(ctx) -> List[SourceFinding]; populated by the rule
# modules at import time via @register
RULE_CHECKS: Dict[str, object] = {}


def register(rule_id: str):
    if rule_id not in ALL_SOURCE_RULES:
        raise ValueError(f"unknown source rule id {rule_id!r}")

    def deco(fn):
        RULE_CHECKS[rule_id] = fn
        return fn
    return deco


# ---------------------------------------------------------------- #
# small AST helpers shared by the rules
# ---------------------------------------------------------------- #

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``threading.Thread(...)`` ->
    ``threading.Thread``; ``Thread(...)`` -> ``Thread``."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    """The string value of a Constant, or the leading literal chunk of
    an f-string (``f"ds-pump-{host}"`` -> ``"ds-pump-"``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "_ds_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_ds_parent", None)
    return None


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    cur = getattr(node, "_ds_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "_ds_parent", None)
    return None
