"""degradation-coverage: silent fallback ladders must be registered.

The resilience layer's contract is that every *persistent* downgrade —
a writer giving up, deep-profiling disarming, static predictions
disabled — lands in ``resilience/degradation.py``'s registry so the
run's final summary (and the fleet monitor) can say what quietly got
worse.  A broad ``except`` that swallows the exception (no ``raise`` on
any path) and carries on is exactly the ladder this rule exists for:
it must call ``degradation.record(...)`` in the handler, be listed in
``manifest.DEGRADATION_WAIVERS`` with a reason (per-window transients,
best-effort cleanup), or it is a finding.

Narrow excepts (``KeyError`` on a parse, ``ImportError`` on an optional
dep probe) are out of scope: the rule keys on catches of ``Exception``
/ ``BaseException`` / bare ``except`` — the shape that eats *anything*.
"""

import ast
from typing import List

from . import manifest
from .core import (
    RULE_DEGRADATION_COVERAGE,
    LintContext,
    SourceFinding,
    dotted,
    register,
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted(e) for e in t.elts]
    else:
        names = [dotted(t)]
    return any(n in _BROAD for n in names)


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when no path out of the handler re-raises."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
    return True


def _registers(handler: ast.ExceptHandler) -> bool:
    """The handler (or code it directly contains) calls into the
    degradation registry: ``degradation.record(...)``, ``record(...)``
    imported from it, or ``<registry>.degrade(...)``."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            leaf = dotted(node.func).rsplit(".", 1)[-1]
            if leaf in ("record", "degrade"):
                return True
    return False


@register(RULE_DEGRADATION_COVERAGE)
def check(ctx: LintContext) -> List[SourceFinding]:
    findings: List[SourceFinding] = []
    for pf in ctx.files:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or not _swallows(node):
                continue
            if _registers(node):
                continue
            qual = pf.qualname_of(node) or "<module>"
            if (pf.path, qual) in manifest.DEGRADATION_WAIVERS:
                continue
            findings.append(SourceFinding(
                RULE_DEGRADATION_COVERAGE, "error",
                "broad except swallows the exception and continues "
                "without registering in the degradation registry",
                path=pf.path, line=node.lineno, scope=qual,
                fix_hint="call resilience.degradation.record(subsystem, "
                         "from_tier, to_tier, reason) in the handler, "
                         "or waive it with a reason in "
                         "source_lint/manifest.py DEGRADATION_WAIVERS"))
    return findings
