"""determinism: no wall clocks or global random state in the
deterministic planes.

The chaos plane's contract (docs/resilience.md) is bitwise: the same
seed and schedule must produce byte-identical fired logs, retry backoff
sequences, and fleet-health verdicts, or chaos reproductions and the
golden tests built on them rot.  So inside the declared planes
(``manifest.DETERMINISTIC_PLANES``) this rule bans:

- ``time.time`` / ``time.time_ns`` / ``time.monotonic`` /
  ``time.perf_counter`` and ``datetime.now/utcnow/today`` — decisions
  must come from injected clocks or step counts, not wall time
  (``time.sleep`` is allowed: it delays, it never *decides*);
- module-level ``random.*`` calls — only instantiated, seeded
  ``random.Random(seed)`` generators are deterministic; the process
  global is shared mutable state any import can perturb.
"""

import ast
from typing import List

from . import manifest
from .core import (
    RULE_DETERMINISM,
    LintContext,
    SourceFinding,
    call_name,
    register,
)

_BANNED_TIME = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}
# random.Random / random.SystemRandom construction is the SANCTIONED
# path (a seeded instance); everything else on the module is banned
_ALLOWED_RANDOM_ATTRS = {"Random", "SystemRandom"}


@register(RULE_DETERMINISM)
def check(ctx: LintContext) -> List[SourceFinding]:
    findings: List[SourceFinding] = []
    for pf in ctx.files:
        if pf.path not in manifest.DETERMINISTIC_PLANES:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn in _BANNED_TIME:
                findings.append(SourceFinding(
                    RULE_DETERMINISM, "error",
                    f"{cn}() read inside the deterministic plane",
                    path=pf.path, line=node.lineno,
                    scope=pf.qualname_of(node),
                    fix_hint="inject the clock (parameter / attribute "
                             "set by the caller) or key off step "
                             "counts — the fired-log contract is "
                             "bitwise (docs/resilience.md)"))
            elif (cn.startswith("random.")
                  and cn.split(".", 1)[1] not in _ALLOWED_RANDOM_ATTRS):
                findings.append(SourceFinding(
                    RULE_DETERMINISM, "error",
                    f"module-level {cn}() inside the deterministic "
                    "plane",
                    path=pf.path, line=node.lineno,
                    scope=pf.qualname_of(node),
                    fix_hint="use a seeded random.Random(seed) instance "
                             "owned by the plane (the process-global "
                             "generator is perturbed by any import)"))
    return findings
