"""Lint rules over traced train-step programs.

Each rule is ``rule(target, cfg) -> list[Finding]`` over an
``AuditTarget`` (a closed jaxpr plus argument metadata); the registry at
the bottom is what the auditor iterates.  Rules are *static* — they read
program structure, never execute it.  The sixth rule (recompile guard) is
a runtime counter and lives in analysis/recompile.py.
"""

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from .. import constants as C
from .findings import (Finding, RULE_COMM_BUDGET, RULE_DONATION,
                       RULE_DTYPE_HAZARD, RULE_HOST_SYNC, RULE_LOCKSTEP)
from .jaxpr_walk import aval_bytes, as_jaxpr, iter_eqns
from .signature import (GATHER_PRIMS, REDUCE_PRIMS, first_divergence,
                        lockstep_signature)


@dataclass
class ArgInfo:
    """One top-level argument of a traced program (a whole pytree
    subtree, e.g. "params"), with the facts the donation rule needs."""
    label: str
    nbytes: int
    donated: bool
    # consumed = the program produces a replacement output for it (the
    # old buffer is dead after the step) — the donation candidates
    consumed: bool


@dataclass
class AuditTarget:
    """One traced program under audit."""
    label: str                      # "grad_step" | "apply_step" | ...
    closed_jaxpr: Any
    args: List[ArgInfo] = field(default_factory=list)
    # per-flattened-invar donation flags + labels (the liveness
    # estimator's aliasing facts); None = conservative all-False
    donated_invars: Optional[List[bool]] = None
    invar_labels: Optional[List[str]] = None
    # engine state resident during this program but not among its args
    # (the modular grad program runs while opt_state sits in HBM)
    resident_extra_bytes: int = 0
    # scan-structure provenance the engine records at build time (gas
    # scan length, streamed-ZeRO-3 plan) — named in overlap findings
    scan_info: dict = field(default_factory=dict)
    # HLO-level SPMD audit hooks (analysis/hlo_audit.py).  ``lower`` is
    # a zero-arg thunk returning the OPTIMIZED post-SPMD HLO text of
    # the program as the engine actually dispatches it (compile-only,
    # never executed); None = the cross-check skips this target.
    # ``spmd_waivers`` are (name, byte_budget, opcodes) expectations
    # for compiler-inserted gather-family wire the sharding contract
    # predicts (ZeRO's param re-gather in the apply program).
    lower: Optional[Any] = None
    spmd_waivers: Tuple = ()


# --------------------------------------------------------------------- #
# rule 1: host-sync lint
# --------------------------------------------------------------------- #
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "debug_print", "callback")
# primitives that force a device->host transfer when they appear inside
# a compiled program with a host destination
_TRANSFER_PRIMS = ("device_put",)


def host_sync_rule(target: AuditTarget, cfg) -> List[Finding]:
    """Host callbacks / transfers inside the step program.  Inside a
    scan/while body they fence every iteration of the hot loop (error);
    at the top level they still sync the step's dispatch (warning)."""
    out = []
    for ctx in iter_eqns(target.closed_jaxpr):
        name = ctx.eqn.primitive.name
        if name in _CALLBACK_PRIMS:
            in_hot_loop = ctx.loop_depth > 0
            sev = "error" if in_hot_loop else "warning"
            where = ("inside a scan/while body — it fences EVERY "
                     "iteration" if in_hot_loop
                     else "in the step program — it fences the dispatch")
            out.append(Finding(
                rule=RULE_HOST_SYNC, severity=sev,
                message=f"host callback `{name}` {where}",
                target=target.label, scope=ctx.scope,
                fix_hint=("move the callback out of the compiled step "
                          "(drain device flags at logging boundaries "
                          "like the fused sentinel) or gate it off the "
                          "hot path")))
        elif name in _TRANSFER_PRIMS:
            devices = ctx.eqn.params.get("devices", ())
            # a device_put staying on-device is a sharding hint, not a
            # transfer; only flag explicit host destinations
            if any("host" in str(d).lower() for d in devices):
                out.append(Finding(
                    rule=RULE_HOST_SYNC, severity="warning",
                    message=("`device_put` to a host memory kind inside "
                             "the step program"),
                    target=target.label, scope=ctx.scope,
                    fix_hint="keep step state device-resident; offload "
                             "belongs in the host optimizer path"))
    return out


# --------------------------------------------------------------------- #
# rule 2: donation audit
# --------------------------------------------------------------------- #
def donation_rule(target: AuditTarget, cfg) -> List[Finding]:
    """Large consumed arguments that are not donated double their HBM for
    the life of the program (old buffer pinned + new output allocated) —
    the exact failure that resurrects OOMs after an innocent refactor
    drops a donate_argnums."""
    out = []
    floor = int(cfg.donation_min_mb * 1024 * 1024)
    for arg in target.args:
        if arg.consumed and not arg.donated and arg.nbytes >= floor:
            mb = arg.nbytes / (1024 * 1024)
            out.append(Finding(
                rule=RULE_DONATION, severity="error",
                message=(f"argument `{arg.label}` ({mb:.1f} MiB) is "
                         "consumed by the step but not donated — its old "
                         "buffer stays pinned alongside the new output, "
                         f"wasting ~{mb:.1f} MiB of HBM"),
                target=target.label,
                fix_hint=(f"add `{arg.label}`'s argnum to donate_argnums "
                          "(the engine donates params/opt_state on both "
                          "the modular apply and the fused step)")))
    return out


def donation_waste_bytes(targets: List[AuditTarget], cfg) -> int:
    floor = int(cfg.donation_min_mb * 1024 * 1024)
    return sum(a.nbytes for t in targets for a in t.args
               if a.consumed and not a.donated and a.nbytes >= floor)


# --------------------------------------------------------------------- #
# rule 3: collective-lockstep signature
# --------------------------------------------------------------------- #
def lockstep_expectation_finding(signature: str, n_collectives: int,
                                 cfg) -> List[Finding]:
    """Report-level check of the COMBINED engine signature (what the CLI
    prints, bench embeds, and users pin in analysis.expected_signature)
    against the configured expectation — per-target signatures feed into
    it but are not individually pinnable."""
    if not cfg.expected_signature or signature is None:
        return []
    if signature.startswith(cfg.expected_signature):
        return []
    return [Finding(
        rule=RULE_LOCKSTEP, severity="error",
        message=(f"collective lockstep signature {signature[:12]} does "
                 f"not match analysis.expected_signature "
                 f"{cfg.expected_signature[:12]} — on a multihost pod a "
                 "config diverging like this would issue a different "
                 "collective sequence and hang "
                 f"({n_collectives} collectives traced)"),
        target="combined",
        fix_hint=("diff the collective sequences (CLI --dump-sequence) "
                  "and re-pin expected_signature only if the change is "
                  "intended on EVERY host"))]


def compare_lockstep(jaxpr_a, jaxpr_b, label_a: str = "a",
                     label_b: str = "b") -> Optional[Finding]:
    """Cross-config / cross-host comparison helper: None when in
    lockstep, else an error finding naming the first divergence."""
    sig_a, seq_a = lockstep_signature(jaxpr_a)
    sig_b, seq_b = lockstep_signature(jaxpr_b)
    if sig_a == sig_b:
        return None
    return Finding(
        rule=RULE_LOCKSTEP, severity="error",
        message=(f"collective sequences diverge between {label_a} "
                 f"({sig_a[:12]}, {len(seq_a)} collectives) and "
                 f"{label_b} ({sig_b[:12]}, {len(seq_b)}): "
                 f"{first_divergence(seq_a, seq_b)}"),
        target=f"{label_a} vs {label_b}",
        fix_hint="align the configs (zero stage, low_bandwidth bits, "
                 "hpz group, mesh axes) before launching a pod")


# --------------------------------------------------------------------- #
# rule 4: dtype-hazard lint
# --------------------------------------------------------------------- #
_HALF_DTYPES = ("bfloat16", "float16")
# shape-only ops a value flows through unchanged — provenance tracking
# follows the upcast wire through these
_TRANSPARENT_PRIMS = ("reshape", "transpose", "broadcast_in_dim",
                      "squeeze", "rev", "slice", "copy")


def dtype_hazard_rule(target: AuditTarget, cfg) -> List[Finding]:
    """Unintended fp32 upcasts on half wires.

    Two hazards, both read off `convert_element_type` provenance:
      (a) a half->fp32 convert feeding a dot/conv — the matmul silently
          runs at fp32 (4x MXU cost on TPU) on data that was deliberately
          half-width;
      (b) a half->fp32 convert feeding a collective — the wire moves 4
          bytes where 2 were intended (the qwZ/qgZ savings silently
          undone).  The engine's OWN fp32 promotions (scalar loss upcast,
          grad unscale into optimizer math, f32_psum_scatter's documented
          promote-reduce-demote) either are scalar, feed elementwise
          optimizer math, or convert straight back — none trip (a)/(b).

    Weak-type promotions surface the same way: jax materializes the
    promotion as a convert_element_type on the wide operand, so an
    accidental `0.1 * bf16_tensor` in fp32 shows up here when it feeds
    compute that matters.
    """
    out = []
    min_elems = int(cfg.dtype_min_elements)
    jaxpr = target.closed_jaxpr

    def scan_jaxpr(jx, scope_prefix=""):
        # provenance: var -> originating half->f32 convert scope, traced
        # through shape-only ops.  Per-subjaxpr (vars don't cross jaxpr
        # boundaries except via invars, which is conservative enough).
        upcast_from: dict = {}
        from .jaxpr_walk import eqn_scope, sub_jaxprs
        for eqn in as_jaxpr(jx).eqns:
            name = eqn.primitive.name
            if name == "convert_element_type":
                src = eqn.invars[0]
                src_aval = getattr(src, "aval", None)
                dst_aval = getattr(eqn.outvars[0], "aval", None)
                if (src_aval is not None and dst_aval is not None
                        and str(src_aval.dtype) in _HALF_DTYPES
                        and str(dst_aval.dtype) == "float32"
                        and _n_elems(dst_aval) >= min_elems):
                    upcast_from[id(eqn.outvars[0])] = (
                        eqn_scope(eqn, scope_prefix), str(src_aval.dtype))
            elif name in _TRANSPARENT_PRIMS:
                src = next((v for v in eqn.invars
                            if id(v) in upcast_from), None)
                if src is not None:
                    upcast_from[id(eqn.outvars[0])] = upcast_from[id(src)]
            elif name in ("dot_general", "conv_general_dilated"):
                for v in eqn.invars:
                    if id(v) in upcast_from:
                        scope, half = upcast_from[id(v)]
                        out.append(Finding(
                            rule=RULE_DTYPE_HAZARD, severity="error",
                            message=(f"`{name}` consumes an operand "
                                     f"upcast from {half} to float32 — "
                                     "the matmul runs at fp32 width on a "
                                     "half wire (silent 4x MXU cost)"),
                            target=target.label,
                            scope=scope or eqn_scope(eqn, scope_prefix),
                            fix_hint=("keep the operand in its compute "
                                      "dtype (check for a stray "
                                      ".astype(float32) or a weak-typed "
                                      "fp32 scalar promoting the wire)")))
                        break
            elif name in GATHER_PRIMS + REDUCE_PRIMS:
                for v in eqn.invars:
                    if id(v) in upcast_from:
                        scope, half = upcast_from[id(v)]
                        # f32_psum_scatter's promote-reduce-demote is the
                        # documented exception: the convert feeds ONLY
                        # the reduction and converts straight back.  A
                        # psum_scatter/reduce_scatter/psum (psum2 inside
                        # shard_map on jax 0.4.x) of an upcast wire is
                        # therefore warning-grade; gathers and
                        # all_to_alls of an upcast wire are real waste.
                        sev = ("warning" if name in
                               ("reduce_scatter", "psum_scatter", "psum",
                                "psum2")
                               else "error")
                        out.append(Finding(
                            rule=RULE_DTYPE_HAZARD, severity=sev,
                            message=(f"collective `{name}` moves a wire "
                                     f"upcast from {half} to float32 — "
                                     "4 bytes/elem where 2 were "
                                     "intended"),
                            target=target.label,
                            scope=scope or eqn_scope(eqn, scope_prefix),
                            fix_hint=("collect in the half dtype, or "
                                      "route through the quantized "
                                      "low-bandwidth collectives "
                                      "(qwZ/qgZ)")))
                        break
            for sub in sub_jaxprs(eqn):
                scan_jaxpr(sub.jaxpr, eqn_scope(eqn, scope_prefix))

    scan_jaxpr(jaxpr)
    return out


def _n_elems(aval) -> int:
    import numpy as np
    return int(np.prod(aval.shape, initial=1))


# --------------------------------------------------------------------- #
# rule 5: comm-budget lint
# --------------------------------------------------------------------- #
# wire-moving families for the BUDGET accounting: collective_wire_bytes'
# families plus psum2 (what a psum traces to inside shard_map on jax
# 0.4.x).  NOT signature.REDUCE_PRIMS: ppermute/pmax/pmin matter for
# lockstep ordering but are excluded from wire volume, keeping this
# comparable with collective_wire_bytes A/B numbers.  One exception:
# a ppermute traced inside the fused-collective-matmul scope
# (constants.FCM_SCOPE, ops/collective_matmul.py) IS the qwZ/qgZ
# payload riding a per-tile ring — those count operand bytes, so a
# fused config's wire volume stays comparable with its modular twin
# instead of reading as zero.
_WIRE_GATHER_PRIMS = GATHER_PRIMS
_WIRE_REDUCE_PRIMS = ("psum_scatter", "reduce_scatter", "all_to_all",
                      "psum", "psum2")


def step_wire_bytes(jaxpr) -> Tuple[int, List[Tuple[str, int]]]:
    """Trip-count-weighted wire bytes of one program: output bytes for
    gathers, operand bytes for reductions, each multiplied by the static
    trip count of its enclosing scans (unlike `collective_wire_bytes`,
    which stays unweighted for same-structure A/B ratios).  cond
    branches contribute their MOST EXPENSIVE branch (only one executes),
    mirroring the flops counter."""
    from .jaxpr_walk import (as_jaxpr, eqn_scope, scope_has_component,
                             sub_jaxprs)
    contributors: List[Tuple[str, int]] = []

    def walk(jx, scope, mult, out):
        total = 0
        for eqn in as_jaxpr(jx).eqns:
            name = eqn.primitive.name
            if name in _WIRE_GATHER_PRIMS:
                b = sum(aval_bytes(v) for v in eqn.outvars) * mult
            elif name in _WIRE_REDUCE_PRIMS:
                b = sum(aval_bytes(v) for v in eqn.invars) * mult
            elif (name == "ppermute" and scope_has_component(
                    eqn_scope(eqn, scope), C.FCM_SCOPE)):
                # fused collective-matmul ring hop: the quantized
                # payload tile on the wire
                b = sum(aval_bytes(v) for v in eqn.invars) * mult
            elif name == "cond":
                probes = []
                for sub in sub_jaxprs(eqn):
                    branch_out: List[Tuple[str, int]] = []
                    probes.append((walk(sub.jaxpr, eqn_scope(eqn, scope),
                                        mult, branch_out), branch_out))
                if probes:
                    cost, branch_contrib = max(probes,
                                               key=lambda p: p[0])
                    total += cost
                    out.extend(branch_contrib)
                continue
            else:
                for sub in sub_jaxprs(eqn):
                    total += walk(sub.jaxpr, eqn_scope(eqn, scope),
                                  mult * (sub.trip_count or 1), out)
                continue
            total += b
            out.append((f"{name}@{eqn_scope(eqn, scope) or '<top>'}", b))
        return total

    total = walk(jaxpr, "", 1, contributors)
    contributors.sort(key=lambda kv: -kv[1])
    return total, contributors


def comm_budget_finding(total_bytes: int,
                        contributors: List[Tuple[str, int]],
                        cfg) -> List[Finding]:
    """Report-level per-OPTIMIZER-STEP wire volume vs the configured
    budget — the total is gas-weighted across every dispatched program
    (the modular grad program counts gas times, scan trip counts are
    multiplied in), matching the report's ``wire_bytes_per_step``.
    Catches dense fallbacks silently reappearing (a skinny-leaf gate
    regression turns one int8 gather back into fp32 and nothing else
    changes)."""
    if cfg.comm_budget_mb is None:
        return []
    budget = int(cfg.comm_budget_mb * 1024 * 1024)
    if total_bytes <= budget:
        return []
    top = "; ".join(f"{k}={v} B" for k, v in contributors[:3])
    return [Finding(
        rule=RULE_COMM_BUDGET, severity="error",
        message=(f"step moves {total_bytes} wire bytes, over the "
                 f"{cfg.comm_budget_mb} MiB budget "
                 f"({budget} B) — top contributors: {top}"),
        target="combined",
        fix_hint=("check for a dense-fallback regression (qwZ skinny-"
                  "leaf gate, hpZ axes) or raise analysis."
                  "comm_budget_mb if the new traffic is intended"))]


# --------------------------------------------------------------------- #
# registry of per-target rules (lockstep expectation and comm budget
# are report-level — lockstep_expectation_finding /
# comm_budget_finding; rule 6, the recompile guard, is runtime:
# recompile.py)
# --------------------------------------------------------------------- #
STATIC_RULES = (
    (RULE_HOST_SYNC, host_sync_rule),
    (RULE_DONATION, donation_rule),
    (RULE_DTYPE_HAZARD, dtype_hazard_rule),
)
