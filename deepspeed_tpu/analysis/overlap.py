"""Overlap analysis — is each collective hidden under compute, or is it
serialized on the critical path?

T3 (arXiv:2401.16677) shows compute/collective overlap is a property of
the program graph, not of the runtime: a collective whose first consumer
follows immediately has nothing to hide behind, no matter how clever the
scheduler, while a collective whose result is carried to the next scan
iteration (the double-buffered prefetch shape, ROADMAP item 1) has the
whole iteration's compute as slack.  Both facts are readable off the
traced jaxpr, so the streamed-ZeRO-3 prefetch can be *verified
statically* and gated in CI before it ever touches hardware.

For every explicit collective (the same wire-moving surface the comm
budget accounts) this module computes:

  distance         equations between issue and first consume at the
                   collective's nesting level (transparent shape-only
                   ops and payload-preserving elementwise epilogues —
                   a quantized gather's dequant — extend the wire, they
                   don't consume it)
  slack_flops      flop-weighted independent work inside that window —
                   everything between issue and first consume is
                   provably independent of the collective's result.
                   A carried collective's window is the FULL iteration
                   (its result is consumed next time around), so its
                   slack is bounded below by one body's flops
  carried          the result escapes the enclosing body (scan carry /
                   region output) instead of being consumed in-body:
                   the double-buffer property, verified
  fused            the collective is a per-tile transport of a fused
                   collective-matmul (ops/collective_matmul.py, traced
                   under the ``constants.FCM_SCOPE`` name scope): the
                   wire is interleaved tile-by-tile with the producer/
                   consumer GEMM by construction, so it is hidden as a
                   STATIC property — the carried-like classification T3
                   fusion earns, gateable via ``require_overlap``
  hidden_fraction  min(1, slack_time / wire_time) under the configured
                   hardware model — how much of the wire the scheduler
                   CAN hide, which upper-bounds what it will

A collective inside a scan/while body whose hidden fraction falls below
``analysis.overlap_min_hidden_fraction`` is serialized on the hot loop's
critical path — a warning finding (error with
``analysis.require_overlap``, the CI posture once prefetch lands).
Top-level collectives are recorded (they feed ``overlap_efficiency`` and
the step-time model) but not flagged: the dispatch boundary serializes
them anyway.
"""

from dataclasses import asdict, dataclass
from typing import Any, Dict, List

from .. import constants as C
from .findings import Finding, RULE_OVERLAP
from .jaxpr_walk import (as_jaxpr, aval_bytes, eqn_scope,
                         scope_has_component, sub_jaxprs)
from .rules import _WIRE_GATHER_PRIMS, _WIRE_REDUCE_PRIMS

_WIRE_PRIMS = _WIRE_GATHER_PRIMS + _WIRE_REDUCE_PRIMS

# ppermute is deliberately NOT a generic wire-mover (ring attention uses
# it for lockstep-relevant but overlap-managed hops; see rules.py) —
# EXCEPT inside the fused-collective-matmul scope, where the per-tile
# ring permutes ARE the qwZ/qgZ payload movers and must be priced
_FCM_TRANSPORT_PRIMS = ("ppermute",)

# shape-only ops a collective result flows through unchanged — following
# the dtype-hazard rule's provenance convention, plus the convert a
# quantized gather's dequant epilogue emits and the `name` tag
# checkpoint_name wraps the streamed gathers in
_TRANSPARENT_PRIMS = ("reshape", "transpose", "broadcast_in_dim",
                      "squeeze", "rev", "slice", "copy",
                      "convert_element_type", "name")

# payload-preserving elementwise ops: when the output keeps the tracked
# operand's shape, the wire flows THROUGH (a quantized gather's dequant
# `payload * scales`, a bias add) rather than being consumed — the
# compute the collective is actually waiting for is the contraction /
# loop boundary further on.  Shape equality is the gate: a reduction or
# contraction changes shape and still counts as the first consumer.
_ELEMENTWISE_FLOWTHROUGH = ("mul", "add", "sub", "div", "max", "min")


def _flows_through(eqn, tracked: set) -> bool:
    name = eqn.primitive.name
    if name in _TRANSPARENT_PRIMS:
        return True
    if name not in _ELEMENTWISE_FLOWTHROUGH or len(eqn.outvars) != 1:
        return False
    out_aval = getattr(eqn.outvars[0], "aval", None)
    if out_aval is None or not hasattr(out_aval, "shape"):
        return False
    for v in eqn.invars:
        if id(v) in tracked:
            aval = getattr(v, "aval", None)
            if (aval is not None and hasattr(aval, "shape")
                    and tuple(aval.shape) == tuple(out_aval.shape)):
                return True
    return False


@dataclass
class CollectiveOverlap:
    """One collective equation's schedule facts."""
    prim: str
    target: str             # traced program ("grad_step", ...)
    scope: str              # name-stack provenance
    loop_depth: int         # enclosing scan/while bodies (0 = top level)
    mult: int               # static trip-count multiplier
    wire_bytes: int         # one issue's wire (gather: out, reduce: in)
    distance_eqns: int      # eqns between issue and first consume
    slack_flops: int        # independent flops inside the window
    carried: bool           # escapes the body (double-buffered prefetch)
    wire_time_s: float
    hidden_fraction: float  # min(1, slack_time / wire_time)
    serialized: bool        # on the critical path (per configured floor)
    fused: bool = False     # per-tile fused collective-matmul transport


def _eqn_wire_bytes(eqn) -> int:
    name = eqn.primitive.name
    if name in _WIRE_GATHER_PRIMS:
        return sum(aval_bytes(v) for v in eqn.outvars)
    return sum(aval_bytes(v) for v in eqn.invars)


class _Chase:
    """One collective result being chased toward its first consumer —
    possibly across call-kind sub-jaxpr boundaries (a custom_vjp gather's
    own jaxpr ends AT the gather; consumption happens in the caller)."""

    __slots__ = ("rec", "tracked")

    def __init__(self, rec: CollectiveOverlap, tracked: set):
        self.rec = rec
        self.tracked = tracked


def _finalize(rec: CollectiveOverlap, cfg, carried: bool) -> None:
    peak_flops_s = cfg.hw_peak_tflops * 1e12
    wire_time = (rec.wire_bytes / (cfg.hw_ici_gbps * 1e9)
                 if cfg.hw_ici_gbps > 0 else 0.0)
    slack_time = (rec.slack_flops / peak_flops_s
                  if peak_flops_s > 0 else 0.0)
    rec.carried = carried
    rec.wire_time_s = wire_time
    rec.hidden_fraction = (1.0 if wire_time <= 0.0
                           else min(1.0, slack_time / wire_time))
    # a carried result is consumed next iteration, under this
    # iteration's remaining compute — the double-buffer property
    rec.serialized = ((not carried) and
                      rec.hidden_fraction < cfg.overlap_min_hidden_fraction)


def _finalize_fused(rec: CollectiveOverlap, cfg) -> None:
    """A fused transport's hiddenness is structural (per-tile under the
    GEMM), not slack-derived: full hidden fraction, never serialized.
    The wire time still feeds the cost model's hidden-comm lane."""
    rec.wire_time_s = (rec.wire_bytes / (cfg.hw_ici_gbps * 1e9)
                       if cfg.hw_ici_gbps > 0 else 0.0)
    rec.hidden_fraction = 1.0
    rec.serialized = False


def _analyze(jaxpr, cfg, target_label, _scope, _mult, _loop_depth):
    """Walk one jaxpr level.  Returns (records, escaped) where escaped
    chases reached this jaxpr's outvars unconsumed, as
    (chase, outvar_positions) pairs for the caller to continue."""
    from ..profiling.flops_profiler import eqn_flops
    jx = as_jaxpr(jaxpr)
    records: List[CollectiveOverlap] = []
    eqns = list(jx.eqns)
    active: List[_Chase] = []

    for i, eqn in enumerate(eqns):
        scope = eqn_scope(eqn, _scope)
        started_here: List[_Chase] = []
        for sub in sub_jaxprs(eqn):
            is_loop = sub.kind in ("scan", "while_body", "while_cond")
            sub_records, sub_escaped = _analyze(
                sub.jaxpr, cfg, target_label, scope,
                _mult * (sub.trip_count or 1),
                _loop_depth + (1 if is_loop else 0))
            records.extend(sub_records)
            outs = list(eqn.outvars)
            sub_outs = list(as_jaxpr(sub.jaxpr).outvars)
            body_flops = None  # one body iteration, computed lazily
            for chase, positions in sub_escaped:
                if is_loop:
                    # escaping a scan/while body = the result rides the
                    # carry into the next iteration: double-buffered.
                    # The schedule window of a carried collective is the
                    # FULL iteration — everything the wire does not feed
                    # (it feeds nothing in-body, it escaped) can hide it,
                    # regardless of where partial eval placed the issue
                    # in the body's eqn order — so the slack is bounded
                    # below by one body's flops.
                    if body_flops is None:
                        from ..profiling.flops_profiler import (
                            count_jaxpr_flops)
                        body_flops = count_jaxpr_flops(sub.jaxpr)
                    chase.rec.slack_flops = max(chase.rec.slack_flops,
                                                body_flops)
                    _finalize(chase.rec, cfg, carried=True)
                elif len(outs) == len(sub_outs):
                    # call-kind boundary (pjit/remat/custom_vjp/
                    # shard_map/branch): 1:1 outvar mapping — keep
                    # chasing in this frame from the call site on
                    chase.tracked = {id(outs[p]) for p in positions
                                     if p < len(outs)}
                    started_here.append(chase)
                else:
                    # unknown outvar mapping: classify with the slack
                    # accumulated so far
                    _finalize(chase.rec, cfg, carried=False)
        # consumption checks against everything issued BEFORE this eqn
        still_active: List[_Chase] = []
        flops = None  # computed once per eqn, shared across chases
        for chase in active:
            touches = any(id(v) in chase.tracked for v in eqn.invars)
            if touches and _flows_through(eqn, chase.tracked):
                chase.tracked.update(id(v) for v in eqn.outvars)
                still_active.append(chase)
            elif touches:
                _finalize(chase.rec, cfg, carried=False)
            else:
                # per-issue slack: eqn_flops already trip-weights its
                # own inner scans, which repeat per issue — the
                # enclosing mult does not (it repeats the ISSUE too)
                if flops is None:
                    flops = eqn_flops(eqn)
                chase.rec.distance_eqns += 1
                chase.rec.slack_flops += flops
                still_active.append(chase)
        active = still_active + started_here
        prim = eqn.primitive.name
        in_fcm = scope_has_component(scope, C.FCM_SCOPE)
        if in_fcm and (prim in _WIRE_PRIMS
                       or prim in _FCM_TRANSPORT_PRIMS):
            # fused collective-matmul transport: the tile's wire is
            # interleaved with the producer/consumer GEMM by
            # construction (the op traces it per tile), so it is hidden
            # as a static property — no chase; classified like carried
            rec = CollectiveOverlap(
                prim=prim, target=target_label,
                scope=scope, loop_depth=_loop_depth, mult=_mult,
                wire_bytes=_eqn_wire_bytes(eqn), distance_eqns=0,
                slack_flops=0, carried=False, wire_time_s=0.0,
                hidden_fraction=0.0, serialized=False, fused=True)
            _finalize_fused(rec, cfg)
            records.append(rec)
        elif prim in _WIRE_PRIMS:
            rec = CollectiveOverlap(
                prim=prim, target=target_label,
                scope=scope, loop_depth=_loop_depth, mult=_mult,
                wire_bytes=_eqn_wire_bytes(eqn), distance_eqns=0,
                slack_flops=0, carried=False, wire_time_s=0.0,
                hidden_fraction=0.0, serialized=False)
            records.append(rec)
            active.append(_Chase(rec, {id(v) for v in eqn.outvars}))

    outvar_pos = {}
    for p, v in enumerate(jx.outvars):
        outvar_pos.setdefault(id(v), []).append(p)
    escaped = []
    for chase in active:
        positions = [p for vid in chase.tracked
                     for p in outvar_pos.get(vid, [])]
        if positions:
            escaped.append((chase, positions))
        else:
            # result is dead at this level (dce leftovers): classify
            # with the slack accumulated
            _finalize(chase.rec, cfg, carried=False)
    return records, escaped


def analyze_overlap(jaxpr, cfg, target_label: str = ""
                    ) -> List[CollectiveOverlap]:
    """Walk a traced program and classify every wire-moving collective."""
    records, escaped = _analyze(jaxpr, cfg, target_label, "", 1, 0)
    for chase, _positions in escaped:
        # reached the program outputs: the dispatch boundary is the
        # consumer; everything after issue was slack
        _finalize(chase.rec, cfg, carried=False)
    return records


def overlap_efficiency(records: List[CollectiveOverlap]) -> float:
    """Bytes-weighted hidden fraction across every collective issue
    (trip counts multiplied in).  1.0 when no explicit collectives —
    there is nothing to serialize."""
    total = sum(r.wire_bytes * r.mult for r in records)
    if total <= 0:
        return 1.0
    hidden = sum(r.wire_bytes * r.mult * r.hidden_fraction
                 for r in records)
    return hidden / total


def summarize_overlap(records: List[CollectiveOverlap]) -> Dict[str, Any]:
    """Report payload: aggregate counts + the per-collective records."""
    return {
        "n_collectives": len(records),
        "n_serialized_hot_loop": sum(
            1 for r in records if r.serialized and r.loop_depth > 0),
        "n_serialized_top_level": sum(
            1 for r in records if r.serialized and r.loop_depth == 0),
        "n_carried": sum(1 for r in records if r.carried),
        "n_fused": sum(1 for r in records if r.fused),
        "records": [asdict(r) for r in records],
    }


def overlap_rule_findings(records: List[CollectiveOverlap], cfg,
                          scan_info: Dict[str, Any] = None
                          ) -> List[Finding]:
    """One finding per serialized collective inside a hot-loop body,
    plus a warning when the streamed-ZeRO-3 plan FORFEITED a requested
    prefetch (the fallback would otherwise be silent).

    With ``stage3_prefetch_mode: carried`` (the default) the streamed
    layer scan issues group i+1's gather into the scan carry under
    group i's compute — in both directions — so its hot-loop gathers
    classify as ``carried`` and this rule stays silent; the serialized
    shape survives in ``unrolled``/``off`` modes and is what
    ``require_overlap`` gates in CI."""
    out: List[Finding] = []
    severity = "error" if cfg.require_overlap else "warning"
    plan = (scan_info or {}).get("zero3_streaming")
    hot_gathers = any(r.loop_depth > 0 and r.prim in _WIRE_GATHER_PRIMS
                      for r in records)
    if plan is not None and plan.get("forfeited") and hot_gathers:
        out.append(Finding(
            rule=RULE_OVERLAP, severity="warning",
            message=("streamed ZeRO-3 prefetch was FORFEITED: "
                     f"{plan['forfeited']} — the layer gathers run "
                     "serialized at use"),
            target=next(r.target for r in records
                        if r.loop_depth > 0
                        and r.prim in _WIRE_GATHER_PRIMS),
            # the forfeit reason itself names the failed constraint (and,
            # for the unrolled even-group case, that carried mode lifts
            # it) — the hint covers the budget levers common to all modes
            fix_hint=("raise stage3_max_live_parameters / "
                      "stage3_prefetch_bucket_size until a double-buffer "
                      "budget fits — the finding names the constraint "
                      "that failed")))
    for r in records:
        if not (r.serialized and r.loop_depth > 0):
            continue
        plan_note = ""
        if plan is not None and r.prim in _WIRE_GATHER_PRIMS:
            plan_note = (f" (streamed ZeRO-3 plan: groups of "
                         f"{plan['layers_per_step']}, "
                         f"prefetch={plan['prefetch']}, "
                         f"mode={plan.get('mode', 'off')})")
        out.append(Finding(
            rule=RULE_OVERLAP, severity=severity,
            message=(f"collective `{r.prim}` ({r.wire_bytes} B x{r.mult}) "
                     "is serialized on a hot-loop critical path: first "
                     f"consumer is {r.distance_eqns} eqn(s) away with "
                     f"{r.slack_flops} independent flops — only "
                     f"{r.hidden_fraction * 100:.0f}% of its "
                     f"{r.wire_time_s * 1e6:.1f} us wire time can hide"
                     + plan_note),
            target=r.target, scope=r.scope,
            fix_hint=("issue the gather for iteration i+1 under "
                      "iteration i's compute (stage3_prefetch_mode="
                      "carried, the double-buffered carry prefetch), or "
                      "shrink the wire (qwZ/hpZ) until the slack "
                      "covers it")))
    return out
