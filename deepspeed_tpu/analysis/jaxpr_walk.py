"""Shared jaxpr traversal — ONE sub-jaxpr dispatch for every walker.

Before this module, `profiling/flops_profiler.count_jaxpr_flops` and
`runtime/comm/low_bandwidth.collective_wire_bytes` each carried their own
copy of the pjit/scan/cond/while/remat/custom_vjp recursion — and each
copy had different gaps (the flops walk missed `remat2`, the primitive
`jax.checkpoint` actually emits, and `shard_map`, so the sparse-gradients
region counted zero flops; the wire walk never saw `while` cond jaxprs).
The Program Auditor (analysis/auditor.py) adds six more jaxpr consumers,
so the dispatch lives here once:

  ``sub_jaxprs(eqn)``  — every sub-jaxpr an equation closes over, tagged
                         with its role (scan body, while cond/body, cond
                         branch, generic call) and trip count when known.
  ``iter_eqns(jaxpr)`` — flat iterator over every equation at every
                         nesting depth with an ``EqnCtx`` carrying scope
                         (name-stack provenance), loop depth, and the
                         static trip-count multiplier.

Dispatch strategy: scan/while/cond are matched by name because their
params need interpretation (trip counts, branch sets); everything else —
pjit, closed_call, remat/remat2/checkpoint, custom_{vjp,jvp}_call*,
shard_map, and any future higher-order primitive — is caught generically
by scanning ``eqn.params`` for values that ARE jaxprs.  New primitives
are walked by default instead of silently skipped.
"""

from typing import Any, Iterator, NamedTuple, Optional, Tuple

# Primitives that repeat their sub-jaxpr a statically-known number of
# times.  (while is NOT here: its trip count is data-dependent, so
# callers see trip_count=None and decide their own convention.)
_LOOP_PRIMS = ("scan",)


def as_jaxpr(jaxpr):
    """Unwrap a ClosedJaxpr (or pass a bare Jaxpr through)."""
    return getattr(jaxpr, "jaxpr", jaxpr)


class SubJaxpr(NamedTuple):
    """One sub-jaxpr of an equation.

    kind        'scan' | 'while_cond' | 'while_body' | 'branch' | 'call'
    jaxpr       the UNWRAPPED inner Jaxpr
    trip_count  static repeat count (scan length) or None
    """
    kind: str
    jaxpr: Any
    trip_count: Optional[int]


def sub_jaxprs(eqn) -> Tuple[SubJaxpr, ...]:
    """Every sub-jaxpr `eqn` closes over, in deterministic param order."""
    name = eqn.primitive.name
    if name == "scan":
        return (SubJaxpr("scan", as_jaxpr(eqn.params["jaxpr"]),
                         int(eqn.params["length"])),)
    if name == "while":
        subs = []
        cond = eqn.params.get("cond_jaxpr")
        if cond is not None:
            subs.append(SubJaxpr("while_cond", as_jaxpr(cond), None))
        body = eqn.params.get("body_jaxpr")
        if body is not None:
            subs.append(SubJaxpr("while_body", as_jaxpr(body), None))
        return tuple(subs)
    if name == "cond":
        return tuple(SubJaxpr("branch", as_jaxpr(b), None)
                     for b in eqn.params.get("branches", ()))
    # Generic: any param value that is (or contains) a jaxpr.  Catches
    # pjit/closed_call/core_call, remat/remat2/checkpoint,
    # custom_vjp_call(+_jaxpr)/custom_jvp_call (their call_jaxpr/
    # fun_jaxpr params), shard_map, and future higher-order primitives.
    import jax
    subs = []
    for key in sorted(eqn.params):
        for leaf in jax.tree.leaves(
                eqn.params[key],
                is_leaf=lambda s: hasattr(s, "jaxpr") or hasattr(s, "eqns")):
            inner = as_jaxpr(leaf)
            if hasattr(inner, "eqns"):
                subs.append(SubJaxpr("call", inner, None))
    return tuple(subs)


def eqn_scope(eqn, prefix: str = "") -> str:
    """name-scope path of an equation: the enclosing prefix (outer
    scan/pjit scopes) joined with the eqn's own traced name stack."""
    stack = str(eqn.source_info.name_stack)
    if prefix and stack:
        return f"{prefix}/{stack}"
    return prefix or stack


def scope_has_component(scope: str, name: str) -> bool:
    """True when ``name`` appears as a whole path COMPONENT of a
    name-scope path — possibly wrapped by transform tags (``jvp(name)``,
    ``transpose(jvp(name))``), which jax's name stack applies to scoped
    eqns under autodiff.  A bare substring test would let an unrelated
    user scope like ``name_block`` match; component boundaries are
    ``/`` and the transform parentheses."""
    import re
    pat = getattr(scope_has_component, "_cache", {}).get(name)
    if pat is None:
        pat = re.compile(r"(?:^|[/(])" + re.escape(name) + r"(?:$|[/)])")
        scope_has_component._cache = {
            **getattr(scope_has_component, "_cache", {}), name: pat}
    return bool(pat.search(scope))


class EqnCtx(NamedTuple):
    """One equation with its structural context inside the whole program.

    eqn         the jax core JaxprEqn
    scope       name-stack provenance path ("" at an unnamed top level)
    mult        product of enclosing static trip counts (scan lengths) —
                how many times this eqn runs per program execution
                (while bodies do not multiply: their count is dynamic)
    loop_depth  number of enclosing scan/while bodies (0 = top level);
                anything with loop_depth > 0 is in a hot-loop body
    branch      True when under a cond branch (may not execute at all)
    """
    eqn: Any
    scope: str
    mult: int
    loop_depth: int
    branch: bool


def iter_eqns(jaxpr, _scope: str = "", _mult: int = 1,
              _loop_depth: int = 0, _branch: bool = False
              ) -> Iterator[EqnCtx]:
    """Depth-first iterator over EVERY equation at every nesting level.

    Visits all cond branches and both while jaxprs (lints must see code
    that MIGHT run); consumers that want max-branch or body-only
    semantics (the flops counter) recurse themselves via sub_jaxprs.
    """
    for eqn in as_jaxpr(jaxpr).eqns:
        yield EqnCtx(eqn, eqn_scope(eqn, _scope), _mult, _loop_depth,
                     _branch)
        for sub in sub_jaxprs(eqn):
            scope = eqn_scope(eqn, _scope)
            in_loop = _loop_depth + (
                1 if sub.kind in ("scan", "while_body", "while_cond") else 0)
            mult = _mult * (sub.trip_count or 1)
            yield from iter_eqns(sub.jaxpr, scope, mult, in_loop,
                                 _branch or sub.kind == "branch")


def aval_bytes(v) -> int:
    """HBM bytes of a jaxpr var/atom's aval (0 for abstract tokens)."""
    import numpy as np
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    try:
        itemsize = np.dtype(aval.dtype).itemsize
    except TypeError:
        return 0
    return int(np.prod(aval.shape, initial=1)) * itemsize
