"""Recompile guard — rule 6, the one runtime pass of the Program Auditor.

A jitted step function retraces whenever an argument's aval (shape/
dtype/weak-type) or a static argument changes.  Occasional retraces are
normal (first call, a final short batch); a retrace STORM — shape-
polymorphic inputs, a Python scalar flapping between int and float, a
fresh tuple of static args per step — silently turns every step into a
multi-second XLA compile.  The engine observes the batch signature of
every dispatch; once the number of DISTINCT signatures exceeds
``analysis.max_retraces`` the guard reports which avals changed instead
of letting the job quietly crawl.
"""

from typing import Any, Optional, Tuple

from .findings import Finding, RULE_RECOMPILE


def batch_signature(tree: Any) -> Tuple:
    """Hashable aval signature of a batch pytree: per-leaf (shape, dtype)
    plus the treedef (a changed pytree STRUCTURE also retraces)."""
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    return (str(treedef),) + tuple(
        (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x))))
        for x in leaves)


def _diff(a: Tuple, b: Tuple) -> str:
    if a[0] != b[0]:
        return "pytree structure changed"
    for i, (la, lb) in enumerate(zip(a[1:], b[1:])):
        if la != lb:
            return (f"leaf {i}: shape/dtype {la[0]}:{la[1]} -> "
                    f"{lb[0]}:{lb[1]}")
    return "argument count changed"


class RecompileGuard:
    """Counts distinct step-function trace signatures at runtime.

    Membership is a set (O(1) per dispatch — the guard must stay cheap
    in exactly the every-step-a-new-shape storm it exists to catch) and
    the stored set is bounded: past the bound every unseen signature
    counts as a retrace without being stored.  A repeated old shape may
    then be over-counted, but a run that far past its budget is already
    storming and the tally only needs to stay monotonic."""

    def __init__(self, max_retraces: int):
        self.max_retraces = int(max_retraces)
        self._sigs: set = set()
        self._last_sig: Optional[Tuple] = None
        self._restored = 0  # retraces carried in from a checkpoint
        self.retraces_seen = 0  # distinct signatures beyond the first
        self.planned_retraces = 0  # announced phase switches (onebit)
        self._store_cap = 4 * self.max_retraces + 64

    def note_planned(self) -> None:
        """Record a PLANNED one-time retrace (the onebit warmup→compressed
        phase switch, docs/onebit.md): the program identity changes while
        the batch signature does not, so the guard both counts the retrace
        (benchmarks read exactly one) and grows the budget by one (a
        planned switch must never trip the storm detector)."""
        self.retraces_seen += 1
        self._restored += 1
        self.max_retraces += 1
        self.planned_retraces += 1

    def observe(self, tree: Any) -> Optional[Finding]:
        """Record one dispatch; returns a Finding when this dispatch
        crossed (or is beyond) the retrace budget, else None."""
        sig = batch_signature(tree)
        if sig in self._sigs:
            return None
        prev = self._last_sig
        if len(self._sigs) < self._store_cap:
            self._sigs.add(sig)
            distinct = len(self._sigs)
        else:
            distinct = self.retraces_seen - self._restored + 2
        self._last_sig = sig
        self.retraces_seen = self._restored + distinct - 1
        if self.retraces_seen <= self.max_retraces:
            return None
        changed = (_diff(prev, sig) if prev is not None
                   else "first traced shape after a checkpoint restore")
        return Finding(
            rule=RULE_RECOMPILE, severity="error",
            message=(f"step function retraced {self.retraces_seen} times "
                     f"(budget {self.max_retraces}) — latest change: "
                     f"{changed}"),
            target="train_step",
            fix_hint=("pad batches to a fixed shape (or a small bucket "
                      "set) and keep dtypes stable; raise "
                      "analysis.max_retraces only if the shape set is "
                      "genuinely that large"))

    # ---- checkpoint round-trip (mirrors the sentinel counters) ------- #
    def counters(self) -> dict:
        return {"retraces_seen": self.retraces_seen,
                "max_retraces": self.max_retraces,
                "planned_retraces": self.planned_retraces}

    def load_counters(self, d: Optional[dict]) -> None:
        """Restore the persisted retrace count.  Signatures themselves
        are not persisted (a resume retraces once by construction, which
        is why the count — not the set — is what rides the checkpoint:
        the budget keeps meaning 'distinct shapes this training run')."""
        if not d:
            return
        self._restored = max(self._restored,
                             int(d.get("retraces_seen", 0)))
        self.retraces_seen = max(self.retraces_seen, self._restored)
        # planned retraces carried in from the checkpoint re-credit the
        # budget exactly once (a resumed run must not trip the storm
        # detector for a switch the previous run already announced)
        planned = int(d.get("planned_retraces", 0))
        new_planned = max(self.planned_retraces, planned)
        self.max_retraces += new_planned - self.planned_retraces
        self.planned_retraces = new_planned
