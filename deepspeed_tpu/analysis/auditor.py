"""Program Auditor — trace the engine's train step to closed jaxprs
(without executing them) and run the static lint registry.

The engine's failure modes stopped being Python bugs when the whole
optimizer step became one XLA program (PR 3) and params started streaming
through quantized collectives (PR 1): a stray host callback fencing the
gas scan, a dropped donate_argnums doubling HBM, a collective sequence
that diverges across hosts and hangs the pod, a silent fp32 upcast on a
bf16 wire.  All of those are *program-shape* properties readable off the
jaxpr — so they are linted here, statically, at engine init / in CI,
instead of being discovered on a burning pod.

Entry points:
  ``audit_engine(engine)``            — full report for a built engine
  ``ProgramAuditor(cfg).run(targets)``— rule registry over explicit
                                        targets (tests, CLI fixtures)
"""

from typing import Any, List, Optional, Tuple

import numpy as np

from .cost_model import build_step_time_model, program_io_bytes
from .findings import AuditReport, Finding, ProgramAuditError
from .hlo_audit import SpmdWaiver, audit_target_hlo, summarize_hlo
from .liveness import estimate_liveness, hbm_budget_finding
from .overlap import (analyze_overlap, overlap_efficiency,
                      overlap_rule_findings, summarize_overlap)
from .rules import (ArgInfo, AuditTarget, STATIC_RULES,
                    comm_budget_finding, donation_waste_bytes,
                    lockstep_expectation_finding, step_wire_bytes)
from .signature import combine_signatures, lockstep_signature


def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape, initial=1)) * np.dtype(dtype).itemsize
        except TypeError:
            # extended dtypes (PRNG keys): count the key payload
            total += int(np.prod(shape, initial=1)) * 4
    return total


def _leaf_count(tree) -> int:
    import jax
    return len(jax.tree.leaves(tree))


def _expand_invars(arg_trees, donated_labels):
    """Flattened per-invar (donated, label) lists for a traced call:
    make_jaxpr flattens the arguments in order, so each argument
    subtree's flags expand across its leaf count."""
    donated, labels = [], []
    for tree, (is_donated, label) in zip(arg_trees, donated_labels):
        n = _leaf_count(tree)
        donated.extend([is_donated] * n)
        labels.extend([f"{label}[{k}]" for k in range(n)])
    return donated, labels


def _engine_scan_info(engine) -> dict:
    """Scan-structure provenance recorded at build time: the fused gas
    scan (runtime/fused_step.py) and the streamed-ZeRO-3 layer plan
    (runtime/zero/stage3_streaming.py, populated during tracing)."""
    info = dict(getattr(engine, "_fused_scan_info", None) or {})
    stream = getattr(engine, "_zero3_stream", None)
    plan = getattr(stream, "last_plan", None)
    if plan is not None:
        info["zero3_streaming"] = {
            "layers_per_step": plan.layers_per_step,
            "prefetch": plan.prefetch,
            "mode": plan.mode,
            "forfeited": plan.forfeited,
            "num_layers": plan.num_layers,
            "params_per_layer": plan.params_per_layer,
        }
    return info


def _grads_template(engine):
    """ShapeDtypeStructs of the accumulated-grad tree (the apply
    program's 4th argument) without running a grad step."""
    import jax
    import jax.numpy as jnp
    grads_half = (engine.config.bf16.enabled
                  and engine.config.bf16.grads_in_compute_dtype)

    def one(p):
        dtype = p.dtype
        if grads_half and jnp.issubdtype(p.dtype, jnp.floating):
            dtype = engine.compute_dtype
        return jax.ShapeDtypeStruct(p.shape, dtype)

    return jax.tree.map(one, engine.params)


def synthesize_sample_batch(engine) -> Optional[Tuple]:
    """A ShapeDtypeStruct batch for tracing the grad program, derived
    from the model's declared shapes (GPT2/BERT-style configs expose
    n_positions + vocab_size).  None when the model's input contract is
    unknown — the auditor then audits the apply program only."""
    import jax
    mcfg = getattr(engine.module, "config", None)
    seq = getattr(mcfg, "n_positions", None)
    if seq is None:
        seq = getattr(mcfg, "max_position_embeddings", None)
    if seq is None or getattr(mcfg, "vocab_size", None) is None:
        return None
    # the dispatched batch is GLOBAL (micro x dp_world): _shard_batch
    # places a full cross-host array, and program structure depends on it
    # (the ZeRO-3 streamed scan only engages when the batch divides the
    # ZeRO world — a micro-batch-sized probe would audit the fallback
    # program instead of the one training dispatches)
    batch = engine.train_micro_batch_size_per_gpu() * engine.world_size
    return (jax.ShapeDtypeStruct((batch, int(seq)), np.int32),)


def _sharded_batch_structs(engine, sample_batch, stacked: bool):
    """ShapeDtypeStructs carrying the shardings ``_shard_batch`` /
    ``_shard_stacked_batch`` would place — the HLO audit must compile
    the program TRAINING dispatches, and in/out shardings are part of
    what the SPMD partitioner sees (an unsharded probe batch would
    audit a different partitioning)."""
    import jax
    dp = engine.world_size
    batch_dim = 1 if stacked else 0
    data = (engine.mesh_ctx.sharding(
        *([None] * batch_dim),
        ("data", "expert")) if dp > 1 else engine.mesh_ctx.replicated())
    rep = engine.mesh_ctx.replicated()

    def place(s):
        fits = (len(s.shape) > batch_dim
                and s.shape[batch_dim] % dp == 0)
        return jax.ShapeDtypeStruct(s.shape, s.dtype,
                                    sharding=data if fits else rep)
    return tuple(place(s) for s in sample_batch)


def _engine_spmd_waivers(engine, kind: str) -> Tuple[SpmdWaiver, ...]:
    """Compiler-inserted gather wire the engine's sharding contract
    PREDICTS, so the HLO cross-check can tell it from a silent reshard:
    ZeRO stage >= 1 re-gathers the updated params at the optimizer
    boundary (the apply program's GSPMD all-gathers ARE the DeepSpeed
    wire model), and stage-3 leaves outside the explicit streamed path
    are gathered at use in forward and backward."""
    stage = engine.config.zero_config.stage
    pbytes = _tree_bytes(engine.params)
    slack = pbytes // 4 + (1 << 20)
    waivers = []
    if kind in ("apply", "fused") and stage >= 1:
        waivers.append(SpmdWaiver("zero_param_regather", pbytes + slack,
                                  ("all-gather",)))
    if kind in ("grad", "fused") and stage >= 3:
        waivers.append(SpmdWaiver("zero3_param_gather_at_use",
                                  2 * pbytes + slack, ("all-gather",)))
    return tuple(waivers)


def _onebit_wire_template(engine):
    """ShapeDtypeStructs of the worker-stacked wire-error state — the
    compressed-phase programs carry it even when the engine itself is
    still in warmup (the auditor prices both phases at init)."""
    import jax
    W = engine._onebit["world"]
    return jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((W,) + tuple(p.shape), np.float32),
        engine.params)


def _onebit_engine_targets(engine, sample_batch) -> List[AuditTarget]:
    """Compressed-phase (post-freeze) audit targets for the onebit wire
    tier (docs/onebit.md).  Program identity differs from warmup — the
    dense DP grad allreduce is gone from the grad program and the
    momentum sync rides the apply program's packed wire — so the phase
    is part of what gets traced, priced, and lockstep-pinned."""
    import jax
    progs = engine._onebit_get_programs()
    wire_tmpl = _onebit_wire_template(engine)
    wire_sharding = progs["wire_sharding"]
    wire_sharded = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                       sharding=wire_sharding), wire_tmpl)
    targets: List[AuditTarget] = []

    fused = progs.get("fused")
    if fused is not None:
        if sample_batch is None:
            return targets
        gas = engine.gradient_accumulation_steps()
        stacked = tuple(
            jax.ShapeDtypeStruct((gas,) + tuple(s.shape), s.dtype)
            for s in sample_batch)
        closed = jax.make_jaxpr(fused["raw"])(
            engine.params, engine.opt_state, engine.scaler_state,
            engine._fused_sent_state, wire_tmpl, engine._rng, stacked, {})
        donated = fused["donate_argnums"]
        args = [
            ArgInfo("params", _tree_bytes(engine.params), 0 in donated,
                    True),
            ArgInfo("opt_state", _tree_bytes(engine.opt_state),
                    1 in donated, True),
            ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                    2 in donated, True),
            ArgInfo("sentinel_state", _tree_bytes(engine._fused_sent_state),
                    3 in donated, True),
            ArgInfo("wire_error", _tree_bytes(wire_tmpl), 4 in donated,
                    True),
            ArgInfo("batch", _tree_bytes(stacked), False, False),
        ]
        arg_trees = (engine.params, engine.opt_state, engine.scaler_state,
                     engine._fused_sent_state, wire_tmpl, engine._rng,
                     stacked, {})
        donated_invars, labels = _expand_invars(arg_trees, [
            (0 in donated, "params"), (1 in donated, "opt_state"),
            (2 in donated, "scaler_state"), (3 in donated,
                                            "sentinel_state"),
            (4 in donated, "wire_error"), (False, "rng"),
            (False, "batch"), (False, "kwargs")])
        sharded_stacked = _sharded_batch_structs(engine, stacked,
                                                 stacked=True)
        targets.append(AuditTarget(
            "fused_step", closed, args,
            donated_invars=donated_invars, invar_labels=labels,
            scan_info=_engine_scan_info(engine),
            lower=lambda: fused["fn"].lower(
                engine.params, engine.opt_state, engine.scaler_state,
                engine._fused_sent_state, wire_sharded, engine._rng,
                sharded_stacked, {}).compile().as_text(),
            spmd_waivers=_engine_spmd_waivers(engine, "fused")))
        return targets

    if sample_batch is not None:
        closed = jax.make_jaxpr(
            lambda p, s, r, *b: progs["loss_and_grads"](p, s, r, *b))(
            engine.params, engine.scaler_state, engine._rng,
            *sample_batch)
        args = [
            ArgInfo("params", _tree_bytes(engine.params), False, False),
            ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                    False, False),
            ArgInfo("batch", _tree_bytes(sample_batch), False, False),
        ]
        donated_invars, labels = _expand_invars(
            (engine.params, engine.scaler_state, engine._rng,
             list(sample_batch)),
            [(False, "params"), (False, "scaler_state"),
             (False, "rng"), (False, "batch")])
        sharded_batch = _sharded_batch_structs(engine, sample_batch,
                                               stacked=False)
        targets.append(AuditTarget(
            "grad_step", closed, args,
            donated_invars=donated_invars, invar_labels=labels,
            resident_extra_bytes=(_tree_bytes(engine.opt_state) +
                                  _tree_bytes(wire_tmpl)),
            scan_info=_engine_scan_info(engine),
            lower=lambda: progs["grad_fn"].lower(
                engine.params, engine.scaler_state, engine._rng,
                *sharded_batch).compile().as_text(),
            spmd_waivers=_engine_spmd_waivers(engine, "grad")))

    grads = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            (engine._onebit["world"],) + tuple(s.shape), s.dtype),
        _grads_template(engine))
    healthy = jax.ShapeDtypeStruct((), np.bool_)
    closed = jax.make_jaxpr(
        lambda p, o, s, g, e, h: progs["apply_core"](p, o, s, g, e, h))(
        engine.params, engine.opt_state, engine.scaler_state, grads,
        wire_tmpl, healthy)
    donated = progs["apply_donate_argnums"]
    args = [
        ArgInfo("params", _tree_bytes(engine.params), 0 in donated, True),
        ArgInfo("opt_state", _tree_bytes(engine.opt_state), 1 in donated,
                True),
        ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                2 in donated, True),
        ArgInfo("grads", _tree_bytes(grads), 3 in donated, True),
        ArgInfo("wire_error", _tree_bytes(wire_tmpl), 4 in donated, True),
    ]
    donated_invars, labels = _expand_invars(
        (engine.params, engine.opt_state, engine.scaler_state, grads,
         wire_tmpl, healthy),
        [(0 in donated, "params"), (1 in donated, "opt_state"),
         (2 in donated, "scaler_state"), (3 in donated, "grads"),
         (4 in donated, "wire_error"), (False, "healthy")])
    grads_sharded = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                       sharding=wire_sharding), grads)
    healthy_arr = jax.ShapeDtypeStruct(
        (), np.bool_, sharding=engine.mesh_ctx.replicated())
    targets.append(AuditTarget(
        "apply_step", closed, args,
        donated_invars=donated_invars, invar_labels=labels,
        scan_info=_engine_scan_info(engine),
        lower=lambda: progs["apply_fn"].lower(
            engine.params, engine.opt_state, engine.scaler_state,
            grads_sharded, wire_sharded,
            healthy_arr).compile().as_text(),
        spmd_waivers=_engine_spmd_waivers(engine, "apply")))
    return targets


def engine_targets(engine, sample_batch: Optional[Tuple] = None,
                   phase: Optional[str] = None) -> List[AuditTarget]:
    """Trace the engine's step program(s) abstractly.

    Modular path: the grad program (dispatched gas times per step) and
    the apply program.  Fused path: the single whole-step program.
    Donation facts come from the argnum tuples the engine recorded next
    to its jit calls (`_apply_donate_argnums` / `_fused_donate_argnums`)
    so the audit reflects what is actually dispatched.

    ``phase`` selects which of an onebit engine's two step programs to
    trace ("warmup" / "compressed" — docs/onebit.md); None follows the
    engine's current phase.  Non-onebit engines ignore it.
    """
    import jax
    targets: List[AuditTarget] = []
    if sample_batch is None:
        sample_batch = synthesize_sample_batch(engine)

    onebit = getattr(engine, "_onebit", None)
    if onebit is not None:
        if phase is None:
            phase = getattr(engine, "_onebit_phase", "warmup")
        if phase == "compressed":
            return _onebit_engine_targets(engine, sample_batch)

    fused_raw = getattr(engine, "_fused_step_raw", None)
    fused_fn = engine._fused_step_fn
    fused_donated = getattr(engine, "_fused_donate_argnums", (0, 1))
    if (onebit is not None
            and getattr(engine, "_onebit_phase", "warmup") == "compressed"
            and engine._onebit_programs is not None):
        # warmup-phase audit of an already-switched engine (checkpoint
        # signature verify): the installed fused artifacts are phase-B,
        # but the phase-A ones were stashed at the switch
        fa = engine._onebit_programs.get("fused_phase_a")
        if fa is not None:
            fused_raw, fused_fn = fa["raw"], fa["fn"]
            fused_donated = fa["donate_argnums"]
    if engine._fused_step_fn is not None and fused_raw is not None:
        if sample_batch is not None:
            gas = engine.gradient_accumulation_steps()
            stacked = tuple(
                jax.ShapeDtypeStruct((gas,) + tuple(s.shape), s.dtype)
                for s in sample_batch)
            closed = jax.make_jaxpr(fused_raw)(
                engine.params, engine.opt_state, engine.scaler_state,
                engine._fused_sent_state, engine._rng, stacked, {})
            donated = fused_donated
            args = [
                ArgInfo("params", _tree_bytes(engine.params),
                        0 in donated, True),
                ArgInfo("opt_state", _tree_bytes(engine.opt_state),
                        1 in donated, True),
                ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                        2 in donated, True),
                ArgInfo("sentinel_state",
                        _tree_bytes(engine._fused_sent_state),
                        3 in donated, True),
                ArgInfo("batch", _tree_bytes(stacked), False, False),
            ]
            arg_trees = (engine.params, engine.opt_state,
                         engine.scaler_state, engine._fused_sent_state,
                         engine._rng, stacked, {})
            donated_invars, labels = _expand_invars(arg_trees, [
                (0 in donated, "params"), (1 in donated, "opt_state"),
                (2 in donated, "scaler_state"),
                (3 in donated, "sentinel_state"),
                (False, "rng"), (False, "batch"), (False, "kwargs")])
            sharded_stacked = _sharded_batch_structs(engine, stacked,
                                                     stacked=True)
            targets.append(AuditTarget(
                "fused_step", closed, args,
                donated_invars=donated_invars, invar_labels=labels,
                scan_info=_engine_scan_info(engine),
                lower=lambda: fused_fn.lower(
                    engine.params, engine.opt_state, engine.scaler_state,
                    engine._fused_sent_state, engine._rng,
                    sharded_stacked, {}).compile().as_text(),
                spmd_waivers=_engine_spmd_waivers(engine, "fused")))
        return targets

    if sample_batch is not None:
        closed = jax.make_jaxpr(
            lambda p, s, r, *b: engine._loss_and_grads(p, s, r, *b))(
            engine.params, engine.scaler_state, engine._rng,
            *sample_batch)
        args = [
            ArgInfo("params", _tree_bytes(engine.params), False, False),
            ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                    False, False),
            ArgInfo("batch", _tree_bytes(sample_batch), False, False),
        ]
        donated_invars, labels = _expand_invars(
            (engine.params, engine.scaler_state, engine._rng,
             list(sample_batch)),
            [(False, "params"), (False, "scaler_state"),
             (False, "rng"), (False, "batch")])
        # opt_state sits in HBM while the grad program runs
        sharded_batch = _sharded_batch_structs(engine, sample_batch,
                                               stacked=False)
        targets.append(AuditTarget(
            "grad_step", closed, args,
            donated_invars=donated_invars, invar_labels=labels,
            resident_extra_bytes=_tree_bytes(engine.opt_state),
            scan_info=_engine_scan_info(engine),
            lower=lambda: engine._grad_fn.lower(
                engine.params, engine.scaler_state, engine._rng,
                *sharded_batch).compile().as_text(),
            spmd_waivers=_engine_spmd_waivers(engine, "grad")))

    if engine._apply_core is not None:
        grads = _grads_template(engine)
        closed = jax.make_jaxpr(
            lambda p, o, s, g: engine._apply_core(p, o, s, g))(
            engine.params, engine.opt_state, engine.scaler_state, grads)
        donated = getattr(engine, "_apply_donate_argnums", (0, 1, 3))
        args = [
            ArgInfo("params", _tree_bytes(engine.params),
                    0 in donated, True),
            ArgInfo("opt_state", _tree_bytes(engine.opt_state),
                    1 in donated, True),
            ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                    2 in donated, True),
            ArgInfo("grads", _tree_bytes(grads), 3 in donated, True),
        ]
        donated_invars, labels = _expand_invars(
            (engine.params, engine.opt_state, engine.scaler_state,
             grads),
            [(0 in donated, "params"), (1 in donated, "opt_state"),
             (2 in donated, "scaler_state"), (3 in donated, "grads")])
        grads_sharded = None
        if engine._apply_fn is not None and engine.grad_shardings is not None:
            grads_sharded = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                                   sharding=sh),
                grads, engine.grad_shardings)
        targets.append(AuditTarget(
            "apply_step", closed, args,
            donated_invars=donated_invars, invar_labels=labels,
            scan_info=_engine_scan_info(engine),
            lower=(None if grads_sharded is None else
                   lambda: engine._apply_fn.lower(
                       engine.params, engine.opt_state,
                       engine.scaler_state,
                       grads_sharded).compile().as_text()),
            spmd_waivers=_engine_spmd_waivers(engine, "apply")))
    return targets


class ProgramAuditor:
    """Run the static rule registry over audit targets."""

    def __init__(self, cfg):
        self.cfg = cfg

    def run(self, targets: List[AuditTarget], gas: int = 1,
            swap=None, hlo: bool = False) -> AuditReport:
        """``swap`` is an optional offload-tier traffic model
        (cost_model.swap_lane) folded into the step-time lower bound —
        a config streaming params/optimizer state from NVMe must not
        rank as if they were HBM-resident.  ``hlo`` additionally lowers
        each target through XLA's SPMD partitioner (compile-only) and
        cross-checks the jaxpr wire story against the compiled program
        (analysis/hlo_audit.py)."""
        report = AuditReport(targets=[t.label for t in targets])
        hlo_audits = []
        for target in targets:
            for _rule_id, rule in STATIC_RULES:
                report.findings.extend(rule(target, self.cfg))
        sigs = []
        contributors = []
        all_records = []
        total_flops = 0
        io_bytes = 0
        peak_liveness = None
        from ..profiling.flops_profiler import count_jaxpr_flops
        for target in targets:
            sig, seq = lockstep_signature(target.closed_jaxpr)
            sigs.append(sig)
            # the grad program is dispatched gas times per optimizer
            # step — its collectives (and wire bytes) repeat in lockstep
            repeat = gas if target.label == "grad_step" else 1
            report.collective_sequence.extend(seq * repeat)
            total, contrib = step_wire_bytes(target.closed_jaxpr)
            report.wire_bytes_per_step += total * repeat
            contributors.extend((f"{target.label}:{k}", v * repeat)
                                for k, v in contrib)
            if hlo:
                hlo_audit, hlo_findings = audit_target_hlo(
                    target, self.cfg, jaxpr_wire_bytes=total)
                hlo_audits.append((hlo_audit, repeat))
                report.findings.extend(hlo_findings)
            # ---- schedule-level analyses -------------------------- #
            records = analyze_overlap(target.closed_jaxpr, self.cfg,
                                      target_label=target.label)
            report.findings.extend(overlap_rule_findings(
                records, self.cfg, target.scan_info))
            all_records.extend(records * repeat)
            total_flops += count_jaxpr_flops(target.closed_jaxpr) * repeat
            io_bytes += program_io_bytes(target.closed_jaxpr) * repeat
            liveness = estimate_liveness(
                target.closed_jaxpr, target.donated_invars,
                target.invar_labels, target.resident_extra_bytes)
            if (peak_liveness is None or
                    liveness.total_bytes > peak_liveness[1].total_bytes):
                peak_liveness = (target.label, liveness)
        report.signature = (combine_signatures(sigs) if sigs else None)
        report.findings.extend(lockstep_expectation_finding(
            report.signature, len(report.collective_sequence), self.cfg))
        contributors.sort(key=lambda kv: -kv[1])
        # budget is checked against the same gas-weighted per-step total
        # the report (and bench rows) publish
        report.findings.extend(comm_budget_finding(
            report.wire_bytes_per_step, contributors, self.cfg))
        report.donation_waste_bytes = donation_waste_bytes(targets,
                                                           self.cfg)
        # peak HBM = the worst single program (programs run one at a
        # time; each target already counts its resident-but-unreferenced
        # engine state)
        report.overlap_efficiency = overlap_efficiency(all_records)
        report.overlap = summarize_overlap(all_records)
        if peak_liveness is not None:
            label, liveness = peak_liveness
            report.peak_hbm_bytes = liveness.total_bytes
            report.peak_hbm_contributors = list(liveness.contributors)
            if liveness.resident_extra_bytes > 0:
                report.peak_hbm_contributors.append(
                    ("<resident engine state>",
                     liveness.resident_extra_bytes))
            report.findings.extend(hbm_budget_finding(
                liveness.total_bytes, label,
                report.peak_hbm_contributors, self.cfg))
        if hlo_audits:
            report.hlo = summarize_hlo(hlo_audits)
        # HLO-only wire (compiler-inserted collectives plus traced wire
        # outside the jaxpr accounting) prices into the exposed-comm
        # lane: predicted_step_time_lb must not undercount what the
        # compiled program actually moves
        report.step_time = build_step_time_model(
            total_flops, io_bytes, all_records, self.cfg, swap=swap,
            hlo_only_wire_bytes=report.hlo.get(
                "hlo_only_wire_bytes_per_step", 0))
        return report


def verify_multihost_lockstep(report: AuditReport) -> List[Finding]:
    """On a multihost pod, allgather the signature digests and flag any
    divergence BEFORE the first collective dispatch can hang it.
    Single-process: no-op."""
    import jax
    if jax.process_count() <= 1 or report.signature is None:
        return []
    import hashlib
    from jax.experimental import multihost_utils
    digest = np.frombuffer(
        hashlib.sha256(report.signature.encode()).digest()[:8],
        dtype=np.int64)
    all_digests = np.asarray(multihost_utils.process_allgather(digest))
    if (all_digests == digest.reshape(1, -1)).all():
        return []
    return [Finding(
        rule="lockstep", severity="error",
        message=(f"collective lockstep signature "
                 f"{report.signature[:12]} differs across hosts — the "
                 "pod WOULD deadlock at the first diverged collective"),
        target="multihost",
        fix_hint="diff each host's config (CLI --dump-sequence) — "
                 "every process must trace the identical step program")]


def engine_swap_lane(engine, swap=None):
    """Offload-tier traffic model for a built engine: when the config
    targets NVMe for the optimizer sweep, the step-time bound must pay
    the disk trips at the measured sweep ceiling.  An explicit ``swap``
    (the autotuner's resident-twin path for offload_param candidates)
    wins; returns None for purely HBM/host-resident configs."""
    if swap is not None:
        return swap
    from .cost_model import swap_lane
    try:
        return swap_lane(engine.config.zero_config,
                         engine.config.aio_config,
                         param_bytes=_tree_bytes(engine.params),
                         opt_state_bytes=_tree_bytes(engine.opt_state))
    except Exception:  # noqa: BLE001 — the lane is provenance, never fatal
        return None


def audit_engine(engine, sample_batch: Optional[Tuple] = None,
                 cfg=None, multihost: bool = True,
                 swap=None, hlo: Optional[bool] = None,
                 phase: Optional[str] = None) -> AuditReport:
    """Full static audit of a built engine.  Never executes the step.

    ``hlo`` forces the HLO-level SPMD cross-check on (True) or off
    (False); None follows ``analysis.hlo_audit``.  The cross-check
    compiles each program through the SPMD partitioner — meaningful
    extra init cost, so it stays opt-in.  ``phase`` audits an onebit
    engine's warmup or compressed step program (docs/onebit.md); None
    follows the engine's current phase."""
    cfg = cfg if cfg is not None else engine.config.analysis_config
    targets = engine_targets(engine, sample_batch, phase=phase)
    report = ProgramAuditor(cfg).run(
        targets, gas=engine.gradient_accumulation_steps(),
        swap=engine_swap_lane(engine, swap),
        hlo=cfg.hlo_audit if hlo is None else hlo)
    if multihost:
        report.findings.extend(verify_multihost_lockstep(report))
    return report


def enforce(report: AuditReport, mode: str, logger: Any = None) -> None:
    """Apply the configured reaction: warn logs every finding, error
    raises ProgramAuditError when error-severity findings exist."""
    if mode == "off" or not report.findings:
        return
    if logger is not None:
        for f in report.findings:
            log = (logger.error if f.severity == "error"
                   else logger.warning)
            log(f.format())
    if mode == "error" and report.has_errors:
        raise ProgramAuditError(report)
