"""Program Auditor — trace the engine's train step to closed jaxprs
(without executing them) and run the static lint registry.

The engine's failure modes stopped being Python bugs when the whole
optimizer step became one XLA program (PR 3) and params started streaming
through quantized collectives (PR 1): a stray host callback fencing the
gas scan, a dropped donate_argnums doubling HBM, a collective sequence
that diverges across hosts and hangs the pod, a silent fp32 upcast on a
bf16 wire.  All of those are *program-shape* properties readable off the
jaxpr — so they are linted here, statically, at engine init / in CI,
instead of being discovered on a burning pod.

Entry points:
  ``audit_engine(engine)``            — full report for a built engine
  ``ProgramAuditor(cfg).run(targets)``— rule registry over explicit
                                        targets (tests, CLI fixtures)
"""

from typing import Any, List, Optional, Tuple

import numpy as np

from .findings import AuditReport, Finding, ProgramAuditError
from .rules import (ArgInfo, AuditTarget, STATIC_RULES,
                    comm_budget_finding, donation_waste_bytes,
                    lockstep_expectation_finding, step_wire_bytes)
from .signature import combine_signatures, lockstep_signature


def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape, initial=1)) * np.dtype(dtype).itemsize
        except TypeError:
            # extended dtypes (PRNG keys): count the key payload
            total += int(np.prod(shape, initial=1)) * 4
    return total


def _grads_template(engine):
    """ShapeDtypeStructs of the accumulated-grad tree (the apply
    program's 4th argument) without running a grad step."""
    import jax
    import jax.numpy as jnp
    grads_half = (engine.config.bf16.enabled
                  and engine.config.bf16.grads_in_compute_dtype)

    def one(p):
        dtype = p.dtype
        if grads_half and jnp.issubdtype(p.dtype, jnp.floating):
            dtype = engine.compute_dtype
        return jax.ShapeDtypeStruct(p.shape, dtype)

    return jax.tree.map(one, engine.params)


def synthesize_sample_batch(engine) -> Optional[Tuple]:
    """A ShapeDtypeStruct batch for tracing the grad program, derived
    from the model's declared shapes (GPT2/BERT-style configs expose
    n_positions + vocab_size).  None when the model's input contract is
    unknown — the auditor then audits the apply program only."""
    import jax
    mcfg = getattr(engine.module, "config", None)
    seq = getattr(mcfg, "n_positions", None)
    if seq is None:
        seq = getattr(mcfg, "max_position_embeddings", None)
    if seq is None or getattr(mcfg, "vocab_size", None) is None:
        return None
    # the dispatched batch is GLOBAL (micro x dp_world): _shard_batch
    # places a full cross-host array, and program structure depends on it
    # (the ZeRO-3 streamed scan only engages when the batch divides the
    # ZeRO world — a micro-batch-sized probe would audit the fallback
    # program instead of the one training dispatches)
    batch = engine.train_micro_batch_size_per_gpu() * engine.world_size
    return (jax.ShapeDtypeStruct((batch, int(seq)), np.int32),)


def engine_targets(engine, sample_batch: Optional[Tuple] = None
                   ) -> List[AuditTarget]:
    """Trace the engine's step program(s) abstractly.

    Modular path: the grad program (dispatched gas times per step) and
    the apply program.  Fused path: the single whole-step program.
    Donation facts come from the argnum tuples the engine recorded next
    to its jit calls (`_apply_donate_argnums` / `_fused_donate_argnums`)
    so the audit reflects what is actually dispatched.
    """
    import jax
    targets: List[AuditTarget] = []
    if sample_batch is None:
        sample_batch = synthesize_sample_batch(engine)

    fused_raw = getattr(engine, "_fused_step_raw", None)
    if engine._fused_step_fn is not None and fused_raw is not None:
        if sample_batch is not None:
            gas = engine.gradient_accumulation_steps()
            stacked = tuple(
                jax.ShapeDtypeStruct((gas,) + tuple(s.shape), s.dtype)
                for s in sample_batch)
            closed = jax.make_jaxpr(fused_raw)(
                engine.params, engine.opt_state, engine.scaler_state,
                engine._fused_sent_state, engine._rng, stacked, {})
            donated = getattr(engine, "_fused_donate_argnums", (0, 1))
            args = [
                ArgInfo("params", _tree_bytes(engine.params),
                        0 in donated, True),
                ArgInfo("opt_state", _tree_bytes(engine.opt_state),
                        1 in donated, True),
                ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                        2 in donated, True),
                ArgInfo("sentinel_state",
                        _tree_bytes(engine._fused_sent_state),
                        3 in donated, True),
                ArgInfo("batch", _tree_bytes(stacked), False, False),
            ]
            targets.append(AuditTarget("fused_step", closed, args))
        return targets

    if sample_batch is not None:
        closed = jax.make_jaxpr(
            lambda p, s, r, *b: engine._loss_and_grads(p, s, r, *b))(
            engine.params, engine.scaler_state, engine._rng,
            *sample_batch)
        args = [
            ArgInfo("params", _tree_bytes(engine.params), False, False),
            ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                    False, False),
            ArgInfo("batch", _tree_bytes(sample_batch), False, False),
        ]
        targets.append(AuditTarget("grad_step", closed, args))

    if engine._apply_core is not None:
        grads = _grads_template(engine)
        closed = jax.make_jaxpr(
            lambda p, o, s, g: engine._apply_core(p, o, s, g))(
            engine.params, engine.opt_state, engine.scaler_state, grads)
        donated = getattr(engine, "_apply_donate_argnums", (0, 1, 3))
        args = [
            ArgInfo("params", _tree_bytes(engine.params),
                    0 in donated, True),
            ArgInfo("opt_state", _tree_bytes(engine.opt_state),
                    1 in donated, True),
            ArgInfo("scaler_state", _tree_bytes(engine.scaler_state),
                    2 in donated, True),
            ArgInfo("grads", _tree_bytes(grads), 3 in donated, True),
        ]
        targets.append(AuditTarget("apply_step", closed, args))
    return targets


class ProgramAuditor:
    """Run the static rule registry over audit targets."""

    def __init__(self, cfg):
        self.cfg = cfg

    def run(self, targets: List[AuditTarget],
            gas: int = 1) -> AuditReport:
        report = AuditReport(targets=[t.label for t in targets])
        for target in targets:
            for _rule_id, rule in STATIC_RULES:
                report.findings.extend(rule(target, self.cfg))
        sigs = []
        contributors = []
        for target in targets:
            sig, seq = lockstep_signature(target.closed_jaxpr)
            sigs.append(sig)
            # the grad program is dispatched gas times per optimizer
            # step — its collectives (and wire bytes) repeat in lockstep
            repeat = gas if target.label == "grad_step" else 1
            report.collective_sequence.extend(seq * repeat)
            total, contrib = step_wire_bytes(target.closed_jaxpr)
            report.wire_bytes_per_step += total * repeat
            contributors.extend((f"{target.label}:{k}", v * repeat)
                                for k, v in contrib)
        report.signature = (combine_signatures(sigs) if sigs else None)
        report.findings.extend(lockstep_expectation_finding(
            report.signature, len(report.collective_sequence), self.cfg))
        contributors.sort(key=lambda kv: -kv[1])
        # budget is checked against the same gas-weighted per-step total
        # the report (and bench rows) publish
        report.findings.extend(comm_budget_finding(
            report.wire_bytes_per_step, contributors, self.cfg))
        report.donation_waste_bytes = donation_waste_bytes(targets,
                                                           self.cfg)
        return report


def verify_multihost_lockstep(report: AuditReport) -> List[Finding]:
    """On a multihost pod, allgather the signature digests and flag any
    divergence BEFORE the first collective dispatch can hang it.
    Single-process: no-op."""
    import jax
    if jax.process_count() <= 1 or report.signature is None:
        return []
    import hashlib
    from jax.experimental import multihost_utils
    digest = np.frombuffer(
        hashlib.sha256(report.signature.encode()).digest()[:8],
        dtype=np.int64)
    all_digests = np.asarray(multihost_utils.process_allgather(digest))
    if (all_digests == digest.reshape(1, -1)).all():
        return []
    return [Finding(
        rule="lockstep", severity="error",
        message=(f"collective lockstep signature "
                 f"{report.signature[:12]} differs across hosts — the "
                 "pod WOULD deadlock at the first diverged collective"),
        target="multihost",
        fix_hint="diff each host's config (CLI --dump-sequence) — "
                 "every process must trace the identical step program")]


def audit_engine(engine, sample_batch: Optional[Tuple] = None,
                 cfg=None, multihost: bool = True) -> AuditReport:
    """Full static audit of a built engine.  Never executes the step."""
    cfg = cfg if cfg is not None else engine.config.analysis_config
    targets = engine_targets(engine, sample_batch)
    report = ProgramAuditor(cfg).run(
        targets, gas=engine.gradient_accumulation_steps())
    if multihost:
        report.findings.extend(verify_multihost_lockstep(report))
    return report


def enforce(report: AuditReport, mode: str, logger: Any = None) -> None:
    """Apply the configured reaction: warn logs every finding, error
    raises ProgramAuditError when error-severity findings exist."""
    if mode == "off" or not report.findings:
        return
    if logger is not None:
        for f in report.findings:
            log = (logger.error if f.severity == "error"
                   else logger.warning)
            log(f.format())
    if mode == "error" and report.has_errors:
        raise ProgramAuditError(report)
