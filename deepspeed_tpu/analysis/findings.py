"""Structured lint findings + the audit report container.

Every rule emits ``Finding`` records instead of log lines so that CI, the
engine init summary, bench rows, and the CLI all consume the same data —
the reference DeepSpeed has no analog (its failure modes surface as hung
pods and OOMs at runtime; see ISSUE 5 / docs/program_auditor.md).
"""

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

SEVERITIES = ("error", "warning", "info")

# rule ids (stable: tests, golden files, and docs key off these)
RULE_HOST_SYNC = "host_sync"
RULE_DONATION = "donation"
RULE_LOCKSTEP = "lockstep"
RULE_DTYPE_HAZARD = "dtype_hazard"
RULE_COMM_BUDGET = "comm_budget"
RULE_RECOMPILE = "recompile"
# schedule-level rules (overlap / liveness / step-time; ISSUE 6)
RULE_OVERLAP = "overlap"
RULE_HBM_BUDGET = "hbm_budget"
# HLO-level SPMD cross-check (analysis/hlo_audit.py; ISSUE 14):
# compiler-inserted gather-family collectives the jaxpr never saw, and
# jaxpr-predicted vs HLO-measured wire drift on the traced ones
RULE_SILENT_RESHARD = "silent_reshard"
RULE_SPMD_DIVERGENCE = "spmd_divergence"

ALL_RULES = (RULE_HOST_SYNC, RULE_DONATION, RULE_LOCKSTEP,
             RULE_DTYPE_HAZARD, RULE_COMM_BUDGET, RULE_RECOMPILE,
             RULE_OVERLAP, RULE_HBM_BUDGET, RULE_SILENT_RESHARD,
             RULE_SPMD_DIVERGENCE)


@dataclass
class Finding:
    """One lint hit: what rule fired, how bad, where in the program, and
    what to do about it."""
    rule: str                 # one of ALL_RULES
    severity: str             # "error" | "warning" | "info"
    message: str              # human-readable defect statement
    target: str = ""          # which traced program ("grad_step", ...)
    scope: str = ""           # eqn name-stack provenance inside the target
    fix_hint: str = ""        # one actionable sentence

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in "
                             f"{SEVERITIES}")
        if self.rule not in ALL_RULES:
            raise ValueError(f"unknown rule id {self.rule!r}")

    def format(self) -> str:
        where = self.target + (f" @ {self.scope}" if self.scope else "")
        hint = f"  hint: {self.fix_hint}" if self.fix_hint else ""
        return (f"[{self.severity.upper():7s}] {self.rule}: {self.message}"
                f" ({where}){hint}")


@dataclass
class AuditReport:
    """Everything one audit pass learned about the program(s)."""
    findings: List[Finding] = field(default_factory=list)
    # collective-lockstep signature of the step program (hex digest) and
    # the human-readable sequence it hashes
    signature: Optional[str] = None
    collective_sequence: List[str] = field(default_factory=list)
    # trip-count-weighted wire bytes per optimizer step
    wire_bytes_per_step: int = 0
    # HBM the donation rule estimates is being wasted (0 when clean)
    donation_waste_bytes: int = 0
    targets: List[str] = field(default_factory=list)
    # ---- schedule-level analyses (overlap / liveness / step-time) ---- #
    # bytes-weighted fraction of collective wire time hidden under
    # independent compute (1.0 when there are no explicit collectives)
    overlap_efficiency: float = 1.0
    # per-collective overlap records + summary (analysis/overlap.py)
    overlap: Dict[str, Any] = field(default_factory=dict)
    # donation-aware static peak HBM estimate across targets, with the
    # top live-buffer contributors at the peak point
    peak_hbm_bytes: int = 0
    peak_hbm_contributors: List[Any] = field(default_factory=list)
    # static step-time lower bound (analysis/cost_model.py)
    step_time: Dict[str, Any] = field(default_factory=dict)
    # HLO-level SPMD cross-check payload (analysis/hlo_audit.py):
    # per-target compiled-program wire accounting, matched vs
    # compiler-inserted, divergence ratio — empty when the audit did
    # not run (analysis.hlo_audit off and no --hlo-audit)
    hlo: Dict[str, Any] = field(default_factory=dict)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    @property
    def has_errors(self) -> bool:
        return any(f.severity == "error" for f in self.findings)

    @property
    def predicted_step_time_lb_s(self) -> Optional[float]:
        return self.step_time.get("predicted_step_time_lb_s")

    # ---- HLO cross-check conveniences (None when the audit is off) -- #
    @property
    def hlo_wire_bytes_per_step(self) -> Optional[int]:
        return self.hlo.get("hlo_wire_bytes_per_step")

    @property
    def hlo_collective_count(self) -> Optional[int]:
        return self.hlo.get("hlo_collective_count")

    @property
    def hlo_divergence_ratio(self) -> Optional[float]:
        return self.hlo.get("divergence_ratio")

    def summary_line(self) -> str:
        c = self.counts()
        sig = (self.signature or "")[:12] or "n/a"
        lb = self.predicted_step_time_lb_s
        lb_ms = f"{lb * 1e3:.2f}" if lb is not None else "n/a"
        return (f"program audit: {c['error']} error(s), "
                f"{c['warning']} warning(s), {c['info']} info over "
                f"{len(self.targets)} program(s); "
                f"wire={self.wire_bytes_per_step} B/step, "
                f"donation_waste={self.donation_waste_bytes} B, "
                f"overlap={self.overlap_efficiency:.2f}, "
                f"peak_hbm={self.peak_hbm_bytes / (1024 * 1024):.1f} MiB, "
                f"step_lb={lb_ms} ms, "
                f"lockstep={sig}")

    def counters(self) -> Dict[str, Any]:
        """Checkpoint-client-state payload (mirrors the sentinel-counter
        round-trip: plain JSON-serializable scalars only)."""
        return {
            "findings_by_severity": self.counts(),
            "wire_bytes_per_step": int(self.wire_bytes_per_step),
            "donation_waste_bytes": int(self.donation_waste_bytes),
            "lockstep_signature": self.signature,
            "overlap_efficiency": float(self.overlap_efficiency),
            "peak_hbm_bytes": int(self.peak_hbm_bytes),
            "predicted_step_time_lb_s": self.predicted_step_time_lb_s,
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps({
            "findings": [asdict(f) for f in self.findings],
            "signature": self.signature,
            "collective_sequence": self.collective_sequence,
            "wire_bytes_per_step": self.wire_bytes_per_step,
            "donation_waste_bytes": self.donation_waste_bytes,
            "targets": self.targets,
            "overlap_efficiency": self.overlap_efficiency,
            "overlap": self.overlap,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "peak_hbm_contributors": [
                list(c) for c in self.peak_hbm_contributors],
            "step_time": self.step_time,
            "hlo": self.hlo,
        }, indent=indent)


class ProgramAuditError(RuntimeError):
    """Raised in ``analysis.mode == "error"`` when error-severity findings
    exist; carries the report for structured handling."""

    def __init__(self, report: AuditReport):
        self.report = report
        errors = [f.format() for f in report.findings
                  if f.severity == "error"]
        super().__init__(
            "program audit failed with error-severity findings "
            "(analysis.mode = \"error\"):\n" + "\n".join(errors))
