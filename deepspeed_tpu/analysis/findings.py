"""Structured lint findings + the audit report container.

Every rule emits ``Finding`` records instead of log lines so that CI, the
engine init summary, bench rows, and the CLI all consume the same data —
the reference DeepSpeed has no analog (its failure modes surface as hung
pods and OOMs at runtime; see ISSUE 5 / docs/program_auditor.md).
"""

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

SEVERITIES = ("error", "warning", "info")

# rule ids (stable: tests, golden files, and docs key off these)
RULE_HOST_SYNC = "host_sync"
RULE_DONATION = "donation"
RULE_LOCKSTEP = "lockstep"
RULE_DTYPE_HAZARD = "dtype_hazard"
RULE_COMM_BUDGET = "comm_budget"
RULE_RECOMPILE = "recompile"

ALL_RULES = (RULE_HOST_SYNC, RULE_DONATION, RULE_LOCKSTEP,
             RULE_DTYPE_HAZARD, RULE_COMM_BUDGET, RULE_RECOMPILE)


@dataclass
class Finding:
    """One lint hit: what rule fired, how bad, where in the program, and
    what to do about it."""
    rule: str                 # one of ALL_RULES
    severity: str             # "error" | "warning" | "info"
    message: str              # human-readable defect statement
    target: str = ""          # which traced program ("grad_step", ...)
    scope: str = ""           # eqn name-stack provenance inside the target
    fix_hint: str = ""        # one actionable sentence

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in "
                             f"{SEVERITIES}")
        if self.rule not in ALL_RULES:
            raise ValueError(f"unknown rule id {self.rule!r}")

    def format(self) -> str:
        where = self.target + (f" @ {self.scope}" if self.scope else "")
        hint = f"  hint: {self.fix_hint}" if self.fix_hint else ""
        return (f"[{self.severity.upper():7s}] {self.rule}: {self.message}"
                f" ({where}){hint}")


@dataclass
class AuditReport:
    """Everything one audit pass learned about the program(s)."""
    findings: List[Finding] = field(default_factory=list)
    # collective-lockstep signature of the step program (hex digest) and
    # the human-readable sequence it hashes
    signature: Optional[str] = None
    collective_sequence: List[str] = field(default_factory=list)
    # trip-count-weighted wire bytes per optimizer step
    wire_bytes_per_step: int = 0
    # HBM the donation rule estimates is being wasted (0 when clean)
    donation_waste_bytes: int = 0
    targets: List[str] = field(default_factory=list)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    @property
    def has_errors(self) -> bool:
        return any(f.severity == "error" for f in self.findings)

    def summary_line(self) -> str:
        c = self.counts()
        sig = (self.signature or "")[:12] or "n/a"
        return (f"program audit: {c['error']} error(s), "
                f"{c['warning']} warning(s), {c['info']} info over "
                f"{len(self.targets)} program(s); "
                f"wire={self.wire_bytes_per_step} B/step, "
                f"donation_waste={self.donation_waste_bytes} B, "
                f"lockstep={sig}")

    def counters(self) -> Dict[str, Any]:
        """Checkpoint-client-state payload (mirrors the sentinel-counter
        round-trip: plain JSON-serializable scalars only)."""
        return {
            "findings_by_severity": self.counts(),
            "wire_bytes_per_step": int(self.wire_bytes_per_step),
            "donation_waste_bytes": int(self.donation_waste_bytes),
            "lockstep_signature": self.signature,
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps({
            "findings": [asdict(f) for f in self.findings],
            "signature": self.signature,
            "collective_sequence": self.collective_sequence,
            "wire_bytes_per_step": self.wire_bytes_per_step,
            "donation_waste_bytes": self.donation_waste_bytes,
            "targets": self.targets,
        }, indent=indent)


class ProgramAuditError(RuntimeError):
    """Raised in ``analysis.mode == "error"`` when error-severity findings
    exist; carries the report for structured handling."""

    def __init__(self, report: AuditReport):
        self.report = report
        errors = [f.format() for f in report.findings
                  if f.severity == "error"]
        super().__init__(
            "program audit failed with error-severity findings "
            "(analysis.mode = \"error\"):\n" + "\n".join(errors))
