"""Config-autotuner search space — enumerate the REAL decision space.

The knobs that decide a training config's step time are not free-form:
they are the axes this repo actually implements and benchmarks — mesh
factorization (data x model x expert over a fixed chip count), ZeRO
stage 1/2/3 with the stage-3 resident-vs-streamed split and its
prefetch mode + group size (docs/zero3_streaming.md), gas/micro splits
of a FIXED global batch (the batch is a hyperparameter, its split is a
schedule choice), the ZeRO++ transport knobs qwZ/qgZ/hpZ
(docs/low_bandwidth_collectives.md), per-tile fused collective-matmul
transports (docs/fused_collective_matmul.md — candidate names carry an
``fcm`` tag), fused vs modular step (docs/fused_step.md), and the
offload tier with its prefetch/pipeline depths (docs/zero_infinity.md).

Enumeration is deterministic (nested loops in a documented order, names
encode every knob) and GATED so the product only contains meaningful
points: stage-3 streaming knobs collapse for stages 1/2, qwZ/hpZ only
modulate streamed stage-3 gathers, qgZ needs a stage >= 2 grad
reduce-scatter, the NVMe tier needs streamed stage 3, and the fused
step is only enumerated where it would not silently fall back
(offload-optimizer configs are host-interactive).  Structural
infeasibilities — a global batch the data world cannot divide, an
elasticity block that rejects the world size — are recorded as pruned
candidates with reasons, never silently skipped.
"""

import copy
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

from .. import constants as C


class AutotuneError(RuntimeError):
    """Search-configuration or search-execution failure."""


@dataclass
class Candidate:
    """One enumerated point: a bench-ready engine config + the flat knob
    summary the leaderboard reports."""
    name: str
    config: Dict[str, Any]
    knobs: Dict[str, Any]


@dataclass
class Pruned:
    """A candidate rejected by a hard constraint, with provenance: which
    pruning stage killed it and why — the empty-search diagnosis is
    built from these."""
    name: str
    stage: str  # "batch" | "hbm_floor" | "trace" | "auditor" | "emit_gate"
    reason: str


@dataclass
class SearchSpace:
    """Resolved enumeration output."""
    candidates: List[Candidate] = field(default_factory=list)
    pruned: List[Pruned] = field(default_factory=list)
    n_enumerated: int = 0


def mesh_factorizations(chips: int, model_sizes, expert_sizes
                        ) -> List[Tuple[int, int, int]]:
    """(data, model, expert) factorizations of `chips` with the model /
    expert axes drawn from the configured choice lists."""
    out = []
    for m in sorted(set(int(v) for v in model_sizes)):
        for e in sorted(set(int(v) for v in expert_sizes)):
            if m < 1 or e < 1 or chips % (m * e) != 0:
                continue
            out.append((chips // (m * e), m, e))
    return out


def batch_splits(global_batch: int, dp_world: int,
                 micro_filter=None) -> List[Tuple[int, int]]:
    """(micro, gas) divisor splits of the fixed global batch over the
    data-parallel world (data x expert axes)."""
    if global_batch % dp_world != 0:
        return []
    per_replica = global_batch // dp_world
    splits = []
    for micro in range(1, per_replica + 1):
        if per_replica % micro != 0:
            continue
        if micro_filter is not None and micro not in micro_filter:
            continue
        splits.append((micro, per_replica // micro))
    return splits


def _deep_merge(dst: Dict[str, Any], overlay: Dict[str, Any]) -> None:
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)


def _candidate_name(stage, streamed, pmode, bucket, micro, gas, data,
                    model, expert, qwz, qgz, hpz, fused, offload,
                    pdepth, odepth, multi_bucket, fcm=False,
                    onebit=False) -> str:
    bits = [f"z{stage}" + ("s" if streamed else "")]
    if streamed:
        bits.append(pmode)
        if multi_bucket:
            bits.append(f"g{bucket}")
    bits.append(f"b{micro}x{gas}")
    bits.append(f"d{data}m{model}e{expert}")
    if qwz:
        bits.append(f"qwz{qwz}")
    if qgz:
        bits.append(f"qgz{qgz}")
    if hpz:
        bits.append(f"hpz{hpz}")
    if fcm:
        bits.append("fcm")
    if onebit:
        bits.append("1bit")
    bits.append("fused" if fused else "mod")
    if offload == C.AUTOTUNING_OFFLOAD_TIER_NVME:
        # the depth axes only modulate the NVMe tier; the cpu tier has
        # no depth knob to encode
        bits.append(f"off-{offload}{pdepth}")
    elif offload != C.AUTOTUNING_OFFLOAD_TIER_NONE:
        bits.append(f"off-{offload}")
    return "-".join(bits)


def _build_config(base: Dict[str, Any], *, stage, streamed, pmode,
                  bucket, micro, gas, data, model, expert, qwz, qgz,
                  hpz, fused, offload, pdepth, odepth,
                  fixed, fcm=False, onebit=False) -> Dict[str, Any]:
    raw = copy.deepcopy(base)
    # candidates are bench-ready engine configs: the search description
    # itself must not ride along
    raw.pop(C.AUTOTUNING, None)
    raw[C.MESH] = {C.MESH_DATA_AXIS: data, C.MESH_MODEL_AXIS: model,
                   C.MESH_EXPERT_AXIS: expert}
    dp_world = data * expert  # MeshContext.data_parallel_world_size
    raw[C.TRAIN_BATCH_SIZE] = micro * gas * dp_world
    raw[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro
    raw[C.GRADIENT_ACCUMULATION_STEPS] = gas

    zo = dict(raw.get(C.ZERO_OPTIMIZATION) or {})
    zo[C.ZERO_OPTIMIZATION_STAGE] = stage
    for key in (C.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD,
                C.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS,
                C.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE,
                C.ZERO_OPTIMIZATION_PREFETCH_MODE,
                C.ZERO_OPTIMIZATION_LOW_BANDWIDTH,
                C.ZERO_OPTIMIZATION_OFFLOAD_PARAM,
                C.ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER):
        zo.pop(key, None)
    if streamed:
        zo[C.ZERO_OPTIMIZATION_PARAM_PERSISTENCE_THRESHOLD] = 0
        zo[C.ZERO_OPTIMIZATION_MAX_LIVE_PARAMETERS] = bucket
        zo[C.ZERO_OPTIMIZATION_PREFETCH_BUCKET_SIZE] = bucket
        zo[C.ZERO_OPTIMIZATION_PREFETCH_MODE] = pmode
    lb = {}
    if qwz:
        lb[C.LOW_BANDWIDTH_QWZ_BITS] = qwz
    if qgz:
        lb[C.LOW_BANDWIDTH_QGZ_BITS] = qgz
    if hpz:
        lb[C.LOW_BANDWIDTH_HPZ_GROUP_SIZE] = hpz
    if fcm:
        lb[C.LOW_BANDWIDTH_FCM] = True
    if onebit:
        lb[C.LOW_BANDWIDTH_ONEBIT] = True
        # the wire format IS the onebit optimizer's error-feedback
        # momentum: swap the base optimizer for its onebit counterpart,
        # keeping lr/betas/... (docs/onebit.md)
        opt = copy.deepcopy(base.get(C.OPTIMIZER) or {})
        name = str(opt.get("type") or "").lower()
        opt["type"] = "OneBitLamb" if "lamb" in name else "OneBitAdam"
        opt.setdefault("params", {})
        raw[C.OPTIMIZER] = opt
    if lb:
        zo[C.ZERO_OPTIMIZATION_LOW_BANDWIDTH] = lb
    if offload == C.AUTOTUNING_OFFLOAD_TIER_CPU:
        zo[C.ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER] = {
            C.OFFLOAD_OPTIMIZER_DEVICE: C.OFFLOAD_CPU_DEVICE}
    elif offload == C.AUTOTUNING_OFFLOAD_TIER_NVME:
        zo[C.ZERO_OPTIMIZATION_OFFLOAD_PARAM] = {
            C.OFFLOAD_PARAM_DEVICE: C.OFFLOAD_NVME_DEVICE,
            C.OFFLOAD_PARAM_PREFETCH_DEPTH: pdepth}
        zo[C.ZERO_OPTIMIZATION_OFFLOAD_OPTIMIZER] = {
            C.OFFLOAD_OPTIMIZER_DEVICE: C.OFFLOAD_NVME_DEVICE,
            C.OFFLOAD_OPTIMIZER_PIPELINE_DEPTH: odepth}
    raw[C.ZERO_OPTIMIZATION] = zo
    raw[C.FUSED_STEP] = {C.FUSED_STEP_ENABLED: bool(fused)}
    if fixed:
        _deep_merge(raw, fixed)
    return raw


def enumerate_candidates(base: Dict[str, Any], tune_cfg,
                         chips: int,
                         global_batch: int) -> SearchSpace:
    """Walk the gated cartesian product and return candidates plus the
    structurally-pruned points (batch-indivisible worlds, elasticity
    rejections).  Raises AutotuneError when the space exceeds
    ``autotuning.max_candidates`` — an oversized search must be narrowed
    explicitly, not silently truncated."""
    space = SearchSpace()
    seen: Dict[str, str] = {}

    meshes = mesh_factorizations(chips, tune_cfg.mesh_model,
                                 tune_cfg.mesh_expert)
    if not meshes:
        raise AutotuneError(
            f"no (data, model, expert) factorization of {chips} chips "
            f"admits model sizes {list(tune_cfg.mesh_model)} x expert "
            f"sizes {list(tune_cfg.mesh_expert)}")
    multi_bucket = len(set(tune_cfg.stage3_bucket_sizes)) > 1
    elastic = base.get(C.ELASTICITY)

    # the 1-bit wire axis (docs/onebit.md) is gated at the BASE config:
    # gradient clipping / sparse gradients conflict with the tier for
    # every candidate, so an infeasible base yields ONE pruned record
    # instead of a trace-prune per enumerated point
    onebit_axis = tuple(sorted(set(bool(v) for v in tune_cfg.onebit)))
    if True in onebit_axis:
        reason = None
        if float(base.get(C.GRADIENT_CLIPPING) or 0.0) > 0:
            reason = (f"base config sets {C.GRADIENT_CLIPPING}="
                      f"{base.get(C.GRADIENT_CLIPPING)}; global-norm "
                      "clipping needs the dense gradient the 1-bit tier "
                      "removes")
        elif base.get(C.SPARSE_GRADIENTS):
            reason = (f"base config sets {C.SPARSE_GRADIENTS}; both "
                      "features rewrite the data-parallel grad "
                      "reduction")
        if reason is not None:
            space.n_enumerated += 1
            space.pruned.append(Pruned(name="1bit", stage="batch",
                                       reason=reason))
            onebit_axis = tuple(v for v in onebit_axis if not v) or \
                (False,)

    streamed_possible = 3 in tune_cfg.zero_stages and any(
        v == C.AUTOTUNING_STAGE3_VARIANT_STREAMED
        for v in tune_cfg.stage3_variants)

    for (data, model, expert) in meshes:
        dp_world = data * expert
        # hpZ divisibility depends only on (hpz, dp_world): check it
        # once per mesh so an indivisible group size yields ONE pruned
        # record, not one per unrelated knob combination
        mesh_hpzs = []
        for hpz in tune_cfg.hpz_group_sizes:
            if (streamed_possible and hpz and hpz > 1
                    and dp_world % hpz != 0):
                space.n_enumerated += 1
                space.pruned.append(Pruned(
                    name=f"hpz{hpz}-d{data}m{model}e{expert}",
                    stage="batch",
                    reason=f"hpz_group_size {hpz} does not divide dp "
                           f"world {dp_world}"))
            else:
                mesh_hpzs.append(hpz)
        splits = batch_splits(global_batch, dp_world,
                              tune_cfg.micro_batches)
        if not splits:
            space.n_enumerated += 1
            space.pruned.append(Pruned(
                name=f"d{data}m{model}e{expert}", stage="batch",
                reason=(f"global batch {global_batch} has no "
                        f"(micro, gas) split over dp world {dp_world}"
                        + (f" admitted by micro_batches="
                           f"{list(tune_cfg.micro_batches)}"
                           if tune_cfg.micro_batches else ""))))
            continue
        if elastic is not None:
            # elasticity batch-triple validity is a hard constraint: the
            # candidate must survive a fleet resize contract, not just
            # divide today's world (reuses the elasticity solver)
            from ..elasticity import (ElasticityError,
                                      compute_elastic_config)
            try:
                compute_elastic_config({C.ELASTICITY: elastic},
                                       world_size=dp_world)
            except ElasticityError as e:
                space.n_enumerated += 1
                space.pruned.append(Pruned(
                    name=f"d{data}m{model}e{expert}", stage="batch",
                    reason=f"elasticity rejects dp world {dp_world}: "
                           f"{e}"))
                continue

        for stage in tune_cfg.zero_stages:
            if stage == 3:
                variants = [
                    v == C.AUTOTUNING_STAGE3_VARIANT_STREAMED
                    for v in tune_cfg.stage3_variants]
            else:
                variants = [False]
            for streamed in variants:
                pmodes = tune_cfg.prefetch_modes if streamed else (None,)
                buckets = (tune_cfg.stage3_bucket_sizes if streamed
                           else (None,))
                # qwZ/hpZ modulate the streamed stage-3 weight gathers;
                # qgZ needs the stage >= 2 grad reduce-scatter; the
                # fused collective-matmul rides the streamed transports
                qwzs = tune_cfg.qwz_bits if streamed else (0,)
                hpzs = tuple(mesh_hpzs) if streamed else (0,)
                qgzs = tune_cfg.qgz_bits if stage >= 2 else (0,)
                fcms = (tuple(sorted(set(
                    tune_cfg.fused_collective_matmul)))
                    if streamed else (False,))
                for (pmode, bucket, micro_gas, qwz, qgz, hpz, fcm,
                     offload) in itertools.product(
                        pmodes, buckets, splits, qwzs, qgzs, hpzs, fcms,
                        tune_cfg.offload):
                    micro, gas = micro_gas
                    if (offload == C.AUTOTUNING_OFFLOAD_TIER_NVME
                            and not streamed):
                        # NVMe params = the ZeRO-Infinity layer-streaming
                        # engine; only the streamed stage-3 shape maps
                        continue
                    pdepths = (tune_cfg.nvme_prefetch_depths
                               if offload == C.AUTOTUNING_OFFLOAD_TIER_NVME
                               else (None,))
                    odepths = (tune_cfg.opt_pipeline_depths
                               if offload == C.AUTOTUNING_OFFLOAD_TIER_NVME
                               else (None,))
                    fuseds = (tune_cfg.fused
                              if offload == C.AUTOTUNING_OFFLOAD_TIER_NONE
                              else (False,))  # host-interactive fallback
                    # the 1-bit wire replaces the DATA-parallel grad
                    # allreduce of a resident stage <= 2 engine: ZeRO-3
                    # streaming has no whole-grad allreduce, offloaded
                    # optimizer state cannot host the packed momentum,
                    # non-data axes shard the grads it syncs, and qgZ
                    # already rewrites the same reduction
                    onebits = (onebit_axis
                               if (stage <= 2 and not streamed
                                   and offload ==
                                   C.AUTOTUNING_OFFLOAD_TIER_NONE
                                   and model == 1 and expert == 1
                                   and not qgz)
                               else (False,))
                    for pdepth, odepth, fused, onebit in \
                            itertools.product(pdepths, odepths,
                                              sorted(set(fuseds)),
                                              onebits):
                        space.n_enumerated += 1
                        name = _candidate_name(
                            stage, streamed, pmode, bucket, micro, gas,
                            data, model, expert, qwz, qgz, hpz, fused,
                            offload, pdepth, odepth, multi_bucket,
                            fcm=fcm, onebit=onebit)
                        cfg = _build_config(
                            base, stage=stage, streamed=streamed,
                            pmode=pmode, bucket=bucket, micro=micro,
                            gas=gas, data=data, model=model,
                            expert=expert, qwz=qwz, qgz=qgz, hpz=hpz,
                            fused=fused, offload=offload, pdepth=pdepth,
                            odepth=odepth, fixed=tune_cfg.fixed,
                            fcm=fcm, onebit=onebit)
                        import json as _json
                        key = _json.dumps(cfg, sort_keys=True)
                        if key in seen:
                            continue  # knob gating can fold two points
                        seen[key] = name
                        space.candidates.append(Candidate(
                            name=name, config=cfg,
                            knobs={
                                "zero_stage": stage,
                                "streamed": streamed,
                                "prefetch_mode": pmode,
                                "stage3_bucket": bucket,
                                "micro_batch": micro, "gas": gas,
                                "mesh": {"data": data, "model": model,
                                         "expert": expert},
                                "qwz_bits": qwz, "qgz_bits": qgz,
                                "hpz_group_size": hpz,
                                "fused_collective_matmul": bool(fcm),
                                "onebit": bool(onebit),
                                "fused_step": bool(fused),
                                "offload": offload,
                                "nvme_prefetch_depth": pdepth,
                                "opt_pipeline_depth": odepth,
                            }))
    if len(space.candidates) > tune_cfg.max_candidates:
        raise AutotuneError(
            f"search space has {len(space.candidates)} candidates, over "
            f"autotuning.max_candidates={tune_cfg.max_candidates} — "
            "narrow the axes (zero_stages, prefetch_modes, qwz_bits, "
            "micro_batches, ...) or raise the cap explicitly; the "
            "autotuner never truncates silently")
    return space


def nearest_divisor_worlds(global_batch: int, chips: int,
                           k: int = 3) -> List[int]:
    """Chip counts nearest to `chips` whose dp world divides the global
    batch — what an all-pruned-at-batch search suggests (reuses the
    elasticity module's nearest-world helper)."""
    from ..elasticity import nearest_valid_world_sizes
    divisors = [w for w in range(1, global_batch + 1)
                if global_batch % w == 0]
    return nearest_valid_world_sizes(divisors, chips, k=k)
