"""Static Program Auditor (docs/program_auditor.md).

Lints the engine's traced train-step programs — host-syncs in the hot
loop, donation misses, collective-lockstep divergence, dtype hazards,
comm-budget breaches — plus a runtime recompile guard.  Shared jaxpr
traversal (``jaxpr_walk``) also backs the flops profiler and the
low-bandwidth wire-byte accounting.
"""

from .auditor import (ProgramAuditor, audit_engine, engine_targets,
                      enforce, synthesize_sample_batch,
                      verify_multihost_lockstep)
from .findings import (ALL_RULES, AuditReport, Finding, ProgramAuditError,
                       RULE_COMM_BUDGET, RULE_DONATION, RULE_DTYPE_HAZARD,
                       RULE_HOST_SYNC, RULE_LOCKSTEP, RULE_RECOMPILE)
from .jaxpr_walk import (EqnCtx, SubJaxpr, as_jaxpr, aval_bytes,
                         eqn_scope, iter_eqns, sub_jaxprs)
from .recompile import RecompileGuard, batch_signature
from .rules import (ArgInfo, AuditTarget, STATIC_RULES, compare_lockstep,
                    lockstep_expectation_finding, step_wire_bytes)
from .signature import (collective_sequence, combine_signatures,
                        first_divergence, lockstep_signature,
                        signature_of_sequence)

__all__ = [
    "ALL_RULES", "ArgInfo", "AuditReport", "AuditTarget", "EqnCtx",
    "Finding", "ProgramAuditError", "ProgramAuditor", "RecompileGuard",
    "RULE_COMM_BUDGET", "RULE_DONATION", "RULE_DTYPE_HAZARD",
    "RULE_HOST_SYNC", "RULE_LOCKSTEP", "RULE_RECOMPILE", "STATIC_RULES",
    "SubJaxpr", "as_jaxpr", "audit_engine", "aval_bytes",
    "batch_signature", "collective_sequence", "combine_signatures",
    "compare_lockstep", "engine_targets", "enforce", "eqn_scope",
    "first_divergence", "iter_eqns", "lockstep_expectation_finding",
    "lockstep_signature",
    "signature_of_sequence", "step_wire_bytes", "sub_jaxprs",
    "synthesize_sample_batch", "verify_multihost_lockstep",
]
