"""Static Program Auditor (docs/program_auditor.md).

Lints the engine's traced train-step programs — host-syncs in the hot
loop, donation misses, collective-lockstep divergence, dtype hazards,
comm-budget breaches — plus a runtime recompile guard.  Shared jaxpr
traversal (``jaxpr_walk``) also backs the flops profiler and the
low-bandwidth wire-byte accounting.
"""

from .auditor import (ProgramAuditor, audit_engine, engine_targets,
                      enforce, synthesize_sample_batch,
                      verify_multihost_lockstep)
from .cost_model import (build_step_time_model, per_lane_predictions,
                         program_io_bytes)
from .findings import (ALL_RULES, AuditReport, Finding, ProgramAuditError,
                       RULE_COMM_BUDGET, RULE_DONATION, RULE_DTYPE_HAZARD,
                       RULE_HBM_BUDGET, RULE_HOST_SYNC, RULE_LOCKSTEP,
                       RULE_OVERLAP, RULE_RECOMPILE, RULE_SILENT_RESHARD,
                       RULE_SPMD_DIVERGENCE)
from .hlo_audit import (HloCollective, HloProgram, HloTargetAudit,
                        SpmdWaiver, audit_target_hlo, summarize_hlo,
                        walk_hlo_collectives)
from .jaxpr_walk import (EqnCtx, SubJaxpr, as_jaxpr, aval_bytes,
                         eqn_scope, iter_eqns, sub_jaxprs)
from .liveness import LivenessReport, estimate_liveness
from .overlap import (CollectiveOverlap, analyze_overlap,
                      overlap_efficiency, summarize_overlap)
from .recompile import RecompileGuard, batch_signature
from .rules import (ArgInfo, AuditTarget, STATIC_RULES, compare_lockstep,
                    lockstep_expectation_finding, step_wire_bytes)
from .signature import (collective_sequence, combine_signatures,
                        first_divergence, lockstep_signature,
                        signature_of_sequence)

__all__ = [
    "ALL_RULES", "ArgInfo", "AuditReport", "AuditTarget",
    "CollectiveOverlap", "EqnCtx", "Finding", "HloCollective",
    "HloProgram", "HloTargetAudit", "LivenessReport",
    "ProgramAuditError", "ProgramAuditor", "RecompileGuard",
    "RULE_COMM_BUDGET", "RULE_DONATION", "RULE_DTYPE_HAZARD",
    "RULE_HBM_BUDGET", "RULE_HOST_SYNC", "RULE_LOCKSTEP", "RULE_OVERLAP",
    "RULE_RECOMPILE", "RULE_SILENT_RESHARD", "RULE_SPMD_DIVERGENCE",
    "SpmdWaiver", "STATIC_RULES", "audit_target_hlo", "summarize_hlo",
    "walk_hlo_collectives",
    "SubJaxpr", "analyze_overlap", "as_jaxpr", "audit_engine",
    "aval_bytes",
    "batch_signature", "build_step_time_model", "collective_sequence",
    "combine_signatures",
    "compare_lockstep", "engine_targets", "enforce", "eqn_scope",
    "estimate_liveness", "first_divergence", "iter_eqns",
    "lockstep_expectation_finding", "lockstep_signature",
    "overlap_efficiency", "per_lane_predictions", "program_io_bytes",
    "signature_of_sequence", "step_wire_bytes", "sub_jaxprs",
    "summarize_overlap", "synthesize_sample_batch",
    "verify_multihost_lockstep",
]
