"""Static step-time model — a roofline lower bound for the traced step.

Every future perf PR ships with predicted-vs-measured provenance
(ROADMAP items 1 and 3): bench rows embed this lower bound next to the
lockstep signature and wire bytes, so "the fused step should be ~X ms"
is a number computed from the program, not a hope.  Three terms, each a
genuine lower bound:

  compute   total jaxpr flops (profiling/flops_profiler walk, scan trip
            counts multiplied in) at the configured peak
  memory    program I/O bytes — every input read and output written at
            least once, whatever XLA fuses in between — at HBM bandwidth
  comm      overlap-adjusted wire time: the hidden fraction of each
            collective (analysis/overlap.py) rides under compute, the
            exposed remainder is added on top

    t_lb = max(compute, memory, hidden_comm) + exposed_comm

The model is deliberately optimistic (true lower bound): measured step
time below it means the model's hardware constants are wrong; measured
far above it bounds how much the schedule is leaving on the table.
"""

from typing import Any, Dict, List

from .jaxpr_walk import as_jaxpr, aval_bytes
from .overlap import CollectiveOverlap


def program_io_bytes(closed_jaxpr) -> int:
    """Bytes the program must move through HBM at least once: every
    input read, every output written."""
    jx = as_jaxpr(closed_jaxpr)
    return (sum(aval_bytes(v) for v in jx.invars)
            + sum(aval_bytes(v) for v in jx.constvars)
            + sum(aval_bytes(v) for v in jx.outvars))


def per_lane_predictions(step_time: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a step-time payload (build_step_time_model output) into
    the per-lane form the runtime monitor's reconciliation consumes
    (monitor/reconcile.py) and bench rows embed: one entry per cost-model
    lane plus the binding term and the lower bound itself.  Single-sourced
    here so the static and measured halves can never disagree on lane
    names."""
    return {
        "compute": step_time["t_compute_s"],
        "memory": step_time["t_memory_s"],
        "hidden_comm": step_time["t_comm_hidden_s"],
        "exposed_comm": step_time["t_comm_exposed_s"],
        "bound": step_time["bound"],
        "predicted_step_time_lb_s": step_time["predicted_step_time_lb_s"],
    }


def build_step_time_model(total_flops: int, io_bytes: int,
                          records: List[CollectiveOverlap],
                          cfg) -> Dict[str, Any]:
    """Combine the three roofline terms into the report payload.

    ``records`` must already be the per-OPTIMIZER-STEP set (the auditor
    repeats the modular grad program's records gas times, matching the
    wire-byte accounting)."""
    peak_flops_s = cfg.hw_peak_tflops * 1e12
    hbm_bw = cfg.hw_hbm_gbps * 1e9
    wire_bw = cfg.hw_ici_gbps * 1e9

    t_compute = total_flops / peak_flops_s
    t_memory = io_bytes / hbm_bw
    hidden_bytes = sum(r.wire_bytes * r.mult * r.hidden_fraction
                       for r in records)
    exposed_bytes = sum(r.wire_bytes * r.mult * (1.0 - r.hidden_fraction)
                        for r in records)
    t_hidden = hidden_bytes / wire_bw
    t_exposed = exposed_bytes / wire_bw

    terms = {"compute": t_compute, "memory": t_memory,
             "hidden_comm": t_hidden}
    bound = max(terms, key=terms.get)
    t_lb = terms[bound] + t_exposed
    return {
        "flops_per_step": int(total_flops),
        "io_bytes_per_step": int(io_bytes),
        "wire_bytes_hidden": int(hidden_bytes),
        "wire_bytes_exposed": int(exposed_bytes),
        "t_compute_s": t_compute,
        "t_memory_s": t_memory,
        "t_comm_hidden_s": t_hidden,
        "t_comm_exposed_s": t_exposed,
        "bound": bound,
        "predicted_step_time_lb_s": t_lb,
        "hw": {"peak_tflops": cfg.hw_peak_tflops,
               "hbm_gbps": cfg.hw_hbm_gbps,
               "ici_gbps": cfg.hw_ici_gbps},
    }
