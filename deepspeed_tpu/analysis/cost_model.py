"""Static step-time model — a roofline lower bound for the traced step.

Every future perf PR ships with predicted-vs-measured provenance
(ROADMAP items 1 and 3): bench rows embed this lower bound next to the
lockstep signature and wire bytes, so "the fused step should be ~X ms"
is a number computed from the program, not a hope.  Three terms, each a
genuine lower bound:

  compute   total jaxpr flops (profiling/flops_profiler walk, scan trip
            counts multiplied in) at the configured peak
  memory    program I/O bytes — every input read and output written at
            least once, whatever XLA fuses in between — at HBM bandwidth
  comm      overlap-adjusted wire time: the hidden fraction of each
            collective (analysis/overlap.py) rides under compute, the
            exposed remainder is added on top.  Fused collective-matmul
            transports (per-tile wire under the producer/consumer GEMM,
            ops/collective_matmul.py) are hidden by construction — they
            price entirely in the hidden lane and are broken out as
            ``wire_bytes_fused`` for attribution
  swap      offload-tier traffic (params/optimizer state streamed from
            NVMe) at the MEASURED aio sweep ceiling, not HBM speed — a
            double-buffered stream (prefetch/pipeline depth >= 2) rides
            under compute like hidden comm, a serialized one is added
            on top like exposed comm

    t_lb = max(compute, memory, hidden_comm, swap_hidden)
           + exposed_comm + swap_exposed

The model is deliberately optimistic (true lower bound): measured step
time below it means the model's hardware constants are wrong; measured
far above it bounds how much the schedule is leaving on the table.
"""

from typing import Any, Dict, List, Optional

from .. import constants as C
from .jaxpr_walk import as_jaxpr, aval_bytes
from .overlap import CollectiveOverlap


def program_io_bytes(closed_jaxpr) -> int:
    """Bytes the program must move through HBM at least once: every
    input read, every output written."""
    jx = as_jaxpr(closed_jaxpr)
    return (sum(aval_bytes(v) for v in jx.invars)
            + sum(aval_bytes(v) for v in jx.constvars)
            + sum(aval_bytes(v) for v in jx.outvars))


def per_lane_predictions(step_time: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize a step-time payload (build_step_time_model output) into
    the per-lane form the runtime monitor's reconciliation consumes
    (monitor/reconcile.py) and bench rows embed: one entry per cost-model
    lane plus the binding term and the lower bound itself.  Single-sourced
    here so the static and measured halves can never disagree on lane
    names."""
    return {
        "compute": step_time["t_compute_s"],
        "memory": step_time["t_memory_s"],
        "hidden_comm": step_time["t_comm_hidden_s"],
        "exposed_comm": step_time["t_comm_exposed_s"],
        "swap": step_time.get("t_swap_s", 0.0),
        "bound": step_time["bound"],
        "predicted_step_time_lb_s": step_time["predicted_step_time_lb_s"],
    }


def hw_constants(cfg) -> Dict[str, float]:
    """The hardware model under the canonical names (C.ANALYSIS_HW_KEYS)
    — what the report payload publishes and what a calibration file
    overrides.  Single-sourced so the two sides can never drift."""
    return {C.ANALYSIS_HW_PEAK_TFLOPS: cfg.hw_peak_tflops,
            C.ANALYSIS_HW_HBM_GBPS: cfg.hw_hbm_gbps,
            C.ANALYSIS_HW_ICI_GBPS: cfg.hw_ici_gbps}


def swap_lane(zero_cfg, aio_cfg, param_bytes: int,
              opt_state_bytes: int) -> Optional[Dict[str, Any]]:
    """Per-step offload-tier traffic model for NVMe-backed configs.

    A streamed config's params never sit in HBM: the step must READ them
    from NVMe every forward (and again on the backward re-fetch) and
    WRITE the updated values back; an NVMe optimizer sweep reads and
    writes its state every step.  Pricing that traffic at HBM speed made
    a streamed config rank identically to a resident one — here it moves
    at the MEASURED aio sweep ceiling for the configured backend
    (runtime/zero/infinity.load_sweep_ceiling), falling back to a
    conservative default when no sweep artifact exists on this host.

    Returns None when neither offload target is NVMe (host-RAM tiers are
    treated as free, matching infinity.py's _HostFetch); otherwise a dict
    build_step_time_model folds into the lower bound: hidden time when
    the tier is double-buffered (prefetch_depth / pipeline_depth >= 2),
    exposed time when serialized.
    """
    op = zero_cfg.offload_param
    oo = zero_cfg.offload_optimizer
    nvme_param = op is not None and op.device == C.OFFLOAD_NVME_DEVICE
    nvme_opt = oo is not None and oo.device == C.OFFLOAD_NVME_DEVICE
    if not nvme_param and not nvme_opt:
        return None

    from ..runtime.zero.infinity import load_sweep_ceiling
    backend = aio_cfg.backend if aio_cfg is not None else (
        C.AIO_BACKEND_DEFAULT)
    ceiling = load_sweep_ceiling(backend)
    if ceiling is None and backend == C.AIO_BACKEND_AUTO:
        # auto resolves per-host; take the best measured backend rather
        # than no ceiling at all
        for b in (C.AIO_BACKEND_IO_URING, C.AIO_BACKEND_BATCHED,
                  C.AIO_BACKEND_THREADPOOL):
            ceiling = load_sweep_ceiling(b)
            if ceiling is not None:
                break
    if ceiling is not None:
        read_gbps = ceiling["read_gbps"]
        write_gbps = ceiling["write_gbps"]
        source = f"sweep_ceiling:{backend}"
    else:
        read_gbps = write_gbps = C.AUTOTUNE_NVME_FALLBACK_GBPS
        source = "fallback_default"

    t_hidden = t_exposed = 0.0
    read_bytes = write_bytes = 0
    if nvme_param:
        # forward read + backward re-fetch; updated params written back
        r, w = 2 * param_bytes, param_bytes
        t = r / (read_gbps * 1e9) + w / (write_gbps * 1e9)
        if op.prefetch_depth >= 2:
            t_hidden += t
        else:
            t_exposed += t
        read_bytes += r
        write_bytes += w
    if nvme_opt:
        # the sweep reads and writes every state leaf once per step
        r = w = opt_state_bytes
        t = r / (read_gbps * 1e9) + w / (write_gbps * 1e9)
        if getattr(oo, "pipeline_depth", 2) >= 2:
            t_hidden += t
        else:
            t_exposed += t
        read_bytes += r
        write_bytes += w
    return {"t_hidden_s": t_hidden, "t_exposed_s": t_exposed,
            "read_bytes": int(read_bytes), "write_bytes": int(write_bytes),
            "read_gbps": read_gbps, "write_gbps": write_gbps,
            "source": source}


def build_step_time_model(total_flops: int, io_bytes: int,
                          records: List[CollectiveOverlap],
                          cfg,
                          swap: Optional[Dict[str, Any]] = None,
                          hlo_only_wire_bytes: int = 0
                          ) -> Dict[str, Any]:
    """Combine the roofline terms into the report payload.

    ``records`` must already be the per-OPTIMIZER-STEP set (the auditor
    repeats the modular grad program's records gas times, matching the
    wire-byte accounting).  ``swap`` is an optional offload-tier traffic
    model (``swap_lane``): its hidden time joins the max() roofline, its
    exposed time is added on top like exposed comm.
    ``hlo_only_wire_bytes`` is per-step wire the HLO-level SPMD audit
    found that the jaxpr accounting never saw (compiler-inserted
    collectives; analysis/hlo_audit.py) — no overlap record exists for
    it, so it prices fully EXPOSED: the lower bound must stop
    undercounting the compiled program's wire."""
    peak_flops_s = cfg.hw_peak_tflops * 1e12
    hbm_bw = cfg.hw_hbm_gbps * 1e9
    wire_bw = cfg.hw_ici_gbps * 1e9

    t_compute = total_flops / peak_flops_s
    t_memory = io_bytes / hbm_bw
    hidden_bytes = sum(r.wire_bytes * r.mult * r.hidden_fraction
                       for r in records)
    exposed_bytes = sum(r.wire_bytes * r.mult * (1.0 - r.hidden_fraction)
                        for r in records)
    # fused collective-matmul transports (per-tile wire under the
    # producer/consumer GEMM) ride at hidden_fraction 1.0 — broken out
    # so the reconciliation can attribute a fused config's win to the
    # hidden-comm lane explicitly
    fused_bytes = sum(r.wire_bytes * r.mult for r in records
                      if getattr(r, "fused", False))
    t_hidden = hidden_bytes / wire_bw
    t_exposed = (exposed_bytes + hlo_only_wire_bytes) / wire_bw
    t_swap_hidden = float(swap["t_hidden_s"]) if swap else 0.0
    t_swap_exposed = float(swap["t_exposed_s"]) if swap else 0.0

    terms = {"compute": t_compute, "memory": t_memory,
             "hidden_comm": t_hidden, "swap": t_swap_hidden}
    bound = max(terms, key=terms.get)
    t_lb = terms[bound] + t_exposed + t_swap_exposed
    out = {
        "flops_per_step": int(total_flops),
        "io_bytes_per_step": int(io_bytes),
        "wire_bytes_hidden": int(hidden_bytes),
        "wire_bytes_exposed": int(exposed_bytes),
        "wire_bytes_fused": int(fused_bytes),
        "wire_bytes_hlo_only": int(hlo_only_wire_bytes),
        "t_compute_s": t_compute,
        "t_memory_s": t_memory,
        "t_comm_hidden_s": t_hidden,
        "t_comm_exposed_s": t_exposed,
        "t_swap_s": t_swap_hidden + t_swap_exposed,
        "bound": bound,
        "predicted_step_time_lb_s": t_lb,
        "hw": {"peak_tflops": cfg.hw_peak_tflops,
               "hbm_gbps": cfg.hw_hbm_gbps,
               "ici_gbps": cfg.hw_ici_gbps},
    }
    if swap is not None:
        out["swap"] = dict(swap)
    return out
