"""Donation-aware static HBM liveness — peak memory of a traced program
before it ever touches hardware.

ZeRO-Infinity-style memory planning (arXiv:2104.07857) starts from a
model of what is resident when; the gpt2_large OOM repaired in PR 1 was
exactly the class of bug a static liveness pass catches on CPU.  The
estimator walks the top-level equation list once, tracking the live set
over aval byte sizes:

  - non-donated program inputs stay live for the whole program (the
    caller keeps the buffer); donated inputs die at their last use;
  - at each equation, outputs whose (shape, dtype) match a
    simultaneously-dying releasable buffer are assumed aliased (XLA's
    input/output aliasing for donated args and scan carries) — they add
    no transient allocation;
  - sub-jaxprs (scan bodies, remat regions, shard_map regions)
    contribute their internal transient peak — the streamed-ZeRO-3
    gathered layer group materializes INSIDE the layer scan body, and
    must count;
  - the report names the top live buffers at the peak point, so an
    over-budget finding says WHAT is pinning HBM, not just how much.

This is an estimate of the program as written: XLA fusion can only
shrink it (fused intermediates never materialize), so the figure is a
safe planning ceiling for ``analysis.hbm_budget_mb``.
"""

from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .findings import Finding, RULE_HBM_BUDGET
from .jaxpr_walk import as_jaxpr, aval_bytes, eqn_scope, sub_jaxprs

_TOP_CONTRIBUTORS = 8


@dataclass
class LivenessReport:
    """Static peak-HBM estimate of one traced program."""
    peak_bytes: int = 0
    # (buffer label, bytes) of the largest live buffers at the peak
    contributors: List[Tuple[str, int]] = field(default_factory=list)
    peak_scope: str = ""
    # engine state resident during this program but not among its args
    # (the modular grad program runs while opt_state sits in HBM)
    resident_extra_bytes: int = 0

    @property
    def total_bytes(self) -> int:
        return self.peak_bytes + self.resident_extra_bytes


def _alias_key(v):
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return None
    return (tuple(aval.shape), str(getattr(aval, "dtype", "?")))


def _inner_extra(jx) -> int:
    """Transient peak of values defined INSIDE a sub-jaxpr (its inputs
    are views of outer buffers, already counted by the caller) — the
    same walker with the frame's inputs registered at zero cost."""
    return estimate_liveness(jx, _count_invars=False).peak_bytes


def estimate_liveness(closed_jaxpr,
                      donated_invars: Optional[List[bool]] = None,
                      invar_labels: Optional[List[str]] = None,
                      resident_extra_bytes: int = 0,
                      _count_invars: bool = True) -> LivenessReport:
    """Peak live bytes of one traced program, donation-aware.

    With ``_count_invars=False`` (sub-jaxpr frames) the inputs and
    consts are registered at zero bytes and non-releasable: they are
    views of outer buffers the caller already counts, must never alias
    this frame's outputs, and never free — the walk then measures only
    the frame's internally-defined transient peak."""
    jx = as_jaxpr(closed_jaxpr)
    eqns = list(jx.eqns)
    invars = list(jx.invars)
    consts = list(jx.constvars)
    n_in = len(invars)
    donated = list(donated_invars or [False] * n_in)
    donated += [False] * (n_in - len(donated))
    labels = list(invar_labels or [])
    labels += [f"arg{k}" for k in range(len(labels), n_in)]

    last_use: Dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            last_use[id(v)] = i
    for v in jx.outvars:
        last_use[id(v)] = len(eqns)

    # live registry: id -> (bytes, label, releasable)
    live: Dict[int, Tuple[int, str, bool]] = {}
    for k, v in enumerate(invars):
        if _count_invars:
            live[id(v)] = (aval_bytes(v), labels[k], bool(donated[k]))
        else:
            live[id(v)] = (0, labels[k], False)
    for k, v in enumerate(consts):
        live[id(v)] = ((aval_bytes(v), f"const{k}", True)
                       if _count_invars else (0, f"const{k}", False))
    live_total = sum(b for b, _, _ in live.values())

    report = LivenessReport(resident_extra_bytes=resident_extra_bytes)

    def snapshot(extra: int, extra_label: str, scope: str,
                 candidate: int) -> None:
        if candidate <= report.peak_bytes:
            return
        report.peak_bytes = candidate
        top = sorted(((lbl, b) for b, lbl, _ in live.values() if b > 0),
                     key=lambda kv: -kv[1])[:_TOP_CONTRIBUTORS]
        if extra > 0:
            top = sorted(top + [(extra_label, extra)],
                         key=lambda kv: -kv[1])[:_TOP_CONTRIBUTORS]
        report.contributors = top
        report.peak_scope = scope

    snapshot(0, "", "<entry>", live_total)

    for i, eqn in enumerate(eqns):
        scope = eqn_scope(eqn, "") or "<top>"
        sub_peak = max((_inner_extra(s.jaxpr) for s in sub_jaxprs(eqn)),
                       default=0)
        # releasable buffers dying at this equation can alias outputs of
        # the same shape/dtype (donated args, scan carries)
        dying_keys = Counter()
        dying_ids = set()
        for v in eqn.invars:
            ent = live.get(id(v))
            if (ent is not None and ent[2] and last_use.get(id(v)) == i
                    and id(v) not in dying_ids):
                key = _alias_key(v)
                if key is not None:
                    dying_keys[key] += 1
                dying_ids.add(id(v))
        alloc = 0
        avail = Counter(dying_keys)
        for ov in eqn.outvars:
            b = aval_bytes(ov)
            key = _alias_key(ov)
            if key is not None and avail[key] > 0:
                avail[key] -= 1
            else:
                alloc += b
        label = f"{eqn.primitive.name}@{scope}"
        snapshot(sub_peak, f"{label} internals", scope,
                 live_total + alloc + sub_peak)
        for ov in eqn.outvars:
            b = aval_bytes(ov)
            live[id(ov)] = (b, label, True)
            live_total += b
        for vid in dying_ids:
            live_total -= live.pop(vid)[0]
        for ov in eqn.outvars:
            if id(ov) in live and id(ov) not in last_use:
                live_total -= live.pop(id(ov))[0]
    return report


def hbm_budget_finding(peak_bytes: int, target_label: str,
                       contributors: List[Tuple[str, int]],
                       cfg) -> List[Finding]:
    """Error finding when the static peak exceeds
    ``analysis.hbm_budget_mb`` — named contributors, caught on CPU."""
    if cfg.hbm_budget_mb is None:
        return []
    budget = int(cfg.hbm_budget_mb * 1024 * 1024)
    if peak_bytes <= budget:
        return []
    top = "; ".join(f"{k}={v} B" for k, v in contributors[:3])
    return [Finding(
        rule=RULE_HBM_BUDGET, severity="error",
        message=(f"static peak HBM estimate {peak_bytes} B exceeds the "
                 f"{cfg.hbm_budget_mb} MiB budget ({budget} B) — top "
                 f"live buffers: {top}"),
        target=target_label,
        fix_hint=("donate the consumed state args, stream params "
                  "(zero stage 3 + max_live), remat activations, or "
                  "raise analysis.hbm_budget_mb if the growth is "
                  "intended"))]
