from .engine import InferenceEngine
