"""InferenceEngine — tensor-parallel fused-kernel serving with KV cache.

Reference: deepspeed/inference/engine.py:19 (InferenceEngine:
_create_model_parallel_group:88, _apply_injection_policy:130, quantized
checkpoint load :145, broadcast-kwargs forward :190) reached via
deepspeed.init_inference (__init__.py:232).

TPU-native architecture:
  - model surgery first: an HF torch model is converted to our stacked-
    pytree GPT2/BERT via module_inject (no in-place nn.Module swapping);
  - tensor parallelism is the mesh "model" axis + the model's
    param_partition_specs — mp_size just sizes that axis; GSPMD inserts
    the per-layer collectives the reference does inside its CUDA kernels;
  - generation is two compiled programs: prefill (flash attention over the
    prompt, emits the KV cache) and a lax.scan'd decode loop (one token per
    step against a static-shape cache) — single dispatch for the whole
    generation, no per-token Python;
  - int8: WeightQuantization rewrites matmul weights to (int8, scale)
    pairs dequantized in the gemm epilogue (HBM halves, MXU still bf16).
"""

import jax
import jax.numpy as jnp

from ..ops.transformer_inference import (DeepSpeedTransformerInference,
                                         KVCache)
from ..parallel import mesh as mesh_mod
from ..runtime.weight_quantizer import WeightQuantization
from ..utils.logging import log_dist


def _is_torch_module(model) -> bool:
    return hasattr(model, "named_parameters") and hasattr(model, "children")


class InferenceEngine:
    def __init__(self, model, mp_size: int = 1, mesh=None, checkpoint=None,
                 dtype=None, injection_policy=None, replace_method="auto",
                 quantization_setting=None, model_parameters=None,
                 moe_experts: int = 1, **kwargs):
        # ---- mesh (mp_size sizes the model axis) ---------------------- #
        if mesh is not None:
            ctx = mesh if isinstance(mesh, mesh_mod.MeshContext) else \
                mesh_mod.MeshContext(mesh)
            mesh_mod.set_mesh_context(ctx)
        else:
            ctx = mesh_mod.get_mesh_context(required=False)
            if ctx is None:
                ctx = mesh_mod.initialize_mesh(data=-1, model=mp_size)
        self.mesh_ctx = ctx
        self.mp_world_size = ctx.model_parallel_world_size
        if mp_size > 1 and self.mp_world_size != mp_size:
            raise ValueError(
                f"mp_size={mp_size} but the active mesh has a model axis of "
                f"{self.mp_world_size} — pass a mesh with model={mp_size} or "
                f"reset the mesh context first")

        # ---- injection (HF torch -> TPU model) ------------------------ #
        if _is_torch_module(model):
            from ..module_inject import replace_transformer_layer
            bf16 = dtype in (None, jnp.bfloat16, "bf16", "bfloat16")
            model, model_parameters = replace_transformer_layer(
                model, policy=injection_policy, bf16=bf16)
        self.module = model

        if model_parameters is None:
            model_parameters = getattr(model, "params", None)
        if model_parameters is None and checkpoint is not None:
            from ..runtime import checkpoint as ckpt_mod
            template = model.init_params(jax.random.PRNGKey(0))
            state, _, _ = ckpt_mod.load_checkpoint_state(
                checkpoint, None, {"module": template}, None)
            model_parameters = state["module"]
        if model_parameters is None:
            raise ValueError("inference needs model weights: pass an HF "
                             "model, model_parameters=, or checkpoint=")

        # ---- int8 quantization (reference :145) ----------------------- #
        self.quantization = None
        if quantization_setting:
            if isinstance(quantization_setting, tuple):
                mlp_extra, groups = quantization_setting
            else:
                mlp_extra, groups = False, int(quantization_setting)
            wq = WeightQuantization(mlp_extra_grouping=mlp_extra,
                                    quantize_groups=groups)
            model_parameters = dict(model_parameters)
            model_parameters["h"] = wq.quantize_stacked_layers(
                model_parameters["h"])
            self.quantization = wq
            log_dist(f"int8-quantized layer weights "
                     f"(groups={groups})", ranks=[0])

        # ---- TP placement --------------------------------------------- #
        specs = (model.param_partition_specs()
                 if hasattr(model, "param_partition_specs") else None)
        self.params = self._place(model_parameters, specs)

        cfg = model.config
        self.inf_layer = DeepSpeedTransformerInference(cfg.layer_config())
        self._fwd = jax.jit(self._forward_impl)
        self._generate_cache = {}
        log_dist(
            f"InferenceEngine: {type(model).__name__} mp={self.mp_world_size}"
            f" dtype={cfg.dtype.__name__}", ranks=[0])

    # ------------------------------------------------------------------ #
    def _place(self, params, specs):
        from ..ops.quant import QuantizedWeight

        def place_leaf(leaf, spec):
            if isinstance(leaf, QuantizedWeight):
                # int8 payloads replicate (scales are tiny; the qweight
                # could shard too, but spec trees target the fp layout)
                return QuantizedWeight(
                    jax.device_put(leaf.qweight, self.mesh_ctx.replicated()),
                    jax.device_put(leaf.scale, self.mesh_ctx.replicated()))
            sharding = (self.mesh_ctx.sharding(*spec) if spec is not None
                        else self.mesh_ctx.replicated())
            return jax.device_put(jnp.asarray(leaf), sharding)

        if specs is None:
            return jax.tree.map(
                lambda leaf: place_leaf(leaf, None), params,
                is_leaf=lambda x: isinstance(x, QuantizedWeight))
        # specs is a prefix tree of PartitionSpecs aligned with params
        flat_p = jax.tree_util.tree_flatten_with_path(
            params, is_leaf=lambda x: isinstance(x, QuantizedWeight))[0]
        spec_map = {}
        for path, spec in jax.tree_util.tree_flatten_with_path(
                specs, is_leaf=lambda x: x is None or hasattr(x, "index"))[0]:
            spec_map[jax.tree_util.keystr(path)] = spec
        out_leaves = []
        for path, leaf in flat_p:
            out_leaves.append(place_leaf(
                leaf, spec_map.get(jax.tree_util.keystr(path))))
        treedef = jax.tree_util.tree_structure(
            params, is_leaf=lambda x: isinstance(x, QuantizedWeight))
        return jax.tree_util.tree_unflatten(treedef, out_leaves)

    # ------------------------------------------------------------------ #
    def _forward_impl(self, params, *args, **kwargs):
        if hasattr(self.module, "logits"):
            return self.module.logits(params, *args, deterministic=True,
                                      **kwargs)
        if hasattr(self.module, "hidden_states"):
            return self.module.hidden_states(params, *args,
                                             deterministic=True, **kwargs)
        return self.module(params, None, *args, **kwargs)

    def forward(self, *args, **kwargs):
        """Logits/hidden-states forward (reference engine.py:190)."""
        return self._fwd(self.params, *args, **kwargs)

    __call__ = forward

    # ------------------------------------------------------------------ #
    # generation (causal models)
    # ------------------------------------------------------------------ #
    def _gen_fn(self, prompt_len: int, max_new: int):
        key = (prompt_len, max_new)
        if key in self._generate_cache:
            return self._generate_cache[key]
        model = self.module  # causal LM with embed/head_logits (GPT2Model)
        cfg = model.config
        layer = self.inf_layer
        n_layers = cfg.num_layers
        heads = cfg.num_heads
        head_dim = cfg.hidden_size // heads
        max_len = prompt_len + max_new
        embed = model.embed
        head_logits = model.head_logits
        # same policy object as training: the config's use_scan property
        # (GPT2Config/BertConfig); fall back to the shared resolver for
        # configs that predate it
        use_scan = getattr(cfg, "use_scan", None)
        if use_scan is None:
            from ..models.layer_stack import resolve_use_scan
            use_scan = resolve_use_scan(getattr(cfg, "scan_layers", None),
                                        n_layers)

        def generate(params, input_ids, rng, temperature):
            b = input_ids.shape[0]

            def zero_cache():
                return jnp.zeros((b, heads, max_len, head_dim), cfg.dtype)

            # Layer-stack execution mirrors training (models/layer_stack.py):
            # scan carries STACKED [L, ...] caches; the unrolled variant
            # keeps a per-layer tuple so no step ever restacks the cache.
            # ---- prefill over the whole prompt ------------------------ #
            h = embed(params, input_ids, 0)

            if use_scan:
                stacked = KVCache(
                    jnp.zeros((n_layers,) + zero_cache().shape, cfg.dtype),
                    jnp.zeros((n_layers,) + zero_cache().shape, cfg.dtype))

                def prefill_body(carry, xs):
                    lp, ck, cv = xs
                    out, cache = layer.prefill(lp, carry, KVCache(ck, cv))
                    return out, (cache.k, cache.v)

                h, (ks, vs) = jax.lax.scan(
                    prefill_body, h, (params["h"], stacked.k, stacked.v))
                caches = KVCache(ks, vs)
            else:
                caches = []
                for i in range(n_layers):
                    lp = jax.tree.map(lambda a: a[i], params["h"])
                    h, cache = layer.prefill(
                        lp, h, KVCache(zero_cache(), zero_cache()))
                    caches.append((cache.k, cache.v))
                caches = tuple(caches)
            logits = head_logits(params, h[:, -1:, :])

            def sample(logits, r):
                logits = logits[:, -1, :]
                return jax.lax.cond(
                    temperature > 0,
                    lambda: jax.random.categorical(
                        r, logits / jnp.maximum(temperature, 1e-6), axis=-1),
                    lambda: jnp.argmax(logits, axis=-1))

            rng, r0 = jax.random.split(rng)
            tok0 = sample(logits, r0)

            # ---- decode: scan over new tokens ------------------------ #
            def decode_step(carry, r):
                caches, tok, pos = carry
                x = embed(params, tok[:, None], pos)

                if use_scan:
                    def layer_body(carry_h, xs):
                        lp, ck, cv = xs
                        out, cache = layer.decode(
                            lp, carry_h, KVCache(ck, cv), pos)
                        return out, (cache.k, cache.v)

                    h, (ks, vs) = jax.lax.scan(
                        layer_body, x, (params["h"], caches.k, caches.v))
                    caches = KVCache(ks, vs)
                else:
                    h, new_caches = x, []
                    for i in range(n_layers):
                        lp = jax.tree.map(lambda a: a[i], params["h"])
                        h, cache = layer.decode(
                            lp, h, KVCache(*caches[i]), pos)
                        new_caches.append((cache.k, cache.v))
                    caches = tuple(new_caches)
                logits = head_logits(params, h)
                nxt = sample(logits, r)
                return (caches, nxt, pos + 1), tok

            # tok0 is generated token #1; each of the max_new-1 scan steps
            # feeds the previous token and samples the next.
            rs = jax.random.split(rng, max_new - 1)
            (_, last, _), toks = jax.lax.scan(
                decode_step, (caches, tok0, jnp.int32(prompt_len)), rs)
            return jnp.concatenate(
                [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)

        fn = jax.jit(generate)
        self._generate_cache[key] = fn
        return fn

    def generate(self, input_ids, max_new_tokens: int = 16,
                 temperature: float = 0.0, rng=None):
        """Greedy (temperature=0) or sampled generation.  Returns the
        generated tokens [B, max_new_tokens] (prompt not included)."""
        if not hasattr(self.module, "logits") or not getattr(
                self.module.config, "tie_word_embeddings", True) and \
                "lm_head" not in self.params:
            raise ValueError("generate() needs a causal LM model")
        input_ids = jnp.asarray(input_ids)
        total = int(input_ids.shape[1]) + int(max_new_tokens)
        n_pos = getattr(self.module.config, "n_positions", None)
        if n_pos is not None and total > n_pos:
            raise ValueError(
                f"prompt ({input_ids.shape[1]}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the model's "
                f"n_positions ({n_pos})")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        fn = self._gen_fn(int(input_ids.shape[1]), int(max_new_tokens))
        return fn(self.params, input_ids, rng,
                  jnp.float32(temperature))
