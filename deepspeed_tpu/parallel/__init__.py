from .mesh import (DATA_AXIS, EXPERT_AXIS, MESH_AXES, MODEL_AXIS, PIPE_AXIS,
                   SEQ_AXIS, ZERO_AXES, MeshContext, get_mesh_context,
                   initialize_mesh, reset_mesh_context, resolve_mesh_shape,
                   set_mesh_context)
from . import groups
from .sequence import (ring_attention, ring_attention_inner,
                       sequence_parallel_attention, sp_attention_inner,
                       ulysses_attention, ulysses_attention_inner)
