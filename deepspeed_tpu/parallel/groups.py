"""Parallel-group registry with the reference's groups API shape.

Reference: deepspeed/utils/groups.py — initialize():71 with scenarios
D / E+D / M / E+D+M (:23-49) and the get_* accessors (:262-399).  On TPU a
"group" is a tuple of mesh axis names: collectives take axis names, not
communicator handles, so the accessors return the axis names to reduce over
plus sizes/ranks derived from the mesh.
"""

from typing import Tuple

from . import mesh as mesh_mod
from .mesh import (DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                   MeshContext)
from ..utils.logging import log_dist


def initialize(ep_size: int = 1, mpu=None, model_parallel_size: int = 1,
               pipe_parallel_size: int = 1, seq_parallel_size: int = 1,
               devices=None) -> MeshContext:
    """Create the global mesh covering the reference's four scenarios:

    - D:      data parallel only                        (ep=mp=pp=1)
    - E+D:    expert + data parallel                    (ep>1)
    - M:      model parallel                            (mp>1)
    - E+D+M:  expert + data + model parallel

    `mpu` parity: if a caller passes an object exposing
    get_model_parallel_world_size(), honor it (reference: groups.py:87-113).
    """
    if mpu is not None and hasattr(mpu, "get_model_parallel_world_size"):
        model_parallel_size = mpu.get_model_parallel_world_size()
    ctx = mesh_mod.initialize_mesh(pipe=pipe_parallel_size, data=-1,
                                   expert=ep_size, seq=seq_parallel_size,
                                   model=model_parallel_size, devices=devices)
    log_dist(f"initialized mesh {dict(ctx.mesh.shape)}", ranks=[0])
    return ctx


def is_initialized() -> bool:
    return mesh_mod.get_mesh_context(required=False) is not None


def _ctx() -> MeshContext:
    return mesh_mod.get_mesh_context()


# --- group accessors: return the mesh axis names a collective reduces over ---
def get_data_parallel_group() -> Tuple[str, ...]:
    """Dense (non-expert) params reduce over data AND expert axes —
    the reference's data-parallel group spans the full DP world."""
    return (DATA_AXIS, EXPERT_AXIS)


def get_expert_parallel_group() -> Tuple[str, ...]:
    return (EXPERT_AXIS,)


def get_expert_data_parallel_group() -> Tuple[str, ...]:
    """Expert params replicate over the leftover data axis only."""
    return (DATA_AXIS,)


def get_model_parallel_group() -> Tuple[str, ...]:
    return (MODEL_AXIS,)


def get_pipe_parallel_group() -> Tuple[str, ...]:
    return (PIPE_AXIS,)


def get_sequence_parallel_group() -> Tuple[str, ...]:
    return (SEQ_AXIS,)


# --- world sizes ---
def get_data_parallel_world_size() -> int:
    return _ctx().data_parallel_world_size


def get_expert_parallel_world_size() -> int:
    return _ctx().expert_parallel_world_size


def get_expert_data_parallel_world_size() -> int:
    return _ctx().expert_data_parallel_world_size


def get_model_parallel_world_size() -> int:
    return _ctx().model_parallel_world_size


def get_pipe_parallel_world_size() -> int:
    return _ctx().pipe_parallel_world_size


def get_sequence_parallel_world_size() -> int:
    return _ctx().seq_parallel_world_size


def get_world_size() -> int:
    return _ctx().world_size


# --- ranks: meaningful under multi-process (one process per host); in a
# single-process SPMD program the "rank" of the calling process is the index of
# its first addressable device along the axis. ---
def _axis_rank(axis: str) -> int:
    import jax
    ctx = _ctx()
    dev = jax.local_devices()[0]
    coords = {}
    import numpy as np
    idx = np.argwhere(ctx.mesh.devices == dev)
    if idx.size == 0:
        return 0
    for name, i in zip(ctx.mesh.axis_names, idx[0]):
        coords[name] = int(i)
    return coords.get(axis, 0)


def get_data_parallel_rank() -> int:
    # The dense DP group spans data×expert, so the rank folds both coords
    # (expert innermost, matching the mesh axis order).
    return _axis_rank(DATA_AXIS) * _ctx().expert_parallel_world_size + _axis_rank(
        EXPERT_AXIS)


def get_model_parallel_rank() -> int:
    return _axis_rank(MODEL_AXIS)


def get_expert_parallel_rank() -> int:
    return _axis_rank(EXPERT_AXIS)
