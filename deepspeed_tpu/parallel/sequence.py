"""Sequence/context parallelism over the ``seq`` mesh axis.

The reference (DeepSpeed v0.5.2) predates sequence parallelism — its
long-context story is block-sparse attention + activation partitioning
(SURVEY.md §5).  On TPU, long context is first-class: the sequence dimension
is sharded over the ``seq`` mesh axis and attention runs either as

  * **ring attention** — K/V shards rotate around the ring via
    ``lax.ppermute`` while each device accumulates its queries' output with a
    flash-style online softmax.  Per-step comms overlap with the block
    attention compute; HBM never holds more than one remote K/V shard.
    (Liu et al., "Ring Attention with Blockwise Transformers".)
  * **Ulysses-style all-to-all** — ``lax.all_to_all`` reshards
    sequence-sharded Q/K/V to head-sharded, runs *exact* local attention on
    the full sequence per head group, and reshards back (DeepSpeed-Ulysses,
    arXiv:2309.14509 — later-era DeepSpeed; here built TPU-native).

Both are exact (not approximations) and bit-compatible with dense attention
up to fp32 accumulation order.

Layout convention matches deepspeed_tpu.ops.flash_attention: [B, H, S, D].
The ``*_inner`` functions run inside an existing ``shard_map`` (manual-mesh
code such as the pipeline engine); the public wrappers shard_map themselves
over the global mesh for GSPMD-style callers.
"""

import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import SEQ_AXIS, MeshContext, get_mesh_context

# Finite "minus infinity" for masked logits; see ops.flash_attention.
from ..ops.flash_attention import DEFAULT_MASK_VALUE, flash_attention


def _axis_size(axis_name: str) -> int:
    # Static under shard_map: psum of a python literal constant-folds.
    return lax.psum(1, axis_name)


# --------------------------------------------------------------------------- #
# Ring attention
# --------------------------------------------------------------------------- #
def ring_attention_inner(q, k, v, axis_name: str = SEQ_AXIS,
                         causal: bool = False,
                         sm_scale: Optional[float] = None):
    """Ring attention over ``axis_name``; call inside shard_map.

    q, k, v: [B, H, S_local, D] — the local sequence shard.  Global sequence
    order follows the ring index (shard i holds positions
    [i*S_local, (i+1)*S_local)).  Returns the local output shard [B,H,S,D].
    """
    sp = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    orig_dtype = q.dtype
    b, h, q_len, d = q.shape
    k_len = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    q_pos = idx * q_len + lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)

    # Ring rotation: shard j hands its current K/V block to shard j+1, so at
    # step i the block on shard `idx` originated on shard (idx - i) % sp.
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def block(m, denom, acc, k_cur, v_cur, src):
        """Flash-style online-softmax update with one remote K/V block.

        Matmuls run in the INPUT dtype with fp32 accumulation (bf16 inputs
        ride the MXU fast path; fp32 inputs keep exact fp32 math — an
        upcast-first einsum would force the slow fp32 matmul passes even
        for bf16 callers).  Softmax statistics are always fp32."""
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * k_len + lax.broadcasted_iota(
                jnp.int32, (q_len, k_len), 1)
            valid = (k_pos <= q_pos)[None, None]
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            # Explicit zero for masked entries: when an entire block is
            # masked, s == m_new == DEFAULT_MASK_VALUE and exp(0)=1 would
            # otherwise pollute the running sum.
            p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)
        denom = denom * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        return m_new, denom, acc

    def step(carry, i):
        # Rotate first, then consume: the local (i=0) block is handled
        # outside the loop, so only sp-1 ppermutes ride the ring.
        k_cur, v_cur, m, denom, acc = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        m, denom, acc = block(m, denom, acc, k_cur, v_cur, (idx - i) % sp)
        return (k_cur, v_cur, m, denom, acc), None

    m0 = jnp.full((b, h, q_len), DEFAULT_MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, h, q_len), jnp.float32)
    a0 = jnp.zeros((b, h, q_len, d), jnp.float32)
    m0, l0, a0 = block(m0, l0, a0, k, v, idx)
    (_, _, _, denom, acc), _ = lax.scan(step, (k, v, m0, l0, a0),
                                    jnp.arange(1, sp))
    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return out.astype(orig_dtype)


# --------------------------------------------------------------------------- #
# Ulysses (all-to-all head↔sequence reshard)
# --------------------------------------------------------------------------- #
def ulysses_attention_inner(q, k, v, axis_name: str = SEQ_AXIS,
                            causal: bool = False,
                            sm_scale: Optional[float] = None,
                            attn_fn: Optional[Callable] = None):
    """Ulysses-style attention; call inside shard_map.

    q, k, v: [B, H, S_local, D].  Requires H % seq_parallel_size == 0.
    all_to_all turns the sequence sharding into a head sharding, local exact
    attention (flash) runs on the full sequence, and the inverse all_to_all
    restores sequence sharding.  Two all-to-alls ride ICI per call — cheaper
    than a ring when S_local is small relative to head count.
    """
    sp = _axis_size(axis_name)
    h = q.shape[1]
    if h % sp != 0:
        raise ValueError(f"Ulysses needs heads ({h}) divisible by the "
                         f"sequence-parallel degree ({sp})")
    attn = attn_fn or (lambda *a: flash_attention(a[0], a[1], a[2],
                                                  causal=causal,
                                                  sm_scale=sm_scale))
    # [B, H, S/sp, D] -> [B, H/sp, S, D]
    qg, kg, vg = (lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                 tiled=True) for x in (q, k, v))
    og = attn(qg, kg, vg)
    # [B, H/sp, S, D] -> [B, H, S/sp, D]
    return lax.all_to_all(og, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def allgather_attention_inner(q, k, v, axis_name: str = SEQ_AXIS,
                              causal: bool = False,
                              sm_scale: Optional[float] = None):
    """All-gather-KV attention; call inside shard_map — the DIVERGENT-
    BRANCH-SAFE sequence-parallel variant.

    q, k, v: [B, H, S_local, D].  K/V are all-gathered over ``axis_name``
    via a zero-pad + ``lax.psum`` (psum is the one collective that
    tolerates living inside ``lax.cond`` branches whose predicates differ
    across OTHER mesh axes: groups whose members all skip the branch
    simply never rendezvous, while ppermute/all_to_all wedge the whole
    collective — measured on the 8-device sim, round 5).  Each device
    then runs exact fp32-softmax attention for its LOCAL query rows
    against the full K/V.  Used by the gated 1F1B pipeline executor,
    whose per-stage branches are exactly that divergent context
    (runtime/pipe/one_f_one_b.py); ring/Ulysses stay the better choice
    everywhere collectives run unconditionally.  FLOPs match ring
    (q_local × K_full); memory holds one full K/V per device instead of
    ring's single remote block.
    """
    sp = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    orig_dtype = q.dtype
    b, h, q_len, d = q.shape
    k_len = k.shape[2]
    s_full = k_len * sp
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    def gather(x):
        z = jnp.zeros((b, h, s_full, d), x.dtype)
        z = lax.dynamic_update_slice_in_dim(z, x, idx * k_len, 2)
        return lax.psum(z, axis_name)

    k_full, v_full = gather(k), gather(v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_full,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = idx * q_len + lax.broadcasted_iota(
            jnp.int32, (q_len, s_full), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (q_len, s_full), 1)
        s = jnp.where((k_pos <= q_pos)[None, None], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_full.dtype), v_full,
                     preferred_element_type=jnp.float32)
    return out.astype(orig_dtype)


def sp_attention_inner(q, k, v, mode: str = "ring", axis_name: str = SEQ_AXIS,
                       causal: bool = False, sm_scale: Optional[float] = None):
    """Mode-dispatched sequence-parallel attention for shard_map callers."""
    if mode == "ring":
        return ring_attention_inner(q, k, v, axis_name, causal, sm_scale)
    if mode == "ulysses":
        return ulysses_attention_inner(q, k, v, axis_name, causal, sm_scale)
    if mode == "allgather":
        return allgather_attention_inner(q, k, v, axis_name, causal, sm_scale)
    raise ValueError(f"Unknown sequence-parallel mode {mode!r}")


# --------------------------------------------------------------------------- #
# Public GSPMD-facing wrappers
# --------------------------------------------------------------------------- #
def _wrap(inner, q, k, v, mesh_ctx: Optional[MeshContext]):
    ctx = mesh_ctx or get_mesh_context()
    spec = P(None, None, SEQ_AXIS, None)
    fn = jax.shard_map(inner, mesh=ctx.mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   mesh_ctx: Optional[MeshContext] = None):
    """Ring attention on globally-shaped [B,H,S,D] arrays; S is sharded over
    the mesh ``seq`` axis (other axes replicated by this wrapper)."""
    inner = functools.partial(ring_attention_inner, axis_name=SEQ_AXIS,
                              causal=causal, sm_scale=sm_scale)
    return _wrap(inner, q, k, v, mesh_ctx)


def ulysses_attention(q, k, v, causal: bool = False,
                      sm_scale: Optional[float] = None,
                      mesh_ctx: Optional[MeshContext] = None):
    """Ulysses attention on globally-shaped [B,H,S,D] arrays."""
    inner = functools.partial(ulysses_attention_inner, axis_name=SEQ_AXIS,
                              causal=causal, sm_scale=sm_scale)
    return _wrap(inner, q, k, v, mesh_ctx)


def sequence_parallel_attention(q, k, v, mode: str = "auto",
                                causal: bool = False,
                                sm_scale: Optional[float] = None,
                                mesh_ctx: Optional[MeshContext] = None):
    """Config-driven entry: mode from DeepSpeedConfig.sequence_parallel_config.

    "auto" picks Ulysses when the head count divides evenly by the seq degree
    (exact attention + fewer collectives), else ring.
    """
    ctx = mesh_ctx or get_mesh_context()
    sp = ctx.seq_parallel_world_size
    if sp == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if mode == "auto":
        mode = "ulysses" if q.shape[1] % sp == 0 else "ring"
    if mode == "ring":
        return ring_attention(q, k, v, causal, sm_scale, ctx)
    if mode == "ulysses":
        return ulysses_attention(q, k, v, causal, sm_scale, ctx)
    raise ValueError(f"Unknown sequence-parallel mode {mode!r}")
