"""Named-axis device mesh — the TPU-native communication substrate.

Plays the role of the reference's process-group bookkeeping
(deepspeed/utils/groups.py:71 initialize, deepspeed/runtime/pipe/topology.py)
— but instead of NCCL communicators, parallelism axes are named axes of a
`jax.sharding.Mesh`, and collectives are XLA collectives (psum / all_gather /
psum_scatter / all_to_all / ppermute) over those axes, riding ICI within a
slice and DCN across slices.

Axis layout (outer → inner): ``pipe, data, expert, seq, model``.
- ``model`` innermost: tensor-parallel collectives are per-layer and
  latency-bound, so they get the closest neighbors on ICI.
- ``pipe`` outermost: stage p2p is bandwidth-light (one activation per
  microbatch boundary).
- ``expert`` subdivides what would otherwise be data-parallel replicas, exactly
  like the reference's expert-parallel groups carved out of the DP world
  (deepspeed/utils/groups.py:23-49 scenarios).
"""

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("pipe", "data", "expert", "seq", "model")

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"

# ZeRO shards over every axis that carries (expert-)data parallelism.
ZERO_AXES = (DATA_AXIS, EXPERT_AXIS)


@dataclass(frozen=True)
class MeshShape:
    pipe: int = 1
    data: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1

    @property
    def total(self) -> int:
        return self.pipe * self.data * self.expert * self.seq * self.model

    def as_tuple(self):
        return (self.pipe, self.data, self.expert, self.seq, self.model)


def resolve_mesh_shape(n_devices: int, pipe: int = 1, data: int = -1,
                       expert: int = 1, seq: int = 1, model: int = 1) -> MeshShape:
    """Resolve a mesh spec where exactly one axis may be -1 (= fill)."""
    sizes = {"pipe": pipe, "data": data, "expert": expert, "seq": seq,
             "model": model}
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"Only one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed mesh axes {sizes}")
        sizes[wild[0]] = n_devices // fixed
    shape = MeshShape(**sizes)
    if shape.total != n_devices:
        raise ValueError(
            f"Mesh shape {shape} needs {shape.total} devices, have {n_devices}")
    return shape


class MeshContext:
    """Owns the device mesh and answers the questions the reference answers via
    groups.get_*_parallel_{rank,world_size,group} (utils/groups.py:262-399)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    # -- factory ------------------------------------------------------- #
    @staticmethod
    def create(pipe: int = 1, data: int = -1, expert: int = 1, seq: int = 1,
               model: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> "MeshContext":
        devices = list(devices if devices is not None else jax.devices())
        shape = resolve_mesh_shape(len(devices), pipe, data, expert, seq, model)
        dev_array = np.asarray(devices).reshape(shape.as_tuple())
        return MeshContext(Mesh(dev_array, MESH_AXES))

    @staticmethod
    def from_config(mesh_config, devices=None) -> "MeshContext":
        return MeshContext.create(
            pipe=mesh_config.pipe, data=mesh_config.data,
            expert=mesh_config.expert, seq=mesh_config.seq,
            model=mesh_config.model, devices=devices)

    # -- sizes --------------------------------------------------------- #
    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    @property
    def data_parallel_world_size(self) -> int:
        # Expert axis carves its replicas out of the DP world, so plain-dense
        # data parallelism spans data×expert (reference scenario E+D).
        return self.axis_size(DATA_AXIS) * self.axis_size(EXPERT_AXIS)

    @property
    def expert_parallel_world_size(self) -> int:
        return self.axis_size(EXPERT_AXIS)

    @property
    def expert_data_parallel_world_size(self) -> int:
        return self.axis_size(DATA_AXIS)

    @property
    def model_parallel_world_size(self) -> int:
        return self.axis_size(MODEL_AXIS)

    @property
    def pipe_parallel_world_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    @property
    def seq_parallel_world_size(self) -> int:
        return self.axis_size(SEQ_AXIS)

    @property
    def world_size(self) -> int:
        return self.mesh.size

    # -- shardings ----------------------------------------------------- #
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def data_sharding(self, *trailing) -> NamedSharding:
        """Batch-dim sharding over every data-carrying axis."""
        return self.sharding((DATA_AXIS, EXPERT_AXIS), *trailing)

    def __repr__(self):
        return f"MeshContext({dict(self.mesh.shape)})"


# ---------------------------------------------------------------------- #
# Global mesh registry — the analog of deepspeed.utils.groups' module-level
# group singletons (utils/groups.py:51-68).
# ---------------------------------------------------------------------- #
_MESH_CTX: Optional[MeshContext] = None


def initialize_mesh(pipe: int = 1, data: int = -1, expert: int = 1, seq: int = 1,
                    model: int = 1, devices=None) -> MeshContext:
    global _MESH_CTX
    _MESH_CTX = MeshContext.create(pipe=pipe, data=data, expert=expert, seq=seq,
                                   model=model, devices=devices)
    return _MESH_CTX


def set_mesh_context(ctx: MeshContext) -> None:
    global _MESH_CTX
    _MESH_CTX = ctx


def get_mesh_context(required: bool = True) -> Optional[MeshContext]:
    if _MESH_CTX is None and required:
        raise RuntimeError(
            "Mesh is not initialized — call deepspeed_tpu.initialize(...) or "
            "deepspeed_tpu.initialize_mesh(...) first")
    return _MESH_CTX


def reset_mesh_context() -> None:
    global _MESH_CTX
    _MESH_CTX = None
