"""deepspeed_tpu — a TPU-native large-model training framework with the
capability surface of DeepSpeed v0.5.2 (reference: deepspeed/__init__.py),
built on JAX/XLA/pjit/Pallas.

Public entry points mirror the reference:
  - initialize(...)        (reference: deepspeed/__init__.py:61)
  - init_inference(...)    (reference: deepspeed/__init__.py:232)
  - add_config_arguments() (reference: deepspeed/__init__.py:216)
"""

from . import compat  # noqa: F401  (installs jax API shims; must be first)
from .version import __version__
from .config import DeepSpeedConfig, DeepSpeedConfigError
from .parallel import (MeshContext, get_mesh_context, initialize_mesh,
                       reset_mesh_context)
from .parallel import groups
from .utils import logger, log_dist
from .utils.distributed import init_distributed
from . import moe
from .runtime import zero  # deepspeed.zero.Init / GatheredParameters parity


def initialize(args=None, model=None, config=None, config_params=None,
               optimizer=None, model_parameters=None, lr_scheduler=None,
               mesh=None, dist_init_required=None, collate_fn=None,
               training_data=None, mpu=None, rng=None, example_input=None,
               param_partition_specs=None):
    """Create a TPU-backed training engine (reference: deepspeed/__init__.py:61).

    Returns (engine, optimizer, dataloader, lr_scheduler) like the reference.
    `model` is a flax module or an apply-style callable; see
    deepspeed_tpu.runtime.engine for details.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.pipe.module import PipelineModule

    cfg = config if config is not None else config_params
    if cfg is None and args is not None:
        cfg = getattr(args, "deepspeed_config", None)
    if cfg is None:
        raise DeepSpeedConfigError("DeepSpeed requires a config (dict or path)")

    # ZeRO-Infinity param offload: layer-streaming engine for models whose
    # params should never be fully HBM-resident (reference: stage3 +
    # offload_param — stage3.py:932; see runtime/zero/infinity.py).
    # Parse the zero block through ZeroConfig so legacy keys
    # (cpu_offload_params) and device defaults dispatch identically to the
    # full config parse.
    from .config import ZeroConfig
    from .config_utils import load_config_dict
    raw = cfg if isinstance(cfg, dict) else (
        load_config_dict(cfg) if isinstance(cfg, str) else
        getattr(cfg, "_param_dict", {}))
    zc = ZeroConfig.from_dict(raw.get("zero_optimization"))
    op = zc.offload_param
    if op is not None and (op.device or "none") != "none":
        if not hasattr(model, "layerwise_api"):
            raise ValueError(
                "zero_optimization.offload_param requires a model exposing "
                "layerwise_api() (streaming groups); GPT2Model does")
        from .runtime.zero.infinity import ZeroInfinityEngine
        engine = ZeroInfinityEngine(
            model=model, config=cfg, model_parameters=model_parameters,
            optimizer=optimizer, lr_scheduler=lr_scheduler, mesh=mesh,
            rng=rng, mpu=mpu, training_data=training_data,
            collate_fn=collate_fn)
        return (engine, engine.optimizer, engine.training_dataloader,
                engine.lr_scheduler)

    if isinstance(model, PipelineModule):
        from .runtime.pipe.engine import PipelineEngine
        if param_partition_specs is not None:
            raise ValueError(
                "param_partition_specs is not supported with a "
                "PipelineModule — declare specs on the stage layers "
                "(PipeLayer.param_partition_specs) instead")
        engine = PipelineEngine(model=model, config=cfg, optimizer=optimizer,
                                lr_scheduler=lr_scheduler, mesh=mesh, mpu=mpu,
                                training_data=training_data,
                                collate_fn=collate_fn, rng=rng,
                                example_input=example_input)
    else:
        engine = DeepSpeedEngine(model=model, config=cfg, optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 lr_scheduler=lr_scheduler, mesh=mesh, mpu=mpu,
                                 training_data=training_data,
                                 collate_fn=collate_fn, rng=rng,
                                 param_partition_specs=param_partition_specs)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, mp_size=1, mesh=None, checkpoint=None, dtype=None,
                   injection_policy=None, replace_method="auto",
                   quantization_setting=None, **kwargs):
    """Create an inference engine (reference: deepspeed/__init__.py:232)."""
    from .inference.engine import InferenceEngine
    return InferenceEngine(model, mp_size=mp_size, mesh=mesh,
                           checkpoint=checkpoint, dtype=dtype,
                           injection_policy=injection_policy,
                           replace_method=replace_method,
                           quantization_setting=quantization_setting, **kwargs)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config args (reference: __init__.py:216)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to ease transition)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable flag (kept for parity)")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated config path (kept for parity)")
    return parser
