"""Elastic batch-size solver.

Pre-computes a (total_batch_size, micro_batch, valid-chip-count set) that stays
consistent as the job is resized between min and max chips, so hyperparameters
survive a scheduler resize.  Reference: deepspeed/elasticity/elasticity.py
(candidate enumeration :21-75, compute_elastic_config :226); this is a pure-math
re-implementation — no torch, no CUDA.
"""

from typing import Dict, List, Tuple

from . import constants as C


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Typed view of the "elasticity" config block
    (reference: deepspeed/elasticity/config.py:30)."""

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get(C.ENABLED, C.ENABLED_DEFAULT)
        if C.MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
            raise ElasticityConfigError(
                f"Elasticity config missing {C.MAX_ACCEPTABLE_BATCH_SIZE}")
        self.max_acceptable_batch_size = param_dict[C.MAX_ACCEPTABLE_BATCH_SIZE]
        if C.MICRO_BATCHES not in param_dict:
            raise ElasticityConfigError(
                f"Elasticity config missing {C.MICRO_BATCHES}")
        self.micro_batches = param_dict[C.MICRO_BATCHES]
        if not isinstance(self.micro_batches, list) or not all(
                isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"Elasticity expected positive int list of micro batches, "
                f"instead saw: {self.micro_batches}")
        self.min_gpus = param_dict.get(C.MIN_GPUS, C.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(C.MAX_GPUS, C.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError("Invalid min/max chips in elasticity config")
        self.min_time = param_dict.get(C.MIN_TIME, C.MIN_TIME_DEFAULT)
        self.version = param_dict.get(C.VERSION, C.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            C.PREFER_LARGER_BATCH, C.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            C.IGNORE_NON_ELASTIC_BATCH_INFO,
            C.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)


# Multipliers with many divisors (highly-composite-style), so candidate batch
# sizes are divisible by many chip counts (reference: elasticity.py HCN_LIST).
_COMPOSITE_MULTIPLIERS = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1080, 1260,
    1680, 2520, 5040, 7560, 10080
]


def get_candidate_batch_sizes(micro_batches: List[int],
                              max_acceptable_batch_size: int) -> List[int]:
    candidates = set()
    for mb in micro_batches:
        for mult in _COMPOSITE_MULTIPLIERS:
            batch = mb * mult
            if batch <= max_acceptable_batch_size:
                candidates.add(batch)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int,
                   max_valid_gpus: int) -> List[int]:
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_gpus_for_mb = batch_size // mb
        for g in range(1, max_gpus_for_mb + 1):
            if max_gpus_for_mb % g == 0 and min_valid_gpus <= g <= max_valid_gpus:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes: List[int], micro_batches: List[int],
                        min_gpus: int, max_gpus: int,
                        prefer_larger: bool) -> Tuple[int, List[int]]:
    max_valid_count = -1
    best_batch = -1
    best_gpus = []
    for batch in candidate_batch_sizes:
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better = len(valid) > max_valid_count
        tie = len(valid) == max_valid_count and prefer_larger and batch > best_batch
        if better or tie:
            max_valid_count = len(valid)
            best_batch = batch
            best_gpus = valid
    if best_batch < 0:
        raise ElasticityError(
            "Unable to find a compatible batch size within the elastic bounds")
    return best_batch, best_gpus


def _get_compatible_micro_batch(final_batch_size: int, micro_batches: List[int],
                                world_size: int,
                                prefer_larger: bool) -> int:
    if final_batch_size % world_size != 0:
        raise ElasticityIncompatibleWorldSize(
            f"World size {world_size} is not valid for final batch size "
            f"{final_batch_size}")
    per_gpu = final_batch_size // world_size
    candidates = [mb for mb in micro_batches if per_gpu % mb == 0]
    if not candidates:
        raise ElasticityIncompatibleWorldSize(
            f"No micro batch in {micro_batches} divides per-chip batch {per_gpu}")
    return max(candidates) if prefer_larger else min(candidates)


def nearest_valid_world_sizes(valid_gpus: List[int], world_size: int,
                              k: int = 3) -> List[int]:
    """The `k` valid chip counts closest to `world_size` (ties resolve
    smaller-first) — what an incompatible-world-size error suggests, and
    what the fleet supervisor shrinks/regrows toward."""
    return sorted(valid_gpus,
                  key=lambda g: (abs(g - world_size), g))[:k]


def _incompatible_world_size_error(world_size: int, final_batch_size: int,
                                   valid_gpus: List[int],
                                   micro_batches: List[int],
                                   prefer_larger: bool
                                   ) -> "ElasticityIncompatibleWorldSize":
    """An ACTIONABLE incompatible-world-size error: names the nearest
    valid world sizes and the micro-batch/gas each would run with, so an
    operator (or the fleet supervisor) can pick a target instead of
    bisecting chip counts against a bare exception."""
    suggestions = []
    for g in nearest_valid_world_sizes(valid_gpus, world_size):
        micro = _get_compatible_micro_batch(final_batch_size, micro_batches,
                                            g, prefer_larger)
        suggestions.append(
            f"{g} chips (micro_batch={micro}, "
            f"gas={final_batch_size // (micro * g)})")
    return ElasticityIncompatibleWorldSize(
        f"World size ({world_size}) is not valid with the current list "
        f"of valid chip counts: {valid_gpus} "
        f"(final batch size {final_batch_size}). Nearest valid world "
        f"sizes: {'; '.join(suggestions) or 'none'} — resize the job to "
        "one of these, or widen elasticity.micro_batch_sizes / "
        "min_gpus / max_gpus to admit the current size.")


def compute_elastic_config(ds_config: Dict, world_size: int = 0):
    """Returns (final_batch_size, valid_gpus[, micro_batch_per_gpu]).

    Reference: deepspeed/elasticity/elasticity.py:226.
    """
    elastic_config = ElasticityConfig(ds_config[C.ELASTICITY])
    if float(elastic_config.version) > C.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {elastic_config.version}")
    candidates = get_candidate_batch_sizes(
        elastic_config.micro_batches, elastic_config.max_acceptable_batch_size)
    final_batch_size, valid_gpus = get_best_candidates(
        candidates, elastic_config.micro_batches, elastic_config.min_gpus,
        elastic_config.max_gpus, elastic_config.prefer_larger_batch_size)
    if world_size > 0:
        if world_size not in valid_gpus:
            raise _incompatible_world_size_error(
                world_size, final_batch_size, valid_gpus,
                elastic_config.micro_batches,
                elastic_config.prefer_larger_batch_size)
        micro = _get_compatible_micro_batch(
            final_batch_size, elastic_config.micro_batches, world_size,
            elastic_config.prefer_larger_batch_size)
        return final_batch_size, valid_gpus, micro
    return final_batch_size, valid_gpus


def apply_elasticity(param_dict: Dict, world_size: int) -> None:
    """Rewrite the batch keys in-place (reference: runtime/config.py:707-757)."""
    elastic_dict = param_dict[C.ELASTICITY]
    ignore = elastic_dict.get(C.IGNORE_NON_ELASTIC_BATCH_INFO,
                              C.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)
    if not ignore:
        for key in (C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                    C.GRADIENT_ACCUMULATION_STEPS):
            if key in param_dict:
                raise ElasticityConfigError(
                    f"Elasticity is enabled, but config still contains {key}; "
                    f"remove it or set {C.IGNORE_NON_ELASTIC_BATCH_INFO}")
    final_batch_size, _, micro = compute_elastic_config(param_dict,
                                                        world_size=world_size)
    gas = final_batch_size // (micro * world_size)
    param_dict[C.TRAIN_BATCH_SIZE] = final_batch_size
    param_dict[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro
    param_dict[C.GRADIENT_ACCUMULATION_STEPS] = gas


def cli_main(argv=None) -> int:
    """ds_elastic analog (reference: bin/ds_elastic): show the elastic
    batch/chip-count compatibility solve for a config file."""
    import argparse
    import json

    parser = argparse.ArgumentParser(description="deepspeed_tpu elasticity")
    parser.add_argument("-c", "--config", required=True,
                        help="DeepSpeed config json with an elasticity block")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)
    result = compute_elastic_config(ds_config, world_size=args.world_size)
    out = {"final_batch_size": result[0], "valid_chip_counts": result[1]}
    if len(result) == 3:
        out["micro_batch_per_chip"] = result[2]
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(cli_main())
