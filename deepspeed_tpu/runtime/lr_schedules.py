"""Batch-wise LR schedules (reference: deepspeed/runtime/lr_schedules.py —
LRRangeTest:301, OneCycle:408, WarmupLR:677, WarmupDecayLR:761).

Each schedule is a pure step→lr function (jit-traceable, so the engine can fold
it into the compiled optimizer step) wrapped in a class with the reference's
step()/get_lr()/state_dict() surface.
"""

from typing import Any, Dict, Optional

import jax.numpy as jnp

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"
CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


class _ScheduleBase:
    """Reference-shaped wrapper: step()/get_lr()/get_last_lr()/state_dict()."""

    def __init__(self, last_batch_iteration: int = -1):
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = None

    # pure function — override
    def lr_at(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.lr_at(step)

    def get_lr(self):
        return [float(self.lr_at(jnp.maximum(self.last_batch_iteration, 0)))]

    def get_last_lr(self):
        return self._last_lr if self._last_lr is not None else self.get_lr()

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_ScheduleBase):
    """LR sweep for range tests (reference: lr_schedules.py:301)."""

    def __init__(self, optimizer=None, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        super().__init__(last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        interval = (jnp.floor(step / self.step_size) if self.staircase
                    else step / self.step_size)
        return self.min_lr * (1.0 + interval * self.step_rate)


class OneCycle(_ScheduleBase):
    """1-cycle policy: min→max over the first phase, max→min over the second,
    then exponential decay (reference: lr_schedules.py:408)."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 0.0,
                 cycle_max_lr: float = 0.001, decay_lr_rate: float = 0.0,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0, cycle_momentum: bool = False,
                 cycle_min_mom: float = 0.8, cycle_max_mom: float = 0.9,
                 decay_mom_rate: float = 0.0, last_batch_iteration: int = -1):
        super().__init__(last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = int(cycle_first_step_size)
        self.second = int(cycle_second_step_size
                          if cycle_second_step_size is not None
                          else cycle_first_step_size)
        self.decay_step_size = int(decay_step_size)
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        total_cycle = float(self.first + self.second)
        up = jnp.clip(step / self.first, 0.0, 1.0)
        down = jnp.clip((step - self.first) / self.second, 0.0, 1.0)
        in_cycle_lr = (self.cycle_min_lr +
                       (self.cycle_max_lr - self.cycle_min_lr) * (up - down))
        decay_steps = jnp.maximum(step - total_cycle, 0.0)
        if self.decay_step_size > 0:
            decay_intervals = decay_steps / self.decay_step_size
        else:
            decay_intervals = decay_steps
        decayed = self.cycle_min_lr / (1.0 + self.decay_lr_rate * decay_intervals)
        return jnp.where(step <= total_cycle, in_cycle_lr, decayed)

    def mom_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / self.first, 0.0, 1.0)
        down = jnp.clip((step - self.first) / self.second, 0.0, 1.0)
        # momentum runs opposite to lr
        return self.cycle_max_mom - (self.cycle_max_mom -
                                     self.cycle_min_mom) * (up - down)


class WarmupLR(_ScheduleBase):
    """Linear warmup from warmup_min_lr to warmup_max_lr, then constant
    (reference: lr_schedules.py:677)."""

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 last_batch_iteration: int = -1):
        super().__init__(last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(1, int(warmup_num_steps))

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / self.warmup_num_steps, 0.0, 1.0)
        return self.min_lr + (self.max_lr - self.min_lr) * frac


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero at total_num_steps
    (reference: lr_schedules.py:761)."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000,
                 warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000, last_batch_iteration: int = -1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, last_batch_iteration)
        self.total_num_steps = int(total_num_steps)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        warmup_lr = super().lr_at(step)
        decay_frac = jnp.clip(
            (self.total_num_steps - step) /
            jnp.maximum(1.0, self.total_num_steps - self.warmup_num_steps),
            0.0, 1.0)
        return jnp.where(step < self.warmup_num_steps, warmup_lr,
                         self.max_lr * decay_frac)


_SCHEDULE_CLASSES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_schedule(name: str, params: Dict[str, Any]):
    """Instantiate a schedule by config name (reference: engine.py
    _scheduler_from_config)."""
    if name not in _SCHEDULE_CLASSES:
        raise ValueError(
            f"Unknown scheduler {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULE_CLASSES[name](**params)
