"""Error-feedback sign compression — the 1-bit Adam/LAMB comm primitive.

Reference: deepspeed/runtime/comm/nccl.py:47 (NcclBackend.
compressed_allreduce): worker-side error compensation, sign+scale
compression, igather+allgather of compressed chunks, server-side error
feedback.

TPU recasting: inside `shard_map` over the data axis the same algorithm is
three lines — compensate, compress to sign·scale, `lax.psum` the compressed
tensor (ICI does the reduction; the wire format is the sign tensor, which
XLA keeps in bf16/int8-width lanes).  Two error-feedback states (worker +
server in the reference) collapse into one because psum has no gather/
scatter asymmetry.

Honest perf note (measured stance of SURVEY.md §7): on ICI the dense psum
is rarely the bottleneck, so compression mainly pays on DCN-spanning
meshes; the API exists for parity and for multi-pod data parallelism.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...parallel.mesh import DATA_AXIS


def compressed_allreduce_inner(x: jnp.ndarray, error: jnp.ndarray,
                               axis_name: str = DATA_AXIS,
                               wire: str = "full"
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One error-compensated 1-bit allreduce step; call inside shard_map.

    x: this worker's tensor (e.g. local momentum update);
    error: carried compensation state (same shape).
    Returns (averaged_decompressed, new_error).

    wire="full": per-worker scale, the psum moves a full-dtype sign*scale
    tensor — same numerics as the reference's per-chunk scaling but NO
    wire-width win (measured in benchmarks/onebit_cost.py; the XLA psum
    cannot weight per-worker operands after an int8 cast).
    wire="int8": the scale is first psum-averaged to a SHARED scalar, the
    sign tensor then rides the wire as int8 (4x narrower than fp32; the
    narrowest dtype XLA collectives move — true 1-bit packing would need
    a bit-packed allgather whose volume scales with world size).  The
    worker's error feedback absorbs the shared-scale approximation the
    same way the reference's server-side error absorbs its second-stage
    compression (runtime/comm/nccl.py:47).
    """
    if wire not in ("full", "int8"):
        raise ValueError(f"wire={wire!r} not in full|int8")
    if wire == "int8":
        # the axis size is static inside shard_map — guard here too, not
        # just in the wrapper (shard_map loops call inner directly)
        world_static = lax.axis_size(axis_name)
        if world_static > 127:
            raise ValueError(
                f"wire='int8' supports at most 127 workers on "
                f"{axis_name!r} (summed signs ride int8 lanes); axis has "
                f"{world_static} — use wire='full'")
    world = lax.psum(1, axis_name)
    compensated = x + error
    # per-worker scale: mean magnitude preserves E[|x|] under sign compression
    # (reference uses norm/sqrt(numel) — same estimator family)
    scale = jnp.mean(jnp.abs(compensated))
    sign = jnp.sign(compensated)
    if wire == "int8":
        shared_scale = lax.psum(scale, axis_name) / world
        summed = lax.psum(sign.astype(jnp.int8), axis_name)
        reduced = shared_scale * summed.astype(x.dtype) / world
        # what THIS worker contributed post-decompression
        applied = shared_scale * sign
        return reduced, compensated - applied
    compressed = scale * sign
    new_error = compensated - compressed
    reduced = lax.psum(compressed, axis_name) / world
    return reduced, new_error


def compressed_allreduce(x_stacked, error_stacked, mesh_ctx=None,
                         axis_name: str = DATA_AXIS, wire: str = "full"):
    """Worker-stacked wrapper: x_stacked [W, ...] holds worker i's tensor in
    row i (sharded over the data axis).  Returns (reduced [W, ...] — every
    row identical — and the new per-worker error stack).

    wire="int8" needs world size <= 127 (the summed sign tensor rides in
    int8 lanes)."""
    from ...parallel.mesh import get_mesh_context
    from jax.sharding import PartitionSpec as P
    ctx = mesh_ctx or get_mesh_context()
    if wire == "int8":
        world = ctx.mesh.shape.get(axis_name, 1)
        if world > 127:
            raise ValueError(
                f"wire='int8' supports at most 127 workers on the "
                f"{axis_name!r} axis (summed signs ride int8 lanes); "
                f"mesh has {world} — use wire='full'")
    spec = P(axis_name)

    def inner(a, b):
        r, e = compressed_allreduce_inner(a[0], b[0], axis_name, wire=wire)
        return r[None], e[None]

    fn = jax.shard_map(inner, mesh=ctx.mesh, in_specs=(spec, spec),
                       out_specs=(spec, spec), check_vma=False)
    return fn(x_stacked, error_stacked)
