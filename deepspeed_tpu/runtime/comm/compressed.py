"""Error-feedback sign compression — the 1-bit Adam/LAMB comm primitive.

Reference: deepspeed/runtime/comm/nccl.py:47 (NcclBackend.
compressed_allreduce): worker-side error compensation, sign+scale
compression, igather+allgather of compressed chunks, server-side error
feedback.

TPU recasting: inside `shard_map` over the data axis the same algorithm is
three lines — compensate, compress to sign·scale, `lax.psum` the compressed
tensor (ICI does the reduction; the wire format is the sign tensor, which
XLA keeps in bf16/int8-width lanes).  Two error-feedback states (worker +
server in the reference) collapse into one because psum has no gather/
scatter asymmetry.

wire="packed" restores the reference's genuinely narrow wire: signs ride
8-per-byte through a two-stage all_to_all + all_gather (the reference's
igather/allgather pair), with blockwise mean-|x| scales riding alongside
— the same blockwise-scale convention as the qwZ/qgZ transports in
``low_bandwidth.py``.  A hierarchical (Frontier-style, arXiv:2501.04266)
variant does a dense intra-group psum first and runs the packed exchange
only across groups.

Honest perf note (measured stance of SURVEY.md §7): on ICI the dense psum
is rarely the bottleneck, so compression mainly pays on DCN-spanning
meshes; the API exists for parity and for multi-pod data parallelism.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ... import constants as C
from ...parallel.mesh import DATA_AXIS
from .low_bandwidth import DEFAULT_BLOCK

_WIRES = ("full", "int8", "packed")


def _packed_sync(compensated: jnp.ndarray, axis_name: str, block: int,
                 wg: int, my_rank: jnp.ndarray,
                 groups: Optional[list]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-stage packed-sign exchange across ``wg`` peers.

    Stage 1 (the reference's igather): every peer packs its signs
    8-per-byte plus blockwise scales and all_to_alls chunk w to peer w;
    each peer decodes and averages its server chunk.  Stage 2 (the
    reference's allgather): the averaged chunk is re-compressed and
    all_gathered back; the second-stage compression residual is folded
    into this peer's OWN chunk slice of the error state, exactly like
    the reference's server-side error.  Padding tail blocks decode to
    garbage but are sliced off before returning.
    """
    n = compensated.size
    dtype = compensated.dtype
    flat = compensated.astype(jnp.float32).reshape(-1)
    chunk = -(-n // (wg * block)) * block  # per-peer chunk, block multiple
    n_pad = chunk * wg
    nb = chunk // block
    flat = jnp.pad(flat, (0, n_pad - n))
    blocks = flat.reshape(wg, nb, block)
    s1 = jnp.mean(jnp.abs(blocks), axis=-1)            # [wg, nb]
    pos1 = blocks >= 0
    bits1 = jnp.packbits(pos1, axis=-1)                # [wg, nb, block//8]
    applied1 = jnp.where(pos1, 1.0, -1.0) * s1[..., None]
    with jax.named_scope(C.ONEBIT_SCOPE):
        bits_recv = lax.all_to_all(bits1, axis_name, 0, 0,
                                   axis_index_groups=groups)
        s1_recv = lax.all_to_all(s1, axis_name, 0, 0,
                                 axis_index_groups=groups)
    sgn_recv = (jnp.unpackbits(bits_recv, axis=-1, count=block)
                .astype(jnp.float32) * 2.0 - 1.0)
    server = jnp.mean(sgn_recv * s1_recv[..., None], axis=0)  # [nb, block]
    s2 = jnp.mean(jnp.abs(server), axis=-1)            # [nb]
    pos2 = server >= 0
    bits2 = jnp.packbits(pos2, axis=-1)                # [nb, block//8]
    applied2 = jnp.where(pos2, 1.0, -1.0) * s2[..., None]
    server_resid = server - applied2
    with jax.named_scope(C.ONEBIT_SCOPE):
        bits_all = lax.all_gather(bits2, axis_name, axis=0,
                                  axis_index_groups=groups)
        s2_all = lax.all_gather(s2, axis_name, axis=0,
                                axis_index_groups=groups)
    decoded = ((jnp.unpackbits(bits_all, axis=-1, count=block)
                .astype(jnp.float32) * 2.0 - 1.0) * s2_all[..., None])
    reduced = (decoded.reshape(-1)[:n].reshape(compensated.shape)
               .astype(dtype))
    # stage-1 residual everywhere; the server residual lands in this
    # peer's own chunk slice (every peer holds a disjoint server chunk,
    # so across the fleet the full residual is accounted exactly once)
    e1 = blocks - applied1
    my_e = lax.dynamic_slice_in_dim(e1, my_rank, 1, axis=0)[0] + server_resid
    e_full = lax.dynamic_update_slice_in_dim(e1, my_e[None], my_rank, axis=0)
    new_error = (e_full.reshape(-1)[:n].reshape(compensated.shape)
                 .astype(dtype))
    return reduced, new_error


def compressed_allreduce_inner(x: jnp.ndarray, error: jnp.ndarray,
                               axis_name: str = DATA_AXIS,
                               wire: str = "full",
                               block: int = DEFAULT_BLOCK,
                               group_size: int = 0
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One error-compensated 1-bit allreduce step; call inside shard_map.

    x: this worker's tensor (e.g. local momentum update);
    error: carried compensation state (same shape).
    Returns (averaged_decompressed, new_error).

    wire="full": per-worker scale, the psum moves a full-dtype sign*scale
    tensor — same numerics as the reference's per-chunk scaling but NO
    wire-width win (measured in benchmarks/onebit_cost.py; the XLA psum
    cannot weight per-worker operands after an int8 cast).
    wire="int8": the scale is first psum-averaged to a SHARED scalar, the
    sign tensor then rides the wire as int8 (4x narrower than fp32).  The
    worker's error feedback absorbs the shared-scale approximation the
    same way the reference's server-side error absorbs its second-stage
    compression (runtime/comm/nccl.py:47).
    wire="packed": true 1-bit lanes — signs packed 8-per-byte with
    blockwise fp32 scales (``block`` elements per scale), moved by the
    reference's two-stage igather/allgather recast as all_to_all +
    all_gather.  ~n/8 sign bytes each way plus n/block scales: ≈14x
    narrower than a dense fp32 psum at block=256 under the repo's wire
    accounting.  ``group_size`` G > 1 selects the Frontier-style
    hierarchical variant: dense psum-mean inside consecutive groups of
    G, packed exchange only across the W/G groups (G must divide the
    axis size; G == axis size degenerates to a plain dense mean).
    """
    if wire not in _WIRES:
        raise ValueError(f"wire={wire!r} not in {'|'.join(_WIRES)}")
    if wire == "int8":
        # the axis size is static inside shard_map — guard here too, not
        # just in the wrapper (shard_map loops call inner directly)
        world_static = lax.axis_size(axis_name)
        if world_static > 127:
            raise ValueError(
                f"wire='int8' supports at most 127 workers on "
                f"{axis_name!r} (summed signs ride int8 lanes); axis has "
                f"{world_static} — use wire='full'")
    if wire == "packed":
        if block < 8 or block % 8:
            raise ValueError(
                f"wire='packed' needs block % 8 == 0 (signs pack "
                f"8-per-byte), got block={block}")
        W = lax.axis_size(axis_name)
        G = int(group_size) if group_size and group_size > 1 else 1
        if G > 1:
            if W % G:
                raise ValueError(
                    f"hierarchical group_size={G} must divide the "
                    f"{axis_name!r} axis size {W}")
            wg = W // G
            groups_intra = [[g * G + i for i in range(G)]
                            for g in range(wg)]
            dense = lax.psum(x, axis_name,
                             axis_index_groups=groups_intra) / G
            if wg == 1:  # G == W: one group, plain dense mean
                return dense + error, jnp.zeros_like(error)
            groups_cross = [[r + g * G for g in range(wg)]
                            for r in range(G)]
            my_rank = lax.axis_index(axis_name) // G
            return _packed_sync(dense + error, axis_name, block, wg,
                                my_rank, groups_cross)
        if W == 1:
            return x + error, jnp.zeros_like(error)
        return _packed_sync(x + error, axis_name, block, W,
                            lax.axis_index(axis_name), None)
    world = lax.psum(1, axis_name)
    compensated = x + error
    # per-worker scale: mean magnitude preserves E[|x|] under sign compression
    # (reference uses norm/sqrt(numel) — same estimator family)
    scale = jnp.mean(jnp.abs(compensated))
    sign = jnp.sign(compensated)
    if wire == "int8":
        shared_scale = lax.psum(scale, axis_name) / world
        summed = lax.psum(sign.astype(jnp.int8), axis_name)
        reduced = shared_scale * summed.astype(x.dtype) / world
        # what THIS worker contributed post-decompression
        applied = shared_scale * sign
        return reduced, compensated - applied
    compressed = scale * sign
    new_error = compensated - compressed
    reduced = lax.psum(compressed, axis_name) / world
    return reduced, new_error


def compressed_allreduce(x_stacked, error_stacked, mesh_ctx=None,
                         axis_name: str = DATA_AXIS, wire: str = "full",
                         block: int = DEFAULT_BLOCK, group_size: int = 0):
    """Worker-stacked wrapper: x_stacked [W, ...] holds worker i's tensor in
    row i (sharded over the data axis).  Returns (reduced [W, ...] — every
    row identical — and the new per-worker error stack).

    wire="int8" needs world size <= 127 (the summed sign tensor rides in
    int8 lanes); wire="packed"/group_size are forwarded to
    :func:`compressed_allreduce_inner`."""
    from ...parallel.mesh import get_mesh_context
    from jax.sharding import PartitionSpec as P
    ctx = mesh_ctx or get_mesh_context()
    if wire == "int8":
        world = ctx.mesh.shape.get(axis_name, 1)
        if world > 127:
            raise ValueError(
                f"wire='int8' supports at most 127 workers on the "
                f"{axis_name!r} axis (summed signs ride int8 lanes); "
                f"mesh has {world} — use wire='full'")
    spec = P(axis_name)

    def inner(a, b):
        r, e = compressed_allreduce_inner(a[0], b[0], axis_name, wire=wire,
                                          block=block, group_size=group_size)
        return r[None], e[None]

    fn = jax.shard_map(inner, mesh=ctx.mesh, in_specs=(spec, spec),
                       out_specs=(spec, spec), check_vma=False)
    return fn(x_stacked, error_stacked)
