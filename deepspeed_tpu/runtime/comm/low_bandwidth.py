"""ZeRO++-style low-bandwidth collectives: qwZ / qgZ building blocks.

Reference: ZeRO++ (https://arxiv.org/pdf/2306.10209) and the Frontier
low-bandwidth-partitioning recipe (https://arxiv.org/pdf/2501.04266).
Three techniques cut ZeRO-3's communication volume:

  qwZ  — blockwise-int8 quantize BEFORE the weight all-gather, dequantize
         after: the gathered bytes shrink ~4x (int8 payload + small fp32
         scales) while the master weights stay fp32.  The backward is the
         UNCHANGED fp32 reduce-scatter (straight-through: the quantizer is
         treated as identity under differentiation, so grads flow exactly
         as in the fp32 path).
  qgZ  — quantized gradient reduce-scatter.  A psum cannot reduce int8
         operands with per-shard scales, so the transport is the ZeRO++
         all-to-all form: quantize my chunk-table, all-to-all so every
         shard receives all copies of ITS chunk, dequantize in fp32 and
         reduce locally.  Optional int4 packing halves the wire again.
         A persistent error-feedback variant (qgz_reduce_scatter)
         generalizes the 1-bit machinery in comm/compressed.py from
         sign+scale to multi-bit blockwise quantization.
  hpZ  — hierarchical secondary partition: see zero/partition.py
         (resolve_hpz_axes / ZeroPartitioner.secondary_shardings) and the
         consumer in zero/stage3_streaming.py.

The scale layout follows ops/quant.py's QuantizedWeight convention —
symmetric per-group scales along the leading (gather) dimension — extended
with optional sub-blocks over the remaining flattened elements so a block
never straddles a shard boundary along the gathered dimension (tiled
all-gathers and chunked reduce-scatters stay self-describing).

Wire-width note: these pay off when the gather/reduce crosses the SLOW
mesh dimension (DCN between slices, or the long ICI axis); on a short
intra-slice axis the dense fp32 collective is usually already overlapped
behind compute (same honesty stance as comm/compressed.py).
"""

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...parallel.mesh import DATA_AXIS

_QMAX = {8: 127, 4: 7}
DEFAULT_BLOCK = 256


def _check_bits(bits: int, what: str) -> None:
    if bits not in _QMAX:
        raise ValueError(f"{what}={bits} unsupported — use 4 or 8 "
                         "(0 disables)")


def largest_divisor_at_most(n: int, bound: int, even: bool = False) -> int:
    bound = max(1, min(n, bound))
    for g in range(bound, 0, -1):
        if n % g == 0 and (not even or g % 2 == 0):
            return g
    return 1


# --------------------------------------------------------------------- #
# blockwise symmetric quantization
# --------------------------------------------------------------------- #
def blockwise_quantize(x: jnp.ndarray, dim: int = 0, bits: int = 8,
                       block: int = DEFAULT_BLOCK
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize ``x`` to int8 (optionally int4-packed) with per-block
    fp32 scales.

    The gather/scatter dimension ``dim`` is moved to the front and kept
    whole in the scale layout — every index along ``dim`` owns its own
    row of blocks, so a tiled collective along ``dim`` moves (q, scale)
    pairs that stay consistent on every receiver.  The remaining
    elements are flattened and split into blocks of at most ``block``
    (the largest divisor, so no padding).

    Returns ``(q, scale)``:
      q     int8 ``[m, nb, bs]`` (bits=8) or ``[m, nb, bs//2]`` packed
            (bits=4; bs forced even, falling back to bits=8 layout only
            when the flattened remainder is odd and indivisible),
      scale fp32 ``[m, nb]``.
    """
    _check_bits(bits, "bits")
    xt = jnp.moveaxis(x, dim, 0)
    m = xt.shape[0]
    rest = int(np.prod(xt.shape[1:])) if xt.ndim > 1 else 1
    flat = xt.reshape(m, rest)
    bs = largest_divisor_at_most(rest, block, even=(bits == 4))
    if bits == 4 and bs % 2 != 0:  # odd `rest` with no even divisor
        bs = largest_divisor_at_most(rest, block)
    nb = rest // bs
    g = flat.reshape(m, nb, bs)
    qmax = _QMAX[bits]
    amax = jnp.max(jnp.abs(g), axis=-1)                       # [m, nb]
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(g / scale[..., None]), -qmax, qmax
                 ).astype(jnp.int8)
    if bits == 4 and bs % 2 == 0:
        q = pack_int4(q)
    return q, scale


def blockwise_dequantize(q: jnp.ndarray, scale: jnp.ndarray, shape,
                         dim: int = 0, dtype=jnp.float32,
                         bits: int = 8) -> jnp.ndarray:
    """Inverse of :func:`blockwise_quantize` for a target array ``shape``
    (the shape AFTER any collective — ``shape[dim]`` may be a gathered
    multiple of the quantized shard's)."""
    _check_bits(bits, "bits")
    shape = tuple(shape)
    moved = (shape[dim],) + tuple(s for i, s in enumerate(shape)
                                  if i != dim)
    if bits == 4 and 2 * int(np.prod(q.shape)) == int(np.prod(moved)):
        q = unpack_int4(q)
    deq = q.astype(jnp.float32) * scale[..., None]
    out = deq.reshape(moved).astype(dtype)
    return jnp.moveaxis(out, 0, dim)


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int8 values in [-7, 7] two-per-byte along the last axis
    (which must be even): out[..., i] holds q[..., 2i] in the low nibble
    and q[..., 2i+1] in the high nibble."""
    lo = q[..., 0::2] & jnp.int8(0xF)
    hi = q[..., 1::2] & jnp.int8(0xF)
    return (lo | (hi << 4)).astype(jnp.int8)


def unpack_int4(p: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4` (sign-extending both nibbles)."""
    lo = ((p & jnp.int8(0xF)) ^ jnp.int8(8)) - jnp.int8(8)
    hi = ((p >> 4) & jnp.int8(0xF) ^ jnp.int8(8)) - jnp.int8(8)
    return jnp.stack([lo, hi], axis=-1).reshape(
        *p.shape[:-1], p.shape[-1] * 2)


def quantized_gather_saves_bytes(shape, dim: int, dtype, bits: int,
                                 block: int = DEFAULT_BLOCK) -> bool:
    """True when a blockwise-quantized collective over an array of
    ``shape``/``dtype`` along ``dim`` moves fewer wire bytes than the
    native-width collective.  Skinny leaves (a bias gathered one layer
    at a time has one element per scale block) pay 4 fp32 scale bytes
    per payload byte — quantizing those INFLATES traffic, so callers
    fall back to the dense path."""
    shape = tuple(shape)
    m = shape[dim]
    rest = int(np.prod(shape)) // max(m, 1)
    bs = largest_divisor_at_most(rest, block, even=(bits == 4))
    if bits == 4 and bs % 2 != 0:
        bs = largest_divisor_at_most(rest, block)
    payload = rest // 2 if (bits == 4 and bs % 2 == 0) else rest
    scale_bytes = (rest // bs) * 4
    native = rest * jnp.dtype(dtype).itemsize
    return payload + scale_bytes < native


def as_quantized_weight(q: jnp.ndarray, scale: jnp.ndarray):
    """Bridge to ops/quant.py's carrier for the 2-D, one-block-per-row
    case: a ``blockwise_quantize(w, dim=0)`` result with ``nb == 1``
    IS a per-row QuantizedWeight (groups == rows, scale ``[rows, 1]``),
    so the fused dequant-matmul kernels accept the gathered payload
    directly."""
    from ...ops.quant import QuantizedWeight
    if q.ndim != 3 or scale.shape[1] != 1:
        raise ValueError(
            f"QuantizedWeight bridge needs a [rows, 1, cols] blockwise "
            f"layout, got q{q.shape} scale{scale.shape}")
    return QuantizedWeight(q.reshape(q.shape[0], -1),
                           scale.reshape(-1, 1))


def f32_psum_scatter(g, axes, dim):
    """Tiled ``psum_scatter`` that promotes half dtypes to fp32 for the
    reduction and demotes after: cross-shard accumulation happens in fp32
    regardless of compute dtype, and the only reduction collective stays
    out of XLA-CPU's AllReducePromotion pass, which hard-aborts on
    half-precision reduction collectives.  Shared transpose of both the
    fp32 gather path (zero/stage3_streaming._all_gather_f32grad) and the
    qgZ-off quantized gather below."""
    half = (jnp.issubdtype(g.dtype, jnp.floating) and
            jnp.dtype(g.dtype).itemsize < 4)
    if half:
        shard = lax.psum_scatter(g.astype(jnp.float32), axes,
                                 scatter_dimension=dim, tiled=True)
        return shard.astype(g.dtype)
    return lax.psum_scatter(g, axes, scatter_dimension=dim, tiled=True)


# --------------------------------------------------------------------- #
# qwZ: quantized weight all-gather (drop-in for _all_gather_f32grad)
# --------------------------------------------------------------------- #
@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def low_bandwidth_all_gather(x, axes, dim, qwz_bits=8, qgz_bits=0,
                             block=DEFAULT_BLOCK):
    """Tiled all-gather with a quantized forward wire (qwZ) and an
    optionally quantized reduce-scatter transpose (qgZ).

    qwz_bits=8/4: the shard is blockwise-quantized before the gather and
    dequantized after — the wire moves int8 (or packed int4) plus the
    fp32 block scales.  qwz_bits=0 gathers at native width.
    qgz_bits=8/4: the backward reduce-scatters the gradient through
    :func:`quantized_psum_scatter`; qgz_bits=0 keeps the fp32
    reduce-scatter of stage3_streaming._all_gather_f32grad, so with qgZ
    off the gradients are BIT-IDENTICAL to the fp32 gather path
    (straight-through quantizer).
    """
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    if not qwz_bits:
        return lax.all_gather(x, axes, axis=dim, tiled=True)
    q, scale = blockwise_quantize(x, dim=dim, bits=qwz_bits, block=block)
    q_g = lax.all_gather(q, axes, axis=0, tiled=True)
    s_g = lax.all_gather(scale, axes, axis=0, tiled=True)
    world = int(np.prod([lax.axis_size(a) for a in axes]))
    shape = tuple(x.shape[:dim]) + (x.shape[dim] * world,) + \
        tuple(x.shape[dim + 1:])
    return blockwise_dequantize(q_g, s_g, shape, dim=dim, dtype=x.dtype,
                                bits=qwz_bits)


def _lbag_fwd(x, axes, dim, qwz_bits, qgz_bits, block):
    return low_bandwidth_all_gather(x, axes, dim, qwz_bits, qgz_bits,
                                    block), None


def _lbag_bwd(axes, dim, qwz_bits, qgz_bits, block, _, g):
    del qwz_bits
    if qgz_bits:
        return (quantized_psum_scatter(g, axes, dim, bits=qgz_bits,
                                       block=block),)
    return (f32_psum_scatter(g, axes, dim),)


low_bandwidth_all_gather.defvjp(_lbag_fwd, _lbag_bwd)


# --------------------------------------------------------------------- #
# qgZ: quantized gradient reduce-scatter (all-to-all transport)
# --------------------------------------------------------------------- #
def _quantized_reduce_scatter_one_axis(x, axis_name, dim, bits, block):
    """One axis of :func:`quantized_psum_scatter`: quantize my chunk
    table, transpose ownership with all_to_all, dequantize + reduce in
    fp32 (the ZeRO++ qgZ pipeline — a psum cannot weight int8 operands
    by per-shard scales, an all-to-all can because dequantization
    happens AFTER transport, on the receiver)."""
    world = lax.axis_size(axis_name)
    xt = jnp.moveaxis(x, dim, 0)
    m = xt.shape[0]
    if m % world != 0:
        raise ValueError(
            f"quantized reduce-scatter: dim {dim} (size {m}) must be "
            f"divisible by the {axis_name!r} axis size {world}")
    tail = xt.shape[1:]
    chunks = xt.reshape((world, m // world) + tail)
    q, scale = blockwise_quantize(chunks, dim=0, bits=bits, block=block)
    # int8 payload + fp32 scales ride the wire; dim 0 == world, so the
    # non-tiled all_to_all is exactly a (device, chunk) transpose
    q_t = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s_t = lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0)
    deq = blockwise_dequantize(q_t, s_t, (world,) + (m // world,) + tail,
                               dim=0, dtype=jnp.float32, bits=bits)
    red = jnp.sum(deq, axis=0)
    return jnp.moveaxis(red.astype(x.dtype), 0, dim)


def quantized_psum_scatter(x, axes, dim, bits: int = 8,
                           block: int = DEFAULT_BLOCK):
    """Drop-in for ``lax.psum_scatter(x, axes, scatter_dimension=dim,
    tiled=True)`` with a quantized wire.  Multiple axes reduce
    sequentially in tuple order, which matches the joint tiled
    psum_scatter's axis-major chunk assignment (each stage re-quantizes
    its partial sums — errors stay blockwise-bounded per stage)."""
    _check_bits(bits, "qgz_bits")
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    for ax in axes:
        x = _quantized_reduce_scatter_one_axis(x, ax, dim, bits, block)
    return x


def qgz_reduce_scatter_inner(x, error, axis_name: str = DATA_AXIS,
                             dim: int = 0, bits: int = 8,
                             block: int = DEFAULT_BLOCK):
    """Error-compensated quantized reduce-scatter; call inside shard_map.

    Generalizes comm/compressed.py's 1-bit error feedback to multi-bit
    blockwise quantization: the persistent ``error`` buffer (same shape
    as ``x``, carried by the caller across steps) absorbs this step's
    quantization residual, so repeated reductions of a persistent signal
    converge on the exact mean (same telescoping argument as 1-bit Adam,
    reference runtime/comm/nccl.py:47).

    Returns ``(reduced_chunk, new_error)`` where ``reduced_chunk`` is
    this shard's SUM over workers of its ``dim``-chunk (divide by the
    axis size for a mean), and ``new_error = (x + error) -
    dequant(quant(x + error))``.
    """
    _check_bits(bits, "qgz_bits")
    world = lax.axis_size(axis_name)
    compensated = x + error
    xt = jnp.moveaxis(compensated, dim, 0)
    m = xt.shape[0]
    if m % world != 0:
        raise ValueError(
            f"qgz reduce-scatter: dim {dim} (size {m}) must be divisible "
            f"by the {axis_name!r} axis size {world}")
    tail = xt.shape[1:]
    chunks = xt.reshape((world, m // world) + tail)
    q, scale = blockwise_quantize(chunks, dim=0, bits=bits, block=block)
    applied = blockwise_dequantize(
        q, scale, chunks.shape, dim=0, dtype=compensated.dtype, bits=bits)
    q_t = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s_t = lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0)
    deq = blockwise_dequantize(q_t, s_t, chunks.shape, dim=0,
                               dtype=jnp.float32, bits=bits)
    reduced = jnp.moveaxis(jnp.sum(deq, axis=0).astype(x.dtype), 0, dim)
    new_error = compensated - jnp.moveaxis(
        applied.reshape((m,) + tail), 0, dim)
    return reduced, new_error


def init_error_feedback(tree):
    """Zero-initialized persistent error buffers matching a grad tree —
    the caller carries these across steps (the analog of the reference's
    worker_error allocation, runtime/comm/nccl.py:47)."""
    return jax.tree.map(jnp.zeros_like, tree)


def qgz_reduce_scatter(x_stacked, error_stacked, mesh_ctx=None,
                       axis_name: str = DATA_AXIS, bits: int = 8,
                       block: int = DEFAULT_BLOCK):
    """Worker-stacked wrapper (same calling convention as
    comm/compressed.py's compressed_allreduce): ``x_stacked [W, ...]``
    holds worker i's tensor in row i, sharded over ``axis_name``.

    Returns ``(reduced [W, chunk...], new_error [W, ...])`` — row i of
    ``reduced`` is worker i's reduce-scattered chunk (sum over workers
    of chunk i of the element dim 0 after the worker dim), and
    ``new_error`` is the per-worker compensation state to carry into the
    next call.
    """
    from ...parallel.mesh import get_mesh_context
    from jax.sharding import PartitionSpec as P
    ctx = mesh_ctx or get_mesh_context()
    spec = P(axis_name)

    def inner(a, e):
        r, ne = qgz_reduce_scatter_inner(a[0], e[0], axis_name, dim=0,
                                         bits=bits, block=block)
        return r[None], ne[None]

    fn = jax.shard_map(inner, mesh=ctx.mesh, in_specs=(spec, spec),
                       out_specs=(spec, spec), check_vma=False)
    return fn(x_stacked, error_stacked)


# --------------------------------------------------------------------- #
# wire-byte accounting (for tests / perf triage)
# --------------------------------------------------------------------- #
_GATHER_PRIMS = ("all_gather",)
_REDUCE_PRIMS = ("psum_scatter", "reduce_scatter", "all_to_all", "psum")


def collective_wire_bytes(jaxpr) -> dict:
    """Walk a (closed) jaxpr — recursing into every sub-jaxpr
    (scan/while/cond/remat/shard_map/custom_vjp bwd...) via the shared
    dispatcher in analysis/jaxpr_walk.py — and sum an approximate wire
    volume per collective family: output bytes for gathers (the payload
    that landed), operand bytes for reductions/all-to-alls (the payload
    that left), plus ``fcm_bytes`` for per-tile fused-collective-matmul
    ring hops (ppermutes traced under constants.FCM_SCOPE — a generic
    ppermute stays excluded, ring attention's hops are lockstep-only).
    Loop trip counts are NOT multiplied in, so use this for
    same-structure A/B ratios (quantized vs fp32 path), not absolute
    traffic — the Program Auditor's comm-budget lint
    (analysis/rules.py:step_wire_bytes) does the trip-weighted version."""
    from ... import constants as _C
    from ...analysis.jaxpr_walk import (aval_bytes, iter_eqns,
                                        scope_has_component)
    out = {"gather_bytes": 0, "reduce_bytes": 0, "fcm_bytes": 0,
           "onebit_bytes": 0}
    for ctx in iter_eqns(jaxpr):
        name = ctx.eqn.primitive.name
        onebit = scope_has_component(ctx.scope, _C.ONEBIT_SCOPE)
        if name in _GATHER_PRIMS:
            b = sum(aval_bytes(v) for v in ctx.eqn.outvars)
            out["gather_bytes"] += b
            if onebit:
                # attribution breakout (docs/onebit.md): the packed-sign
                # exchange is already counted in the gather/reduce totals;
                # this keys how much of the wire is the 1-bit momentum sync
                out["onebit_bytes"] += b
        elif name in _REDUCE_PRIMS:
            b = sum(aval_bytes(v) for v in ctx.eqn.invars)
            out["reduce_bytes"] += b
            if onebit:
                out["onebit_bytes"] += b
        elif name == "ppermute" and scope_has_component(ctx.scope,
                                                        _C.FCM_SCOPE):
            out["fcm_bytes"] += sum(aval_bytes(v)
                                    for v in ctx.eqn.invars)
    return out
