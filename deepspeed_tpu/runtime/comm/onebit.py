"""1-bit (communication-compressed) optimizers — placeholder wiring.

Reference: deepspeed/runtime/fp16/onebit/adam.py:14 (OnebitAdam),
onebit/lamb.py:471 (OnebitLamb), runtime/comm/nccl.py:47
(compressed_allreduce = sign compression + error feedback).

The full TPU implementation (sign-compressed psum with error feedback inside
shard_map over the data axis) lands with the comm subsystem; until then the
optimizer math falls back to uncompressed Adam/LAMB so configs referencing
OneBitAdam still train correctly (warmup behavior == full-precision stage).
"""

from ...utils.logging import logger


def build_onebit_optimizer(name, cfg, lr):
    import optax
    logger.warning(
        f"{name}: compressed-communication stage not yet wired; running the "
        f"full-precision (warmup-equivalent) path")
    betas = cfg.get("betas", (0.9, 0.999))
    if "lamb" in name:
        from ..optimizers import _lamb
        return _lamb(lr, b1=betas[0], b2=betas[1],
                     eps=cfg.get("eps", 1e-6),
                     weight_decay=cfg.get("weight_decay", 0.0))
    return optax.adam(lr, b1=betas[0], b2=betas[1], eps=cfg.get("eps", 1e-8))
