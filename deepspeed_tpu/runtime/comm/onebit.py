"""1-bit (communication-compressed) optimizers.

Reference: deepspeed/runtime/fp16/onebit/adam.py:14 (OnebitAdam) and
onebit/lamb.py:471 (OnebitLamb): full-precision Adam/LAMB "warmup" until
`freeze_step`, then the variance freezes and the momentum is synchronized
through an error-compensated 1-bit allreduce
(runtime/comm/nccl.py:47 compressed_allreduce).

TPU recasting: the engine's gradients arrive already data-parallel-reduced
(XLA collective inside the compiled grad program), so the optimizer keeps
the *numerics* of the compressed stage — sign·scale momentum with error
feedback, frozen variance — as an optax transformation; the wire-level
compressed collective itself lives in comm/compressed.py
(compressed_allreduce_inner) for shard_map training loops that want the
DCN bandwidth win.  On ICI-bound meshes the dense psum is typically faster
— benchmark before enabling (SURVEY.md §7 honesty note).
"""

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ...utils.logging import log_dist
from .low_bandwidth import DEFAULT_BLOCK


class OnebitState(NamedTuple):
    count: jnp.ndarray
    m: optax.Updates
    v: optax.Updates
    error: optax.Updates


def _sign_compress(m, error):
    comp = m + error
    scale = jnp.mean(jnp.abs(comp))
    cm = scale * jnp.sign(comp)
    return cm, comp - cm


def adam_step_math(m, v, bias1, bias2, eps, weight_decay=0.0, p=None):
    """The raw (pre-lr) Adam step — single-sourced so the engine's
    compressed-phase apply region (docs/onebit.md) and the optax path
    below can never drift numerically."""
    step = (m / bias1) / (jnp.sqrt(v / bias2) + eps)
    if weight_decay > 0 and p is not None:
        step = step + weight_decay * p
    return step


def lamb_trust_math(u, p, lr, min_trust, max_trust):
    """LAMB trust scaling of an update ``u = -lr*step`` (reference
    onebit/lamb.py:232-249): the ratio is defined on the RAW step, so lr
    is divided back out of the update norm — single-sourced with
    :func:`onebit_lamb` for the engine's compressed-phase apply."""
    p_norm = jnp.linalg.norm(p.reshape(-1))
    raw_norm = (jnp.linalg.norm(u.reshape(-1)) /
                jnp.maximum(lr, 1e-30))
    ratio = jnp.where(
        (p_norm > 0) & (raw_norm > 0),
        jnp.clip(p_norm / raw_norm, min_trust, max_trust), 1.0)
    return u * ratio


def onebit_leaf_saves_bytes(shape, dtype, world: int,
                            block: int = DEFAULT_BLOCK) -> bool:
    """Per-leaf wire-cost gate (the quantized_gather_saves_bytes idiom):
    True when the packed two-stage sign exchange moves fewer bytes than
    a dense psum of the leaf, under the repo's wire accounting
    (all_to_all at operand bytes, all_gather at output bytes).  Skinny
    leaves — biases, layernorm scales — lose to the blockwise-scale
    overhead plus chunk padding and stay on the dense wire."""
    n = math.prod(shape) if shape else 1
    dense = n * jnp.dtype(dtype).itemsize
    chunk = -(-n // (world * block)) * block
    n_pad = chunk * world
    nb = chunk // block
    # bits each way (8 signs/byte) + fp32 blockwise scales each way
    packed = n_pad // 4 + 8 * world * nb
    return packed < dense


def init_onebit_wire_error(params, world: int):
    """Worker-stacked error-feedback state for the packed wire: one
    fp32 residual per worker per leaf, [W, ...] sharded over the data
    axis so each device holds only its own row."""
    return jax.tree.map(
        lambda p: jnp.zeros((world,) + tuple(p.shape), jnp.float32), params)


def onebit_hyperparams(name: str, cfg: dict) -> dict:
    """The onebit optimizers' hyperparameters with their defaults —
    single-sourced between :func:`build_onebit_optimizer` and the
    engine's compressed-phase program builder."""
    betas = tuple(cfg.get("betas", (0.9, 0.999)))
    is_lamb = "lamb" in name
    hp = {"b1": float(betas[0]), "b2": float(betas[1]),
          "freeze_step": int(cfg.get("freeze_step", 100)),
          "weight_decay": float(cfg.get("weight_decay", 0.0)),
          "eps": float(cfg.get("eps", 1e-6 if is_lamb else 1e-8)),
          "lamb": is_lamb}
    if is_lamb:
        hp["min_trust"] = float(cfg.get("min_coeff", 0.01))
        hp["max_trust"] = float(cfg.get("max_coeff", 10.0))
    return hp


def onebit_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100) -> optax.GradientTransformation:
    """OnebitAdam (reference onebit/adam.py:14) as an optax transform."""

    def init(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return OnebitState(jnp.zeros((), jnp.int32), zeros,
                           jax.tree.map(jnp.zeros_like, params), zeros)

    def update(grads, state, params=None):
        count = state.count + 1
        in_warmup = count <= freeze_step

        m_raw = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                             state.m, grads)

        def warm(mr, err):
            return mr, err

        compressed = jax.tree.map(
            lambda mr, err: jax.lax.cond(in_warmup, warm, _sign_compress,
                                         mr, err),
            m_raw, state.error)
        m_new = jax.tree.map(lambda t: t[0], compressed,
                             is_leaf=lambda t: isinstance(t, tuple))
        err_new = jax.tree.map(lambda t: t[1], compressed,
                               is_leaf=lambda t: isinstance(t, tuple))

        # variance freezes after warmup (reference: exp_avg_sq stops
        # updating once compression starts)
        v_new = jax.tree.map(
            lambda v, g: jnp.where(in_warmup, b2 * v + (1 - b2) * g * g, v),
            state.v, grads)

        lr = (learning_rate(count - 1) if callable(learning_rate)
              else learning_rate)
        bias1 = 1 - b1 ** count.astype(jnp.float32)
        bias2 = 1 - b2 ** jnp.minimum(
            count, freeze_step).astype(jnp.float32)

        def upd(m, v, p):
            return -lr * adam_step_math(m, v, bias1, bias2, eps,
                                        weight_decay, p)

        updates = (jax.tree.map(upd, m_new, v_new, params)
                   if params is not None else
                   jax.tree.map(lambda m, v: upd(m, v, None), m_new, v_new))
        return updates, OnebitState(count, m_new, v_new, err_new)

    return optax.GradientTransformation(init, update)


def onebit_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-6, weight_decay: float = 0.0,
                freeze_step: int = 100,
                min_trust: float = 0.01, max_trust: float = 10.0
                ) -> optax.GradientTransformation:
    """OnebitLamb (reference onebit/lamb.py:471): onebit_adam step scaled by
    the per-leaf LAMB trust ratio."""
    base = onebit_adam(learning_rate, b1, b2, eps, 0.0, freeze_step)

    def init(params):
        return base.init(params)

    def update(grads, state, params=None):
        updates, new_state = base.update(grads, state, params)
        if params is None:
            return updates, new_state
        lr = (learning_rate(state.count)
              if callable(learning_rate) else learning_rate)
        lr = jnp.asarray(lr, jnp.float32)
        if weight_decay > 0:
            # decoupled decay enters before the trust ratio (LAMB):
            # update = -lr*(adam_step + wd*p); base holds -lr*adam_step
            updates = jax.tree.map(
                lambda u, p: u - lr * weight_decay * p, updates, params)

        updates = jax.tree.map(
            lambda u, p: lamb_trust_math(u, p, lr, min_trust, max_trust),
            updates, params)
        return updates, new_state

    return optax.GradientTransformation(init, update)


def build_onebit_optimizer(name, cfg, lr):
    hp = onebit_hyperparams(name, cfg)
    log_dist(
        f"{name}: warmup(full-precision) for {hp['freeze_step']} steps, "
        f"then error-feedback 1-bit momentum with frozen variance",
        ranks=[0])
    if hp["lamb"]:
        return onebit_lamb(lr, b1=hp["b1"], b2=hp["b2"], eps=hp["eps"],
                           weight_decay=hp["weight_decay"],
                           freeze_step=hp["freeze_step"],
                           min_trust=hp["min_trust"],
                           max_trust=hp["max_trust"])
    return onebit_adam(lr, b1=hp["b1"], b2=hp["b2"], eps=hp["eps"],
                       weight_decay=hp["weight_decay"],
                       freeze_step=hp["freeze_step"])
