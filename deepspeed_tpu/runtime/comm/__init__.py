from .compressed import compressed_allreduce, compressed_allreduce_inner
from .low_bandwidth import (as_quantized_weight, blockwise_dequantize,
                            blockwise_quantize, init_error_feedback,
                            low_bandwidth_all_gather, qgz_reduce_scatter,
                            qgz_reduce_scatter_inner,
                            quantized_gather_saves_bytes,
                            quantized_psum_scatter)
