"""ZeRO-3 explicit parameter streaming — gather-at-use with live-set control.

Reference semantics: stage3.py's PartitionedParameterCoordinator (:294) keeps
at most ``stage3_max_live_parameters`` gathered at once, prefetches the next
``stage3_prefetch_bucket_size`` elements ahead of use (PrefetchCoordinator
:169), and releases each submodule's params after use (:460).  The reference
implements this with per-module torch hooks and hand-scheduled NCCL
all-gathers.

TPU recasting: for stacked-layer models (leaves ``[L, ...]`` scanned with
``lax.scan``), the live-set control is a *program structure*, not a hook
protocol.  The layer stack runs inside a partial-manual ``jax.shard_map``
over the ZeRO ("data","expert") axes:

  - each scan step ``lax.all_gather``\\ s exactly one layer group's shards
    (tiled) — the gather-at-use of stage3.py:522 ``_all_gather``;
  - when the scan step ends, XLA frees the gathered buffer — the release of
    stage3.py:460 ``release_sub_module``;
  - the group size is chosen so ``layers_per_step × params_per_layer ≤
    stage3_max_live_parameters`` — max-live honored by construction;
  - with prefetch enabled (``stage3_prefetch_bucket_size`` covering a
    group) the scan carries a double buffer: the gather for group ``i+1``
    is ISSUED into the scan carry before group ``i``'s compute and
    consumed one iteration later (``stage3_prefetch_mode: carried``, the
    default), so the gather's issue→first-consume distance spans a full
    group of MXU work — overlap as a *program-graph property* (T3,
    arXiv:2401.16677) that the Schedule Auditor verifies statically,
    rather than a scheduling opportunity XLA may or may not take.  The
    backward re-gather sweep is double-buffered the same way.  This is
    the role of PrefetchCoordinator's trace-based lookahead, without
    needing a trace (the scan order IS the trace);
    ``stage3_prefetch_mode: unrolled`` keeps the legacy unroll-2 body
    (overlap left to XLA's latency-hiding scheduler);
  - the backward of a tiled all-gather over the ZeRO axes is a
    psum-scatter — run in fp32 regardless of compute dtype
    (_all_gather_f32grad): layer gradients leave the region already
    reduce-scattered to their owner shard with fp32 accumulation
    (stage3.py:1908 grad partitioning, tightened).

Tensor-parallel ("model") and any other non-ZeRO axes stay *automatic*
(GSPMD) inside the region — explicit ZeRO streaming composes with
declarative TP.

Scan-in-scan (fused whole-step program, runtime/fused_step.py): the fused
train step wraps this layer scan in an OUTER ``lax.scan`` over the
microbatch axis.  No special casing is needed here, but the composition
leans on an invariant of this file: gathered layer groups are NEVER saved
as residuals.  In ``carried`` mode that is structural — the hand-written
VJP's residuals are the group-boundary activation carries plus the
sharded inputs, and the backward re-gathers (``_build_carried_stream``);
in ``unrolled``/``off`` modes the ``zero3_gathered`` checkpoint-name
policy (see ``gather_group``) does the same job through the remat
machinery.  Without the invariant the fused program would save gas ×
(full unsharded model) and defeat max_live across microbatches, not just
within one.  Tested by test_fused_step.py::test_fused_zero3_streaming_parity.
"""

import logging
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec

from ...constants import ZERO_OPTIMIZATION_PREFETCH_MODES as PREFETCH_MODES
from ...ops.collective_matmul import fcm_all_gather, fcm_reduce_scatter
from ...parallel.mesh import MeshContext, ZERO_AXES
from ...utils.logging import log_dist
from ..comm.low_bandwidth import (f32_psum_scatter, largest_divisor_at_most,
                                  low_bandwidth_all_gather,
                                  quantized_gather_saves_bytes,
                                  quantized_psum_scatter)
from .partition import (filter_spec_axes, resolve_hpz_axes,
                        zero_partition_spec)


@dataclass(frozen=True)
class StreamPlan:
    """How the layer stack is grouped and prefetched.

    ``mode`` is the prefetch structure actually applied: ``carried`` is
    the double-buffered scan carry (gather for group i+1 issued under
    group i's compute, in both the forward and the backward re-gather
    sweep), ``unrolled`` is the legacy unroll-2 loop body (XLA's
    latency-hiding scheduler must find the overlap), ``off`` gathers
    each group at use.  ``forfeited`` records WHY a requested prefetch
    degraded to ``off`` (surfaced by the Schedule Auditor's overlap
    report and logged once at trace time)."""
    layers_per_step: int
    prefetch: bool
    num_layers: int
    params_per_layer: int
    mode: str = "off"
    forfeited: Optional[str] = None

    @property
    def live_parameters(self) -> int:
        """Worst-case simultaneously-gathered parameter count."""
        mult = 2 if self.prefetch else 1
        return mult * self.layers_per_step * self.params_per_layer


def plan_layer_streaming(num_layers: int, params_per_layer: int,
                         max_live_parameters: int,
                         prefetch_bucket_size: int,
                         prefetch_mode: str = "carried") -> StreamPlan:
    """Consume the stage-3 knobs into a concrete (group, prefetch) plan.

    ``stage3_max_live_parameters`` bounds the gathered set (reference
    zero/config.py ``max_live_parameters``); ``stage3_prefetch_bucket_size``
    enables lookahead when it covers at least one more layer group;
    ``stage3_prefetch_mode`` picks the prefetch program structure:

      carried   (default) the gather for group i+1 rides the scan carry —
                issue→first-consume spans a full group of MXU work, and
                the only constraint is >= 2 groups (any divisor group
                count works);
      unrolled  the legacy unroll-2 loop body — needs an EVEN group
                count (otherwise prefetch would cost double the gathers
                for zero overlap) and leaves the overlap to XLA's
                latency-hiding scheduler;
      off       gather at use, no lookahead.
    """
    if prefetch_mode not in PREFETCH_MODES:
        raise ValueError(
            f"stage3_prefetch_mode={prefetch_mode!r} — supported modes are "
            f"{list(PREFETCH_MODES)}")
    base_budget = max(1, int(max_live_parameters) // max(
        1, params_per_layer))
    # a bucket smaller than one layer group is the documented prefetch
    # OFF switch (no forfeit); a bucket that ASKS for prefetch which the
    # live-parameter budget then cannot honor is a loud forfeit below
    wants = (prefetch_mode != "off" and
             int(prefetch_bucket_size) >= params_per_layer)
    want_prefetch = wants and base_budget >= 2
    forfeited = None
    if wants and not want_prefetch:
        forfeited = (
            f"stage3_max_live_parameters holds {base_budget} layer(s) — "
            "a double buffer needs at least 2 (current + prefetched "
            "group)")
    if want_prefetch:
        # live set holds current + prefetched group
        budget = base_budget // 2
        if prefetch_mode == "carried":
            candidates = [g for g in range(1, budget + 1)
                          if num_layers % g == 0 and num_layers // g >= 2]
            if candidates:
                return StreamPlan(layers_per_step=max(candidates),
                                  prefetch=True, num_layers=num_layers,
                                  params_per_layer=params_per_layer,
                                  mode="carried")
            forfeited = (
                f"{num_layers} layer(s) cannot form >= 2 groups within "
                f"the double-buffer budget of {budget} group(s)")
        else:
            # the unroll-2 execution needs an EVEN number of groups —
            # otherwise prefetch would silently cost double the gathers
            # for zero overlap
            candidates = [g for g in range(1, budget + 1)
                          if num_layers % g == 0 and
                          (num_layers // g) % 2 == 0
                          and num_layers // g >= 2]
            if candidates:
                return StreamPlan(layers_per_step=max(candidates),
                                  prefetch=True, num_layers=num_layers,
                                  params_per_layer=params_per_layer,
                                  mode="unrolled")
            forfeited = (
                f"no group size with an EVEN group count divides "
                f"{num_layers} layers within the double-buffer budget of "
                f"{budget} group(s) (unrolled prefetch pairs groups; "
                f"stage3_prefetch_mode=carried has no such constraint)")
    g = largest_divisor_at_most(num_layers, base_budget)
    return StreamPlan(layers_per_step=g, prefetch=False,
                      num_layers=num_layers,
                      params_per_layer=params_per_layer, mode="off",
                      forfeited=forfeited)


def _jaxpr_has_pallas(jaxpr) -> bool:
    """Recursively walk a jaxpr (and every sub-jaxpr riding in eqn
    params) for pallas primitives."""
    for eqn in jaxpr.eqns:
        if "pallas" in eqn.primitive.name:
            return True
        for v in eqn.params.values():
            for sub in jax.tree.leaves(
                    v, is_leaf=lambda x: hasattr(x, "jaxpr") or
                    hasattr(x, "eqns")):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns") and _jaxpr_has_pallas(inner):
                    return True
    return False


def _body_uses_pallas(body, init_carry, p_tree, p_leaves, extra_xs) -> bool:
    """Abstractly trace ONE layer application of the user body and report
    whether it contains a pallas_call (which the shard_map vma analysis
    cannot see through).  Tracing failures — e.g. a body that needs the
    live mesh context — return True so check_vma stays conservatively
    off."""
    try:
        layer0 = p_tree.unflatten(
            [jax.ShapeDtypeStruct(leaf.shape[1:], leaf.dtype) for leaf in p_leaves])
        extras0 = jax.tree.map(
            lambda e: jax.ShapeDtypeStruct(e.shape[1:], e.dtype), extra_xs)
        carry0 = jax.tree.map(
            lambda c: jax.ShapeDtypeStruct(c.shape, c.dtype), init_carry)
        jaxpr = jax.make_jaxpr(
            lambda c, leaf, e: body(c, (leaf,) + tuple(e)))(
            carry0, layer0, extras0)
        return _jaxpr_has_pallas(jaxpr.jaxpr)
    except Exception:  # noqa: BLE001 — conservative on any trace failure
        return True


def _restrict_to_manual(spec: PartitionSpec, manual: frozenset
                        ) -> PartitionSpec:
    """Strip non-manual axes from a spec (shard_map in_specs may only name
    manual axes; auto axes ride along on the array sharding)."""
    return filter_spec_axes(spec, manual.__contains__)


def _gather_dims(spec: PartitionSpec, manual: frozenset):
    """[(dim, (axes...)), ...] — where tiled all-gathers must run."""
    out = []
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(a for a in axes if a in manual)
        if kept:
            out.append((dim, kept))
    return out


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _all_gather_f32grad(x, axes, dim):
    """Tiled all-gather whose transpose reduce-scatters in float32.

    Forward: identical to ``lax.all_gather(tiled=True)`` — shards move at
    their native width (bf16 gathers cost bf16 bytes).  Backward: the layer
    gradient is promoted to fp32 BEFORE the ``psum_scatter`` and demoted
    back after, so the cross-shard gradient reduction accumulates in fp32
    regardless of compute dtype (the reference reduces fp16 grads natively,
    stage3.py:1908; fp32 accumulation strictly tightens that).  This also
    keeps the manual region's only reduction collective out of XLA-CPU's
    AllReducePromotion pass, which hard-aborts on half-precision reduction
    collectives ('Invalid binary instruction opcode copy') — bf16 streaming
    now runs identically on CPU and TPU."""
    return lax.all_gather(x, axes, axis=dim, tiled=True)


def _ag_fwd(x, axes, dim):
    return _all_gather_f32grad(x, axes, dim), None


def _ag_bwd(axes, dim, _, g):
    return (f32_psum_scatter(g, axes, dim),)


_all_gather_f32grad.defvjp(_ag_fwd, _ag_bwd)


def _index_tree(tree, i):
    """Dynamic per-group slice of a ``[steps, ...]``-stacked pytree."""
    return jax.tree.map(
        lambda leaf: lax.dynamic_index_in_dim(leaf, i, keepdims=False), tree)


def _body_closes_over_tracers(body) -> bool:
    """True when the user body (or a callable it closes over, two levels
    deep) captures live JAX tracers.  NO streaming mode differentiates
    such a body — shard_map cannot transpose captured tracers
    (NotImplementedError in off/unrolled), and the carried custom_vjp
    differentiates only its explicit inputs (UnexpectedTracerError) —
    both failures surface deep inside grad with no hint at the cause,
    so scan() detects the capture up front and logs the actionable
    diagnosis: thread those values through ``stacked_params`` /
    ``extra_xs``.  (Forward-only use still works: the captured value
    rides the region as a replicated const.)"""
    seen = set()

    def has_tracer(v):
        try:
            return any(isinstance(leaf, jax.core.Tracer)
                       for leaf in jax.tree.leaves(v))
        except Exception:  # noqa: BLE001 — exotic leaves: assume clean
            return False

    def check(fn, depth):
        if depth > 2 or not callable(fn) or id(fn) in seen:
            return False
        seen.add(id(fn))
        fn = getattr(fn, "__func__", fn)  # unwrap bound methods
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                v = cell.cell_contents
            except ValueError:  # empty cell
                continue
            if isinstance(v, jax.core.Tracer) or has_tracer(v):
                return True
            if callable(v) and check(v, depth + 1):
                return True
        return False

    return check(body, 0)


def _build_carried_stream(steps: int, gather_group, run_group,
                          scatter_grads):
    """Carried double-buffer executor with a hand-scheduled VJP.

    Program structure (``stage3_prefetch_mode: carried``)::

        forward:  full(0) = gather(group 0)                 # prologue
                  scan i = 0 .. S-2, carry (act, full(i)):
                      issue gather(group i+1)  -> next carry
                      act = compute(act, full(i))           # a FULL group
                                                            # of MXU slack
                  act = compute(act, full(S-1))             # epilogue
        backward: re-gather(S-1), issue re-gather(S-2)      # prologue
                  reverse scan i = S-2 .. 1, carry (cot, full(i)):
                      issue re-gather(group i-1) -> next carry
                      cot = vjp(compute)(cot) @ full(i)
                  cot = vjp(compute)(cot) @ full(0)         # epilogue

    Why a custom VJP instead of the ``zero3_gathered`` checkpoint-name
    policy alone: a gathered buffer riding a ``lax.scan`` carry is a
    *body input* of every step, and scan partial evaluation demands body
    inputs as stacked residuals — the name policy only prunes values
    produced INSIDE the rematerialized body, so the naive carried scan
    saves ``steps x group`` = the full unsharded model and defeats
    ``stage3_max_live_parameters`` outright (verified: the stacked
    ``[S, full]`` residual appears in the grad jaxpr).  Hand-writing the
    VJP extends the policy's intent across the carry: gathered buffers
    are dropped from residuals entirely and RE-GATHERED in the backward
    (the reference's backward re-fetch, stage3.py:546
    PreBackwardFunction) — and the re-gathers get their own carried
    double buffer, so the backward's wire hides under the backward's
    compute exactly like the forward's.

    The residuals saved are the per-group INPUT activation carries (the
    forward scan's ys) plus the sharded inputs; each group's internal
    activations are rematerialized inside its backward step (``jax.vjp``
    re-runs ``run_group`` from the saved carry).  That is one extra
    forward pass of the layer stack per step — the deliberate trade for
    taking BOTH directions' gathers off the critical path while keeping
    peak gathered memory at ``2 x layers_per_step x params_per_layer``
    (models running remat anyway, e.g. ``activation_checkpointing``,
    were already paying it).

    ``steps`` must be >= 2 (the plan guarantees it in carried mode).
    ``gather_group(shards) -> full``, ``run_group(act, full, extras) ->
    act`` and ``scatter_grads(g_full) -> g_shards`` (the exact transpose
    of ``gather_group``'s wire, qwZ/qgZ aware) come from the enclosing
    :meth:`Zero3StreamContext.scan` trace.
    """

    if steps < 2:
        raise ValueError(
            f"carried prefetch needs >= 2 layer groups, got {steps} — "
            "plan_layer_streaming should have forfeited to mode=off")

    # a list of leaves is a pytree: _index_tree slices shard groups too
    _group_shards = _index_tree

    def _forward(c0, params_g, extras_g):
        first = gather_group(_group_shards(params_g, 0))

        def fbody(carry, i):
            c, cur = carry
            # issue i+1's gather BEFORE group i's compute: the result is
            # consumed next iteration (carried), so its wire has the
            # whole group's MXU work as slack
            nxt = gather_group(_group_shards(params_g, i + 1))
            c_out = run_group(c, cur, _index_tree(extras_g, i))
            return (c_out, nxt), c

        (c_pen, last), c_ins = lax.scan(
            fbody, (c0, first), jnp.arange(steps - 1))
        c_fin = run_group(c_pen, last, _index_tree(extras_g, steps - 1))
        return c_fin, (c_pen, c_ins)

    @jax.custom_vjp
    def carried(c0, params_g, extras_g):
        return _forward(c0, params_g, extras_g)[0]

    def carried_fwd(c0, params_g, extras_g):
        c_fin, (c_pen, c_ins) = _forward(c0, params_g, extras_g)
        # residuals: group-boundary activation carries (c_ins[0] IS c0)
        # + the SHARDED inputs — never a gathered buffer
        return c_fin, (c_pen, c_ins, params_g, extras_g)

    def carried_bwd(res, g_out):
        c_pen, c_ins, params_g, extras_g = res
        ex_leaves = jax.tree.leaves(extras_g)
        ex_tree = jax.tree.structure(extras_g)
        is_float = [jnp.issubdtype(leaf.dtype, jnp.inexact)
                    for leaf in ex_leaves]

        def float_only(g_ex):
            return [leaf for leaf, f in zip(jax.tree.leaves(g_ex), is_float)
                    if f]

        def group_vjp(c_in, full, ex_i, g_c):
            _, vjp_fn = jax.vjp(run_group, c_in, full, ex_i)
            return vjp_fn(g_c)

        # group S-1: backward re-fetch, with S-2's re-gather issued
        # BEFORE the transposed compute (the backward's own prologue
        # double buffer)
        full_last = gather_group(_group_shards(params_g, steps - 1))
        full_prev = gather_group(_group_shards(params_g, steps - 2))
        g_c, g_full, g_ex = group_vjp(
            c_pen, full_last, _index_tree(extras_g, steps - 1), g_out)
        g_sh_last = scatter_grads(g_full)
        g_ex_last = float_only(g_ex)

        def bbody(carry, i):
            g_c, cur = carry
            nxt = gather_group(_group_shards(params_g, i - 1))
            g_c, g_full, g_ex = group_vjp(
                _index_tree(c_ins, i), cur, _index_tree(extras_g, i), g_c)
            return (g_c, nxt), (scatter_grads(g_full), float_only(g_ex))

        (g_c, cur0), (g_sh_mid, g_ex_mid) = lax.scan(
            bbody, (g_c, full_prev), jnp.arange(1, steps - 1),
            reverse=True)

        # group 0: consumes the last carried re-gather
        g_c0, g_full, g_ex = group_vjp(
            _index_tree(c_ins, 0), cur0, _index_tree(extras_g, 0), g_c)
        g_sh0 = scatter_grads(g_full)
        g_ex0 = float_only(g_ex)

        g_params = [jnp.concatenate([a[None], mid, b[None]], axis=0)
                    for a, mid, b in zip(g_sh0, g_sh_mid, g_sh_last)]
        out_ex, fi = [], 0
        for leaf, f in zip(ex_leaves, is_float):
            if f:
                out_ex.append(jnp.concatenate(
                    [g_ex0[fi][None], g_ex_mid[fi], g_ex_last[fi][None]],
                    axis=0))
                fi += 1
            else:
                # integer / PRNG-key extras take the conventional float0
                # cotangent
                out_ex.append(np.zeros(jnp.shape(leaf), jax.dtypes.float0))
        return g_c0, g_params, jax.tree.unflatten(ex_tree, out_ex)

    carried.defvjp(carried_fwd, carried_bwd)
    return carried


class Zero3StreamContext:
    """Installable streaming executor for stacked-layer models.

    The engine builds one of these when zero stage 3 runs with explicit
    gathering, and hands it to the model via ``install_zero3_streaming``.
    The model then calls :meth:`scan` instead of ``lax.scan`` for its layer
    stack; everything else about the model is unchanged.
    """

    def __init__(self, mesh_ctx: MeshContext, max_live_parameters: int,
                 prefetch_bucket_size: int,
                 persistence_threshold: int = 0,
                 low_bandwidth=None, prefetch_mode: str = "carried"):
        # validation lives at the config boundary (config.py) and in
        # plan_layer_streaming (the public planner); no third copy here
        self.ctx = mesh_ctx
        self.max_live_parameters = int(max_live_parameters)
        self.prefetch_bucket_size = int(prefetch_bucket_size)
        self.prefetch_mode = prefetch_mode
        self.persistence_threshold = int(persistence_threshold)
        self.axis_sizes = {a: mesh_ctx.axis_size(a) for a in ZERO_AXES}
        self.manual = frozenset(
            a for a in ZERO_AXES if mesh_ctx.axis_size(a) > 1)
        self._plan_logged = False
        # ZeRO++-style low-bandwidth collectives (config.py
        # ZeroLowBandwidthConfig; comm/low_bandwidth.py): qwZ quantizes
        # the weight gathers, qgZ the grad reduce-scatters, hpZ confines
        # the hot-loop gathers to a sub-mesh via a secondary partition.
        self.lbc = (low_bandwidth if low_bandwidth is not None and
                    getattr(low_bandwidth, "enabled", False) else None)
        # T3-style fused collective-matmul (ops/collective_matmul.py):
        # the qwZ/qgZ transports move per-tile over a ring instead of as
        # one monolithic collective — the Schedule Auditor classifies
        # the per-tile wire as fused/hidden (docs/fused_collective_
        # matmul.md)
        self.fcm = bool(self.lbc is not None and getattr(
            self.lbc, "fused_collective_matmul", False))
        self.param_manual = self.manual
        self.param_axis_sizes = dict(self.axis_sizes)
        # last StreamPlan actually applied by scan() — set during
        # tracing, so the Schedule Auditor (analysis/auditor.py) can
        # name the streamed scan's structure in overlap findings
        self.last_plan: Optional[StreamPlan] = None
        if self.lbc is not None and self.lbc.hpz_group_size > 1:
            hpz = resolve_hpz_axes(self.axis_sizes,
                                   self.lbc.hpz_group_size)
            self.param_manual = frozenset(hpz) & self.manual
            self.param_axis_sizes = {
                a: (self.axis_sizes[a] if a in self.param_manual else 1)
                for a in ZERO_AXES}

    @property
    def active(self) -> bool:
        """Streaming is a no-op on a 1-way ZeRO mesh."""
        return bool(self.manual)

    def fold_shard_index(self, key):
        """Fold the ZeRO shard index into an rng key — models call this on
        per-layer dropout keys inside the streamed region so masks stay
        independent across batch shards.  Only legal inside the manual
        region (scan body); callers must gate on :meth:`usable`."""
        for ax in sorted(self.manual):
            key = jax.random.fold_in(key, lax.axis_index(ax))
        return key

    def usable(self, init_carry, carry_batch_dim: int = 0,
               params=None) -> bool:
        """True when :meth:`scan` will actually stream.  Models MUST gate
        both the scan call and any fold_shard_index use on this — it is the
        same predicate scan applies internally (scan falls back to a plain
        lax.scan when it is False).

        Streaming cannot apply when: 1-way ZeRO mesh, the global mesh has
        moved on since install (the model object outlives the engine —
        e.g. reused for inference), or the batch doesn't divide the ZeRO
        world (batch-1 decode).

        Half precision streams on every backend: the region's only
        reduction collective (the gather's transpose) runs in fp32 via
        ``_all_gather_f32grad``, which sidesteps XLA-CPU's half-precision
        AllReducePromotion abort that used to force a GSPMD fallback
        here."""
        del params  # kept for call-site compatibility
        if not self.active:
            return False
        from ...parallel import mesh as mesh_mod
        cur = mesh_mod.get_mesh_context(required=False)
        if cur is None or cur.mesh is not self.ctx.mesh:
            return False
        zero_world = int(np.prod([self.axis_sizes[a] for a in self.manual]))
        for leaf in jax.tree.leaves(init_carry):
            shape = getattr(leaf, "shape", ())
            if len(shape) <= carry_batch_dim or \
                    shape[carry_batch_dim] % zero_world != 0:
                return False
        return True

    # ------------------------------------------------------------------ #
    def _per_layer_zero_spec(self, leaf, tp_spec: Optional[PartitionSpec]
                             ) -> PartitionSpec:
        """ZeRO spec of ONE layer's slice (shape ``leaf.shape[1:]``) — the
        same decision function as ZeroPartitioner (partition.py), applied
        per-layer so the stream always shards within a layer and never
        across the layer axis (a layer-axis shard could not be gathered
        one group at a time).  When the engine's stacked-tree placement
        picked a different dim, shard_map simply reshards at entry.

        With hpZ on, ``param_axis_sizes`` confines the spec to the
        sub-mesh axes: the region entry reshard materializes the
        SECONDARY weight copy (one gather over the slow axes for the
        whole grouped stack, amortized across the scan — ZeRO++ hpZ's
        secondary allocation), and every hot-loop gather below stays
        within the fast sub-mesh."""
        tp_inner = (PartitionSpec(*list(tp_spec)[1:])
                    if tp_spec is not None else None)
        return zero_partition_spec(tuple(leaf.shape[1:]),
                                   self.param_axis_sizes,
                                   self.persistence_threshold, tp_inner)

    def _leaf_wire_bits(self, leaf, dim):
        """Per-leaf, per-direction quantization decision ``(qwz, qgz)``:
        a direction keeps its configured bits only when the narrowed
        payload actually beats the wire it replaces — a skinny leaf
        (bias gathered one layer at a time) would pay more in fp32
        block scales than it saves, so it degrades to 0 (dense) per
        direction.  The forward compares against the leaf's native
        width; the backward against fp32, because that is what the
        dense fallback's reduce-scatter moves for every float dtype
        (f32_psum_scatter promotes half grads)."""
        lbc = self.lbc
        if lbc is None or not jnp.issubdtype(leaf.dtype, jnp.floating):
            return 0, 0
        qwz = lbc.qwz_bits if (lbc.qwz_bits and quantized_gather_saves_bytes(
            leaf.shape, dim, leaf.dtype, lbc.qwz_bits, lbc.block_size)
        ) else 0
        qgz = lbc.qgz_bits if (lbc.qgz_bits and quantized_gather_saves_bytes(
            leaf.shape, dim, jnp.float32, lbc.qgz_bits, lbc.block_size)
        ) else 0
        return qwz, qgz

    def _gather_leaf(self, leaf, axes, dim):
        """One tiled all-gather: quantized wire per direction when it
        pays (``_leaf_wire_bits``), the fp32-transpose gather
        otherwise.  With ``fused_collective_matmul`` on, float leaves
        route through the per-tile ring transport instead — bitwise the
        same values, but the wire moves tile-by-tile under the
        consuming compute and classifies as fused/hidden in the
        Schedule Auditor's overlap report."""
        qwz, qgz = self._leaf_wire_bits(leaf, dim)
        if self.fcm and jnp.issubdtype(leaf.dtype, jnp.floating):
            return fcm_all_gather(leaf, axes, dim, qwz, qgz,
                                  self.lbc.block_size)
        if qwz or qgz:
            return low_bandwidth_all_gather(leaf, axes, dim, qwz, qgz,
                                            self.lbc.block_size)
        return _all_gather_f32grad(leaf, axes, dim)

    def plan_for(self, stacked_params: Any) -> StreamPlan:
        leaves = jax.tree.leaves(stacked_params)
        num_layers = int(leaves[0].shape[0])
        per_layer = sum(
            int(np.prod(leaf.shape[1:])) for leaf in leaves)
        return plan_layer_streaming(num_layers, per_layer,
                                    self.max_live_parameters,
                                    self.prefetch_bucket_size,
                                    self.prefetch_mode)

    def _leaf_transpose_plan(self, local_shape, dtype, dims):
        """Static transpose schedule of ``gather_group``'s wire for one
        leaf: ``[(dim, axes, qgz_bits), ...]`` in FORWARD gather order.
        The quantization decision replays ``_gather_leaf``'s per-step
        ``_leaf_wire_bits`` on the simulated intermediate shapes, so the
        carried backward's hand-applied scatter moves exactly the bytes
        ``low_bandwidth_all_gather``'s own transpose would (qgZ
        quantized reduce-scatter when configured and paying, the fp32
        promote-reduce-demote otherwise)."""
        shape = list(local_shape)
        plan = []
        for dim, axes in dims:
            leaf = jax.ShapeDtypeStruct(tuple(shape), dtype)
            _qwz, qgz = self._leaf_wire_bits(leaf, dim + 1)
            # the transpose wire depends only on qgz: both _lbag_bwd
            # (qwz path) and _ag_bwd (dense path) fall back to
            # f32_psum_scatter when qgz == 0
            plan.append((dim + 1, tuple(axes), qgz))
            world = int(np.prod([self.param_axis_sizes[a] for a in axes]))
            shape[dim + 1] *= world
        return plan

    # ------------------------------------------------------------------ #
    def scan(self, body, init_carry, stacked_params: Any, extra_xs: Any,
             param_tp_specs: Any = None, carry_batch_dim: int = 0):
        """Drop-in for ``lax.scan(body, init, (params, *extras))`` where
        ``body(carry, (layer_params, *layer_extras)) -> (carry, None)``.

        stacked_params: pytree of ``[L, ...]`` leaves to ZeRO-stream.
        extra_xs: pytree of ``[L, ...]`` leaves passed through replicated
        (layer RNGs, PLD keep-probabilities, ...).
        param_tp_specs: optional matching tree of tensor-parallel
        PartitionSpecs for the stacked leaves (layer axis included).
        carry_batch_dim: dimension of each carry leaf sharded over the ZeRO
        axes (the batch dimension).
        """
        if not self.usable(init_carry, carry_batch_dim,
                           params=stacked_params):
            carry, _ = lax.scan(
                lambda c, xs: body(c, xs),
                init_carry, (stacked_params,) + tuple(extra_xs))
            return carry

        plan = self.plan_for(stacked_params)
        if not self._plan_logged and _body_closes_over_tracers(body):
            # no streaming mode can DIFFERENTIATE a body that captures
            # traced values (shard_map cannot transpose captured
            # tracers; the carried custom_vjp differentiates only its
            # explicit inputs) — both failures are opaque deep inside
            # grad, so name the fix up front.  Forward-only use works.
            log_dist(
                "ZeRO-3 streaming: the scan body closes over traced "
                "values — gradients cannot flow to them through the "
                "streamed region (expect UnexpectedTracerError / "
                "NotImplementedError under grad); thread those values "
                "through stacked_params/extra_xs instead",
                ranks=[0], level=logging.WARNING)
        self.last_plan = plan
        if plan.forfeited and not self._plan_logged:
            # requested overlap fell back to serialized gathers — a
            # capacity fallback the operator should see once, loudly
            try:
                from ..resilience.degradation import record as degrade
                degrade("zero3_prefetch", "overlapped", "serialized",
                        plan.forfeited)
            except Exception:  # pragma: no cover — partial install
                pass
        if not self._plan_logged:
            lb = ""
            if self.lbc is not None:
                # key off the CONFIG, not param_manual == manual: a
                # group size equal to the full ZeRO world is a
                # configured (degenerate) hpZ, not "off"
                hpz = (sorted(self.param_manual)
                       if self.lbc.hpz_group_size > 1 else "off")
                lb = (f", low_bandwidth: qwz={self.lbc.qwz_bits}b "
                      f"qgz={self.lbc.qgz_bits}b hpz={hpz}"
                      f"{' fcm' if self.fcm else ''}")
            log_dist(
                f"ZeRO-3 streaming: {plan.num_layers} layers in groups of "
                f"{plan.layers_per_step}, prefetch={plan.prefetch} "
                f"(mode={plan.mode}), live<= {plan.live_parameters:,} "
                f"params (max_live={self.max_live_parameters:,}){lb}",
                ranks=[0])
            if plan.forfeited:
                log_dist(
                    f"ZeRO-3 streaming: prefetch FORFEITED — "
                    f"{plan.forfeited}; falling back to serialized "
                    f"at-use gathers ({plan.num_layers} layers in groups "
                    f"of {plan.layers_per_step})",
                    ranks=[0], level=logging.WARNING)
            self._plan_logged = True

        mesh = self.ctx.mesh
        manual = self.manual
        g = plan.layers_per_step
        steps = plan.num_layers // g

        # -- sharding specs for every shard_map operand ----------------- #
        if param_tp_specs is None:
            param_tp_specs = jax.tree.map(lambda _: None, stacked_params)
        tp_list = jax.tree.leaves(
            param_tp_specs,
            is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))
        p_leaves, p_tree = jax.tree_util.tree_flatten(stacked_params)
        if len(tp_list) != len(p_leaves):
            raise ValueError("param_tp_specs must mirror stacked_params")
        p_manual = self.param_manual  # == manual unless hpZ restricts it
        inner_specs = [self._per_layer_zero_spec(leaf, s)
                       for leaf, s in zip(p_leaves, tp_list)]
        in_param_specs = [
            PartitionSpec(None, *list(_restrict_to_manual(s, p_manual)))
            for s in inner_specs]
        gathers = [_gather_dims(s, p_manual) for s in inner_specs]
        # A leaf not gathered over EVERY manual axis enters the region
        # replicated along the uncovered axes, so its gradient is a psum
        # over those axes at the shard_map transpose boundary.  Such
        # half-precision leaves are widened to fp32 at entry (cast back to
        # their dtype at use) so that psum accumulates in fp32 — matching
        # _all_gather_f32grad's fp32 reduce-scatter for the gathered dims,
        # and keeping every reduction collective the region emits out of
        # XLA-CPU's half-precision AllReducePromotion abort.  Without hpZ
        # the uncovered leaves are the ones too small to shard further, so
        # the widened transfer is noise.  With hpZ EVERY leaf is uncovered
        # by design (gathers stop at param_manual; the slow outer axes
        # reduce grads once at the boundary) — the fp32 widening then
        # doubles the once-per-step entry reshard, a deliberate trade: the
        # hot-loop per-layer gathers, which hpZ is buying back, stay at
        # the quantized/native width, and the boundary grad psum must be
        # fp32 anyway (accumulation + the XLA-CPU abort above).
        leaf_dtypes = [leaf.dtype for leaf in p_leaves]

        def _covered_axes(dims):
            cov = set()
            for _, axes in dims:
                cov.update(axes)
            return cov

        widen = [
            _covered_axes(dims) != set(manual) and
            jnp.issubdtype(dt, jnp.floating) and jnp.dtype(dt).itemsize < 4
            for dims, dt in zip(gathers, leaf_dtypes)]

        def group_leaf(leaf):
            return leaf.reshape((steps, g) + tuple(leaf.shape[1:]))

        grouped_params = [
            group_leaf(leaf.astype(jnp.float32) if w else leaf)
            for leaf, w in zip(p_leaves, widen)]
        grouped_extras = jax.tree.map(group_leaf, extra_xs)
        # the group reshape shifts every dim by one: shift specs too
        def shift(spec):
            return PartitionSpec(None, *list(spec))
        in_specs_params = [shift(s) for s in in_param_specs]

        carry_spec = jax.tree.map(
            lambda c: PartitionSpec(
                *([None] * carry_batch_dim),
                tuple(sorted(manual, key=ZERO_AXES.index))),
            init_carry)
        extras_specs = jax.tree.map(lambda _: PartitionSpec(), grouped_extras)

        def gather_group(shards):
            """all-gather one layer group's param shards into full arrays.
            The +1 dim shift accounts for the group dimension.  Gathered
            values are checkpoint-named so the step's remat policy DROPS
            them from the saved residuals: without this, lax.scan's VJP
            would stack every step's gathered group — the full unsharded
            model — as a residual, defeating max_live entirely.  Backward
            re-gathers instead (exactly the reference's backward re-fetch,
            stage3.py:546 PreBackwardFunction)."""
            full = []
            for leaf, dims, dt, w in zip(shards, gathers, leaf_dtypes,
                                         widen):
                for dim, axes in dims:
                    leaf = self._gather_leaf(leaf, axes, dim + 1)
                if w:
                    leaf = leaf.astype(dt)
                full.append(checkpoint_name(leaf, "zero3_gathered"))
            return full

        def run_group(carry, full_group, extras_group):
            """Unrolled pass over the g layers inside one gathered group."""
            for j in range(g):
                layer = p_tree.unflatten(
                    [leaf[j] for leaf in full_group])
                extras_j = jax.tree.map(lambda e: e[j], extras_group)
                carry, _ = body(carry, (layer,) + tuple(extras_j))
            return carry

        if plan.mode == "carried":
            # Carried double-buffer prefetch (_build_carried_stream): the
            # gather for group i+1 rides the scan carry, issued under
            # group i's compute, and the hand-written VJP re-gathers in a
            # reverse scan with its own carried double buffer — gathered
            # buffers never become scan residuals (the naive carried
            # structure would stack the full unsharded model; see the
            # builder's docstring), preserving StreamPlan.live_parameters'
            # 2x bound.  The transpose schedule below replays the exact
            # qwZ/qgZ wire decisions _gather_leaf makes, from the LOCAL
            # (in-region) shard shapes.
            block = self.lbc.block_size if self.lbc is not None else 0

            def local_group_shape(k):
                shape = [g] + list(p_leaves[k].shape[1:])
                for d, axes in gathers[k]:
                    world = int(np.prod(
                        [self.param_axis_sizes[a] for a in axes]))
                    shape[d + 1] //= world
                return shape

            transpose_plans = [
                self._leaf_transpose_plan(
                    local_group_shape(k),
                    jnp.float32 if widen[k] else leaf_dtypes[k],
                    gathers[k])
                for k in range(len(p_leaves))]

            fcm = self.fcm

            def scatter_grads(g_full):
                out = []
                for gk, plan_k, w in zip(g_full, transpose_plans, widen):
                    if w:  # transpose of gather_group's cast-back to dt
                        gk = gk.astype(jnp.float32)
                    for d, axes, qgz in reversed(plan_k):
                        if fcm and jnp.issubdtype(gk.dtype, jnp.floating):
                            # per-tile ring scatter: the backward GEMM's
                            # epilogue wire, classified fused/hidden
                            gk = fcm_reduce_scatter(gk, axes, d,
                                                    bits=qgz, block=block)
                        elif qgz:
                            gk = quantized_psum_scatter(gk, axes, d,
                                                        bits=qgz,
                                                        block=block)
                        else:
                            gk = f32_psum_scatter(gk, axes, d)
                    out.append(gk)
                return out

            carried = _build_carried_stream(steps, gather_group,
                                            run_group, scatter_grads)

            def region_fn(carry, params_grouped, extras_grouped):
                return carried(carry, params_grouped, extras_grouped)
        else:
            def step(c, xs):
                shards, extras_g = xs
                full = gather_group(shards)
                return run_group(c, full, extras_g), None

            # Save every intermediate EXCEPT the gathered params:
            # activations are stored as usual (no recompute tax), only the
            # all-gathers rerun in backward.
            step = jax.checkpoint(
                step,
                policy=jax.checkpoint_policies.
                save_anything_except_these_names("zero3_gathered"))

            # Unrolled prefetch = unroll-2 over groups: the two gathers in
            # the unrolled loop body are independent of each other's
            # compute, so XLA's latency-hiding scheduler MAY hoist
            # gather(i+1) alongside compute(i) — the PrefetchCoordinator's
            # lookahead (stage3.py:169) as a loop structure, but only as a
            # scheduling opportunity, not a program property (the carried
            # mode makes it structural).  The plan guarantees an even
            # group count whenever unrolled prefetch is on.
            unroll = 2 if plan.prefetch else 1

            def region_fn(carry, params_grouped, extras_grouped):
                carry, _ = lax.scan(
                    step, carry, (params_grouped, extras_grouped),
                    unroll=unroll)
                return carry

        # check_vma SCOPED (advisor r3): pallas_call outputs carry no
        # varying-mesh-axes metadata, so the vma analysis rejects any
        # Pallas kernel (flash attention, Pallas LN) inside the manual
        # region at trace time — but a Pallas-FREE body (CPU sim, XLA
        # dispatch, custom models) keeps the analysis ON, catching
        # cross-shard replication bugs where it can.  Detection traces
        # the user body once abstractly and walks the jaxpr for pallas
        # primitives; an untraceable body (needs the mesh context)
        # conservatively keeps the analysis off.
        check_vma = not _body_uses_pallas(body, init_carry, p_tree,
                                          p_leaves, extra_xs)
        streamed = jax.shard_map(
            region_fn, mesh=mesh,
            in_specs=(carry_spec, in_specs_params, extras_specs),
            out_specs=carry_spec, axis_names=set(manual),
            check_vma=check_vma)
        return streamed(init_carry, grouped_params, grouped_extras)
