"""ZeRO-Offload: optimizer states in TPU-VM host DRAM, stepped by native
host Adam while the chips hold only compute-dtype parameters.

Reference: the stage-2 CPU-offload path (runtime/zero/stage2.py:976-1125
pinned-buffer grad staging + DeepSpeedCPUAdam step + fp16 copy back).  The
TPU recasting: device keeps bf16/fp16 params; each step the (already
ZeRO-sharded, already data-parallel-reduced) gradients are fetched to host,
the C++ OpenMP Adam (csrc/adam/host_adam.cpp) updates fp32 master + moments
in place, and the updated params return to HBM via an async device_put —
fused with the fp32→bf16 cast in native code (the adam_update_copy analog).

The dynamic-loss-scale overflow check runs on host for free during the
gradient fetch (stage2.py:1783 has a dedicated allreduce for this).
"""

from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...utils.logging import log_dist
from ...ops.adam import DeepSpeedCPUAdam


def _global_grad_norm(leaves) -> float:
    sq = 0.0
    for g in leaves:
        sq += float(np.vdot(g, g).real)
    return float(np.sqrt(sq))


class HostOffloadOptimizer:
    """Owns the host-side fp32 master/moments and the native Adam step.

    apply() is synchronous host math between two async device epochs: the
    grad fetch blocks on the last device program, the device_put of updated
    params dispatches without blocking the next forward.
    """

    def __init__(self, master_params: Any, optimizer_name: str,
                 optimizer_params: dict, gradient_clipping: float = 0.0):
        name = (optimizer_name or "adam").lower()
        if name not in ("adam", "adamw"):
            raise ValueError(
                f"offload_optimizer supports Adam/AdamW, got {optimizer_name!r}"
                " (reference: only DeepSpeedCPUAdam is offloadable —"
                " stage2.py:1011 cpu_offload requires it)")
        p = dict(optimizer_params or {})
        betas = p.get("betas", (0.9, 0.999))
        self.opt = DeepSpeedCPUAdam(
            master_params, lr=p.get("lr", 1e-3), betas=tuple(betas),
            eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=(name == "adamw" or bool(p.get("adam_w_mode", False))))
        self.gradient_clipping = float(gradient_clipping or 0.0)
        log_dist(
            f"ZeRO-Offload: host {name} over "
            f"{sum(leaf.size for leaf in jax.tree.leaves(self.opt.params))}"
            f" params, "
            f"native={self.opt.using_native}", ranks=[0])

    @property
    def master_params(self):
        return self.opt.params

    def step_count(self) -> int:
        return self.opt.step_count

    def apply(self, grads_device: Any, scale_inv: float,
              lr: Optional[float], store_dtype, *,
              boxed: bool = False) -> Any:
        """Fetch grads, step host Adam, return updated device-ready params
        (or None on overflow — the caller skips and rescales).

        boxed=True: grads_device is a ONE-ELEMENT LIST ownership box —
        the tree is taken out of it (box[0] -> None) so this call owns
        the only reference and the native sweep can free each grad leaf
        right after its update.  At multi-billion-param scale the grad
        tier is tens of GB and holding it through the sweep doubles the
        step's host peak (the r4 4.2B OOM).  Explicit keyword, not a
        structural guess: a legitimate one-element-list PYTREE must never
        be mutated."""
        if boxed:
            tree = grads_device[0]
            grads_device[0] = None
        else:
            tree = grads_device
        g_leaves = [np.asarray(g, dtype=np.float32)
                    for g in jax.tree.leaves(tree)]
        tree = None  # leaves now owned by g_leaves alone (when boxed)
        finite = all(np.isfinite(g).all() for g in g_leaves)
        if not finite:
            return None

        def writable(i):
            # np.asarray of a device array is a zero-copy READ-ONLY view
            # when dtypes match (the fast gas=1/no-clip path never touches
            # it); in-place scaling/clipping must copy that leaf first —
            # lazily, so the copy cost is only paid where a write happens
            if not g_leaves[i].flags.writeable:
                g_leaves[i] = g_leaves[i].copy()
            return g_leaves[i]

        if scale_inv != 1.0:
            for i in range(len(g_leaves)):
                g = writable(i)
                g *= scale_inv
        if self.gradient_clipping > 0.0:
            norm = _global_grad_norm(g_leaves)
            if norm > self.gradient_clipping:
                clip = self.gradient_clipping / (norm + 1e-6)
                for i in range(len(g_leaves)):
                    g = writable(i)
                    g *= clip
        if store_dtype == jnp.bfloat16:
            # Native fused update+cast writes the device-bound bf16 copy;
            # passing the leaf LIST lets the sweep free each grad leaf
            # after its update (step Nones out consumed entries).
            return self.opt.step(lr=lr, emit_bf16=True, leaf_list=g_leaves)
        self.opt.step(lr=lr, leaf_list=g_leaves)
        return jax.tree.map(
            lambda pm: pm.astype(np.dtype(store_dtype))
            if pm.dtype == np.float32 and store_dtype != jnp.float32
            else pm, self.opt.params)

    def load_master_params(self, params: Any) -> None:
        """Overwrite the host fp32 master from a (device or host) param tree
        without touching moments — used when a checkpoint restores module
        weights but not optimizer state."""
        src_leaves = jax.tree.structure(self.opt.params).flatten_up_to(params)
        for dst, src in zip(jax.tree.leaves(self.opt.params), src_leaves):
            dst[...] = np.asarray(src, dtype=dst.dtype)

    # -- checkpoint ----------------------------------------------------- #
    def state_dict(self):
        return self.opt.state_dict()

    def load_state_dict(self, sd):
        self.opt.load_state_dict(sd)
