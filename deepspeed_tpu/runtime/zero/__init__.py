from .partition import ZeroPartitioner, zero_partition_spec
