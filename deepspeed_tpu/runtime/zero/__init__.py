from .partition import (ZeroPartitioner, resolve_hpz_axes,
                        zero_partition_spec)
from .api import GatheredParameters, Init
from .offload import HostOffloadOptimizer
from .tiling import TiledLinear
