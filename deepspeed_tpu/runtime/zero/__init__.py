from .partition import ZeroPartitioner, zero_partition_spec
from .api import GatheredParameters, Init
from .offload import HostOffloadOptimizer
from .tiling import TiledLinear
