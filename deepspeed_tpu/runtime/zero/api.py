"""User-facing ZeRO-3 construction API — zero.Init and GatheredParameters.

Reference: deepspeed/runtime/zero/partition_parameters.py — Init:339
(subclass-init interception so a 100B model never materializes unsharded)
and GatheredParameters:1079 (context manager that allgathers partitioned
params for code needing full tensors).

TPU recasting: JAX params are explicit pytrees, so no class interception is
needed — `Init` is a context manager under which `materialize(init_fn,
rng)` builds each shard directly into its ZeRO placement: the weights are
created via `jax.jit(init_fn, out_shardings=...)`, so every device only
ever materializes its own partition (the eval_shape + sharded-init recipe
of SURVEY.md §7 step 4).  `GatheredParameters` produces a temporarily
replicated (fully-gathered) copy for host-side surgery and scatters edits
back on exit.
"""

from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...parallel.mesh import MeshContext, get_mesh_context
from ...utils.logging import log_dist
from .partition import ZeroPartitioner


class Init:
    """Sharded-from-birth parameter construction (reference Init:339).

    Usage:
        with zero.Init(config=ds_config, mesh_ctx=ctx) as zinit:
            params = zinit.materialize(model.init_params, rng,
                                       base_specs=model.param_partition_specs())

    Every leaf is produced by a compiled init whose out_sharding is its
    ZeRO partition — peak per-device memory is the shard size, never the
    full parameter (the reference's whole reason for intercepting
    __init__).
    """

    def __init__(self, config=None, mesh_ctx: Optional[MeshContext] = None,
                 stage: int = 3, dtype=jnp.float32):
        if config is not None:
            stage = config.zero_optimization_stage
        self.stage = stage
        self.dtype = dtype
        self.mesh_ctx = mesh_ctx
        self._partitioner = None

    def __enter__(self):
        ctx = self.mesh_ctx or get_mesh_context()
        self.mesh_ctx = ctx
        self._partitioner = ZeroPartitioner(ctx, self.stage)
        return self

    def __exit__(self, *exc):
        return False

    def materialize(self, init_fn: Callable, rng, *args,
                    base_specs: Any = None) -> Any:
        """Run init_fn(rng, *args) with ZeRO out_shardings — XLA builds each
        leaf directly as its shard."""
        shapes = jax.eval_shape(init_fn, rng, *args)
        shardings = self._partitioner.param_shardings(shapes, base_specs)
        params = jax.jit(init_fn, out_shardings=shardings)(rng, *args)
        n = sum(int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(params))
        log_dist(f"zero.Init: materialized {n} params sharded at stage "
                 f"{self.stage}", ranks=[0])
        return params

    def shard_existing(self, params: Any, base_specs: Any = None) -> Any:
        """Scatter an already-materialized tree (the convert-after-load
        path, reference _convert_to_deepspeed_param:527)."""
        shardings = self._partitioner.param_shardings(params, base_specs)
        return jax.tree.map(jax.device_put, params, shardings)


class GatheredParameters:
    """Temporarily gather sharded params to full (replicated) arrays
    (reference GatheredParameters:1079).

    with GatheredParameters(params, modifier_rank=0) as full:
        full["w"] = new_value        # host-side surgery
    # on exit, edits are re-scattered into the original shardings via
    # .updated (or in place if a setter callback was given)
    """

    def __init__(self, params: Any, modifier_rank: Optional[int] = None,
                 mesh_ctx: Optional[MeshContext] = None,
                 on_exit: Optional[Callable[[Any], None]] = None):
        self.params = params
        self.modifier_rank = modifier_rank
        self.mesh_ctx = mesh_ctx or get_mesh_context()
        self.on_exit = on_exit
        self.updated: Optional[Any] = None
        self._full = None

    def __enter__(self):
        # np.array on a sharded jax.Array performs the gather; copy=True
        # yields writable host buffers for in-place surgery
        self._full = jax.tree.map(
            lambda leaf: np.array(leaf) if isinstance(leaf, jax.Array) else leaf,
            self.params)
        return self._full

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        if self.modifier_rank is not None:
            # re-scatter (possibly modified) values into original shardings
            self.updated = jax.tree.map(
                lambda full, orig: jax.device_put(
                    jnp.asarray(full, dtype=orig.dtype), orig.sharding)
                if isinstance(orig, jax.Array) else full,
                self._full, self.params)
            if self.on_exit is not None:
                self.on_exit(self.updated)
        return False
