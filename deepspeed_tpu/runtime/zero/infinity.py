"""ZeRO-Infinity layer-streaming engine — train models whose parameters do
not fit in HBM.

Reference: the stage-3 + NVMe composition — parameters paged from NVMe at
fetch time (runtime/swap_tensor/partitioned_param_swapper.py:36, wired at
stage3.py:932), gradients partitioned to CPU/NVMe (stage3.py:2088), and
optimizer states swapped around a sub_group-wise step (stage3.py:2777,
2633-2686).  That is the reference's "40B params on one V100" story
(BASELINE.md).

TPU recasting (no autograd hooks; a Python-driven streaming step around
small jitted programs):

  HBM      : boundary activations + at most TWO layer groups of params at a
             time (current + async prefetch) — never the whole model;
  host/NVMe: compute-dtype parameter groups (PartitionedParamSwapper when
             offload_param.device == "nvme"; host arrays for "cpu"), fp32
             gradient accumulators, and the fp32 master + Adam moments
             owned by the host/NVMe optimizer tier (zero/offload.py,
             swap_tensor/optimizer_swapper.py);
  step     : forward streams layer groups up through the loss (head runs
             fused with value_and_grad so the loss cotangent is ready);
             backward re-streams the groups in reverse, rematerializing
             each layer's forward with jax.vjp from its saved input;
             the optimizer sweep then pipelines NVMe master/moment reads,
             native host Adam, and write-backs leaf by leaf.

The model opts in by exposing `layerwise_api()` (models/gpt2.py) — the
split/join of its params into ordered streaming groups plus pure embed /
layer / head-loss functions.  `deepspeed_tpu.initialize` dispatches here
when `zero_optimization.offload_param` is configured on such a model.
"""

import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...config import DeepSpeedConfig
from ...utils.logging import log_dist
from ...utils.timer import ThroughputTimer
from ..engine import resolve_mesh_ctx

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
SWEEP_RESULTS_PATH = os.environ.get(
    "DS_AIO_SWEEP_RESULTS",
    os.path.join(_REPO_ROOT, "benchmarks", "aio_sweep_results.txt"))


def load_sweep_ceiling(backend: str,
                       path: str = None) -> Optional[Dict[str, float]]:
    """Measured read/write GB/s ceiling for `backend` from the aio sweep
    artifact (benchmarks/aio_sweep_results.txt `aio_best_config` line) —
    the denominator of the engine's achieved-bytes/s honesty report.
    Returns None when no sweep has been run on this host."""
    path = path or SWEEP_RESULTS_PATH
    best = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get("metric") == "aio_best_config":
                    best = row  # later lines win (append-only artifact)
    except OSError:
        return None
    if best is None:
        return None
    ceilings = best.get("ceilings")
    if ceilings is not None:
        if backend in ceilings:
            return {"read_gbps": float(ceilings[backend]["read_gbps"]),
                    "write_gbps": float(ceilings[backend]["write_gbps"])}
        # the sweep never measured THIS backend — no ceiling, rather
        # than another backend's number as a false denominator
        return None
    # pre-backend-axis artifact: one global best
    if "read_gbps" in best:
        return {"read_gbps": float(best["read_gbps"]),
                "write_gbps": float(best["write_gbps"])}
    return None


class _HostFetch:
    """swap_in handle for host-RAM parameter groups (no NVMe tier): the
    'read' is free, so it is all hidden and zero bytes."""

    def __init__(self, tree):
        self._tree = tree
        self.nbytes = 0
        self.hidden_s = 0.0
        self.exposed_s = 0.0

    def wait(self, copy: bool = True):
        return self._tree


class ZeroInfinityEngine:
    """forward/backward/step protocol over streamed parameter groups."""

    def __init__(self, model=None, config=None, model_parameters=None,
                 optimizer=None, lr_scheduler=None, mesh=None, rng=None,
                 training_data=None, collate_fn=None, mpu=None,
                 param_partition_specs=None):
        if not hasattr(model, "layerwise_api"):
            raise ValueError(
                "offload_param requires a model exposing layerwise_api() "
                "(streaming groups) — GPT2Model does; see models/gpt2.py")
        if optimizer is not None:
            raise ValueError(
                "offload_param drives the host/NVMe optimizer tier — a "
                "client optax optimizer cannot be streamed")
        self.module = model
        self.mesh_ctx = resolve_mesh_ctx(config, mesh)
        dp = self.mesh_ctx.data_parallel_world_size
        self.config = (config if isinstance(config, DeepSpeedConfig)
                       else DeepSpeedConfig(config, world_size=dp))
        if self.config.fp16.enabled:
            raise ValueError(
                "the streaming engine is bf16/fp32-native; use bf16 instead "
                "of fp16 (dynamic loss scaling is unnecessary on TPU)")
        self.compute_dtype = (jnp.bfloat16 if self.config.bf16.enabled
                              else jnp.float32)

        api = model.layerwise_api()
        self._split = api["split"]
        self._join = api["join"]
        # memory-lean variant (frees group leaves as it stacks); models
        # that don't provide one fall back to the plain join
        self._join_consuming = api.get("join_consuming", api["join"])
        self._embed_fn = api["embed_fn"]
        self._layer_fn = api["layer_fn"]
        self._head_loss_fn = api["head_loss_fn"]
        self.num_layers = api["num_layers"]
        self._order = (["embed"] +
                       [f"layer{i}" for i in range(self.num_layers)] +
                       ["head"])

        if model_parameters is None:
            raise ValueError("model_parameters is required")

        # ---- host/NVMe tiers ----------------------------------------- #
        zc = self.config.zero_config
        op = zc.offload_param
        import ml_dtypes  # bf16 numpy dtype
        self._np_dtype = (ml_dtypes.bfloat16
                          if self.compute_dtype == jnp.bfloat16
                          else np.float32)
        # cast straight to the compute numpy dtype — no transient fp32 copy
        # of the full model (this engine exists because the model is big)
        groups_compute = self._split(jax.tree.map(
            lambda a: np.asarray(a).astype(self._np_dtype)
            if np.issubdtype(np.asarray(a).dtype, np.floating) or
            str(np.asarray(a).dtype) == "bfloat16" else np.asarray(a),
            model_parameters))
        self._use_nvme_params = op is not None and op.device == "nvme"
        # swap-in look-ahead: how many window buffers the sweeps may hold
        # in flight (2 = double buffer; < 2 serializes reads at use).
        # Validated against buffer_count at the config boundary.
        self._prefetch_depth = (int(op.prefetch_depth)
                                if op is not None else 0)
        if self._use_nvme_params:
            from ..swap_tensor.partitioned_param_swapper import (
                PartitionedParamSwapper)
            swap_dir = os.path.join(
                op.nvme_path or "/tmp/deepspeed_tpu_nvme", "zero_stage_3",
                "params")
            self._swapper = PartitionedParamSwapper(
                swap_dir, groups_compute,
                buffer_count=max(2, op.buffer_count),
                aio_config=self.config.aio_config,
                retry_policy=self.config.resilience_config
                .build_retry_policy())
            for name, tree in groups_compute.items():
                self._swapper.write(name, tree, async_op=True)
            self._swapper.flush_writes()
            self._swapper.snapshot_stats()  # init writes are not step I/O
            self._swapper.drain_write_events()  # ...nor step trace spans
            self._host_groups = None
        else:
            self._swapper = None
            self._host_groups = groups_compute

        # fp32 master + moments: NVMe or host Adam tier.  The fp32 tree is
        # consumed by the tier's constructor (NVMe writes it to files and
        # drops it; host keeps it — that IS the master copy).
        oo = zc.offload_optimizer
        full_f32 = jax.tree.map(lambda a: np.asarray(a, np.float32),
                                model_parameters)
        if oo is not None and oo.device == "nvme":
            from ..swap_tensor import create_nvme_offload_optimizer
            self._opt = create_nvme_offload_optimizer(
                full_f32, self.config,
                gradient_clipping=self.config.gradient_clipping)
        else:
            from .offload import HostOffloadOptimizer
            self._opt = HostOffloadOptimizer(
                full_f32, self.config.optimizer_name or "adam",
                self.config.optimizer_params,
                gradient_clipping=self.config.gradient_clipping)
        del full_f32

        # ---- compiled programs --------------------------------------- #
        cdt = self.compute_dtype

        def cast(tree):
            return jax.tree.map(
                lambda a: a.astype(cdt) if jnp.issubdtype(
                    jnp.asarray(a).dtype, jnp.floating) else jnp.asarray(a),
                tree)

        self._jit_embed = jax.jit(
            lambda e, ids, r: self._embed_fn(cast(e), ids, r))
        self._jit_layer = jax.jit(
            lambda p, h, r, i: self._layer_fn(cast(p), h, r, i))

        def head_valgrad(head_g, embed_g, h, ids, labels):
            def f(hg, eg, hh):
                return self._head_loss_fn(cast(hg), cast(eg), hh, ids,
                                          labels)
            (loss), grads = jax.value_and_grad(f, argnums=(0, 1, 2))(
                head_g, embed_g, h)
            return loss, grads

        self._jit_head = jax.jit(head_valgrad)

        def layer_vjp(p, x, ct, r, i):
            _, vjp = jax.vjp(lambda pp, xx: self._layer_fn(cast(pp), xx,
                                                           r, i), p, x)
            return vjp(ct)

        self._jit_layer_vjp = jax.jit(layer_vjp)

        def embed_vjp(e, ids, ct, r):
            def f(eg):
                h = self._embed_fn(cast(eg), ids, r)
                return jnp.vdot(h.astype(jnp.float32),
                                ct.astype(jnp.float32))
            return jax.grad(f)(e)

        self._jit_embed_vjp = jax.jit(embed_vjp)

        # ---- bookkeeping --------------------------------------------- #
        self.lr_scheduler = lr_scheduler
        self.training_dataloader = self._configure_dataloader(
            training_data, collate_fn)
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._rng = rng if rng is not None else jax.random.PRNGKey(42)
        self._grad_groups: Optional[Dict[str, Any]] = None
        self._acts = None
        self._pending = None
        self._last_loss = None
        self.max_live_param_groups = 0
        self._live_now = 0
        # cross-sweep carries: each sweep's FIRST swap-in is issued at the
        # tail of the adjacent sweep (backward's first group under the
        # head compute, next forward's embed under the optimizer sweep) —
        # without these the first read of every sweep is structurally
        # serialized
        self._fwd_carry = None
        self._bwd_carry = None
        # ---- swap-overlap accounting (per optimizer-step window) ----- #
        self._swap_events: List[Dict[str, float]] = []
        self._step_t0: Optional[float] = None
        self.last_swap_stats: Optional[Dict[str, Any]] = None
        self.serialized_swap_steps = 0
        backend = (self._swapper.write_handle.backend_name
                   if self._swapper is not None else "none")
        self.aio_backend = backend
        self.sweep_ceiling = (load_sweep_ceiling(backend)
                              if self._swapper is not None else None)
        self.tput_timer = ThroughputTimer(
            batch_size=self.config.train_micro_batch_size_per_gpu,
            num_workers=dp,
            steps_per_output=self.config.steps_per_print)
        # ---- runtime telemetry monitor (docs/telemetry.md) ------------ #
        # The streaming engine has no static roofline (its step is a
        # host-driven sweep, not one traced program) — reconciliation
        # here is the SWAP lane: achieved GB/s + overlap vs the aio
        # sweep ceiling, which _finalize_swap_stats measures per step.
        self.monitor = None
        self._monitor_seq = None
        if self.config.monitor_config.enabled and (
                jax.process_index() == 0 or
                self.config.monitor_config.fleet or
                self.config.monitor_config.heartbeat):
            from ...monitor import TrainingMonitor
            self.monitor = TrainingMonitor(
                self.config.monitor_config,
                steps_per_print=self.config.steps_per_print,
                predictions=None,
                boundary_fn=self._monitor_boundary_reads,
                swap_stats_fn=lambda: self.last_swap_stats,
                process_index=jax.process_index(),
                world_size=jax.process_count(),
                meta={"engine": type(self).__name__,
                      "params_on": ("nvme" if self._use_nvme_params
                                    else "host"),
                      "aio_backend": self.aio_backend,
                      "prefetch_depth": self._prefetch_depth,
                      "sweep_ceiling": self.sweep_ceiling})
        n_params = sum(int(np.prod(np.shape(leaf)))
                       for leaf in jax.tree.leaves(model_parameters))
        log_dist(
            f"ZeroInfinityEngine: {n_params:,} params in "
            f"{len(self._order)} streamed groups, params_on="
            f"{'nvme' if self._use_nvme_params else 'host'}, "
            f"optimizer={type(self._opt).__name__}, "
            f"aio_backend={self.aio_backend}, "
            f"prefetch_depth={self._prefetch_depth}"
            + (f", sweep_ceiling={self.sweep_ceiling['read_gbps']:.2f}GB/s "
               "read" if self.sweep_ceiling else ""), ranks=[0])

    # ------------------------------------------------------------------ #
    def _configure_dataloader(self, training_data, collate_fn):
        """Same per-process sharding contract as DeepSpeedEngine
        (runtime/engine.py _configure_dataloader)."""
        if training_data is None:
            return None
        from ..dataloader import DeepSpeedDataLoader
        nproc = jax.process_count()
        dp = self.mesh_ctx.data_parallel_world_size
        per_process = (self.config.train_micro_batch_size_per_gpu *
                       dp) // nproc
        return DeepSpeedDataLoader(
            training_data, batch_size=per_process, collate_fn=collate_fn,
            data_parallel_world_size=nproc,
            data_parallel_rank=jax.process_index())

    @property
    def optimizer(self):
        return self._opt

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def is_gradient_accumulation_boundary(self) -> bool:
        return self.micro_steps % self.gradient_accumulation_steps() == 0

    def estimate_memory(self):
        """Per-tier byte estimate: HBM holds only the streaming window."""
        group_bytes = {}
        for name in self._order:
            tree = self._group_host(name)
            group_bytes[name] = sum(
                np.asarray(leaf).nbytes for leaf in jax.tree.leaves(tree))
        total = sum(group_bytes.values())
        hbm_window = 2 * max(group_bytes.values())
        n = sum(int(np.prod(np.shape(leaf))) for name in self._order
                for leaf in jax.tree.leaves(self._group_host(name)))
        return {
            "hbm_param_window": hbm_window,
            "host_or_nvme_params": total,
            "grads_fp32_host": 4 * n,
            "optimizer_fp32_nvme_or_host": 12 * n,
            "total_hbm_params": hbm_window,   # vs 2n/4n resident baselines
        }

    # ------------------------------------------------------------------ #
    def _group_host(self, name: str):
        if self._swapper is not None:
            return self._swapper.get(name)
        return self._host_groups[name]

    def _release_device(self, ref):
        """Callers MUST rebind: ``p = self._release_device(p)`` — deleting a
        local alias alone would keep the device arrays alive and push peak
        residency past the 2-group window."""
        self._live_now -= 1
        del ref
        return None

    # ---- carried swap-in machinery ----------------------------------- #
    # The sweeps walk a fetch PLAN (ordered group names).  _take(pos)
    # first issues the next prefetch_depth-1 plan positions' NVMe reads,
    # THEN waits for position pos — so group i+1's disk read runs while
    # group i's wait returns (usually instantly, read done under the
    # previous group's compute) and its jitted compute dispatches.  The
    # in-flight handles live in `inflight`, the sweep's carry — the PR 7
    # carried-double-buffer discipline one tier down, with two (or
    # prefetch_depth) pinned window buffers instead of HBM gather slots.

    def _swap_in(self, name: str):
        if self._swapper is not None:
            return self._swapper.swap_in(name)
        return _HostFetch(self._host_groups[name])

    def _sweep_state(self, plan: List[str]):
        return {"plan": plan, "inflight": {}}

    def _take(self, st, pos: int, extra: int = 0):
        """Device params for plan position `pos`; issues the look-ahead.
        `extra` widens it when upcoming positions are consumed by ONE
        compute (the head + tied-embed pair) — without it the pair's
        second read could only start after the first's wait."""
        plan, inflight = st["plan"], st["inflight"]
        if self._prefetch_depth >= 2:
            for k in range(pos, min(pos + self._prefetch_depth, len(plan))):
                if k not in inflight:
                    inflight[k] = self._swap_in(plan[k])
        handle = inflight.pop(pos, None)
        if handle is None:
            # prefetch disabled (or depth exhausted): pay the read inline
            handle = self._swap_in(plan[pos])
        tree = handle.wait()
        if self._prefetch_depth >= 2 and extra:
            # the widened tail issues AFTER the wait: `tree` is a detached
            # copy, so pos's window slot is evictable and the pair fits
            # even in a two-buffer window
            ahead = self._prefetch_depth + extra
            for k in range(pos + 1, min(pos + ahead, len(plan))):
                if k not in inflight:
                    inflight[k] = self._swap_in(plan[k])
        if handle.nbytes:
            # t_issue/t_done are absolute perf_counter stamps: the monitor
            # trace exporter turns the window into a Perfetto span
            self._swap_events.append({
                "name": plan[pos], "bytes": float(handle.nbytes),
                "hidden_s": handle.hidden_s, "exposed_s": handle.exposed_s,
                "t_issue": handle.t_issue,
                "t_done": (handle.t_issue + handle.hidden_s +
                           handle.exposed_s)})
        self._live_now += 1
        self.max_live_param_groups = max(self.max_live_param_groups,
                                         self._live_now)
        return jax.tree.map(jnp.asarray, tree)

    def _release_group(self, name: str) -> None:
        if self._swapper is not None:
            self._swapper.release(name)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ------------------------------------------------------------------ #
    _trace = bool(int(os.environ.get("DS_INFINITY_TRACE", "0")))

    def _t(self, msg):
        if self._trace:
            import time as _time
            print(f"[inf-trace] {msg} @{_time.time():.1f}", flush=True)

    def forward(self, input_ids, labels=None):
        """Stream groups forward; returns the loss.  The head runs fused
        with value_and_grad so backward() starts with the cotangent ready
        (the reference's PreBackwardFunction re-fetch begins the same way,
        stage3.py:546).

        The fetch plan is carried: _take(i) issues layer i+1's (and, at
        depth > 2, further) NVMe reads BEFORE waiting on layer i, so the
        disk streams the next group while this group's compute holds the
        device — swap-in latency hides under MXU work instead of
        serializing the sweep."""
        self.tput_timer.start()
        if self.monitor is not None:
            self.monitor.mark_step_start()
            self._monitor_seq = int(np.shape(input_ids)[-1])
        if self._step_t0 is None:
            self._step_t0 = time.perf_counter()
        self._t("fwd start")
        rng = self._next_rng() if self._is_dropout_mode() else None
        ids = jnp.asarray(input_ids)
        lbl = None if labels is None else jnp.asarray(labels)

        plan = (["embed"] + [f"layer{i}" for i in range(self.num_layers)]
                + ["head", "embed"])
        st = self._sweep_state(plan)
        if self._fwd_carry is not None:     # issued under the last step()
            st["inflight"][0] = self._fwd_carry
            self._fwd_carry = None
        embed_g = self._take(st, 0)
        h = self._jit_embed(embed_g, ids, rng)
        acts = [h]
        # release the embed group during the layer sweep — the head step
        # re-fetches it (tied wte); peak device residency stays at 2 groups
        embed_g = self._release_device(embed_g)
        self._release_group("embed")
        for i in range(self.num_layers):
            # on the last layer the look-ahead covers BOTH head groups —
            # jit_head consumes head + tied embed in one compute, so the
            # pair must stream together under this layer's window
            extra = 1 if i == self.num_layers - 1 else 0
            p = self._take(st, 1 + i, extra=extra)
            h = self._jit_layer(p, h, rng, jnp.int32(i))
            acts.append(h)
            p = self._release_device(p)
            self._release_group(f"layer{i}")

        self._t("fwd layers done")
        head_g = self._take(st, 1 + self.num_layers)
        embed_g = self._take(st, 2 + self.num_layers)
        loss, (g_head, g_embed_head, dh) = self._jit_head(
            head_g, embed_g, h, ids, lbl)
        head_g = self._release_device(head_g)
        embed_g = self._release_device(embed_g)
        self._release_group("head")
        self._release_group("embed")
        if self._prefetch_depth >= 2 and self._swapper is not None:
            # backward's first group streams in under the head compute
            self._bwd_carry = self._swap_in(f"layer{self.num_layers - 1}")
        self._t("fwd head done")
        self._acts = acts
        self._pending = {"rng": rng, "ids": ids, "dh": dh,
                         "g_head": g_head, "g_embed_head": g_embed_head}
        self._last_loss = loss
        return loss

    __call__ = forward

    def _is_dropout_mode(self) -> bool:
        cfg = getattr(self.module, "config", None)
        if cfg is None:
            return False
        return any(getattr(cfg, k, 0.0) > 0.0 for k in
                   ("embd_dropout", "attn_dropout", "hidden_dropout"))

    def backward(self, loss=None):
        """Re-stream groups in reverse; accumulate fp32 grads on host
        (the reference partitions grads to CPU/NVMe — stage3.py:2088).

        Gradient fetches are PIPELINED one group behind the compute: the
        device->host copy of layer i+1's grads is started asynchronously
        (copy_to_host_async) and materialized while layer i's vjp runs,
        so transfer overlaps compute instead of serializing it (the
        reference overlaps the same way on a side CUDA stream,
        stage2.py:1326; VERDICT r2 weak #7).  Device residency: the params
        window + up to TWO grad groups transiently (the in-flight copy
        and the one the running vjp is producing) — size beyond-HBM
        configs accordingly."""
        assert self._pending is not None, "backward() before forward()"
        pend, acts = self._pending, self._acts
        rng, ids, dh = pend["rng"], pend["ids"], pend["dh"]

        def acc(name, tree):
            host = jax.tree.map(lambda a: np.asarray(a, np.float32), tree)
            if self._grad_groups is None:
                self._grad_groups = {}
            if name in self._grad_groups:
                self._grad_groups[name] = jax.tree.map(
                    np.add, self._grad_groups[name], host)
            else:
                self._grad_groups[name] = host

        def start_copy(name, tree):
            for leaf in jax.tree.leaves(tree):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            return (name, tree)

        self._t("bwd start")
        inflight = start_copy("head", pend["g_head"])
        plan = ([f"layer{i}" for i in reversed(range(self.num_layers))]
                + ["embed"])
        st = self._sweep_state(plan)
        if self._bwd_carry is not None:     # issued under the head compute
            st["inflight"][0] = self._bwd_carry
            self._bwd_carry = None
        for pos, i in enumerate(reversed(range(self.num_layers))):
            p = self._take(st, pos)
            gp, dh = self._jit_layer_vjp(p, acts[i], dh, rng, jnp.int32(i))
            # materialize the PREVIOUS group (its async copy overlapped
            # this vjp's dispatch) before starting the next copy — one
            # d2h copy in flight at a time
            acc(*inflight)
            inflight = start_copy(f"layer{i}", gp)
            p = self._release_device(p)
            self._release_group(f"layer{i}")
            self._t(f"bwd layer{i} done")

        embed_g = self._take(st, self.num_layers)
        g_embed = self._jit_embed_vjp(embed_g, ids, dh, rng)
        g_embed = jax.tree.map(jnp.add, g_embed,
                               jax.tree.map(jnp.asarray,
                                            pend["g_embed_head"]))
        acc(*inflight)
        acc("embed", g_embed)
        embed_g = self._release_device(embed_g)
        self._release_group("embed")
        if self._prefetch_depth >= 2 and self._swapper is not None:
            # next forward's embed streams in under the optimizer sweep
            # (write() keeps the pending slot coherent when the step
            # rewrites the group's file)
            self._fwd_carry = self._swap_in("embed")
        self._acts = None
        self._pending = None
        self.micro_steps += 1
        return loss if loss is not None else self._last_loss

    def step(self):
        """Optimizer sweep at the accumulation boundary: the host/NVMe tier
        pipelines master/moment reads, native Adam, and write-backs leaf by
        leaf (reference: stage3.py:2777 sub_group step)."""
        if not self.is_gradient_accumulation_boundary():
            return
        assert self._grad_groups is not None, "step() before backward()"
        gas = self.gradient_accumulation_steps()
        # consuming join: each layer-group grad leaf is freed as its row
        # is copied into the stacked layout, so the join transient is one
        # stacked leaf — the naive join's full second copy (~17 GB on a
        # 4.2B model) OOMed a 125 GB host at exactly this point (r4)
        self._t("step join start")
        box = [self._join_consuming(self._grad_groups)]
        self._grad_groups = None  # leaves now owned by the box alone
        lr = None
        if self.lr_scheduler is not None:
            lr = float(self.lr_scheduler.lr_at(self._opt.step_count()))
        # ownership-box call: apply takes the tree out of the box, so the
        # native sweep can free each grad leaf right after its update
        self._t("step apply start")
        new_host = self._opt.apply(box, 1.0 / gas, lr,
                                   self.compute_dtype, boxed=True)
        self._t("step apply done")
        overflow = new_host is None
        if not overflow:
            # astype(copy=False): the emit_bf16 path already returns the
            # store dtype — an unconditional astype here was a second
            # full-model copy at exactly the step's memory peak
            new_groups = self._split(jax.tree.map(
                lambda a: np.asarray(a).astype(self._np_dtype, copy=False)
                if np.issubdtype(np.asarray(a).dtype, np.floating) or
                str(np.asarray(a).dtype) == "bfloat16" else np.asarray(a),
                new_host))
            if self._swapper is not None:
                for name, tree in new_groups.items():
                    self._swapper.write(name, tree, async_op=True)
                self._swapper.flush_writes()
            else:
                self._host_groups = new_groups
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
        else:
            self.skipped_steps += 1
        self.global_steps += 1
        self.tput_timer.stop(global_step=True)
        self._finalize_swap_stats()
        if self.monitor is not None:
            from ...monitor import record as mrec
            tokens = (self.config.train_batch_size * self._monitor_seq
                      if self._monitor_seq else None)
            self.monitor.end_step(
                self.global_steps, loss=self._last_loss, tokens=tokens,
                counters={mrec.F_SKIPPED_STEPS: self.skipped_steps,
                          mrec.F_DISPATCHES_PER_STEP: None},
                # THIS step's swap stats are already host data — records
                # carry per-step values, not the window boundary's
                swap=self.last_swap_stats)
        if self.global_steps % self.config.steps_per_print == 0:
            stats = self.last_swap_stats or {}
            extra = ""
            if stats.get("read_bytes"):
                extra = (f", swap_read={stats['read_gbps']:.2f}GB/s"
                         + (f" ({stats['read_vs_ceiling']:.0%} of sweep "
                            "ceiling)" if stats.get("read_vs_ceiling")
                            is not None else "")
                         + f", overlap={stats['overlap_fraction']:.0%}")
            log_dist(f"step={self.global_steps}, "
                     f"loss={float(self._last_loss):.6f}{extra}", ranks=[0])

    # ------------------------------------------------------------------ #
    def _finalize_swap_stats(self):
        """Fold the step window's swap-in handle timings into the honesty
        report: achieved bytes/s (lower bound — per-group issue->done
        windows), the bytes-weighted overlap fraction (how much of the
        swap traffic hid under compute), and the serialized-swap-in
        finding (auditor-style WARNING: prefetch was configured but a
        group's read was paid inline on the critical path)."""
        events, self._swap_events = self._swap_events, []
        t0, self._step_t0 = self._step_t0, None
        if (self.monitor is not None and self.monitor.trace_active
                and self._swapper is not None):
            # the step's I/O timeline becomes Perfetto spans: swap-in
            # issue→done windows (+ exposed-wait tails) and the write-
            # back issue→flush windows
            self.monitor.trace.add_swap_read_events(
                events, step=self.global_steps)
            self.monitor.trace.add_swap_write_events(
                self._swapper.drain_write_events(), step=self.global_steps)
        if self._swapper is None:
            self.last_swap_stats = None
            return
        io = self._swapper.snapshot_stats()
        read_bytes = sum(e["bytes"] for e in events)
        hidden_s = sum(e["hidden_s"] for e in events)
        exposed_s = sum(e["exposed_s"] for e in events)
        overlap_bytes = sum(
            e["bytes"] * (e["hidden_s"] / (e["hidden_s"] + e["exposed_s"]))
            for e in events if e["hidden_s"] + e["exposed_s"] > 0)
        serialized = [e["name"] for e in events
                      if e["exposed_s"] > max(e["hidden_s"], 1e-4)]
        window_s = hidden_s + exposed_s
        stats: Dict[str, Any] = {
            "aio_backend": self.aio_backend,
            "prefetch_depth": self._prefetch_depth,
            "read_bytes": read_bytes,
            "read_exposed_s": exposed_s,
            "read_hidden_s": hidden_s,
            # lower bound: per-group issue->done windows overlap each
            # other at depth > 2, so the true device-side rate is >= this
            "read_gbps": (read_bytes / window_s / 1e9) if window_s else 0.0,
            "overlap_bytes": overlap_bytes,
            "overlap_fraction": (overlap_bytes / read_bytes
                                 if read_bytes else 1.0),
            "serialized_swap_ins": serialized,
            "serialized_reads_inline": io.get("serialized_reads", 0.0),
            "write_bytes": io.get("write_bytes", 0.0),
            "write_exposed_s": io.get("write_wait_s", 0.0),
            "step_wall_s": (time.perf_counter() - t0) if t0 else 0.0,
        }
        if self.sweep_ceiling is not None and stats["read_gbps"]:
            stats["sweep_read_gbps"] = self.sweep_ceiling["read_gbps"]
            stats["read_vs_ceiling"] = (stats["read_gbps"] /
                                        self.sweep_ceiling["read_gbps"])
        else:
            stats["read_vs_ceiling"] = None
        opt_stats = getattr(self._opt, "last_sweep_stats", None)
        if opt_stats is not None:
            stats["optimizer_sweep"] = dict(opt_stats)
        if serialized and self._prefetch_depth >= 2:
            self.serialized_swap_steps += 1
            log_dist(
                f"[infinity-schedule] WARNING: {len(serialized)} serialized "
                f"swap-in(s) this step ({', '.join(serialized[:4])}"
                f"{'...' if len(serialized) > 4 else ''}) — the NVMe read "
                "was paid on the critical path despite prefetch_depth="
                f"{self._prefetch_depth}.  The disk is slower than the "
                "per-group compute window; raise the group size, deepen "
                "the prefetch, or check the aio backend "
                f"({self.aio_backend}) against the sweep ceiling.",
                ranks=[0])
        self.last_swap_stats = stats

    def swap_stats(self) -> Optional[Dict[str, Any]]:
        """Swap-overlap report for the last completed optimizer step."""
        return self.last_swap_stats

    def _monitor_boundary_reads(self) -> Dict[str, Any]:
        """Flush-boundary reads for the monitor (host-side: the streaming
        optimizer tier owns its step count as a plain int)."""
        lr = None
        if self.lr_scheduler is not None:
            try:
                lr = float(self.lr_scheduler.lr_at(self._opt.step_count()))
            except Exception:  # noqa: BLE001
                lr = None
        return {"lr": lr, "loss_scale": None}

    # ------------------------------------------------------------------ #
    def module_state_dict(self):
        """Consolidated fp32 master weights (from the optimizer tier)."""
        return self._opt.master_params

    def save_checkpoint(self, save_dir, tag=None, client_state=None):
        from .. import checkpoint as ckpt_mod
        tag = tag or f"global_step{self.global_steps}"
        client = dict(client_state or {})
        client.update({"global_steps": self.global_steps,
                       "micro_steps": self.micro_steps,
                       "skipped_steps": self.skipped_steps,
                       # bit-exact dropout resume (same as DeepSpeedEngine)
                       "engine_rng": np.asarray(
                           jax.random.key_data(self._rng)).tolist(),
                       "engine_rng_impl": str(
                           jax.random.key_impl(self._rng))})
        return ckpt_mod.save_checkpoint_state(
            save_dir, tag, module_state={"module": self.module_state_dict()},
            optimizer_state={"optimizer": self._opt.state_dict()},
            client_state=client)

    def load_checkpoint(self, load_dir, tag=None):
        from .. import checkpoint as ckpt_mod
        module_tmpl = {"module": self.module_state_dict()}
        opt_tmpl = {"optimizer": self._opt.state_dict()}
        module_state, opt_state, client = ckpt_mod.load_checkpoint_state(
            load_dir, tag, module_tmpl, opt_tmpl)
        self._opt.load_state_dict(opt_state["optimizer"])
        master = module_state["module"]
        self._opt.load_master_params(master)
        new_groups = self._split(jax.tree.map(
            lambda a: np.asarray(a, np.float32).astype(self._np_dtype),
            master))
        if self._swapper is not None:
            for name, tree in new_groups.items():
                self._swapper.write(name, tree, async_op=True)
            self._swapper.flush_writes()
            # restore writes are not step I/O: keep them out of the next
            # step's trace (same exclusion as the init write-back)
            self._swapper.drain_write_events()
        else:
            self._host_groups = new_groups
        self.global_steps = client.get("global_steps", 0)
        self.micro_steps = client.get("micro_steps", 0)
        self.skipped_steps = client.get("skipped_steps", 0)
        if client.get("engine_rng") is not None:
            try:
                self._rng = jax.random.wrap_key_data(
                    jnp.asarray(np.asarray(client["engine_rng"],
                                           np.uint32)),
                    impl=client.get("engine_rng_impl", "threefry2x32"))
            except Exception as e:  # noqa: BLE001 — old/foreign ckpt
                log_dist(f"engine_rng restore skipped: {e}", ranks=[0])
        return load_dir, client
